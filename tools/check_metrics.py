#!/usr/bin/env python
"""Lint: every ``filodb_*`` metric family emitted in code is documented in
doc/observability.md, and every family the doc names exists in code.

Companion to tools/check_spans.py (make test-observability): the doc's
metrics reference is the operator contract — an undocumented metric is
invisible to dashboards and runbooks, and a documented-but-deleted one is a
broken alert waiting to fire never.

Method: walk the package AST (no imports — runs without jax) collecting
every string constant matching ``filodb_[a-z0-9_]+`` (registration calls,
collector tuples, docstring references — all legitimate family mentions),
then compare against the same regex over doc/observability.md. Both sides
normalize to the family STEM — trailing ``_total``/``_bucket``/``_sum``/
``_count`` exposition suffixes stripped — so counters registered as
``filodb_queries`` match the documented ``filodb_queries_total`` and
histogram families match any of their derived series names.

Exit code 0 = clean, 1 = violations (printed one per line).
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
PKG = ROOT / "filodb_tpu"
DOC = ROOT / "doc" / "observability.md"

# a family mention must not be preceded by a name character (excludes the
# `_filodb_chunkmeta_all` magic selector) nor followed by `*` (glob-style
# prose references like "filodb_tpu_*" aren't family names)
NAME_RE = re.compile(r"(?<![A-Za-z0-9_])filodb_[a-z0-9_]+")
FULL_RE = re.compile(r"^filodb_[a-z0-9]+(_[a-z0-9]+)*$")


def find_names(text: str):
    for m in NAME_RE.finditer(text):
        end = m.end()
        if end < len(text) and text[end] == "*":
            continue  # glob-style prose reference, not a family name
        yield m.group(0)
SUFFIXES = ("_total", "_bucket", "_sum", "_count")

# strings that match the metric-name shape but aren't metric families
ALLOW = {
    "filodb_tpu",  # the package itself (and the filodb_tpu_* glob's stem)
}


def stem(name: str) -> str:
    for suf in SUFFIXES:
        if name.endswith(suf) and len(name) > len(suf) + len("filodb_"):
            return name[: -len(suf)]
    return name


def code_stems() -> tuple[set[str], dict[str, list[str]]]:
    stems: set[str] = set()
    where: dict[str, list[str]] = {}
    for path in sorted(PKG.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            print(f"SYNTAX ERROR {path}: {e}")
            sys.exit(1)
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                for m in find_names(node.value):
                    m = m.rstrip("_")
                    if not FULL_RE.match(m) or m in ALLOW:
                        continue
                    s = stem(m)
                    stems.add(s)
                    where.setdefault(s, []).append(
                        f"{path.relative_to(ROOT)}:{node.lineno}"
                    )
    return stems, where


def doc_stems() -> set[str]:
    text = DOC.read_text()
    out = set()
    for m in find_names(text):
        m = m.rstrip("_")
        if FULL_RE.match(m) and m not in ALLOW:
            out.add(stem(m))
    return out


PERF_DOC = ROOT / "doc" / "perf.md"


def fused_reason_violations() -> list[str]:
    """Label-taxonomy lint for ``filodb_fused_fallback_total{reason}``:
    the canonical set (metrics.FUSED_FALLBACK_REASONS) must match BOTH the
    doc/perf.md fallback table's rows and every literal reason the code
    records — a reason recorded but undocumented is an undashboarded
    series, a documented-but-unrecorded one is a dead runbook row, and a
    canonical entry with NO recording call site is a dead taxonomy entry
    (a burned-down fallback whose reason must leave the frozenset and the
    doc table together)."""
    out: list[str] = []
    # canonical set, read from the AST (no imports — runs without jax)
    canon: set[str] = set()
    tree = ast.parse((PKG / "metrics.py").read_text())
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and node.targets
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "FUSED_FALLBACK_REASONS"):
            for c in ast.walk(node.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    canon.add(c.value)
    if not canon:
        return ["fused-fallback lint: FUSED_FALLBACK_REASONS not found in "
                "filodb_tpu/metrics.py"]
    # literal reasons the code records. Direct call sites —
    # record_fused_fallback("x") and the FusedAggregateExec fallback helper
    # self._fall(ctx, "x") — feed the recorded-but-not-canonical check;
    # most reasons flow through a variable (returned from a classifier,
    # threaded through _grid_variant), so the dead-entry direction counts
    # any EXACT-match string constant in package code outside the
    # frozenset itself (docstrings never equal a bare reason name).
    recorded: set[str] = set()
    mentioned: set[str] = set()
    for path in sorted(PKG.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        tree = ast.parse(path.read_text())
        if path.name == "metrics.py":
            # skip the canonical frozenset's own literals
            for node in ast.walk(tree):
                if (isinstance(node, ast.Assign) and node.targets
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id == "FUSED_FALLBACK_REASONS"):
                    skip = {id(c) for c in ast.walk(node.value)}
                    break
            else:
                skip = set()
        else:
            skip = set()
        for node in ast.walk(tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and node.value in canon and id(node) not in skip):
                mentioned.add(node.value)
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = getattr(fn, "attr", None) or getattr(fn, "id", None)
            if name == "record_fused_fallback" and node.args:
                a = node.args[0]
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    recorded.add(a.value)
            elif name == "_fall" and len(node.args) >= 2:
                a = node.args[1]
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    recorded.add(a.value)
    # documented rows: the doc/perf.md fallback table's `reason` column
    # (the table under "Reason taxonomy:", up to the next heading — other
    # two-column tables in the doc are not reason taxonomies)
    text = PERF_DOC.read_text()
    m = re.search(r"Reason taxonomy:(.*?)^#", text, re.S | re.M)
    table = m.group(1) if m else ""
    documented = set(re.findall(r"^\| `([a-z_]+)` \|", table, re.M))
    for r in sorted(recorded - canon):
        out.append(
            f"fused-fallback reason {r!r} recorded in code but missing from "
            f"metrics.FUSED_FALLBACK_REASONS (it would be minted as "
            f"reason=\"unknown\")"
        )
    for r in sorted(canon - (recorded | mentioned)):
        out.append(
            f"fused-fallback reason {r!r} is canonical but no code records "
            f"it — dead taxonomy entry; remove it from "
            f"metrics.FUSED_FALLBACK_REASONS and doc/perf.md's fallback "
            f"table together"
        )
    for r in sorted(canon - documented):
        out.append(
            f"fused-fallback reason {r!r} is canonical but undocumented — "
            f"add a row to doc/perf.md's fallback table"
        )
    for r in sorted(documented - canon):
        out.append(
            f"doc/perf.md documents fused-fallback reason {r!r} that no "
            f"code can record"
        )
    return out


def standing_violations() -> list[str]:
    """Standing-engine taxonomy lint: (a) every ``filodb_standing_*``
    family emitted in code carries a HELP text (metrics.HELP_TEXTS — the
    families are new; shipping one without operator-facing help would be a
    silent gap the doc lint alone can't see, since docstrings mentioning a
    family satisfy it), and (b) the registry's canonical demotion-reason
    set (standing/registry.DEMOTE_REASONS) includes the fused-fallback
    member ``standing_nondecomposable`` — the two taxonomies must share
    that entry or demotions and fallback counts drift apart."""
    out: list[str] = []
    helped: set[str] = set()
    tree = ast.parse((PKG / "metrics.py").read_text())
    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.Assign) and node.targets:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):  # HELP_TEXTS: dict[...] = {...}
            target = node.target
        if (target is not None and isinstance(target, ast.Name)
                and target.id == "HELP_TEXTS" and node.value is not None
                and isinstance(node.value, ast.Dict)):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    helped.add(k.value)
    code, where = code_stems()
    for s in sorted(code):
        if s.startswith("filodb_standing") and s not in helped:
            locs = ", ".join(where.get(s, [])[:2])
            out.append(
                f"standing family {s}* emitted ({locs}) without a HELP "
                f"text in metrics.HELP_TEXTS"
            )
    reg = PKG / "standing" / "registry.py"
    demote: set[str] = set()
    if reg.exists():
        for node in ast.walk(ast.parse(reg.read_text())):
            if (isinstance(node, ast.Assign) and node.targets
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "DEMOTE_REASONS"):
                for c in ast.walk(node.value):
                    if isinstance(c, ast.Constant) and isinstance(c.value, str):
                        demote.add(c.value)
        if "standing_nondecomposable" not in demote:
            out.append(
                "standing/registry.DEMOTE_REASONS must include "
                "'standing_nondecomposable' (the shared fused-fallback "
                "taxonomy entry)"
            )
    return out


def rollup_violations() -> list[str]:
    """Rollup-tier taxonomy lint (downsample/rollup.py): (a) every
    ``filodb_rollup_*`` family emitted in code carries a HELP text in
    metrics.HELP_TEXTS, and (b) the canonical maintenance-event set
    (metrics.ROLLUP_EVENTS — the ``filodb_rollup_maintenance{event}``
    label taxonomy) matches every literal event the code records via
    ``record_rollup_event("...")`` — an unrecognized literal would be
    minted as event="unknown", a canonical-but-unrecorded one is a dead
    dashboard row. The ``rollup_ineligible`` fused-fallback reason is
    covered by the shared three-way fused_reason lint above."""
    out: list[str] = []
    helped: set[str] = set()
    canon: set[str] = set()
    tree = ast.parse((PKG / "metrics.py").read_text())
    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.Assign) and node.targets:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        if target is None or not isinstance(target, ast.Name):
            continue
        if (target.id == "HELP_TEXTS" and node.value is not None
                and isinstance(node.value, ast.Dict)):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    helped.add(k.value)
        elif target.id == "ROLLUP_EVENTS" and node.value is not None:
            for c in ast.walk(node.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    canon.add(c.value)
    if not canon:
        return ["rollup lint: ROLLUP_EVENTS not found in "
                "filodb_tpu/metrics.py"]
    code, where = code_stems()
    for s in sorted(code):
        if s.startswith("filodb_rollup") and s not in helped:
            locs = ", ".join(where.get(s, [])[:2])
            out.append(
                f"rollup family {s}* emitted ({locs}) without a HELP "
                f"text in metrics.HELP_TEXTS"
            )
    recorded: set[str] = set()
    for path in sorted(PKG.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        for node in ast.walk(ast.parse(path.read_text())):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = getattr(fn, "attr", None) or getattr(fn, "id", None)
            if name == "record_rollup_event" and node.args:
                a = node.args[0]
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    recorded.add(a.value)
    for r in sorted(recorded - canon):
        out.append(
            f"rollup maintenance event {r!r} recorded in code but missing "
            f"from metrics.ROLLUP_EVENTS (it would be minted as "
            f"event=\"unknown\")"
        )
    for r in sorted(canon - recorded):
        out.append(
            f"rollup maintenance event {r!r} is canonical but no code "
            f"records it — dead dashboard row"
        )
    return out


def alerting_violations() -> list[str]:
    """Alert-taxonomy lint (obs/alerting.py + obs/notify.py): (a) every
    ``filodb_alert*`` family emitted in code carries a HELP text in
    metrics.HELP_TEXTS; (b) the canonical state set (alerting.ALERT_STATES
    — the ``alertstate`` label taxonomy on ``filodb_alerts`` and the
    ``ALERTS`` write-back series) matches the doc's "canonical
    ``alertstate`` values" line in doc/observability.md, and every literal
    ``alertstate`` value in the package is a member — an off-taxonomy
    literal would mint a state no dashboard row matches."""
    out: list[str] = []
    helped: set[str] = set()
    tree = ast.parse((PKG / "metrics.py").read_text())
    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.Assign) and node.targets:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        if (target is not None and isinstance(target, ast.Name)
                and target.id == "HELP_TEXTS" and node.value is not None
                and isinstance(node.value, ast.Dict)):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    helped.add(k.value)
    code, where = code_stems()
    for s in sorted(code):
        if s.startswith("filodb_alert") and s not in helped:
            locs = ", ".join(where.get(s, [])[:2])
            out.append(
                f"alerting family {s}* emitted ({locs}) without a HELP "
                f"text in metrics.HELP_TEXTS"
            )
    # canonical state set, read from the AST (no imports — runs without jax)
    canon: set[str] = set()
    alerting = PKG / "obs" / "alerting.py"
    for node in ast.walk(ast.parse(alerting.read_text())):
        if (isinstance(node, ast.Assign) and node.targets
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "ALERT_STATES"):
            for c in ast.walk(node.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    canon.add(c.value)
    if not canon:
        return out + ["alerting lint: ALERT_STATES not found in "
                      "filodb_tpu/obs/alerting.py"]
    # the doc's canonical-states line must agree (the operator contract)
    m = re.search(r"canonical `alertstate` values:([^\n]*)", DOC.read_text())
    documented = set(re.findall(r"`([a-z_]+)`", m.group(1))) if m else set()
    if not m:
        out.append(
            "doc/observability.md is missing the 'canonical `alertstate` "
            "values:' line the alerting lint checks"
        )
    else:
        for s in sorted(canon - documented):
            out.append(
                f"alertstate {s!r} is canonical but missing from "
                f"doc/observability.md's canonical-values line"
            )
        for s in sorted(documented - canon):
            out.append(
                f"doc/observability.md documents alertstate {s!r} that is "
                f"not in alerting.ALERT_STATES"
            )
    # every literal alertstate value in the package is canonical
    for path in sorted(PKG.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        for node in ast.walk(ast.parse(path.read_text())):
            vals: list[tuple[str, int]] = []
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if (kw.arg == "alertstate"
                            and isinstance(kw.value, ast.Constant)
                            and isinstance(kw.value.value, str)):
                        vals.append((kw.value.value, node.lineno))
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if (isinstance(k, ast.Constant)
                            and k.value == "alertstate"
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)):
                        vals.append((v.value, node.lineno))
            for v, lineno in vals:
                if v not in canon:
                    out.append(
                        f"literal alertstate {v!r} "
                        f"({path.relative_to(ROOT)}:{lineno}) is not in "
                        f"alerting.ALERT_STATES"
                    )
    return out


OPS = PKG / "ops"


def _is_jit_decorator(d: ast.expr) -> bool:
    """True for ``@jax.jit``, ``@jax.jit(...)``, ``@pjit(...)`` and
    ``@functools.partial(jax.jit, ...)`` decorator shapes."""
    if isinstance(d, ast.Attribute) and d.attr in ("jit", "pjit"):
        return True
    if isinstance(d, ast.Name) and d.id == "pjit":
        return True
    if isinstance(d, ast.Call):
        if _is_jit_decorator(d.func):
            return True
        return any(_is_jit_decorator(a) for a in d.args)
    return False


def jit_registration_violations() -> list[str]:
    """Executable-registry coverage lint (obs/kernels.py): every jit
    wrapper defined in ``ops/`` — decorated defs AND ``x = jax.jit(...)``
    assignments — must be registered with the kernel observatory via a
    ``KERNELS.register_jits(...)`` call in the same module (kwarg name ==
    wrapper name). A kernel added without registration would dispatch
    outside the observatory: its compiles and device costs would be
    invisible to /debug/kernels, the recompile-storm detector and the
    attestation artifact."""
    out: list[str] = []
    for path in sorted(OPS.glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        jits: dict[str, int] = {}
        registered: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef) and any(
                _is_jit_decorator(d) for d in node.decorator_list
            ):
                jits[node.name] = node.lineno
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ) and _is_jit_decorator(node.value.func):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        jits[t.id] = node.lineno
            elif (isinstance(node, ast.Call)
                  and getattr(node.func, "attr", None) == "register_jits"):
                for kw in node.keywords:
                    if kw.arg:
                        registered.add(kw.arg)
                for a in node.args:
                    if isinstance(a, ast.Constant) and isinstance(a.value, str):
                        registered.add(a.value)
        for name, lineno in sorted(jits.items()):
            if name not in registered:
                out.append(
                    f"jit wrapper {name!r} "
                    f"({path.relative_to(ROOT)}:{lineno}) is not registered "
                    f"with the executable registry — add it to the module's "
                    f"KERNELS.register_jits(...) call (obs/kernels.py)"
                )
    return out


def main() -> int:
    code, where = code_stems()
    doc = doc_stems()
    violations: list[str] = list(fused_reason_violations())
    violations.extend(standing_violations())
    violations.extend(rollup_violations())
    violations.extend(alerting_violations())
    violations.extend(jit_registration_violations())
    for s in sorted(code - doc):
        locs = ", ".join(where.get(s, [])[:2])
        violations.append(
            f"emitted but undocumented: {s}* ({locs}) — add it to "
            f"doc/observability.md's metrics reference"
        )
    for s in sorted(doc - code):
        violations.append(
            f"documented but not emitted: {s}* — doc/observability.md names "
            f"a family no code registers"
        )
    if violations:
        print(f"metrics-doc lint: {len(violations)} violation(s)")
        for v in violations:
            print(f"  {v}")
        return 1
    print(f"metrics-doc lint: OK — {len(code)} metric families, code and "
          f"doc agree")
    return 0


if __name__ == "__main__":
    sys.exit(main())
