#!/usr/bin/env python
"""One-command hardware attestation (``make attest``).

The ROADMAP's real-TPU attestation item: every BENCH_r*.json so far is
CPU-only, so all scaling/amortization claims lack hardware counterparts —
and a bare latency number is only trustworthy if the run can PROVE what
actually compiled, dispatched, and fell back. This command runs the
bench-smoke floor workloads + the MULTICHIP dryrun and emits ONE signed-off
``ATTEST_<backend>.json`` bundling:

- **platform inventory** — python/jax versions, device list (platform +
  kind), host facts — probed in a short-timeout child (the image's TPU
  plugin can wedge on backend init; the artifact must record that honestly
  rather than hang).
- **floor verdicts** — every benchmarks/bench_smoke_floor.json entry run
  through the same gate ``make bench-smoke`` applies (match-vs-oracle +
  floor), with the measurement embedded.
- **kernel-observatory snapshots** — each workload's per-executable
  registry (obs/kernels.py) captured via FILODB_KERNEL_SNAPSHOT: which
  fused executables compiled and dispatched, device p50/p99, which
  fallbacks fired, recompile storms. The PROOF half: "the fused path
  served this number" instead of "a number appeared".
- **MULTICHIP dryrun** — the sharded canonical query + hist_quantile
  executed end-to-end with the one-dispatch-across-the-mesh assertions
  (__graft_entry__.dryrun_multichip), with its own kernel snapshot.
- **verdict + digest** — pass/fail over all of the above and a sha256
  content digest (the sign-off: any later edit breaks it).

Runnable today on the CPU backend and unchanged on hardware: the bench
workers label their backend honestly (a wedged TPU plugin degrades to an
attested CPU artifact, never a silent lie).

Usage:
    python tools/attest.py                    # full run, ATTEST_<backend>.json
    python tools/attest.py --smoke            # fast machinery check (make bench-smoke)
    python tools/attest.py --only sum_rate_100k_series_range_query_p50
    python tools/attest.py --no-multichip --floor-file my_floors.json
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, HERE)

import bench_smoke  # noqa: E402 — sibling tool, shares the floor gate

ATTEST_VERSION = 1

# the artifact contract (doc/observability.md "Kernel & compile
# observatory" documents it; tests/test_kernel_obs.py validates against
# THIS table — one definition)
SCHEMA: dict[str, type] = {
    "version": int,
    "time": str,
    "backend": str,
    "platform": dict,
    "floors": list,
    "multichip": dict,
    "kernels": dict,
    "verdict": str,
    "digest": str,
}
FLOOR_FIELDS = ("metric", "ok", "verdict")


def validate_attestation(doc: dict) -> list[str]:
    """Schema check for an attestation artifact; returns violations."""
    out = []
    for field, typ in SCHEMA.items():
        if field not in doc:
            out.append(f"missing field {field!r}")
        elif not isinstance(doc[field], typ):
            out.append(
                f"field {field!r} is {type(doc[field]).__name__}, "
                f"want {typ.__name__}"
            )
    for i, fl in enumerate(doc.get("floors") or []):
        for f in FLOOR_FIELDS:
            if f not in fl:
                out.append(f"floors[{i}] missing {f!r}")
    if doc.get("verdict") not in ("pass", "fail"):
        out.append(f"verdict must be pass|fail, got {doc.get('verdict')!r}")
    body = {k: v for k, v in doc.items() if k != "digest"}
    want = hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()
    ).hexdigest()
    if doc.get("digest") != want:
        out.append("digest does not match content")
    return out


def probe_accelerator(timeout_s: int = 60) -> bool:
    """Can a real accelerator backend initialize AND run a matmul? The
    bench watchdog's probe (short-lived child, hard timeout — the image's
    TPU plugin can wedge forever on backend init). A bad verdict pins the
    run to CPU so the artifact degrades to an honest CPU attestation
    instead of hanging."""
    sys.path.insert(0, REPO)
    try:
        import bench

        return bench._probe_tpu_uncached(timeout_s)
    finally:
        sys.path.remove(REPO)


def platform_inventory(cpu: bool, timeout_s: int = 90) -> dict:
    """Device/platform facts from a short-timeout child — the artifact's
    inventory must be probed where a wedged accelerator plugin can only
    cost a timeout, never hang the attestation. ``cpu=False`` (healthy
    accelerator probe) leaves the platform to jax's auto-detection so the
    inventory lists the REAL devices the floors ran on."""
    code = (
        "import json, os, platform, sys\n"
        "import jax\n"
        "print(json.dumps({\n"
        "  'python': sys.version.split()[0],\n"
        "  'jax': jax.__version__,\n"
        "  'platform': platform.platform(),\n"
        "  'hostname': platform.node(),\n"
        "  'cpu_count': os.cpu_count(),\n"
        "  'devices': [{'platform': d.platform, 'kind': d.device_kind,\n"
        "               'id': d.id} for d in jax.devices()],\n"
        "}))\n"
    )
    env = dict(os.environ)
    if cpu:
        env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], timeout=timeout_s,
            capture_output=True, text=True, env=env,
        )
        if proc.returncode == 0:
            return json.loads(proc.stdout.strip().splitlines()[-1])
        return {"error": f"probe rc={proc.returncode}: {proc.stderr[-400:]}"}
    except subprocess.TimeoutExpired:
        return {"error": f"platform probe timed out after {timeout_s}s "
                         "(wedged accelerator plugin)"}


def _read_snapshot(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def run_floors(entries: list[dict],
               cpu: bool = True) -> tuple[list[dict], dict]:
    """Run every floor entry with a kernel-snapshot capture; returns the
    floor verdicts (measurement + per-workload observatory totals embedded)
    and the aggregate kernel proof."""
    floors = []
    agg = {"dispatches": 0, "compiles": 0, "fused_families": set(),
           "fallbacks": {}, "storms": {}}
    for entry in entries:
        with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
            snap_path = tf.name
        try:
            ok, verdict, got = bench_smoke.run_entry(
                entry, extra_env={"FILODB_KERNEL_SNAPSHOT": snap_path},
                cpu=cpu,
            )
            snap = _read_snapshot(snap_path)
        finally:
            try:
                os.unlink(snap_path)
            except OSError:
                pass
        fl = {"metric": entry["metric"], "ok": bool(ok), "verdict": verdict,
              "measurement": got}
        if snap is not None:
            fl["kernels"] = {
                "totals": snap.get("totals"),
                "storms": (snap.get("kernels") or {}).get("storms", {}),
                "counters": snap.get("counters", {}),
            }
            tot = snap.get("totals") or {}
            agg["dispatches"] += int(tot.get("dispatches", 0))
            agg["compiles"] += int(tot.get("compiles", 0))
            agg["fused_families"].update(tot.get("fused_families", []))
            for k, v in (snap.get("counters") or {}).items():
                if k.startswith("filodb_fused_fallback"):
                    agg["fallbacks"][k] = agg["fallbacks"].get(k, 0) + v
            agg["storms"].update(
                (snap.get("kernels") or {}).get("storms", {})
            )
        floors.append(fl)
        print(f"attest: {verdict}", flush=True)
    agg["fused_families"] = sorted(agg["fused_families"])
    return floors, agg


def run_multichip(n_devices: int, timeout_s: int = 600) -> dict:
    """The MULTICHIP dryrun in a child, with its own kernel snapshot: the
    sharded canonical query + hist_quantile end-to-end, ONE dispatch each
    across the mesh (the dryrun asserts it; we record the proof)."""
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        snap_path = tf.name
    code = (
        "import json, __graft_entry__ as g\n"
        f"g.dryrun_multichip({n_devices})\n"
        "from filodb_tpu.obs.kernels import KERNELS\n"
        f"json.dump({{'totals': KERNELS.totals(),"
        f" 'storms': KERNELS.snapshot()['storms']}},"
        f" open({snap_path!r}, 'w'))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], timeout=timeout_s,
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        snap = _read_snapshot(snap_path)
        out = {
            "ok": proc.returncode == 0,
            "devices": n_devices,
            "virtual_cpu": True,  # the dryrun forces a virtual CPU mesh
            "output": proc.stdout.strip()[-1500:],
        }
        if proc.returncode != 0:
            out["error"] = proc.stderr[-1500:]
        if snap is not None:
            out["kernels"] = snap
        return out
    except subprocess.TimeoutExpired:
        return {"ok": False, "devices": n_devices,
                "error": f"dryrun timed out after {timeout_s}s"}
    finally:
        try:
            os.unlink(snap_path)
        except OSError:
            pass


# the --smoke machinery check: one tiny canonical-query workload — proves
# the bench->snapshot->verdict->digest pipeline end to end in seconds
# without gating on a real floor (the real gate already ran in bench-smoke)
SMOKE_ENTRY = {
    "metric": "sum_rate_100k_series_range_query_p50",
    "series": 256,
    "runs": 1,
    "p50_ms_floor": 1e9,
    "env": {},
}


def build_artifact(floors: list[dict], agg: dict, multichip: dict,
                   platform: dict, backend: str) -> dict:
    floors_ok = bool(floors) and all(f["ok"] for f in floors)
    mc_ok = multichip.get("ok", False) if multichip.get("ran", True) else True
    fused_served = bool(agg.get("fused_families"))
    doc = {
        "version": ATTEST_VERSION,
        "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "backend": backend,
        "platform": platform,
        "floors": floors,
        "multichip": multichip,
        "kernels": {
            "proof": {
                "dispatches": agg.get("dispatches", 0),
                "compiles": agg.get("compiles", 0),
                "fused_families_dispatched": agg.get("fused_families", []),
                "fused_path_served": fused_served,
            },
            "fallbacks": agg.get("fallbacks", {}),
            "storms": agg.get("storms", {}),
        },
        "verdict": ("pass" if floors_ok and mc_ok and fused_served
                    else "fail"),
    }
    doc["digest"] = hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()
    ).hexdigest()
    return doc


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None,
                    help="artifact path (default ATTEST_<backend>.json)")
    ap.add_argument("--floor-file", default=bench_smoke.FLOOR_FILE)
    ap.add_argument("--only", default=None,
                    help="comma-separated floor metrics to run")
    ap.add_argument("--no-multichip", action="store_true")
    ap.add_argument("--multichip-devices", type=int, default=8)
    ap.add_argument("--backend", choices=("auto", "cpu"), default="auto",
                    help="auto (default): probe the accelerator in a "
                         "hard-timeout child and run the floors on it when "
                         "healthy — a wedged plugin degrades to an honest "
                         "CPU attestation; cpu: pin the CPU backend")
    ap.add_argument("--smoke", action="store_true",
                    help="fast machinery check (one tiny workload, temp "
                         "artifact unless --out)")
    args = ap.parse_args(argv)

    if args.smoke:
        entries = [dict(SMOKE_ENTRY)]
    else:
        with open(args.floor_file) as f:
            floor = json.load(f)
        entries = floor["entries"] if "entries" in floor else [floor]
        if args.only:
            keep = {m.strip() for m in args.only.split(",")}
            entries = [e for e in entries if e["metric"] in keep]
            if not entries:
                print(f"attest: no floor entries match --only {args.only}")
                return 1

    cpu = True
    if args.backend == "auto" and not args.smoke:
        cpu = not probe_accelerator()
        print(f"attest: accelerator probe -> "
              f"{'CPU fallback' if cpu else 'hardware backend'}", flush=True)
    platform = platform_inventory(cpu=cpu)
    floors, agg = run_floors(entries, cpu=cpu)
    backend = next(
        (f["measurement"].get("backend") for f in floors
         if f.get("measurement") and f["measurement"].get("backend")),
        "cpu",
    )
    if args.no_multichip or args.smoke:
        multichip = {"ran": False, "ok": True,
                     "note": "skipped (--no-multichip/--smoke)"}
    else:
        multichip = {"ran": True, **run_multichip(args.multichip_devices)}

    doc = build_artifact(floors, agg, multichip, platform, backend)
    bad = validate_attestation(doc)
    if bad:
        print("attest: INTERNAL schema violations: " + "; ".join(bad))
        return 1

    if args.out:
        out_path = args.out
    elif args.smoke:
        out_path = os.path.join(tempfile.gettempdir(),
                                f"ATTEST_{backend}_smoke.json")
    else:
        out_path = os.path.join(REPO, f"ATTEST_{backend}.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    n_ok = sum(1 for fl in floors if fl["ok"])
    print(
        f"attest: {doc['verdict'].upper()} — {n_ok}/{len(floors)} floors ok, "
        f"fused families {doc['kernels']['proof']['fused_families_dispatched']}"
        f", multichip "
        f"{'ok' if multichip.get('ok') else multichip.get('note', 'FAIL')}, "
        f"digest {doc['digest'][:12]}… -> {out_path}"
    )
    return 0 if doc["verdict"] == "pass" else 1


if __name__ == "__main__":
    sys.exit(main())
