#!/usr/bin/env python
"""CPU bench smoke gate (make bench-smoke): a 2k-series, 3-run bench.py
worker on the CPU backend must not regress p50 by more than 25% against the
checked-in floor (benchmarks/bench_smoke_floor.json), and must keep
match=True against the numpy oracle.

This is the perf analog of the golden plan tests: small enough to run in CI
(~10 s total), big enough that losing the fused single-dispatch path, the
superblock cache, or the staging cache shows up as a multiple, not a blip.
Update the floor deliberately — in the same PR as a justified perf change —
never to paper over a regression.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FLOOR_FILE = os.path.join(REPO, "benchmarks", "bench_smoke_floor.json")
REGRESSION_TOLERANCE = 0.25  # fail beyond floor * (1 + this)


def main() -> int:
    with open(FLOOR_FILE) as f:
        floor = json.load(f)
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        FILODB_BENCH_SERIES=str(floor["series"]),
        FILODB_BENCH_RUNS=str(floor["runs"]),
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--worker", "--cpu"],
        env=env, capture_output=True, text=True, cwd=REPO, timeout=600,
    )
    sys.stderr.write(proc.stderr[-2000:])
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    if proc.returncode != 0 or not lines:
        print(f"bench-smoke: worker failed rc={proc.returncode}")
        return 1
    got = json.loads(lines[-1])
    p50 = float(got["value"])
    limit = float(floor["p50_ms_floor"]) * (1.0 + REGRESSION_TOLERANCE)
    verdict = []
    ok = True
    if not got.get("match", False):
        verdict.append("FAIL: result does not match the numpy oracle")
        ok = False
    if p50 <= 0:
        verdict.append("FAIL: no measurement")
        ok = False
    elif p50 > limit:
        verdict.append(
            f"FAIL: p50 {p50:.2f}ms regresses >25% vs floor "
            f"{floor['p50_ms_floor']}ms (limit {limit:.2f}ms)"
        )
        ok = False
    else:
        verdict.append(
            f"OK: p50 {p50:.2f}ms within limit {limit:.2f}ms "
            f"(floor {floor['p50_ms_floor']}ms, phases {got.get('phases_ms')})"
        )
    print("bench-smoke: " + "; ".join(verdict))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
