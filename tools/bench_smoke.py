#!/usr/bin/env python
"""CPU bench smoke gate (make bench-smoke): small bench.py workers on the
CPU backend must not regress p50 by more than 25% against the checked-in
floors (benchmarks/bench_smoke_floor.json), and must keep match=True
against the numpy oracles. One floor entry per workload — the north-star
``sum(rate(...))`` and the fused histogram/epilogue pipeline's
``histogram_quantile(0.99, sum by (le) (rate(..._bucket[5m])))``.

This is the perf analog of the golden plan tests: small enough to run in CI
(~30 s total), big enough that losing the fused single-dispatch path, the
shared-window hist kernel, the superblock cache, or the staging cache shows
up as a multiple, not a blip. Update a floor deliberately — in the same PR
as a justified perf change — never to paper over a regression.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FLOOR_FILE = os.path.join(REPO, "benchmarks", "bench_smoke_floor.json")
REGRESSION_TOLERANCE = 0.25  # fail beyond floor * (1 + this)


def run_entry(entry: dict, extra_env: dict | None = None,
              cpu: bool = True) -> tuple[bool, str, dict | None]:
    """Run one floor entry's bench worker. Returns ``(ok, verdict,
    measurement)`` — the parsed worker JSON rides along so callers beyond
    the smoke gate (tools/attest.py embeds floor verdicts + measurements
    into the attestation artifact) don't re-run the workload.

    ``cpu=False`` (the attestation harness after a healthy accelerator
    probe) leaves the platform to jax's auto-detection so the worker runs
    — and honestly labels — the real backend; the smoke gate itself always
    pins cpu (its floors are CPU numbers)."""
    env = dict(
        os.environ,
        FILODB_BENCH_SERIES=str(entry["series"]),
        FILODB_BENCH_RUNS=str(entry["runs"]),
        **{k: str(v) for k, v in (entry.get("env") or {}).items()},
        **{k: str(v) for k, v in (extra_env or {}).items()},
    )
    if cpu:
        env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--worker"]
        + (["--cpu"] if cpu else []),
        env=env, capture_output=True, text=True, cwd=REPO, timeout=600,
    )
    sys.stderr.write(proc.stderr[-2000:])
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    name = entry["metric"]
    if proc.returncode != 0 or not lines:
        return False, f"{name}: worker failed rc={proc.returncode}", None
    got = json.loads(lines[-1])
    if got.get("metric") != name:
        return False, (
            f"{name}: FAIL worker emitted metric {got.get('metric')!r} — "
            "floor entry and bench.py METRIC out of sync"
        ), got
    value = float(got["value"])
    if not got.get("match", False):
        return False, f"{name}: FAIL result does not match the numpy oracle", got
    if value <= 0:
        return False, f"{name}: FAIL no measurement", got
    if "qps_floor_min" in entry:
        # HIGHER is better (throughput workloads): fail when the measured
        # value drops >25% below the checked-in floor
        floor = float(entry["qps_floor_min"])
        limit = floor * (1.0 - REGRESSION_TOLERANCE)
        if value < limit:
            return False, (
                f"{name}: FAIL {value:.1f} qps regresses >25% vs floor "
                f"{floor} qps (limit {limit:.1f} qps)"
            ), got
        return True, (
            f"{name}: OK {value:.1f} qps above limit {limit:.1f} qps "
            f"(floor {floor} qps, phases {got.get('phases_ms')})"
        ), got
    limit = float(entry["p50_ms_floor"]) * (1.0 + REGRESSION_TOLERANCE)
    if value > limit:
        return False, (
            f"{name}: FAIL p50 {value:.2f}ms regresses >25% vs floor "
            f"{entry['p50_ms_floor']}ms (limit {limit:.2f}ms)"
        ), got
    return True, (
        f"{name}: OK p50 {value:.2f}ms within limit {limit:.2f}ms "
        f"(floor {entry['p50_ms_floor']}ms, phases {got.get('phases_ms')})"
    ), got


def main() -> int:
    with open(FLOOR_FILE) as f:
        floor = json.load(f)
    entries = floor["entries"] if "entries" in floor else [floor]
    ok = True
    verdicts = []
    for entry in entries:
        good, verdict, _got = run_entry(entry)
        ok = ok and good
        verdicts.append(verdict)
    print("bench-smoke: " + "; ".join(verdicts))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
