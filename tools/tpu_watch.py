"""TPU tunnel watchdog: harvest an attested on-TPU benchmark number the
moment ANY healthy tunnel window appears during the round.

The TPU plugin in this environment wedges for hours at a time and can
recover without warning; the end-of-round bench alone has missed every
healthy window for three rounds running. This watchdog runs for the whole
working session:

- every ``PROBE_EVERY_S`` seconds, probe the accelerator in a short-timeout
  child process (backend init + a small matmul — the plugin wedges on init,
  so the probe must never run in the watchdog process itself);
- log every attempt with a timestamp to ``TPU_WATCH_LOG.txt`` (an empty
  round's log is the proof that zero healthy windows existed);
- on the first healthy probe, immediately run the quick-mode bench
  (25k series, few timed runs, persistent jit cache = minimal tunnel
  exposure), then escalate to the full 100k-series north-star workload;
- append every successful measurement as timestamped JSON to
  ``BENCH_TPU_ATTESTED.json`` and git-commit that artifact right away, so
  a later wedge (or the end of the round) cannot lose it.

Run via ``make tpu-watch`` (foreground) or ``make tpu-watch-bg``.
Workload contract: reference QueryInMemoryBenchmark.scala:121-125 scaled to
the driver's 100k-series target (BASELINE.md north star).
"""

from __future__ import annotations

import fcntl
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")
LOG = os.path.join(REPO, "TPU_WATCH_LOG.txt")
OUT = os.path.join(REPO, "BENCH_TPU_ATTESTED.json")
LOCKFILE = os.path.join(REPO, ".tpu_watch.lock")

_lock_fh = None  # module-global: the flock lives as long as the process


def acquire_singleton_lock() -> bool:
    """Exactly ONE watchdog instance may append to TPU_WATCH_LOG.txt: two
    interleaved probe streams double-count probes and misstate the cycle
    (round-5 advisor finding). flock on a pidfile — held for the process
    lifetime, vanishes with the process (no stale-pidfile handling
    needed)."""
    global _lock_fh
    fh = open(LOCKFILE, "a+")
    try:
        fcntl.flock(fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        fh.seek(0)
        holder = fh.read().strip() or "unknown pid"
        fh.close()
        print(f"tpu-watch already running ({holder}); refusing to "
              f"double-append to {os.path.basename(LOG)}", flush=True)
        return False
    fh.truncate(0)
    fh.write(f"{os.getpid()}\n")
    fh.flush()
    _lock_fh = fh  # keep the fd (and with it the lock) alive
    return True

PROBE_EVERY_S = int(os.environ.get("TPU_WATCH_PROBE_EVERY_S", 120))
PROBE_TIMEOUT_S = int(os.environ.get("TPU_WATCH_PROBE_TIMEOUT_S", 30))
DEADLINE_S = float(os.environ.get("TPU_WATCH_DEADLINE_S", 11.0 * 3600))
QUICK_SERIES = int(os.environ.get("TPU_WATCH_QUICK_SERIES", 25_000))
FULL_SERIES = int(os.environ.get("TPU_WATCH_FULL_SERIES", 100_000))

_PROBE_CODE = (
    "import jax, jax.numpy as jnp\n"
    "d = jax.devices()\n"
    "assert d and d[0].platform != 'cpu', d\n"
    "x = jnp.ones((256, 256), jnp.bfloat16)\n"
    "(x @ x).block_until_ready()\n"
    "print('TPU_OK', d[0].platform, d[0].device_kind)\n"
)


def log(msg: str) -> None:
    line = f"{time.strftime('%Y-%m-%dT%H:%M:%S%z')} {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def register_probe_gauges() -> bool:
    """Publish probe results as ``filodb_tpu_*`` gauges in the shared
    Registry (filodb_tpu.telemetry parses the watch log at scrape time —
    the same collector a FiloServer wires via config telemetry.tpu_watch_log,
    so probe health rides /metrics and the _system self-scrape instead of
    living only in TPU_WATCH_LOG.txt). Best-effort: the watchdog must keep
    probing even when the package can't import (e.g. torn venv)."""
    try:
        sys.path.insert(0, REPO)
        from filodb_tpu.telemetry import register_tpu_watch_collector

        register_tpu_watch_collector(LOG)
        return True
    except Exception as e:  # noqa: BLE001 — observability must not stop probing
        print(f"tpu-watch: probe gauges unavailable: {e}", flush=True)
        return False


def probe() -> bool:
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE], timeout=PROBE_TIMEOUT_S,
            capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        log(f"probe TIMEOUT after {PROBE_TIMEOUT_S}s (wedged plugin)")
        return False
    if proc.returncode == 0 and "TPU_OK" in proc.stdout:
        log(f"probe OK: {proc.stdout.strip()}")
        return True
    log(f"probe FAIL rc={proc.returncode}: {proc.stderr.strip()[-300:]}")
    return False


def run_bench(series: int, runs: int, timeout_s: int) -> dict | None:
    """One bench.py --worker child on the real backend; returns its JSON."""
    env = dict(
        os.environ,
        FILODB_BENCH_SERIES=str(series),
        FILODB_BENCH_RUNS=str(runs),
        FILODB_BENCH_WORKER_DEADLINE=str(time.time() + timeout_s - 20),
        JAX_COMPILATION_CACHE_DIR=os.path.join(REPO, ".jax_cache"),
    )
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, BENCH, "--worker"], timeout=timeout_s,
            capture_output=True, text=True, cwd=REPO, env=env,
        )
    except subprocess.TimeoutExpired:
        log(f"bench series={series} TIMEOUT after {timeout_s}s")
        return None
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    tail = proc.stderr.strip().splitlines()[-3:]
    log(f"bench series={series} rc={proc.returncode} {time.time()-t0:.0f}s "
        + " | ".join(tail))
    if proc.returncode == 0 and lines:
        try:
            return json.loads(lines[-1])
        except ValueError:
            return None
    return None


def attest(parsed: dict, kind: str) -> None:
    """Append a measurement to BENCH_TPU_ATTESTED.json and commit it."""
    entries = []
    if os.path.exists(OUT):
        try:
            with open(OUT) as f:
                entries = json.load(f)["measurements"]
        except (ValueError, KeyError):
            entries = []
    entries.append(dict(parsed, kind=kind,
                        attested_at=time.strftime("%Y-%m-%dT%H:%M:%S%z")))
    with open(OUT, "w") as f:
        json.dump({"measurements": entries}, f, indent=1)
        f.write("\n")
    log(f"ATTESTED {kind}: {json.dumps(parsed)}")
    # stage then commit only these two artifacts (OUT starts untracked, so a
    # pathspec-limited commit without add would abort), retrying around
    # index.lock races with the interactive session
    for attempt in range(5):
        a = subprocess.run(
            ["git", "add", "--", os.path.basename(OUT), os.path.basename(LOG)],
            cwd=REPO, capture_output=True, text=True,
        )
        if a.returncode != 0:
            if "index.lock" in a.stderr:
                time.sleep(3 * (attempt + 1))
                continue
            log(f"add failed (non-lock): {a.stderr.strip()[-200:]}")
            return
        r = subprocess.run(
            ["git", "commit", "-m", f"tpu-watch: attested {kind} TPU measurement",
             "--", os.path.basename(OUT), os.path.basename(LOG)],
            cwd=REPO, capture_output=True, text=True,
        )
        if r.returncode == 0:
            log("committed attested artifact")
            return
        if "index.lock" not in r.stderr:
            log(f"commit failed (non-lock): {r.stderr.strip()[-200:]}")
            return
        time.sleep(3 * (attempt + 1))
    log("commit failed: persistent index.lock")


def main() -> None:
    if not acquire_singleton_lock():
        sys.exit(1)
    register_probe_gauges()
    deadline = time.time() + DEADLINE_S
    log(f"watchdog start: probe every {PROBE_EVERY_S}s, timeout {PROBE_TIMEOUT_S}s, "
        f"deadline in {DEADLINE_S/3600:.1f}h")
    have_quick = have_full = False
    n_probes = n_ok = 0
    while time.time() < deadline and not have_full:
        cycle_t0 = time.monotonic()
        n_probes += 1
        if probe():
            n_ok += 1
            if not have_quick:
                got = run_bench(QUICK_SERIES, runs=5, timeout_s=420)
                if got and got.get("backend") != "cpu":
                    attest(got, "quick")
                    have_quick = True
                else:
                    continue  # window closed mid-bench: back to probing
            if have_quick and not have_full:
                got = run_bench(FULL_SERIES, runs=15, timeout_s=1500)
                if got and got.get("backend") != "cpu":
                    attest(got, "full")
                    have_full = True
                    break
        # true-cycle pacing: sleep the REMAINDER of the probe period, so the
        # logged cadence is PROBE_EVERY_S, not PROBE_EVERY_S + probe/bench
        # duration (the round-5 advisor caught the log drifting to 150 s)
        time.sleep(max(0.0, PROBE_EVERY_S - (time.monotonic() - cycle_t0)))
    log(f"watchdog done: {n_probes} probes, {n_ok} healthy, "
        f"quick={have_quick} full={have_full}")


if __name__ == "__main__":
    main()
