#!/usr/bin/env python
"""Lint: every ExecPlan subclass must execute under a tracing span.

The tracing contract (doc/observability.md) is that ``ExecPlan.execute`` is
the ONE place spans wrap plan-node execution — subclasses implement
``do_execute`` and inherit the instrumented template method. A subclass that
overrides ``execute`` without opening a span silently drops its subtree out
of every trace, EXPLAIN ANALYZE rendering, and the slow-query log.

This check walks the package AST (no imports — runs without jax):

- collects every class transitively subclassing ``ExecPlan``;
- flags any that define ``execute`` unless that override visibly opens a
  span (calls ``span(``) or delegates to ``super().execute``;
- asserts the base ``ExecPlan.execute`` itself opens a span.

Exit code 0 = clean, 1 = violations (printed one per line).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

PKG = Path(__file__).resolve().parent.parent / "filodb_tpu"


def base_names(cls: ast.ClassDef) -> list[str]:
    out = []
    for b in cls.bases:
        if isinstance(b, ast.Name):
            out.append(b.id)
        elif isinstance(b, ast.Attribute):
            out.append(b.attr)
    return out


def method(cls: ast.ClassDef, name: str) -> ast.FunctionDef | None:
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def opens_span(fn: ast.FunctionDef) -> bool:
    """True when the method body calls span(...) or super().execute(...)."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id == "span":
            return True
        if isinstance(f, ast.Attribute):
            if f.attr == "span":
                return True
            if (
                f.attr == "execute"
                and isinstance(f.value, ast.Call)
                and isinstance(f.value.func, ast.Name)
                and f.value.func.id == "super"
            ):
                return True
    return False


def main() -> int:
    classes: dict[str, ast.ClassDef] = {}
    files: dict[str, Path] = {}
    for path in sorted(PKG.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            print(f"SYNTAX ERROR {path}: {e}")
            return 1
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                classes[node.name] = node
                files[node.name] = path

    # transitive closure over class names (same-name collisions across
    # modules are acceptable at this granularity — plan classes are unique)
    plan_classes: set[str] = {"ExecPlan"}
    changed = True
    while changed:
        changed = False
        for name, cls in classes.items():
            if name not in plan_classes and plan_classes & set(base_names(cls)):
                plan_classes.add(name)
                changed = True
    plan_classes.discard("ExecPlan")

    violations: list[str] = []
    base = classes.get("ExecPlan")
    if base is None:
        violations.append("ExecPlan base class not found")
    else:
        base_exec = method(base, "execute")
        if base_exec is None or not opens_span(base_exec):
            violations.append(
                f"{files['ExecPlan']}: ExecPlan.execute does not open a span"
            )

    for name in sorted(plan_classes):
        fn = method(classes[name], "execute")
        if fn is not None and not opens_span(fn):
            violations.append(
                f"{files[name]}:{fn.lineno}: {name}.execute overrides the "
                "instrumented template without opening a span"
            )

    if violations:
        print(f"span-coverage lint: {len(violations)} violation(s)")
        for v in violations:
            print(f"  {v}")
        return 1
    print(
        f"span-coverage lint: OK — {len(plan_classes)} ExecPlan subclasses "
        "all execute under a span"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
