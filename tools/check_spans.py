#!/usr/bin/env python
"""Lint: every ExecPlan subclass must execute under a tracing span, and
the query-phase decomposition must stay canonical and complete.

The tracing contract (doc/observability.md) is that ``ExecPlan.execute`` is
the ONE place spans wrap plan-node execution — subclasses implement
``do_execute`` and inherit the instrumented template method. A subclass that
overrides ``execute`` without opening a span silently drops its subtree out
of every trace, EXPLAIN ANALYZE rendering, and the slow-query log.

This check walks the package AST (no imports — runs without jax):

- collects every class transitively subclassing ``ExecPlan``;
- flags any that define ``execute`` unless that override visibly opens a
  span (calls ``span(``) or delegates to ``super().execute``;
- asserts the base ``ExecPlan.execute`` itself opens a span.

Phase-coverage lint (the query observatory, doc/observability.md "Query
observatory" — mirroring check_metrics.py's fused-fallback taxonomy lint):

- every phase literal in the package (``span(..., phase="x")`` kwargs,
  ``rec.phase("x")`` context-manager calls, ``rec.add("x", ...)``) must be
  a member of the canonical ``metrics.QUERY_PHASES`` set — an unknown
  phase name would mint an undashboarded histogram series;
- every QueryEngine execution entry (``_query_range_uncoalesced``,
  ``query_instant``, ``execute_plan``) must capture ``parse_plan`` and
  ``admission`` exactly once;
- every fused dispatch path (``span("fused:dispatch...")`` sites in
  ``FusedAggregateExec.do_execute``) must route through
  ``_dispatch_fused``, which must decompose into ``admission`` (queue
  wait) + ``dispatch``; the stage phase must be captured exactly once.

Exit code 0 = clean, 1 = violations (printed one per line).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

PKG = Path(__file__).resolve().parent.parent / "filodb_tpu"


def base_names(cls: ast.ClassDef) -> list[str]:
    out = []
    for b in cls.bases:
        if isinstance(b, ast.Name):
            out.append(b.id)
        elif isinstance(b, ast.Attribute):
            out.append(b.attr)
    return out


def method(cls: ast.ClassDef, name: str) -> ast.FunctionDef | None:
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def opens_span(fn: ast.FunctionDef) -> bool:
    """True when the method body calls span(...) or super().execute(...)."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id == "span":
            return True
        if isinstance(f, ast.Attribute):
            if f.attr == "span":
                return True
            if (
                f.attr == "execute"
                and isinstance(f.value, ast.Call)
                and isinstance(f.value.func, ast.Name)
                and f.value.func.id == "super"
            ):
                return True
    return False


def _canonical_phases() -> set[str]:
    """metrics.QUERY_PHASES, read from the AST (no imports)."""
    out: set[str] = set()
    tree = ast.parse((PKG / "metrics.py").read_text())
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and node.targets
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "QUERY_PHASES"):
            for c in ast.walk(node.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    out.add(c.value)
    return out


def _phase_literals(tree: ast.AST):
    """(phase-literal, lineno) pairs from one module: ``phase=`` kwargs on
    span() calls, ``<x>.phase("...")`` context-manager calls, and
    ``rec.add("...", ...)`` recorder bumps."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = getattr(f, "attr", None) or getattr(f, "id", None)
        if name == "span":
            for kw in node.keywords:
                if (kw.arg == "phase" and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)):
                    yield kw.value.value, node.lineno
        elif name == "phase" and isinstance(f, ast.Attribute) and node.args:
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                yield a.value, node.lineno
        elif (name == "add" and isinstance(f, ast.Attribute)
              and isinstance(f.value, ast.Name) and f.value.id == "rec"
              and node.args):
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                yield a.value, node.lineno


def _count_in(fn: ast.AST, want: str, kinds=("phase", "add", "span")) -> int:
    n = 0
    for lit, _ in _phase_literals(fn):
        if lit == want:
            n += 1
    return n


def phase_violations(classes: dict[str, ast.ClassDef]) -> list[str]:
    out: list[str] = []
    canon = _canonical_phases()
    if not canon:
        return ["phase lint: QUERY_PHASES not found in filodb_tpu/metrics.py"]
    # (a) canonical-set rejection over the whole package
    for path in sorted(PKG.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        for lit, lineno in _phase_literals(tree):
            if lit not in canon:
                out.append(
                    f"{path}:{lineno}: unknown query phase {lit!r} — not in "
                    f"metrics.QUERY_PHASES {sorted(canon)}"
                )
    # (b) engine entry coverage: parse_plan + admission exactly once each
    planner = ast.parse((PKG / "coordinator" / "planner.py").read_text())
    entries = {"_query_range_uncoalesced", "query_instant", "execute_plan"}
    seen_entries = set()
    for node in ast.walk(planner):
        if isinstance(node, ast.FunctionDef) and node.name in entries:
            seen_entries.add(node.name)
            for want in ("parse_plan", "admission"):
                n = _count_in(node, want)
                if n != 1:
                    out.append(
                        f"QueryEngine.{node.name} captures phase {want!r} "
                        f"{n} times (must be exactly once)"
                    )
    for missing in sorted(entries - seen_entries):
        out.append(f"QueryEngine.{missing} not found for phase lint")
    # (c) fused path: one stage capture; every fused:dispatch span routes
    # through _dispatch_fused; _dispatch_fused splits admission + dispatch
    fused = classes.get("FusedAggregateExec")
    if fused is None:
        out.append("FusedAggregateExec not found for phase lint")
        return out
    do_exec = method(fused, "do_execute")
    disp = method(fused, "_dispatch_fused")
    if do_exec is None or disp is None:
        out.append("FusedAggregateExec.do_execute/_dispatch_fused missing")
        return out
    n_stage = _count_in(do_exec, "stage")
    if n_stage != 1:
        out.append(
            f"FusedAggregateExec.do_execute captures phase 'stage' "
            f"{n_stage} times (must be exactly once)"
        )
    n_spans = n_routed = 0
    for node in ast.walk(do_exec):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = getattr(f, "attr", None) or getattr(f, "id", None)
        if name == "span" and node.args:
            a = node.args[0]
            text = None
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                text = a.value
            elif isinstance(a, ast.JoinedStr) and a.values and isinstance(
                    a.values[0], ast.Constant):
                text = str(a.values[0].value)
            if text and text.startswith("fused:dispatch"):
                n_spans += 1
        elif name == "_dispatch_fused":
            n_routed += 1
    if n_spans != n_routed or n_routed == 0:
        out.append(
            f"FusedAggregateExec.do_execute has {n_spans} fused:dispatch "
            f"spans but {n_routed} _dispatch_fused calls — every dispatch "
            "path must route through the phase-decomposing helper"
        )
    for want in ("admission", "dispatch"):
        if _count_in(disp, want) == 0:
            out.append(
                f"FusedAggregateExec._dispatch_fused never records phase "
                f"{want!r} — the queue-wait/launch decomposition is gone"
            )
    return out


def main() -> int:
    classes: dict[str, ast.ClassDef] = {}
    files: dict[str, Path] = {}
    for path in sorted(PKG.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:
            print(f"SYNTAX ERROR {path}: {e}")
            return 1
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                classes[node.name] = node
                files[node.name] = path

    # transitive closure over class names (same-name collisions across
    # modules are acceptable at this granularity — plan classes are unique)
    plan_classes: set[str] = {"ExecPlan"}
    changed = True
    while changed:
        changed = False
        for name, cls in classes.items():
            if name not in plan_classes and plan_classes & set(base_names(cls)):
                plan_classes.add(name)
                changed = True
    plan_classes.discard("ExecPlan")

    violations: list[str] = []
    base = classes.get("ExecPlan")
    if base is None:
        violations.append("ExecPlan base class not found")
    else:
        base_exec = method(base, "execute")
        if base_exec is None or not opens_span(base_exec):
            violations.append(
                f"{files['ExecPlan']}: ExecPlan.execute does not open a span"
            )

    for name in sorted(plan_classes):
        fn = method(classes[name], "execute")
        if fn is not None and not opens_span(fn):
            violations.append(
                f"{files[name]}:{fn.lineno}: {name}.execute overrides the "
                "instrumented template without opening a span"
            )

    violations.extend(phase_violations(classes))

    if violations:
        print(f"span-coverage lint: {len(violations)} violation(s)")
        for v in violations:
            print(f"  {v}")
        return 1
    print(
        f"span-coverage lint: OK — {len(plan_classes)} ExecPlan subclasses "
        "all execute under a span; query-phase coverage canonical"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
