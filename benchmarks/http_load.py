"""HTTP load benchmark: latency percentiles for query_range against a live
server (reference gatling/ simulations). Run: python -m benchmarks.http_load
[concurrency] [requests]."""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.parse
import urllib.request

import numpy as np

BASE = 1_600_000_000_000


def main(concurrency: int = 8, total_requests: int = 200):
    from filodb_tpu.server import FiloServer
    from filodb_tpu.testkit import counter_batch, machine_metrics

    # first-compiles can exceed the default 60s deadline on CPU; the harness
    # measures warm latency, so give compile room
    srv = FiloServer({
        "dataset": "prometheus", "shards": 8,
        "query": {"timeout_s": 300},
    })
    port = srv.start(port=0)
    srv.memstore.ingest_routed(
        "prometheus", counter_batch(n_series=200, n_samples=720, start_ms=BASE), spread=3)
    srv.memstore.ingest_routed(
        "prometheus", machine_metrics(n_series=200, n_samples=720, start_ms=BASE), spread=3)

    queries = [
        "sum(rate(http_requests_total[5m]))",
        "sum by (instance) (rate(http_requests_total[5m]))",
        "max_over_time(heap_usage0[5m])",
        "heap_usage0",
    ]
    start_s = (BASE + 600_000) / 1000
    end_s = (BASE + 7_000_000) / 1000
    urls = [
        f"http://127.0.0.1:{port}/api/v1/query_range?query={urllib.parse.quote(q)}"
        f"&start={start_s}&end={end_s}&step=60"
        for q in queries
    ]
    # warm the staging caches + jit
    for u in urls:
        with urllib.request.urlopen(u, timeout=300) as r:
            assert json.loads(r.read())["status"] == "success"

    latencies: list[float] = []
    errors = [0]
    lock = threading.Lock()
    counter = [0]

    def worker():
        while True:
            with lock:
                if counter[0] >= total_requests:
                    return
                i = counter[0]
                counter[0] += 1
            u = urls[i % len(urls)]
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(u, timeout=300) as r:
                    json.loads(r.read())
                with lock:
                    latencies.append(time.perf_counter() - t0)
            except Exception:
                with lock:
                    errors[0] += 1

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    srv.stop()
    lat = np.array(latencies) * 1e3
    out = {
        "metric": "http_query_range_latency",
        "value": round(float(np.percentile(lat, 50)), 2),
        "unit": "ms_p50",
        "p95_ms": round(float(np.percentile(lat, 95)), 2),
        "p99_ms": round(float(np.percentile(lat, 99)), 2),
        "qps": round(len(lat) / wall, 1),
        "errors": errors[0],
        "concurrency": concurrency,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    c = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 200
    main(c, n)
