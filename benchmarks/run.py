"""Microbenchmark suite (reference jmh/src/main/scala/filodb.jmh/ — the 23
JMH benchmarks, SURVEY.md §6; principal ones mirrored here). Each prints one
JSON line; ``python -m benchmarks.run`` runs all and emits a JSON array.

Unlike bench.py (the driver's single north-star number on real TPU), these
cover the component workloads: encoding, ingestion, index lookups, gateway
parse, planner materialization, query QPS in-memory and under ingest,
histogram queries.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# make `python benchmarks/run.py` work like `python -m benchmarks.run`:
# direct file invocation puts benchmarks/ (not the repo root) on sys.path
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def _bench(fn, n_iters=5, warmup=1):
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(n_iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


BASE = 1_600_000_000_000
RESULTS = []


def report(name, value, unit):
    rec = {"metric": name, "value": round(value, 4), "unit": unit}
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)


def bench_encoding():
    """reference EncodingBenchmark / DoubleVectorSimdBenchmark."""
    from filodb_tpu.core import encodings as E

    rng = np.random.default_rng(0)
    ts = BASE + np.arange(100_000, dtype=np.int64) * 10_000 + rng.integers(-50, 50, 100_000)
    vals = 50 + rng.standard_normal(100_000)
    dt = _bench(lambda: E.encode_int64(ts))
    report("encode_delta_delta_100k", 100_000 / dt / 1e6, "Msamples/s")
    dt = _bench(lambda: E.encode_double(vals))
    report("encode_xor_double_100k", 100_000 / dt / 1e6, "Msamples/s")
    enc = E.encode_double(vals)
    dt = _bench(lambda: E.decode(enc))
    report("decode_xor_double_100k", 100_000 / dt / 1e6, "Msamples/s")
    report("xor_double_bytes_per_sample", enc.nbytes / 100_000, "bytes")


def bench_nan_sum():
    from filodb_tpu import native

    rng = np.random.default_rng(1)
    v = rng.standard_normal(1_000_000)
    v[rng.integers(0, len(v), 1000)] = np.nan
    dt = _bench(lambda: native.nan_sum(v))
    report("native_nan_sum_1m", 1e6 / dt / 1e9, "Gsamples/s")


def bench_ingestion():
    """reference IngestionBenchmark: records/sec into a shard."""
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.testkit import machine_metrics

    batch = machine_metrics(n_series=1000, n_samples=100, start_ms=BASE)

    def run():
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("b"), [0])
        ms.ingest("b", 0, batch)

    dt = _bench(run, n_iters=3)
    report("ingest_100k_rows", 100_000 / dt / 1e6, "Mrows/s")


def bench_index():
    """reference PartKeyIndexBenchmark: lookups/sec. PartKeyIndex is the
    vectorized posting-bitmap index since ISSUE 14 — these numbers measure
    the new path (the pre-bitmap set-arithmetic numbers live on in
    BENCH_LOCAL history and the retained SetBasedPartKeyIndex oracle)."""
    from filodb_tpu.core.filters import equals, regex
    from filodb_tpu.memstore.index import PartKeyIndex

    idx = PartKeyIndex()
    for i in range(100_000):
        idx.add_partkey(i, {
            "_metric_": f"metric_{i % 100}", "host": f"h{i % 1000}", "dc": f"dc{i % 10}",
        }, 0)
    f_eq = [equals("_metric_", "metric_5"), equals("dc", "dc3")]
    dt = _bench(lambda: [idx.part_ids_from_filters(f_eq, 0, 2**62) for _ in range(100)])
    report("index_equality_lookups", 100 / dt, "lookups/s")
    f_re = [regex("host", "h1.*")]
    dt = _bench(lambda: [idx.part_ids_from_filters(f_re, 0, 2**62) for _ in range(10)])
    report("index_regex_lookups", 10 / dt, "lookups/s")


def bench_index_1m():
    """1M-partkey index at the reference PartKeyIndexBenchmark scale:
    equality vs range-aware regex vs label-values on the NATIVE backend
    (tantivy analog). Bar (VERDICT r4 item 8): prefix regex within ~4x of
    equality at 1M partkeys. FILODB_BENCH_INDEX_SERIES overrides the scale."""
    import os

    from filodb_tpu.core.filters import equals, regex
    from filodb_tpu.memstore.index_native import (
        NativePartKeyIndex,
        native_index_available,
    )

    if not native_index_available():
        return
    n = int(os.environ.get("FILODB_BENCH_INDEX_SERIES", 1_000_000))
    idx = NativePartKeyIndex()
    t0 = time.perf_counter()
    for i in range(n):
        idx.add_partkey(i, {
            "_metric_": f"metric_{i % 1000}", "host": f"h{i % 10_000}",
            "dc": f"dc{i % 10}", "_ws_": "demo", "_ns_": f"ns{i % 20}",
        }, 0)
    report(f"index_build_{n // 1000}k", n / (time.perf_counter() - t0), "keys/s")
    tag = f"{n // 1000}k"
    # ~n/1000 result ids for every probe below, so rates compare the LOOKUP
    # machinery, not differing result sizes
    f_eq = [equals("_metric_", "metric_5")]
    dt = _bench(lambda: [idx.part_ids_from_filters(f_eq, 0, 2**62) for _ in range(50)])
    eq_rate = 50 / dt
    report(f"index_eq_lookups_{tag}", eq_rate, "lookups/s")
    # prefix regex: h123 + h1230..h1239 of 10k host values (~= eq result size)
    f_pre = [regex("host", "h123.*")]
    dt = _bench(lambda: [idx.part_ids_from_filters(f_pre, 0, 2**62) for _ in range(50)])
    pre_rate = 50 / dt
    report(f"index_prefix_regex_lookups_{tag}", pre_rate, "lookups/s")
    report("index_prefix_regex_vs_eq", eq_rate / pre_rate, "x")
    # general anchored regex with a literal prefix + tail match
    f_re = [regex("host", "h12[0-9]?")]
    dt = _bench(lambda: [idx.part_ids_from_filters(f_re, 0, 2**62) for _ in range(50)])
    report(f"index_regex_lookups_{tag}", 50 / dt, "lookups/s")
    dt = _bench(lambda: [idx.label_values([], "_metric_", 0, 2**62) for _ in range(20)])
    report(f"index_label_values_{tag}", 20 / dt, "lookups/s")


def bench_index_bitmap_1m():
    """1M-partkey BITMAP index (the default backend, memstore/postings.py):
    build rate + the probe set bench_index_1m runs on the native backend,
    plus the warm Grafana-storm regex pool the match cache serves
    (doc/perf.md 'Vectorized part-key index'). FILODB_BENCH_INDEX_SERIES
    overrides the scale."""
    import os

    from filodb_tpu.core.filters import equals, regex
    from filodb_tpu.memstore.index import PartKeyIndex

    n = int(os.environ.get("FILODB_BENCH_INDEX_SERIES", 1_000_000))
    idx = PartKeyIndex()
    t0 = time.perf_counter()
    for i in range(n):
        idx.add_partkey(i, {
            "_metric_": f"metric_{i % 1000}", "host": f"h{i % 10_000}",
            "dc": f"dc{i % 10}", "_ws_": "demo", "_ns_": f"ns{i % 20}",
        }, 0)
    tag = f"{n // 1000}k"
    report(f"index_bitmap_build_{tag}", n / (time.perf_counter() - t0), "keys/s")
    f_eq = [equals("_metric_", "metric_5")]
    dt = _bench(lambda: [idx.part_ids_from_filters(f_eq, 0, 2**62) for _ in range(50)])
    report(f"index_bitmap_eq_lookups_{tag}", 50 / dt, "lookups/s")
    f_pre = [regex("host", "h123.*")]
    dt = _bench(lambda: [idx.part_ids_from_filters(f_pre, 0, 2**62) for _ in range(50)])
    report(f"index_bitmap_prefix_regex_lookups_{tag}", 50 / dt, "lookups/s")
    f_re = [regex("host", "h12[0-9]?")]
    dt = _bench(lambda: [idx.part_ids_from_filters(f_re, 0, 2**62) for _ in range(50)])
    report(f"index_bitmap_regex_lookups_{tag}", 50 / dt, "lookups/s")
    # warm 64-pattern pool: the repeated-selector storm the per-label match
    # cache exists for (each pattern still pays OR + extraction per call)
    pool = [[regex("host", f"h1{i:02d}[0-9]?")] for i in range(64)]
    for f in pool:
        idx.part_ids_from_filters(f, 0, 2**62)
    k = [0]

    def storm():
        for _ in range(50):
            idx.part_ids_from_filters(pool[k[0] % 64], 0, 2**62)
            k[0] += 1

    dt = _bench(storm)
    report(f"index_bitmap_regex_pool_lookups_{tag}", 50 / dt, "lookups/s")
    dt = _bench(lambda: [idx.label_values([], "_metric_", 0, 2**62) for _ in range(20)])
    report(f"index_bitmap_label_values_{tag}", 20 / dt, "lookups/s")


def bench_gateway_parse():
    """reference GatewayBenchmark: line-protocol msgs/sec."""
    from filodb_tpu.gateway.parsers import parse_influx_line, parse_prom_text

    lines = [
        f"cpu,host=h{i},dc=dc{i % 3} value={i}.5 1600000000000000000" for i in range(10_000)
    ]
    dt = _bench(lambda: [list(parse_influx_line(l)) for l in lines])
    report("influx_parse", len(lines) / dt / 1e3, "kmsgs/s")
    text = "\n".join(f'm{i}{{h="x{i}"}} {i} 1600000000000' for i in range(10_000))
    dt = _bench(lambda: list(parse_prom_text(text)))
    report("prom_text_parse", 10_000 / dt / 1e3, "kmsgs/s")
    # full ingest-side batch build: native scanner + key memo vs regex path
    from filodb_tpu.gateway.parsers import prom_text_to_batches_and_exemplars

    dt = _bench(lambda: prom_text_to_batches_and_exemplars(text, 0))
    report("prom_text_to_batches", 10_000 / dt / 1e3, "kmsgs/s")


def bench_planner():
    """reference PlannerBenchmark: plans/sec."""
    from filodb_tpu.coordinator.planner import SingleClusterPlanner
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.query.promql import query_range_to_logical_plan

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("b"), range(8))
    planner = SingleClusterPlanner(ms, "b")
    q = 'sum by (job) (rate(http_requests_total{env="prod",dc=~"us.*"}[5m]))'

    def run():
        for _ in range(100):
            plan = query_range_to_logical_plan(q, 1000, 5000, 15)
            planner.materialize(plan)

    dt = _bench(run)
    report("parse_and_plan", 100 / dt, "plans/s")


def bench_query_in_memory():
    """reference QueryInMemoryBenchmark: 8 shards, 100 series x 720 samples
    (2h @ 10s), sum(rate) range queries."""
    from filodb_tpu.coordinator.planner import QueryEngine
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.testkit import counter_batch, machine_metrics

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(8))
    ms.ingest_routed("prometheus", counter_batch(n_series=100, n_samples=720, start_ms=BASE), spread=3)
    ms.ingest_routed("prometheus", machine_metrics(n_series=100, n_samples=720, start_ms=BASE), spread=3)
    engine = QueryEngine(ms, "prometheus")
    start, end = (BASE + 600_000) / 1000, (BASE + 7_000_000) / 1000

    def q1():
        engine.query_range("sum(rate(http_requests_total[5m]))", start, end, 60)

    q1()  # warm staging cache + jit
    dt = _bench(q1, n_iters=10)
    report("query_sum_rate_100series_qps", 1 / dt, "qps")

    def q2():
        engine.query_range("min_over_time(heap_usage0[5m])", start, end, 60)

    q2()
    dt = _bench(q2, n_iters=10)
    report("query_min_over_time_qps", 1 / dt, "qps")


def bench_query_hicard():
    """reference QueryHiCardInMemoryBenchmark: 8000 series, 2000 queried."""
    from filodb_tpu.coordinator.planner import QueryEngine
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.testkit import counter_batch

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(8))
    for ns in range(4):
        ms.ingest_routed(
            "prometheus",
            counter_batch(n_series=2000, n_samples=120, start_ms=BASE, ns=f"App-{ns}"),
            spread=3,
        )
    engine = QueryEngine(ms, "prometheus")
    start, end = (BASE + 400_000) / 1000, (BASE + 1_100_000) / 1000

    def q():
        engine.query_range('sum(rate(http_requests_total{_ns_="App-1"}[5m]))', start, end, 60)

    q()
    dt = _bench(q, n_iters=5)
    report("query_hicard_2000_of_8000_qps", 1 / dt, "qps")


def bench_histogram_query():
    """reference HistogramQueryBenchmark: sum(rate) + quantile over native
    histograms."""
    from filodb_tpu.coordinator.planner import QueryEngine
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.testkit import histogram_batch

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    ms.ingest_routed("prometheus", histogram_batch(n_series=100, n_samples=240, start_ms=BASE), spread=2)
    engine = QueryEngine(ms, "prometheus")
    start, end = (BASE + 400_000) / 1000, (BASE + 2_200_000) / 1000

    def q():
        engine.query_range(
            "histogram_quantile(0.9, sum(rate(http_request_latency[5m])))", start, end, 60
        )

    q()
    dt = _bench(q, n_iters=5)
    report("query_hist_quantile_qps", 1 / dt, "qps")


def bench_jitter_query():
    """Regular vs jittered scrape grids on the engine fast paths (VERDICT r2
    weak #2: the irregular-timestamp gap). Reference semantics contract:
    PeriodicSamplesMapper.scala:256 window iterators over arbitrary ts."""
    import jax

    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.core.records import SeriesBatch
    from filodb_tpu.core.schemas import Dataset, METRIC_TAG, PROM_COUNTER, shard_for
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.parallel.mesh import make_mesh

    import os

    rng = np.random.default_rng(5)
    n = 720
    n_series = int(os.environ.get("FILODB_BENCH_JITTER_SERIES", 4000))
    nominal = BASE + np.arange(n, dtype=np.int64) * 10_000
    start, end = (BASE + 600_000) / 1000, (BASE + 7_000_000) / 1000

    def build(jitter, hole_frac=0.0):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), range(8))
        incr = rng.uniform(0, 10, size=(n_series, n))
        vals = np.cumsum(incr, axis=1) + 1e9
        for i in range(n_series):
            tags = {METRIC_TAG: "rq_total", "_ws_": "w", "_ns_": "n",
                    "inst": f"h{i}"}
            shard = shard_for(tags, spread=3, num_shards=8)
            ts = nominal
            v = vals[i]
            if jitter:
                ts = nominal + np.rint(
                    rng.uniform(-jitter, jitter, n) * 10_000).astype(np.int64)
            if hole_frac:
                keep = np.ones(n, bool)
                drop = rng.choice(np.arange(1, n - 1),
                                  size=max(1, int(hole_frac * n)),
                                  replace=False)
                keep[drop] = False
                ts, v = ts[keep], v[keep]
            ms.shard("prometheus", shard).ingest_series(
                SeriesBatch(PROM_COUNTER, tags, ts, {"count": v})
            )
        return QueryEngine(ms, "prometheus",
                           PlannerParams(mesh=make_mesh(jax.devices()[:1])))

    results = {}
    for label, jitter, holes in (
        ("regular", 0.0, 0.0), ("jitter1pct", 0.01, 0.0),
        ("jitter5pct", 0.05, 0.0), ("jitter20pct", 0.2, 0.0),
        ("jitter5pct_holes0.5pct", 0.05, 0.005),
    ):
        engine = build(jitter, holes)

        def q():
            r = engine.query_range("sum(rate(rq_total[5m]))", start, end, 60)
            np.asarray(r.grids[0].values_np())

        q()  # warm
        dt = _bench(q, n_iters=10)
        results[label] = dt
        tag = f"{n_series // 1000}k"
        report(f"query_sum_rate_{tag}_{label}_p50", dt * 1e3, "ms")
    report("jitter5pct_vs_regular_ratio",
           results["jitter5pct"] / results["regular"], "x")
    report("jitter_holes_vs_regular_ratio",
           results["jitter5pct_holes0.5pct"] / results["regular"], "x")


ALL = [
    bench_encoding, bench_nan_sum, bench_ingestion, bench_index,
    bench_index_1m, bench_index_bitmap_1m, bench_gateway_parse, bench_planner,
    bench_query_in_memory, bench_query_hicard, bench_histogram_query,
    bench_jitter_query,
]


def main():
    from filodb_tpu.config import apply_platform_env

    apply_platform_env()  # FILODB_PLATFORM=cpu must win over a wedged plugin
    args = [a for a in sys.argv[1:] if not a.startswith("-")]
    only = args[0] if args else None
    isolate = "--no-isolate" not in sys.argv and only is None
    if not isolate:
        exact = any(only == f.__name__ for f in ALL) if only else False
        for fn in ALL:
            if only and (fn.__name__ != only if exact else only not in fn.__name__):
                continue
            fn()
        print(json.dumps(RESULTS))
        return
    # one subprocess per bench: a fresh heap for every measurement, so a
    # memory-heavy bench (the 1M index build) cannot degrade the ones that
    # run after it — numbers of record must not depend on suite order
    import subprocess

    for fn in ALL:
        try:
            p = subprocess.run(
                [sys.executable, "-m", "benchmarks.run", fn.__name__,
                 "--no-isolate"],
                capture_output=True, text=True, cwd=_ROOT,
                timeout=int(os.environ.get("FILODB_BENCH_FN_TIMEOUT_S", 1800)),
            )
        except subprocess.TimeoutExpired:
            # a hung bench (e.g. the wedged TPU plugin) must not kill the
            # rest of the suite — that is the whole point of isolation
            rec = {"metric": f"FAILED_{fn.__name__}", "value": -1,
                   "unit": "timeout"}
            RESULTS.append(rec)
            print(json.dumps(rec), flush=True)
            continue
        for line in p.stdout.splitlines():
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                RESULTS.append(rec)
                print(line, flush=True)
        if p.returncode != 0:
            rec = {"metric": f"FAILED_{fn.__name__}", "value": -1,
                   "unit": "error"}
            RESULTS.append(rec)
            print(json.dumps(rec), flush=True)
            sys.stderr.write(p.stderr[-500:] + "\n")
    print(json.dumps(RESULTS))


def bench_mesh_paths():
    """Distributed execution paths (needs >=2 devices; skipped otherwise)."""
    import jax

    if len(jax.devices()) < 2:
        return
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.parallel.mesh import make_mesh
    from filodb_tpu.testkit import counter_batch

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(8))
    ms.ingest_routed("prometheus", counter_batch(n_series=400, n_samples=360, start_ms=BASE), spread=3)
    engine = QueryEngine(ms, "prometheus", PlannerParams(mesh=make_mesh()))
    start, end = (BASE + 400_000) / 1000, (BASE + 3_400_000) / 1000

    def q():
        engine.query_range("sum(rate(http_requests_total[5m]))", start, end, 60)

    q()
    dt = _bench(q, n_iters=10)
    report("mesh_sum_rate_qps", 1 / dt, "qps")


ALL.append(bench_mesh_paths)


def bench_serialization():
    """Prom JSON rendering throughput (the serving-edge cost), measured on
    the PRODUCTION bytes path: stream_matrix fragments — exactly what both
    the buffered and chunked-streaming edges send (native row renderer when
    libfilodbrender is built, vectorized numpy tier otherwise)."""
    from filodb_tpu import native as N
    from filodb_tpu.api import promjson as J
    from filodb_tpu.query.rangevector import Grid, QueryResult

    rng = np.random.default_rng(0)
    vals = rng.standard_normal((1000, 120)).astype(np.float32)
    g = Grid([{"_metric_": "m", "i": str(i)} for i in range(1000)],
             BASE, 60_000, 120, vals)
    res = QueryResult(grids=[g])
    dt = _bench(lambda: b"".join(J.stream_matrix(res)))
    report(f"prom_json_render[{J.active_render_format()}]",
           1000 * 120 / dt / 1e6, "Msamples/s")
    if N.render_lib() is not None:
        # numpy tier on the same workload (what an un-built checkout serves)
        orig = N.render_matrix_rows
        N.render_matrix_rows = lambda ts, v: None
        try:
            dt = _bench(lambda: b"".join(J.stream_matrix(res)))
            report("prom_json_render[numpy]", 1000 * 120 / dt / 1e6, "Msamples/s")
        finally:
            N.render_matrix_rows = orig

    from filodb_tpu.api.arrow_edge import result_to_ipc

    dt = _bench(lambda: result_to_ipc(res))
    report("arrow_ipc_render", 1000 * 120 / dt / 1e6, "Msamples/s")

    # gRPC columnar stream frames (query/proto_plan.py): serialize + parse
    from filodb_tpu.query.proto_plan import frames_to_result, result_to_frames

    def grpc_roundtrip():
        wire = [f.SerializeToString() for f in result_to_frames(res)]
        from filodb_tpu.api.query_exec_pb2 import StreamFrame

        return frames_to_result(StreamFrame.FromString(b) for b in wire)

    dt = _bench(grpc_roundtrip)
    report("grpc_frames_roundtrip", 1000 * 120 / dt / 1e6, "Msamples/s")


ALL.append(bench_serialization)


def bench_concurrent_queries():
    """QPS scaling under concurrent clients (VERDICT r4 item 6; reference
    analog: the shared instrumented pool, QueryScheduler.scala:29-73).
    16 clients fan the same dashboard query out; single-flight coalescing
    turns the fan-out into one kernel launch per arrival window, so QPS
    must scale, not flatline. FILODB_BENCH_CONC_SERIES sets the scale
    (default 20k; the bar was stated at 100k)."""
    import os
    import threading
    import time as _t

    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.coordinator.scheduler import QueryScheduler
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.testkit import counter_batch

    n_series = int(os.environ.get("FILODB_BENCH_CONC_SERIES", 20_000))
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(8))
    ms.ingest_routed(
        "prometheus",
        counter_batch(n_series=n_series, n_samples=120, start_ms=BASE),
        spread=3,
    )
    engine = QueryEngine(
        ms, "prometheus",
        PlannerParams(scheduler=QueryScheduler(), deadline_s=120),
    )
    start, end = (BASE + 400_000) / 1000, (BASE + 1_100_000) / 1000
    q = "sum(rate(http_requests_total[5m]))"
    engine.query_range(q, start, end, 60)  # warm staging + jit

    def measure(n_clients: int, seconds: float = 4.0) -> float:
        done = []
        stop = _t.monotonic() + seconds

        def client():
            k = 0
            while _t.monotonic() < stop:
                engine.query_range(q, start, end, 60)
                k += 1
            done.append(k)

        threads = [threading.Thread(target=client) for _ in range(n_clients)]
        t0 = _t.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return sum(done) / (_t.monotonic() - t0)

    qps1 = measure(1)
    qps16 = measure(16)
    tag = f"{n_series // 1000}k"
    report(f"concurrent_qps_1client_{tag}", qps1, "qps")
    report(f"concurrent_qps_16clients_{tag}", qps16, "qps")
    report("concurrent_qps_scaling_1_to_16", qps16 / qps1, "x")


ALL.append(bench_concurrent_queries)


def bench_query_and_ingest():
    """Query QPS while ingestion runs concurrently (reference
    QueryAndIngestBenchmark.scala: 'measure impact of ingestion on
    querying' — ingest invalidates the staging caches, so each query pays a
    re-stage; the ratio against the idle QPS is the contract)."""
    import threading
    import time as _t

    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.testkit import counter_batch

    n_series, n_samples = 800, 1080  # the reference's scale (3h @ 10s)
    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(2))
    ms.ingest_routed(
        "prometheus",
        counter_batch(n_series=n_series, n_samples=n_samples, start_ms=BASE),
        spread=1,
    )
    engine = QueryEngine(ms, "prometheus", PlannerParams(deadline_s=120))
    start = (BASE + 600_000) / 1000
    # live-edge panel: its range covers the ENTIRE incoming stream (the
    # ingester below appends ~100 s of data per batch, up to 100 batches),
    # so every batch lands in-range and invalidates the staging cache —
    # each query during ingest genuinely pays the re-stage
    end = (BASE + n_samples * 10_000 + 100 * 100_000) / 1000
    q = "sum(rate(http_requests_total[5m]))"
    engine.query_range(q, start, end, 60)

    dt_idle = _bench(lambda: engine.query_range(q, start, end, 60), n_iters=5)
    report("query_idle_800x1080_qps", 1 / dt_idle, "qps")

    # pre-generate the ingest stream (the reference notes the pseudorandom
    # producer's CPU pollutes the measurement) and ingest at a DEFINED rate
    # (one 10-sample-per-series batch per 100 ms = 80k samples/s), so the
    # metric is "query cost while a realistic stream ingests", not "query
    # cost while a tight loop saturates the core"
    t0 = BASE + n_samples * 10_000
    batches = [
        counter_batch(n_series=n_series, n_samples=10, start_ms=t0 + i * 100_000)
        for i in range(100)
    ]
    stop = threading.Event()
    ingested = [0]

    def ingester():
        i = 0
        while not stop.is_set():
            ingested[0] += ms.ingest_routed(
                "prometheus", batches[i % len(batches)], spread=1
            )
            i += 1
            stop.wait(0.1)

    # historical query: its range ends BEFORE the live ingest head, so the
    # selective stage-cache invalidation must keep it cached under ingest;
    # its impact ratio uses ITS OWN idle baseline (shorter range — dividing
    # by the live query's idle latency would conflate range length with
    # ingest impact)
    hist_end = (BASE + (n_samples - 60) * 10_000) / 1000
    engine.query_range(q, start, hist_end, 60)
    dt_hist_idle = _bench(
        lambda: engine.query_range(q, start, hist_end, 60), n_iters=5
    )

    th = threading.Thread(target=ingester)
    th.start()
    try:
        t0 = _t.monotonic()
        k = 0
        while _t.monotonic() - t0 < 5.0:
            engine.query_range(q, start, end, 60)
            k += 1
        dt_busy = (_t.monotonic() - t0) / k
        t0 = _t.monotonic()
        k = 0
        while _t.monotonic() - t0 < 5.0:
            engine.query_range(q, start, hist_end, 60)
            k += 1
        dt_hist = (_t.monotonic() - t0) / k
    finally:
        stop.set()
        th.join()
    assert ingested[0] > 0, "ingester must actually run during the window"
    report("query_under_ingest_800x1080_qps", 1 / dt_busy, "qps")
    report("ingest_impact_on_query", dt_busy / dt_idle, "x")
    report("query_historical_under_ingest_qps", 1 / dt_hist, "qps")
    report("ingest_impact_on_historical_query", dt_hist / dt_hist_idle, "x")


ALL.append(bench_query_and_ingest)


def bench_query_on_demand():
    """Queries served ~100% by on-demand paging from the column store
    (reference QueryOnDemandBenchmark.scala: evict everything, query, page
    back in). Every query drops the paged chunks again so each one pays the
    full ODP read."""
    import shutil
    import tempfile

    from filodb_tpu.coordinator.planner import QueryEngine
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.memstore.shard import StoreConfig
    from filodb_tpu.store.columnstore import LocalColumnStore
    from filodb_tpu.store.flush import FlushCoordinator
    from filodb_tpu.testkit import machine_metrics

    n_series, n_samples = 100, 720  # the reference's scale (2h @ 10s)
    root = tempfile.mkdtemp(prefix="filodb-odp-bench-")
    try:
        store = LocalColumnStore(root)
        ms = TimeSeriesMemStore(
            StoreConfig(max_chunk_size=100, retention_ms=1_000_000)
        )
        ms.setup(Dataset("prometheus"), [0])
        sh = ms.shard("prometheus", 0)
        sh.odp_store = store
        ms.ingest(
            "prometheus", 0,
            machine_metrics(n_series=n_series, n_samples=n_samples, start_ms=BASE),
        )
        FlushCoordinator(ms, store).flush_shard("prometheus", 0)
        # retention keeps only the newest ~100 samples resident: the queried
        # window below is entirely evicted, so every query reads the store
        evict_now = BASE + n_samples * 10_000
        engine = QueryEngine(ms, "prometheus")
        start = (BASE + 600_000) / 1000
        end = start + 55 * 60  # reference queryIntervalMin = 55
        q = "sum(rate(heap_usage0[5m]))"

        def cold_query():
            sh.evict_for_retention(now_ms=evict_now)
            engine.query_range(q, start, end, 60)

        cold_query()
        pages0 = sh.odp_stats_pages
        dt = _bench(cold_query, n_iters=5)
        assert sh.odp_stats_pages > pages0, "queries must actually page in"
        report("query_odp_100x720_qps", 1 / dt, "qps")
    finally:
        shutil.rmtree(root, ignore_errors=True)


ALL.append(bench_query_on_demand)


def bench_render():
    """Native sample-fragment renderer (promrender.cpp), the serving-edge
    hot loop — VERDICT r3 weak #1 bar: >=10 Msamples/s on 2M random-f64
    samples (worst-case shortest-repr values), one warm call."""
    from filodb_tpu import native as N
    from filodb_tpu.api import promjson as J

    rng = np.random.default_rng(7)
    n = 2_000_000
    ts = 1.6e9 + np.arange(n) * 10.0
    vals = rng.uniform(0, 1e9, n)
    vals[::1000] = np.nan
    if N.render_values(ts[:8], vals[:8]) is not None:
        dt = _bench(lambda: N.render_values(ts, vals), n_iters=5)
        report("prom_render_native_2M_random", n / dt / 1e6, "Msamples/s")
        dt = _bench(lambda: N.render_values(ts, np.floor(vals)), n_iters=5)
        report("prom_render_native_2M_integral", n / dt / 1e6, "Msamples/s")
    # pure-Python fallback on a 100k slice (it is ~30x slower)
    m = 100_000

    def py_render():
        keep = ~np.isnan(vals[:m])
        parts = (
            f'[{J._ts3(float(t))},"{J._fmt(v)}"]'
            for t, v in zip(ts[:m][keep], vals[:m][keep])
        )
        return ("[" + ",".join(parts) + "]").encode()

    dt = _bench(py_render, n_iters=3)
    report("prom_render_python_100k_random", m / dt / 1e6, "Msamples/s")


ALL.append(bench_render)


if __name__ == "__main__":
    main()
