"""Microbenchmark suite (reference jmh/src/main/scala/filodb.jmh/ — the 23
JMH benchmarks, SURVEY.md §6; principal ones mirrored here). Each prints one
JSON line; ``python -m benchmarks.run`` runs all and emits a JSON array.

Unlike bench.py (the driver's single north-star number on real TPU), these
cover the component workloads: encoding, ingestion, index lookups, gateway
parse, planner materialization, query QPS in-memory and under ingest,
histogram queries.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# make `python benchmarks/run.py` work like `python -m benchmarks.run`:
# direct file invocation puts benchmarks/ (not the repo root) on sys.path
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def _bench(fn, n_iters=5, warmup=1):
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(n_iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


BASE = 1_600_000_000_000
RESULTS = []


def report(name, value, unit):
    rec = {"metric": name, "value": round(value, 4), "unit": unit}
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)


def bench_encoding():
    """reference EncodingBenchmark / DoubleVectorSimdBenchmark."""
    from filodb_tpu.core import encodings as E

    rng = np.random.default_rng(0)
    ts = BASE + np.arange(100_000, dtype=np.int64) * 10_000 + rng.integers(-50, 50, 100_000)
    vals = 50 + rng.standard_normal(100_000)
    dt = _bench(lambda: E.encode_int64(ts))
    report("encode_delta_delta_100k", 100_000 / dt / 1e6, "Msamples/s")
    dt = _bench(lambda: E.encode_double(vals))
    report("encode_xor_double_100k", 100_000 / dt / 1e6, "Msamples/s")
    enc = E.encode_double(vals)
    dt = _bench(lambda: E.decode(enc))
    report("decode_xor_double_100k", 100_000 / dt / 1e6, "Msamples/s")
    report("xor_double_bytes_per_sample", enc.nbytes / 100_000, "bytes")


def bench_nan_sum():
    from filodb_tpu import native

    rng = np.random.default_rng(1)
    v = rng.standard_normal(1_000_000)
    v[rng.integers(0, len(v), 1000)] = np.nan
    dt = _bench(lambda: native.nan_sum(v))
    report("native_nan_sum_1m", 1e6 / dt / 1e9, "Gsamples/s")


def bench_ingestion():
    """reference IngestionBenchmark: records/sec into a shard."""
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.testkit import machine_metrics

    batch = machine_metrics(n_series=1000, n_samples=100, start_ms=BASE)

    def run():
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("b"), [0])
        ms.ingest("b", 0, batch)

    dt = _bench(run, n_iters=3)
    report("ingest_100k_rows", 100_000 / dt / 1e6, "Mrows/s")


def bench_index():
    """reference PartKeyIndexBenchmark: lookups/sec."""
    from filodb_tpu.core.filters import equals, regex
    from filodb_tpu.memstore.index import PartKeyIndex

    idx = PartKeyIndex()
    for i in range(100_000):
        idx.add_partkey(i, {
            "_metric_": f"metric_{i % 100}", "host": f"h{i % 1000}", "dc": f"dc{i % 10}",
        }, 0)
    f_eq = [equals("_metric_", "metric_5"), equals("dc", "dc3")]
    dt = _bench(lambda: [idx.part_ids_from_filters(f_eq, 0, 2**62) for _ in range(100)])
    report("index_equality_lookups", 100 / dt, "lookups/s")
    f_re = [regex("host", "h1.*")]
    dt = _bench(lambda: [idx.part_ids_from_filters(f_re, 0, 2**62) for _ in range(10)])
    report("index_regex_lookups", 10 / dt, "lookups/s")


def bench_gateway_parse():
    """reference GatewayBenchmark: line-protocol msgs/sec."""
    from filodb_tpu.gateway.parsers import parse_influx_line, parse_prom_text

    lines = [
        f"cpu,host=h{i},dc=dc{i % 3} value={i}.5 1600000000000000000" for i in range(10_000)
    ]
    dt = _bench(lambda: [list(parse_influx_line(l)) for l in lines])
    report("influx_parse", len(lines) / dt / 1e3, "kmsgs/s")
    text = "\n".join(f'm{i}{{h="x{i}"}} {i} 1600000000000' for i in range(10_000))
    dt = _bench(lambda: list(parse_prom_text(text)))
    report("prom_text_parse", 10_000 / dt / 1e3, "kmsgs/s")
    # full ingest-side batch build: native scanner + key memo vs regex path
    from filodb_tpu.gateway.parsers import prom_text_to_batches_and_exemplars

    dt = _bench(lambda: prom_text_to_batches_and_exemplars(text, 0))
    report("prom_text_to_batches", 10_000 / dt / 1e3, "kmsgs/s")


def bench_planner():
    """reference PlannerBenchmark: plans/sec."""
    from filodb_tpu.coordinator.planner import SingleClusterPlanner
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.query.promql import query_range_to_logical_plan

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("b"), range(8))
    planner = SingleClusterPlanner(ms, "b")
    q = 'sum by (job) (rate(http_requests_total{env="prod",dc=~"us.*"}[5m]))'

    def run():
        for _ in range(100):
            plan = query_range_to_logical_plan(q, 1000, 5000, 15)
            planner.materialize(plan)

    dt = _bench(run)
    report("parse_and_plan", 100 / dt, "plans/s")


def bench_query_in_memory():
    """reference QueryInMemoryBenchmark: 8 shards, 100 series x 720 samples
    (2h @ 10s), sum(rate) range queries."""
    from filodb_tpu.coordinator.planner import QueryEngine
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.testkit import counter_batch, machine_metrics

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(8))
    ms.ingest_routed("prometheus", counter_batch(n_series=100, n_samples=720, start_ms=BASE), spread=3)
    ms.ingest_routed("prometheus", machine_metrics(n_series=100, n_samples=720, start_ms=BASE), spread=3)
    engine = QueryEngine(ms, "prometheus")
    start, end = (BASE + 600_000) / 1000, (BASE + 7_000_000) / 1000

    def q1():
        engine.query_range("sum(rate(http_requests_total[5m]))", start, end, 60)

    q1()  # warm staging cache + jit
    dt = _bench(q1, n_iters=10)
    report("query_sum_rate_100series_qps", 1 / dt, "qps")

    def q2():
        engine.query_range("min_over_time(heap_usage0[5m])", start, end, 60)

    q2()
    dt = _bench(q2, n_iters=10)
    report("query_min_over_time_qps", 1 / dt, "qps")


def bench_query_hicard():
    """reference QueryHiCardInMemoryBenchmark: 8000 series, 2000 queried."""
    from filodb_tpu.coordinator.planner import QueryEngine
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.testkit import counter_batch

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(8))
    for ns in range(4):
        ms.ingest_routed(
            "prometheus",
            counter_batch(n_series=2000, n_samples=120, start_ms=BASE, ns=f"App-{ns}"),
            spread=3,
        )
    engine = QueryEngine(ms, "prometheus")
    start, end = (BASE + 400_000) / 1000, (BASE + 1_100_000) / 1000

    def q():
        engine.query_range('sum(rate(http_requests_total{_ns_="App-1"}[5m]))', start, end, 60)

    q()
    dt = _bench(q, n_iters=5)
    report("query_hicard_2000_of_8000_qps", 1 / dt, "qps")


def bench_histogram_query():
    """reference HistogramQueryBenchmark: sum(rate) + quantile over native
    histograms."""
    from filodb_tpu.coordinator.planner import QueryEngine
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.testkit import histogram_batch

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(4))
    ms.ingest_routed("prometheus", histogram_batch(n_series=100, n_samples=240, start_ms=BASE), spread=2)
    engine = QueryEngine(ms, "prometheus")
    start, end = (BASE + 400_000) / 1000, (BASE + 2_200_000) / 1000

    def q():
        engine.query_range(
            "histogram_quantile(0.9, sum(rate(http_request_latency[5m])))", start, end, 60
        )

    q()
    dt = _bench(q, n_iters=5)
    report("query_hist_quantile_qps", 1 / dt, "qps")


def bench_jitter_query():
    """Regular vs jittered scrape grids on the engine fast paths (VERDICT r2
    weak #2: the irregular-timestamp gap). Reference semantics contract:
    PeriodicSamplesMapper.scala:256 window iterators over arbitrary ts."""
    import jax

    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.core.records import SeriesBatch
    from filodb_tpu.core.schemas import Dataset, METRIC_TAG, PROM_COUNTER, shard_for
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.parallel.mesh import make_mesh

    import os

    rng = np.random.default_rng(5)
    n = 720
    n_series = int(os.environ.get("FILODB_BENCH_JITTER_SERIES", 4000))
    nominal = BASE + np.arange(n, dtype=np.int64) * 10_000
    start, end = (BASE + 600_000) / 1000, (BASE + 7_000_000) / 1000

    def build(jitter, hole_frac=0.0):
        ms = TimeSeriesMemStore()
        ms.setup(Dataset("prometheus"), range(8))
        incr = rng.uniform(0, 10, size=(n_series, n))
        vals = np.cumsum(incr, axis=1) + 1e9
        for i in range(n_series):
            tags = {METRIC_TAG: "rq_total", "_ws_": "w", "_ns_": "n",
                    "inst": f"h{i}"}
            shard = shard_for(tags, spread=3, num_shards=8)
            ts = nominal
            v = vals[i]
            if jitter:
                ts = nominal + np.rint(
                    rng.uniform(-jitter, jitter, n) * 10_000).astype(np.int64)
            if hole_frac:
                keep = np.ones(n, bool)
                drop = rng.choice(np.arange(1, n - 1),
                                  size=max(1, int(hole_frac * n)),
                                  replace=False)
                keep[drop] = False
                ts, v = ts[keep], v[keep]
            ms.shard("prometheus", shard).ingest_series(
                SeriesBatch(PROM_COUNTER, tags, ts, {"count": v})
            )
        return QueryEngine(ms, "prometheus",
                           PlannerParams(mesh=make_mesh(jax.devices()[:1])))

    results = {}
    for label, jitter, holes in (
        ("regular", 0.0, 0.0), ("jitter1pct", 0.01, 0.0),
        ("jitter5pct", 0.05, 0.0), ("jitter20pct", 0.2, 0.0),
        ("jitter5pct_holes0.5pct", 0.05, 0.005),
    ):
        engine = build(jitter, holes)

        def q():
            r = engine.query_range("sum(rate(rq_total[5m]))", start, end, 60)
            np.asarray(r.grids[0].values_np())

        q()  # warm
        dt = _bench(q, n_iters=10)
        results[label] = dt
        tag = f"{n_series // 1000}k"
        report(f"query_sum_rate_{tag}_{label}_p50", dt * 1e3, "ms")
    report("jitter5pct_vs_regular_ratio",
           results["jitter5pct"] / results["regular"], "x")
    report("jitter_holes_vs_regular_ratio",
           results["jitter5pct_holes0.5pct"] / results["regular"], "x")


ALL = [
    bench_encoding, bench_nan_sum, bench_ingestion, bench_index,
    bench_gateway_parse, bench_planner, bench_query_in_memory,
    bench_query_hicard, bench_histogram_query, bench_jitter_query,
]


def main():
    from filodb_tpu.config import apply_platform_env

    apply_platform_env()  # FILODB_PLATFORM=cpu must win over a wedged plugin
    only = sys.argv[1] if len(sys.argv) > 1 else None
    for fn in ALL:
        if only and only not in fn.__name__:
            continue
        fn()
    print(json.dumps(RESULTS))


def bench_mesh_paths():
    """Distributed execution paths (needs >=2 devices; skipped otherwise)."""
    import jax

    if len(jax.devices()) < 2:
        return
    from filodb_tpu.coordinator.planner import PlannerParams, QueryEngine
    from filodb_tpu.core.schemas import Dataset
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.parallel.mesh import make_mesh
    from filodb_tpu.testkit import counter_batch

    ms = TimeSeriesMemStore()
    ms.setup(Dataset("prometheus"), range(8))
    ms.ingest_routed("prometheus", counter_batch(n_series=400, n_samples=360, start_ms=BASE), spread=3)
    engine = QueryEngine(ms, "prometheus", PlannerParams(mesh=make_mesh()))
    start, end = (BASE + 400_000) / 1000, (BASE + 3_400_000) / 1000

    def q():
        engine.query_range("sum(rate(http_requests_total[5m]))", start, end, 60)

    q()
    dt = _bench(q, n_iters=10)
    report("mesh_sum_rate_qps", 1 / dt, "qps")


ALL.append(bench_mesh_paths)


def bench_serialization():
    """Prom JSON rendering throughput (the serving-edge cost)."""
    from filodb_tpu.api.promjson import render_matrix
    from filodb_tpu.query.rangevector import Grid, QueryResult

    rng = np.random.default_rng(0)
    vals = rng.standard_normal((1000, 120)).astype(np.float32)
    g = Grid([{"_metric_": "m", "i": str(i)} for i in range(1000)],
             BASE, 60_000, 120, vals)
    res = QueryResult(grids=[g])
    dt = _bench(lambda: render_matrix(res))
    report("prom_json_render", 1000 * 120 / dt / 1e6, "Msamples/s")

    from filodb_tpu.api.arrow_edge import result_to_ipc

    dt = _bench(lambda: result_to_ipc(res))
    report("arrow_ipc_render", 1000 * 120 / dt / 1e6, "Msamples/s")

    # gRPC columnar stream frames (query/proto_plan.py): serialize + parse
    from filodb_tpu.query.proto_plan import frames_to_result, result_to_frames

    def grpc_roundtrip():
        wire = [f.SerializeToString() for f in result_to_frames(res)]
        from filodb_tpu.api.query_exec_pb2 import StreamFrame

        return frames_to_result(StreamFrame.FromString(b) for b in wire)

    dt = _bench(grpc_roundtrip)
    report("grpc_frames_roundtrip", 1000 * 120 / dt / 1e6, "Msamples/s")


ALL.append(bench_serialization)


def bench_render():
    """Native sample-fragment renderer (promrender.cpp), the serving-edge
    hot loop — VERDICT r3 weak #1 bar: >=10 Msamples/s on 2M random-f64
    samples (worst-case shortest-repr values), one warm call."""
    from filodb_tpu import native as N
    from filodb_tpu.api import promjson as J

    rng = np.random.default_rng(7)
    n = 2_000_000
    ts = 1.6e9 + np.arange(n) * 10.0
    vals = rng.uniform(0, 1e9, n)
    vals[::1000] = np.nan
    if N.render_values(ts[:8], vals[:8]) is not None:
        dt = _bench(lambda: N.render_values(ts, vals), n_iters=5)
        report("prom_render_native_2M_random", n / dt / 1e6, "Msamples/s")
        dt = _bench(lambda: N.render_values(ts, np.floor(vals)), n_iters=5)
        report("prom_render_native_2M_integral", n / dt / 1e6, "Msamples/s")
    # pure-Python fallback on a 100k slice (it is ~30x slower)
    m = 100_000

    def py_render():
        keep = ~np.isnan(vals[:m])
        parts = (
            f'[{J._ts3(float(t))},"{J._fmt(v)}"]'
            for t, v in zip(ts[:m][keep], vals[:m][keep])
        )
        return ("[" + ",".join(parts) + "]").encode()

    dt = _bench(py_render, n_iters=3)
    report("prom_render_python_100k_random", m / dt / 1e6, "Msamples/s")


ALL.append(bench_render)


if __name__ == "__main__":
    main()
