"""Keyed single-flight: ONE build per key under concurrency.

The tree grew four hand-rolled copies of the same double-checked-locking
discipline (parallel/exec._get_wm, mxu_kernels.window_matrices,
aggregations.group_ids_memo, staging.SuperblockCache.build_lock) — same
defect class, four bespoke implementations (ROADMAP open item). This module
is the one shared implementation; every site now routes through it.

Contract shared by all users: a *miss* takes the key's flight lock,
re-checks its cache, and only then builds — so N racing identical cold
requests produce exactly one expensive construction (device upload, O(S)
regroup, superblock concat) while the losers block briefly and reuse the
winner's result. Flight locks are created on demand and pruned
opportunistically; a racer holding a pruned lock merely degrades to a
duplicate build, never to corruption.
"""

from __future__ import annotations

import threading
from collections import OrderedDict


class KeyedSingleFlight:
    """Per-key build serialization with a bounded, self-pruning lock table.

    ``alive`` (optional) is a predicate over keys consulted at prune time:
    locks whose key is still interesting (e.g. present in the caller's
    cache) survive, the rest are dropped. Without it, an oversized table is
    simply cleared — both are safe, see the module contract."""

    def __init__(self, max_keys: int = 256, alive=None):
        self.max_keys = max_keys
        self._alive = alive
        self._lock = threading.Lock()
        self._locks: dict = {}

    def lock(self, key) -> threading.Lock:
        """The flight lock for ``key`` (created on demand)."""
        with self._lock:
            lk = self._locks.get(key)
            if lk is None:
                if len(self._locks) >= self.max_keys:
                    if self._alive is not None:
                        self._locks = {
                            k: v for k, v in self._locks.items()
                            if self._alive(k)
                        }
                    if len(self._locks) >= self.max_keys:
                        self._locks.clear()
                lk = threading.Lock()
                self._locks[key] = lk
            return lk

    def __len__(self) -> int:
        with self._lock:
            return len(self._locks)


# process-wide flight table for object-attached memo dicts (window matrices,
# group ids): keys embed id(obj), so distinct blocks never contend; an
# id-reuse collision after GC merely serializes two unrelated builds
_MEMO_FLIGHT = KeyedSingleFlight(max_keys=512)


def memo_on(obj, attr: str, key, build):
    """Get-or-build ``key`` in a memo dict attached to ``obj`` as ``attr``.

    The fast path is one lock-free dict probe. The attach itself goes
    through ``obj.__dict__.setdefault`` (atomic under the GIL), so two
    threads missing on *different* keys of the same object can never clobber
    each other's freshly-attached dict. Build raised? Nothing is cached —
    the next caller retries."""
    cache = obj.__dict__.setdefault(attr, {})
    hit = cache.get(key)
    if hit is not None:
        return hit
    with _MEMO_FLIGHT.lock((id(obj), attr, key)):
        hit = cache.get(key)
        if hit is None:
            hit = build()
            cache[key] = hit
        return hit


class SingleFlightLRU:
    """Bounded LRU cache whose misses build single-flight per key.

    The shape ``parallel/exec._get_wm`` needs: hits refresh recency under
    one cache lock; a miss builds outside it (builds upload device-resident
    matrices and must not serialize unrelated keys) but inside the key's
    flight lock, then inserts and evicts oldest-first."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._d: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        self._flight = KeyedSingleFlight(
            max_keys=max(4 * capacity, 16), alive=lambda k: k in self._d
        )

    def _probe(self, key):
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                return self._d[key]
            return None

    def get_or_build(self, key, ctor):
        hit = self._probe(key)
        if hit is not None:
            return hit
        with self._flight.lock(key):
            hit = self._probe(key)
            if hit is not None:
                return hit
            v = ctor()
            with self._lock:
                self._d[key] = v
                while len(self._d) > self.capacity:
                    self._d.popitem(last=False)
            return v

    def pop(self, key):
        with self._lock:
            return self._d.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._d.clear()

    def keys(self) -> list:
        with self._lock:
            return list(self._d)

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._d
