"""``python -m filodb_tpu`` -> the CLI."""

from .cli import main

main()
