"""Flush pipeline + restart recovery (reference L2/L3:
TimeSeriesShard.createFlushTasks:1352 / doFlushSteps:1462 / writeChunks:1636 /
commitCheckpoint:1551; recovery: recoverIndex:774 + IndexBootstrapper +
checkpoint replay, doc/ingestion.md:114-133).

Flow: per flush group — seal write buffers, persist encoded chunks + dirty
partkeys, then commit the stream offset checkpoint. Recovery reverses it:
rebuild partitions/index from the store, then tell the ingestion source the
min checkpoint to replay from (rows at/before a group's own checkpoint are
skipped by the group watermark, exactly the reference's scheme).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..core.schemas import SCHEMAS
from ..memstore.partition import Chunk, TimeSeriesPartition
from .columnstore import ColumnStore


@dataclass
class FlushResult:
    chunks_written: int = 0
    partkeys_written: int = 0
    groups_flushed: int = 0


class FlushCoordinator:
    def __init__(self, memstore, store: ColumnStore, downsampler=None, preagg=None):
        self.memstore = memstore
        self.store = store
        # optional ShardDownsampler: emits downsample records during flush
        # (reference ShardDownsampler runs inside doFlushSteps)
        self.downsampler = downsampler
        # optional PreaggMaintainer: accumulates :agg series during flush
        self.preagg = preagg
        # one flush cycle at a time: concurrent flushes (maintenance loop +
        # /admin/flush) would both collect the same unflushed chunks before
        # either marks them flushed and double-write them to the store
        import threading

        self._lock = threading.RLock()

    def flush_shard(self, dataset: str, shard_num: int, offset: int | None = None) -> FlushResult:
        with self._lock:
            return self._flush_shard(dataset, shard_num, offset)

    def _flush_shard(self, dataset: str, shard_num: int, offset: int | None = None) -> FlushResult:
        shard = self.memstore.shard(dataset, shard_num)
        res = FlushResult()
        offset = offset if offset is not None else shard.ingested_offset
        for group in range(shard.config.groups_per_shard):
            tasks = shard.create_flush_task(group)
            for part, chunks in tasks:
                self.store.write_chunks(
                    dataset, shard_num, group, part.part_id, part.tags, part.schema, chunks
                )
                self.store.write_partkey(
                    dataset, shard_num, part.tags, part.earliest_ts(), part.latest_ts()
                )
                if self.downsampler is not None:
                    self.downsampler.downsample_chunks(shard_num, part, chunks)
                if self.preagg is not None and self.preagg.dataset == dataset:
                    self.preagg.process_chunks(shard_num, part, chunks)
                part.mark_flushed(chunks[-1].end_ts)
                res.chunks_written += len(chunks)
                res.partkeys_written += 1
                shard.stats.chunks_flushed += len(chunks)
            # checkpoint commits AFTER chunk + partkey writes (reference
            # commitCheckpoint ordering guarantees replay covers data loss)
            self.store.write_checkpoint(dataset, shard_num, group, offset)
            res.groups_flushed += 1
        # index time lifecycle: partitions that stopped ingesting get a real
        # end time so time-filtered lookups prune them (reference
        # updateIndexWithEndTime inside the flush path)
        shard.update_index_end_times()
        if self.preagg is not None and self.preagg.dataset == dataset:
            self.preagg.emit(shard_num)
        return res

    def flush_all(self, dataset: str) -> FlushResult:
        total = FlushResult()
        with self._lock:
            for s in self.memstore.shard_nums(dataset):
                r = self._flush_shard(dataset, s)
                total.chunks_written += r.chunks_written
                total.partkeys_written += r.partkeys_written
                total.groups_flushed += r.groups_flushed
        return total


def _reconcile_chunks(part: TimeSeriesPartition) -> None:
    """Collapse duplicate / overlapping chunks loaded from the store.

    The batch downsampler commits by MERGE into the live shard dir
    (downsample/distributed.py): its output coexists with ingest-time
    streaming flushes of the same periods, and a redone shard (after a
    claim steal) re-commits equivalent chunks. Read-side contract: per
    timestamp, the sample from the chunk with the LATER end_ts wins (the
    more complete computation — a partial period's value is superseded as
    its raw inputs fill in), ties broken by row count; exact duplicates
    collapse to one. Chunk sets with no time overlap — the normal raw
    path — are untouched. Trimmed chunks keep decoded arrays only;
    re-encoding happens at next flush as usual."""
    chunks = part.chunks
    if len(chunks) < 2 or not any(
        chunks[i].start_ts <= chunks[i - 1].end_ts for i in range(1, len(chunks))
    ):
        return
    claimed: set[int] = set()
    kept = []
    for c in sorted(chunks, key=lambda c: (c.end_ts, c.n), reverse=True):
        ts = np.asarray(c.column("timestamp"))
        mask = np.fromiter((int(t) not in claimed for t in ts), bool, len(ts))
        if mask.all():
            kept.append(c)
        elif mask.any():
            cols = list((c.arrays or c.encoded).keys())
            arrays = {name: np.asarray(c.column(name))[mask] for name in cols}
            tsm = arrays["timestamp"]
            kept.append(Chunk(int(tsm[0]), int(tsm[-1]), int(mask.sum()), arrays))
        claimed.update(int(t) for t in ts)
    part.chunks = sorted(kept, key=lambda c: c.start_ts)


def recover_shard(memstore, store: ColumnStore, dataset: str, shard_num: int) -> int:
    """Rebuild a shard from the column store. Returns the min checkpointed
    offset to replay the ingestion stream from (-1 if none)."""
    shard = memstore.shard(dataset, shard_num)
    # 1. partkeys -> partitions + index (reference bootstrapPartKey:797)
    for rec in store.read_partkeys(dataset, shard_num):
        tags = rec["tags"]
        from ..core.schemas import canonical_partkey

        pk = canonical_partkey(tags)
        if pk not in shard._by_partkey:
            # schema resolved when chunks arrive; default gauge until then.
            # Index with the persisted start/end times (reference
            # bootstrapPartKey:797 carries the partkey table's time range);
            # a resumed ingest reactivates the end-time sentinel.
            from ..core.schemas import GAUGE

            shard._create_partition(
                tags, GAUGE, pk,
                start_ts=int(rec.get("start", 0)), end_ts=int(rec.get("end", 2**62)),
            )
    # 2. chunks -> partitions (decoded on load; re-encode happens on flush)
    from ..core.encodings import decode

    for header, schema_name, encs in store.read_chunks(dataset, shard_num):
        tags = header["tags"]
        from ..core.schemas import canonical_partkey

        pk = canonical_partkey(tags)
        schema = SCHEMAS[schema_name]
        pid = shard._by_partkey.get(pk)
        if pid is None:
            pid = shard._create_partition(tags, schema, pk, start_ts=int(header["start"]))
        part = shard.partitions[pid]
        part.schema = schema
        arrays = {}
        for col_name, enc in zip(header["cols"], encs):
            a = decode(enc)
            col = schema.column(col_name)
            from ..core.schemas import ColumnType

            if col.ctype == ColumnType.DOUBLE:
                a = a.astype(np.float64, copy=False)
            arrays[col_name] = a
        chunk = Chunk(header["start"], header["end"], header["n"], arrays, dict(zip(header["cols"], encs)))
        # insert maintaining time order; chunks persisted in seal order so
        # append + occasional sort is enough
        part.chunks.append(chunk)
        part.mark_flushed(chunk.end_ts)
        shard.evictable.offer(part.part_id)  # recovered chunks are reclaimable
    for part in shard.partitions.values():
        part.chunks.sort(key=lambda c: c.start_ts)
        _reconcile_chunks(part)
    with shard._lock:
        shard.version += 1
        shard._record_effect(0, 0, True)
        shard._clear_stage_cache()
    # 3. checkpoints -> replay offset (reference: replay from min(checkpoints))
    cps = store.read_checkpoints(dataset, shard_num)
    return min(cps.values()) if cps else -1
