"""Persistence layer (reference L3: store/ChunkSink.scala, ChunkSource.scala,
cassandra/CassandraColumnStore.scala:55 — chunk tables, partkey tables,
checkpoint table).

The durable backend here is a local filesystem layout (object-store-shaped:
one append-only segment file per (shard, flush-group) plus partkey and
checkpoint JSON journals) standing in for Cassandra. The API mirrors the
reference's ColumnStore so a different backend can slot in.

Layout under root/:
  <dataset>/shard-<n>/chunks-g<g>.seg   — framed encoded chunk sets
  <dataset>/shard-<n>/partkeys.jsonl    — partkey journal (tags, start, end)
  <dataset>/checkpoints.json            — (shard, group) -> offset
"""

from __future__ import annotations

import json
import os
import struct
import threading
from dataclasses import dataclass
from typing import Iterable, Mapping, Sequence

import numpy as np

from ..core.encodings import Encoded
from ..core.schemas import SCHEMAS, Schema
from ..memstore.partition import Chunk

_FRAME = struct.Struct("<IHH")  # payload len, schema_id, n_columns


class ColumnStore:
    """Write/read API (reference ChunkSink + ChunkSource raw reads)."""

    def write_chunks(self, dataset, shard, group, part_id, partkey_tags, schema, chunks):
        raise NotImplementedError

    def write_partkey(self, dataset, shard, tags, start_ts, end_ts):
        raise NotImplementedError

    def write_checkpoint(self, dataset, shard, group, offset):
        raise NotImplementedError

    def read_checkpoints(self, dataset, shard) -> dict[int, int]:
        raise NotImplementedError

    def read_partkeys(self, dataset, shard) -> list[dict]:
        raise NotImplementedError

    def read_chunks(self, dataset, shard) -> Iterable[tuple[dict, str, list[dict]]]:
        raise NotImplementedError


class NullColumnStore(ColumnStore):
    """In-memory no-op sink so shards and queries run without persistence
    (reference NullColumnStore, ChunkSink.scala:159)."""

    def __init__(self):
        self.chunks_written = 0
        self.partkeys_written = 0
        self.checkpoints: dict = {}

    def write_chunks(self, dataset, shard, group, part_id, partkey_tags, schema, chunks):
        self.chunks_written += len(chunks)

    def write_partkey(self, dataset, shard, tags, start_ts, end_ts):
        self.partkeys_written += 1

    def write_checkpoint(self, dataset, shard, group, offset):
        self.checkpoints[(dataset, shard, group)] = offset

    def read_checkpoints(self, dataset, shard):
        return {
            g: off
            for (d, s, g), off in self.checkpoints.items()
            if d == dataset and s == shard
        }

    def read_partkeys(self, dataset, shard):
        return []

    def read_chunks(self, dataset, shard):
        return []


FORMAT_VERSION = 1


class LocalColumnStore(ColumnStore):
    def __init__(self, root: str):
        self.root = root
        self._lock = threading.Lock()
        os.makedirs(root, exist_ok=True)
        # store format versioning (refuse to misread future layouts)
        vpath = os.path.join(root, "FORMAT")
        if os.path.exists(vpath):
            with open(vpath) as f:
                ver = int(f.read().strip() or 1)
            if ver > FORMAT_VERSION:
                raise ValueError(
                    f"store at {root} has format v{ver}; this build reads <= v{FORMAT_VERSION}"
                )
        else:
            with open(vpath, "w") as f:
                f.write(str(FORMAT_VERSION))

    def _shard_dir(self, dataset, shard) -> str:
        d = os.path.join(self.root, dataset, f"shard-{shard}")
        os.makedirs(d, exist_ok=True)
        return d

    # -- writes ----------------------------------------------------------

    def write_chunks(self, dataset, shard, group, part_id, partkey_tags, schema: Schema,
                     chunks: Sequence[Chunk]):
        """Append framed encoded chunk sets (reference
        CassandraColumnStore.write:207)."""
        path = os.path.join(self._shard_dir(dataset, shard), f"chunks-g{group}.seg")
        with self._lock, open(path, "ab") as f:
            for c in chunks:
                enc = c.ensure_encoded(schema)
                header = {
                    "tags": dict(partkey_tags),
                    "schema": schema.name,
                    "start": c.start_ts,
                    "end": c.end_ts,
                    "n": c.n,
                    "cols": list(enc.keys()),
                }
                hdr = json.dumps(header).encode()
                payloads = [e.to_bytes() for e in enc.values()]
                f.write(_FRAME.pack(len(hdr), schema.schema_id, len(payloads)))
                f.write(struct.pack("<I", len(hdr)))
                f.write(hdr)
                for p in payloads:
                    f.write(struct.pack("<I", len(p)))
                    f.write(p)

    def write_partkey(self, dataset, shard, tags, start_ts, end_ts):
        path = os.path.join(self._shard_dir(dataset, shard), "partkeys.jsonl")
        with self._lock, open(path, "a") as f:
            f.write(json.dumps({"tags": dict(tags), "start": int(start_ts), "end": int(end_ts)}) + "\n")

    def write_checkpoint(self, dataset, shard, group, offset):
        """reference CheckpointTable: per (dataset, shard, group) offsets."""
        path = os.path.join(self.root, dataset, "checkpoints.json")
        with self._lock:
            data = {}
            if os.path.exists(path):
                with open(path) as f:
                    data = json.load(f)
            data[f"{shard}/{group}"] = int(offset)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, path)

    # -- reads -----------------------------------------------------------

    def read_checkpoints(self, dataset, shard) -> dict[int, int]:
        path = os.path.join(self.root, dataset, "checkpoints.json")
        if not os.path.exists(path):
            return {}
        with open(path) as f:
            data = json.load(f)
        out = {}
        for k, v in data.items():
            s, g = k.split("/")
            if int(s) == shard:
                out[int(g)] = v
        return out

    def read_partkeys(self, dataset, shard) -> list[dict]:
        path = os.path.join(self.root, dataset, f"shard-{shard}", "partkeys.jsonl")
        if not os.path.exists(path):
            return []
        out: dict[str, dict] = {}
        with open(path) as f:
            for line in f:
                rec = json.loads(line)
                key = json.dumps(rec["tags"], sort_keys=True)
                out[key] = rec  # last write wins (end-time updates)
        return list(out.values())

    def read_chunks(self, dataset, shard):
        """Yield (header, schema_name, [Encoded per column]) for every chunk
        set in the shard (reference readRawPartitions:774).

        A truncated tail (crash mid-append) ends that segment's iteration
        cleanly — everything before the torn frame is served; the next flush
        appends after it (the torn frame is bounded garbage the reader skips
        forever, matching the reference's torn-write tolerance)."""
        d = os.path.join(self.root, dataset, f"shard-{shard}")
        if not os.path.isdir(d):
            return
        for fn in sorted(os.listdir(d)):
            if not fn.startswith("chunks-"):
                continue
            with open(os.path.join(d, fn), "rb") as f:
                while True:
                    try:
                        frame = f.read(_FRAME.size)
                        if len(frame) < _FRAME.size:
                            break
                        _, schema_id, n_cols = _FRAME.unpack(frame)
                        hdr_len_raw = f.read(4)
                        if len(hdr_len_raw) < 4:
                            break
                        (hlen,) = struct.unpack("<I", hdr_len_raw)
                        hdr_raw = f.read(hlen)
                        if len(hdr_raw) < hlen:
                            break
                        header = json.loads(hdr_raw)
                        encs = []
                        torn = False
                        for _ in range(n_cols):
                            plen_raw = f.read(4)
                            if len(plen_raw) < 4:
                                torn = True
                                break
                            (plen,) = struct.unpack("<I", plen_raw)
                            payload = f.read(plen)
                            if len(payload) < plen:
                                torn = True
                                break
                            encs.append(Encoded.from_bytes(payload))
                        if torn:
                            break
                    except (json.JSONDecodeError, struct.error, ValueError):
                        break  # corrupted frame: stop this segment
                    yield header, header["schema"], encs
