"""Persistence layer (reference L3: store/ChunkSink.scala, ChunkSource.scala,
cassandra/CassandraColumnStore.scala:55 — chunk tables, partkey tables,
checkpoint table).

The durable backend here is a local filesystem layout (object-store-shaped:
one append-only segment file per (shard, flush-group) plus partkey and
checkpoint JSON journals) standing in for Cassandra. The API mirrors the
reference's ColumnStore so a different backend can slot in.

Layout under root/:
  <dataset>/shard-<n>/chunks-g<g>.seg   — framed encoded chunk sets
  <dataset>/shard-<n>/partkeys.jsonl    — partkey journal (tags, start, end)
  <dataset>/checkpoints.json            — (shard, group) -> offset
"""

from __future__ import annotations

import io
import json
import os
import struct
import threading
from dataclasses import dataclass
from typing import Iterable, Mapping, Sequence

import numpy as np

from ..core.encodings import Encoded
from ..core.schemas import SCHEMAS, Schema
from ..memstore.partition import Chunk

_FRAME = struct.Struct("<IHH")  # payload len, schema_id, n_columns


def torn_final_line(path: str) -> bool:
    """A crashed writer can leave a jsonl journal without a trailing
    newline; the next append must write ``\\n`` first or its first record
    merges into the half-written line and corrupts ONE entry. True when
    that guard byte is needed."""
    if not os.path.exists(path) or os.path.getsize(path) == 0:
        return False
    with open(path, "rb") as chk:
        chk.seek(-1, os.SEEK_END)
        return chk.read(1) != b"\n"


class ColumnStore:
    """Write/read API (reference ChunkSink + ChunkSource raw reads)."""

    def write_chunks(self, dataset, shard, group, part_id, partkey_tags, schema, chunks):
        raise NotImplementedError

    def write_partkey(self, dataset, shard, tags, start_ts, end_ts):
        raise NotImplementedError

    def write_checkpoint(self, dataset, shard, group, offset):
        raise NotImplementedError

    def read_checkpoints(self, dataset, shard) -> dict[int, int]:
        raise NotImplementedError

    def read_partkeys(self, dataset, shard) -> list[dict]:
        raise NotImplementedError

    def read_chunks(self, dataset, shard) -> Iterable[tuple[dict, str, list[dict]]]:
        raise NotImplementedError

    def read_chunks_selective(
        self, dataset, shard, partkeys, start_ms: int, end_ms: int
    ) -> Iterable[tuple[dict, str, list]]:
        """Read only chunk sets belonging to ``partkeys`` (canonical partkey
        bytes) overlapping [start_ms, end_ms] (reference readRawPartitions:774
        reads per-partition row ranges, not the whole table). Default: filter
        over the full scan; backends with a manifest seek directly."""
        from ..core.schemas import canonical_partkey

        want = set(partkeys)
        for header, schema_name, encs in self.read_chunks(dataset, shard):
            if header["end"] < start_ms or header["start"] > end_ms:
                continue
            if canonical_partkey(header["tags"]) in want:
                yield header, schema_name, encs


class NullColumnStore(ColumnStore):
    """In-memory no-op sink so shards and queries run without persistence
    (reference NullColumnStore, ChunkSink.scala:159)."""

    def __init__(self):
        self.chunks_written = 0
        self.partkeys_written = 0
        self.checkpoints: dict = {}

    def write_chunks(self, dataset, shard, group, part_id, partkey_tags, schema, chunks):
        self.chunks_written += len(chunks)

    def write_partkey(self, dataset, shard, tags, start_ts, end_ts):
        self.partkeys_written += 1

    def write_checkpoint(self, dataset, shard, group, offset):
        self.checkpoints[(dataset, shard, group)] = offset

    def read_checkpoints(self, dataset, shard):
        return {
            g: off
            for (d, s, g), off in self.checkpoints.items()
            if d == dataset and s == shard
        }

    def read_partkeys(self, dataset, shard):
        return []

    def read_chunks(self, dataset, shard):
        return []


FORMAT_VERSION = 1


def _iter_frames(f, decode_payloads: bool = True):
    """THE segment-frame parser (single source of truth for the on-disk frame
    layout). Yields ``(offset, length, header, encs)`` for each complete frame
    from the file's current position; ``encs`` is None when
    ``decode_payloads`` is False. Stops cleanly at the first torn or corrupt
    frame (reference torn-write tolerance)."""
    while True:
        off = f.tell()
        try:
            frame = f.read(_FRAME.size)
            if len(frame) < _FRAME.size:
                return
            _, _schema_id, n_cols = _FRAME.unpack(frame)
            hdr_len_raw = f.read(4)
            if len(hdr_len_raw) < 4:
                return
            (hlen,) = struct.unpack("<I", hdr_len_raw)
            hdr_raw = f.read(hlen)
            if len(hdr_raw) < hlen:
                return
            header = json.loads(hdr_raw)
            encs = [] if decode_payloads else None
            for _ in range(n_cols):
                plen_raw = f.read(4)
                if len(plen_raw) < 4:
                    return
                (plen,) = struct.unpack("<I", plen_raw)
                payload = f.read(plen)
                if len(payload) < plen:
                    return
                if decode_payloads:
                    encs.append(Encoded.from_bytes(payload))
        except (json.JSONDecodeError, struct.error, ValueError, KeyError):
            return
        yield off, f.tell() - off, header, encs


class LocalColumnStore(ColumnStore):
    def __init__(self, root: str):
        self.root = root
        self._lock = threading.Lock()
        # selective-read instrumentation + cached parsed manifests
        self.stats_selective_bytes = 0
        self._manifest_cache: dict[tuple[str, int], tuple[float, int, list]] = {}
        os.makedirs(root, exist_ok=True)
        # store format versioning (refuse to misread future layouts)
        vpath = os.path.join(root, "FORMAT")
        if os.path.exists(vpath):
            with open(vpath) as f:
                ver = int(f.read().strip() or 1)
            if ver > FORMAT_VERSION:
                raise ValueError(
                    f"store at {root} has format v{ver}; this build reads <= v{FORMAT_VERSION}"
                )
        else:
            with open(vpath, "w") as f:
                f.write(str(FORMAT_VERSION))

    def _shard_dir(self, dataset, shard) -> str:
        d = os.path.join(self.root, dataset, f"shard-{shard}")
        os.makedirs(d, exist_ok=True)
        return d

    # -- writes ----------------------------------------------------------

    def write_chunks(self, dataset, shard, group, part_id, partkey_tags, schema: Schema,
                     chunks: Sequence[Chunk]):
        """Append framed encoded chunk sets (reference
        CassandraColumnStore.write:207). Each frame's (partkey-hash, segment,
        byte offset/length, time range) is journaled to the shard manifest so
        selective ODP reads can seek straight to the needed frames (the
        reference's per-partition Cassandra row keys play this role). Manifest
        lines are written after their frames in program order, but OS flush
        ordering is not guaranteed — the selective reader therefore treats
        every entry as untrusted and skips frames that fail to parse."""
        from ..core.schemas import canonical_partkey, hash64

        seg = f"chunks-g{group}.seg"
        path = os.path.join(self._shard_dir(dataset, shard), seg)
        mpath = os.path.join(self._shard_dir(dataset, shard), "manifest.jsonl")
        pk_hex = f"{hash64(canonical_partkey(partkey_tags)):016x}"
        with self._lock:
            # upgrading a pre-manifest shard: backfill the manifest from the
            # existing segments ONCE, or selective reads would silently hide
            # every chunk written before the upgrade
            if not os.path.exists(mpath) and any(
                fn.startswith("chunks-") for fn in os.listdir(self._shard_dir(dataset, shard))
            ):
                self._backfill_manifest(dataset, shard, mpath)
        with self._lock, open(path, "ab") as f, open(mpath, "ab") as mf:
            if torn_final_line(mpath):
                mf.write(b"\n")
            for c in chunks:
                enc = c.ensure_encoded(schema)
                header = {
                    "tags": dict(partkey_tags),
                    "schema": schema.name,
                    "start": c.start_ts,
                    "end": c.end_ts,
                    "n": c.n,
                    "cols": list(enc.keys()),
                }
                hdr = json.dumps(header).encode()
                payloads = [e.to_bytes() for e in enc.values()]
                off = f.tell()
                f.write(_FRAME.pack(len(hdr), schema.schema_id, len(payloads)))
                f.write(struct.pack("<I", len(hdr)))
                f.write(hdr)
                for p in payloads:
                    f.write(struct.pack("<I", len(p)))
                    f.write(p)
                mf.write((json.dumps({
                    "pk": pk_hex, "seg": seg, "off": off, "len": f.tell() - off,
                    "start": c.start_ts, "end": c.end_ts,
                }) + "\n").encode())
            self._manifest_cache.pop((dataset, shard), None)

    def _backfill_manifest(self, dataset, shard, mpath):
        """One-time manifest build for a shard written before manifests
        existed: scan every segment frame, recording offsets. Written to a
        temp file then renamed so a crash mid-backfill retries cleanly."""
        from ..core.schemas import canonical_partkey, hash64

        d = os.path.dirname(mpath)
        tmp = mpath + ".tmp"
        with open(tmp, "w") as mf:
            for fn in sorted(os.listdir(d)):
                if not fn.startswith("chunks-"):
                    continue
                with open(os.path.join(d, fn), "rb") as f:
                    for off, length, header, _ in _iter_frames(f, decode_payloads=False):
                        pk_hex = f"{hash64(canonical_partkey(header['tags'])):016x}"
                        mf.write(json.dumps({
                            "pk": pk_hex, "seg": fn, "off": off, "len": length,
                            "start": header["start"], "end": header["end"],
                        }) + "\n")
        os.replace(tmp, mpath)
        self._manifest_cache.pop((dataset, shard), None)

    def write_partkey(self, dataset, shard, tags, start_ts, end_ts):
        path = os.path.join(self._shard_dir(dataset, shard), "partkeys.jsonl")
        with self._lock, open(path, "a") as f:
            f.write(json.dumps({"tags": dict(tags), "start": int(start_ts), "end": int(end_ts)}) + "\n")

    def write_checkpoint(self, dataset, shard, group, offset):
        """reference CheckpointTable: per (dataset, shard, group) offsets."""
        path = os.path.join(self.root, dataset, "checkpoints.json")
        with self._lock:
            data = {}
            if os.path.exists(path):
                with open(path) as f:
                    data = json.load(f)
            data[f"{shard}/{group}"] = int(offset)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, path)

    # -- reads -----------------------------------------------------------

    def read_checkpoints(self, dataset, shard) -> dict[int, int]:
        path = os.path.join(self.root, dataset, "checkpoints.json")
        if not os.path.exists(path):
            return {}
        with open(path) as f:
            data = json.load(f)
        out = {}
        for k, v in data.items():
            s, g = k.split("/")
            if int(s) == shard:
                out[int(g)] = v
        return out

    def read_partkeys(self, dataset, shard) -> list[dict]:
        path = os.path.join(self.root, dataset, f"shard-{shard}", "partkeys.jsonl")
        if not os.path.exists(path):
            return []
        out: dict[str, dict] = {}
        with open(path) as f:
            for line in f:
                rec = json.loads(line)
                key = json.dumps(rec["tags"], sort_keys=True)
                out[key] = rec  # last write wins (end-time updates)
        return list(out.values())

    def read_chunks(self, dataset, shard):
        """Yield (header, schema_name, [Encoded per column]) for every chunk
        set in the shard (reference readRawPartitions:774).

        A truncated tail (crash mid-append) ends that segment's iteration
        cleanly — everything before the torn frame is served; the next flush
        appends after it (the torn frame is bounded garbage the reader skips
        forever, matching the reference's torn-write tolerance)."""
        d = os.path.join(self.root, dataset, f"shard-{shard}")
        if not os.path.isdir(d):
            return
        for fn in sorted(os.listdir(d)):
            if not fn.startswith("chunks-"):
                continue
            with open(os.path.join(d, fn), "rb") as f:
                for _off, _len, header, encs in _iter_frames(f):
                    yield header, header["schema"], encs

    def _manifest(self, dataset, shard) -> list[dict] | None:
        """Parsed manifest entries for a shard, cached by (mtime, size).
        None when the shard predates manifests (callers full-scan)."""
        mpath = os.path.join(self.root, dataset, f"shard-{shard}", "manifest.jsonl")
        if not os.path.exists(mpath):
            return None
        key = (dataset, shard)
        st = os.stat(mpath)
        cached = self._manifest_cache.get(key)
        if cached is not None and cached[0] == st.st_mtime and cached[1] == st.st_size:
            return cached[2]
        # hold the store lock for the read+repair: write_chunks appends the
        # segment frame and its manifest line under the same lock, so a
        # repair scan can never mistake a mid-flush frame for an orphan (and
        # append a duplicate entry), and the stat taken under the lock is
        # consistent with what was read
        with self._lock:
            st = os.stat(mpath)
            entries = []
            with open(mpath) as f:
                for line in f:
                    try:
                        entries.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # torn/merged line: later appends stay visible
            repaired = self._repair_manifest(dataset, shard, mpath, entries)
            if repaired:
                entries.extend(repaired)
                st = os.stat(mpath)  # repair appended under this same lock
            self._manifest_cache[key] = (st.st_mtime, st.st_size, entries)
        return entries

    def _repair_manifest(self, dataset, shard, mpath, entries) -> list[dict]:
        """Re-index segment bytes beyond what the manifest covers (a crash
        between the segment append and the manifest append orphans the frame;
        OS flush ordering between the two files is not guaranteed either).
        Parses frames from the first uncovered offset; appends recovered
        entries to the manifest. Torn garbage at the boundary ends the scan,
        exactly like the full-scan reader. Caller MUST hold self._lock."""
        from ..core.schemas import canonical_partkey, hash64

        d = os.path.dirname(mpath)
        by_seg: dict[str, list[tuple[int, int]]] = {}
        for e in entries:
            by_seg.setdefault(e["seg"], []).append((e["off"], e["off"] + e["len"]))
        recovered = []
        for fn in sorted(os.listdir(d)):
            if not fn.startswith("chunks-"):
                continue
            path = os.path.join(d, fn)
            size = os.path.getsize(path)
            # uncovered byte ranges of this segment (an orphan can sit BETWEEN
            # covered frames when later appends succeeded after the crash)
            holes: list[tuple[int, int]] = []
            pos = 0
            for o, end in sorted(by_seg.get(fn, ())):
                if o > pos:
                    holes.append((pos, o))
                pos = max(pos, end)
            if size > pos:
                holes.append((pos, size))
            if not holes:
                continue
            with open(path, "rb") as f:
                for hs, he in holes:
                    f.seek(hs)
                    for off, length, header, _ in _iter_frames(f, decode_payloads=False):
                        if off + length > he:
                            break
                        pk_hex = f"{hash64(canonical_partkey(header['tags'])):016x}"
                        recovered.append({
                            "pk": pk_hex, "seg": fn, "off": off, "len": length,
                            "start": header["start"], "end": header["end"],
                        })
        if recovered:
            with open(mpath, "a") as mf:
                for e in recovered:
                    mf.write(json.dumps(e) + "\n")
        return recovered

    def read_chunks_selective(self, dataset, shard, partkeys, start_ms, end_ms):
        """Manifest-seek read: only frames of the requested partkeys
        overlapping the time range are read and decoded (reference
        OnDemandPagingShard.scala:147 + readRawPartitions:774 read only the
        needed partitions/rows). Falls back to the filtering full scan for
        pre-manifest stores."""
        from ..core.schemas import canonical_partkey, hash64

        entries = self._manifest(dataset, shard)
        if entries is None:
            yield from super().read_chunks_selective(dataset, shard, partkeys, start_ms, end_ms)
            return
        want = {f"{hash64(pk):016x}" for pk in partkeys}
        pk_bytes = set(partkeys)
        by_seg: dict[str, list[dict]] = {}
        for e in entries:
            if e["pk"] in want and e["end"] >= start_ms and e["start"] <= end_ms:
                by_seg.setdefault(e["seg"], []).append(e)
        d = os.path.join(self.root, dataset, f"shard-{shard}")
        for seg, hits in sorted(by_seg.items()):
            hits.sort(key=lambda e: e["off"])
            try:
                f = open(os.path.join(d, seg), "rb")
            except OSError:
                continue  # entry outlived its segment (manifest is a journal)
            with f:
                for e in hits:
                    f.seek(e["off"])
                    raw = f.read(e["len"])
                    if len(raw) < e["len"]:
                        continue  # torn frame
                    self.stats_selective_bytes += len(raw)
                    # a stale manifest entry (manifest durable, frame torn,
                    # then overwritten by a later append) yields garbage here
                    # — _iter_frames stops without yielding and we skip it,
                    # like the full-scan reader does
                    got = next(_iter_frames(io.BytesIO(raw)), None)
                    if got is None:
                        continue
                    _, _, header, encs = got
                    # 64-bit hash collisions are ~impossible at TSDB scale but
                    # cheap to exclude exactly
                    if canonical_partkey(header["tags"]) not in pk_bytes:
                        continue
                    yield header, header["schema"], encs
