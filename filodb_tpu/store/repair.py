"""Data repair / maintenance jobs (reference spark-jobs:
repair/ChunkCopier + PartitionKeysCopier (cross-cluster data migration),
cardbuster/CardinalityBusterMain (delete partkeys matching filters),
DSIndexJob (copy partkey updates to downsample keyspace)).

Host-side batch jobs over the column store — no Spark needed at this scale;
each job streams segments and is restartable.
"""

from __future__ import annotations

from typing import Sequence

from ..core.encodings import Encoded
from ..core.filters import ColumnFilter
from ..core.schemas import SCHEMAS, canonical_partkey
from .columnstore import ColumnStore, LocalColumnStore


def copy_chunks(
    src: ColumnStore, dst: ColumnStore, dataset: str, shard_nums: Sequence[int],
    start_ms: int | None = None, end_ms: int | None = None,
) -> int:
    """Copy chunk sets between stores, optionally time-filtered (reference
    ChunkCopier — used for cluster migration / repair)."""
    n = 0
    for shard in shard_nums:
        for header, schema_name, encs in src.read_chunks(dataset, shard):
            if start_ms is not None and header["end"] < start_ms:
                continue
            if end_ms is not None and header["start"] > end_ms:
                continue
            schema = SCHEMAS.get(schema_name)
            if schema is None:
                continue
            # re-frame into the destination (decoded form not needed)
            from ..memstore.partition import Chunk

            chunk = Chunk(
                header["start"], header["end"], header["n"], None,
                dict(zip(header["cols"], encs)),
            )
            dst.write_chunks(dataset, shard, 0, -1, header["tags"], schema, [chunk])
            n += 1
    return n


def copy_partkeys(
    src: ColumnStore, dst: ColumnStore, dataset: str, shard_nums: Sequence[int]
) -> int:
    """reference PartitionKeysCopier / DSIndexJob."""
    n = 0
    for shard in shard_nums:
        for rec in src.read_partkeys(dataset, shard):
            dst.write_partkey(dataset, shard, rec["tags"], rec["start"], rec["end"])
            n += 1
    return n


def bust_cardinality(
    store: LocalColumnStore, dataset: str, shard_nums: Sequence[int],
    filters: Sequence[ColumnFilter],
) -> int:
    """Delete partkeys (and their chunks) matching the filters (reference
    CardinalityBusterMain — the escape hatch for cardinality explosions).
    Rewrites the shard segments without the matching series; returns series
    deleted."""
    import json
    import os

    deleted = 0
    for shard in shard_nums:
        victims: set[bytes] = set()
        for rec in store.read_partkeys(dataset, shard):
            tags = rec["tags"]
            if all(f.matches(tags.get(f.column)) for f in filters):
                victims.add(canonical_partkey(tags))
        if not victims:
            continue
        deleted += len(victims)
        # rewrite partkey journal
        d = store._shard_dir(dataset, shard)
        pk_path = os.path.join(d, "partkeys.jsonl")
        keep = [
            rec for rec in store.read_partkeys(dataset, shard)
            if canonical_partkey(rec["tags"]) not in victims
        ]
        with open(pk_path, "w") as f:
            for rec in keep:
                f.write(json.dumps(rec) + "\n")
        # rewrite chunk segments without victim series
        chunks = [
            (header, schema_name, encs)
            for header, schema_name, encs in store.read_chunks(dataset, shard)
            if canonical_partkey(header["tags"]) not in victims
        ]
        for fn in os.listdir(d):
            if fn.startswith("chunks-"):
                os.remove(os.path.join(d, fn))
        from ..memstore.partition import Chunk

        for header, schema_name, encs in chunks:
            schema = SCHEMAS.get(schema_name)
            if schema is None:
                continue
            chunk = Chunk(header["start"], header["end"], header["n"], None,
                          dict(zip(header["cols"], encs)))
            store.write_chunks(dataset, shard, 0, -1, header["tags"], schema, [chunk])
    return deleted
