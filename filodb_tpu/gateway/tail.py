"""File-tailing ingestion stream (the Kafka-shaped transport for this
image: an append-only JSONL log on shared storage; offsets are line
numbers, replay is a seek — the same recovery contract as
KafkaIngestionStream.scala:26 manual commits).

Record format per line: {"metric", "tags", "ts_ms", "value"} or a batch
{"batch": [records...]}. ``follow()`` keeps reading as the file grows
(consumer-group-of-one semantics).
"""

from __future__ import annotations

import json
import os
import time
from typing import Iterator

import numpy as np

from ..core.records import RecordBatch
from ..core.schemas import GAUGE, METRIC_TAG
from .stream import IngestionStream


def _to_batch(records: list[dict]) -> RecordBatch:
    tags_list, ts, vals = [], [], []
    for rec in records:
        tags = dict(rec.get("tags", {}))
        metric = rec.get("metric") or tags.get("__name__") or tags.get(METRIC_TAG, "unknown")
        tags.pop("__name__", None)
        tags[METRIC_TAG] = metric
        tags_list.append(tags)
        ts.append(int(rec["ts_ms"]))
        vals.append(float(rec["value"]))
    return RecordBatch(
        GAUGE, np.asarray(ts, dtype=np.int64),
        {"value": np.asarray(vals, dtype=np.float64)}, tags_list,
    )


class JsonlTailStream(IngestionStream):
    def __init__(self, path: str, batch_lines: int = 500):
        self.path = path
        self.batch_lines = batch_lines

    def batches(self, from_offset: int = 0) -> Iterator[tuple[int, RecordBatch]]:
        """One pass over the current file contents (no follow)."""
        if not os.path.exists(self.path):
            return
        with open(self.path) as f:
            yield from self._consume(f, from_offset, follow=False, stop=lambda: True)

    def follow(self, from_offset: int = 0, poll_s: float = 0.2,
               stop=lambda: False) -> Iterator[tuple[int, RecordBatch]]:
        """Tail the file as it grows until ``stop()`` returns True."""
        while not os.path.exists(self.path):
            if stop():
                return
            time.sleep(poll_s)
        with open(self.path) as f:
            yield from self._consume(f, from_offset, follow=True, stop=stop, poll_s=poll_s)

    def _consume(self, f, from_offset, follow, stop, poll_s: float = 0.2):
        offset = 0
        buf: list[dict] = []
        buf_start = 0
        while True:
            line = f.readline()
            if not line:
                if buf:
                    yield offset - 1, _to_batch(buf)
                    buf = []
                if not follow or stop():
                    return
                time.sleep(poll_s)
                continue
            if not line.endswith("\n") and follow:
                # partial line still being written: rewind and retry
                f.seek(f.tell() - len(line))
                time.sleep(poll_s)
                continue
            if offset >= from_offset and line.strip():
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    rec = None
                if rec:
                    if not buf:
                        buf_start = offset
                    if "batch" in rec:
                        buf.extend(rec["batch"])
                    else:
                        buf.append(rec)
            offset += 1
            if len(buf) >= self.batch_lines:
                yield offset - 1, _to_batch(buf)
                buf = []
