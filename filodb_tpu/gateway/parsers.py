"""Ingest line-protocol parsers (reference L7 gateway/:
InfluxProtocolParser.scala / InputRecord.scala:15 PrometheusInputRecord —
Influx line protocol and Prometheus text exposition -> ingestion records).
"""

from __future__ import annotations

import math
import re
from typing import Iterable

import numpy as np

from ..core.records import RecordBatch
from ..core.schemas import GAUGE, METRIC_TAG, PROM_COUNTER, Schema


def _unescape(s: str) -> str:
    return s.replace("\\,", ",").replace("\\ ", " ").replace("\\=", "=").replace('\\"', '"')


_INFLUX_SPLIT = re.compile(r"(?<!\\) ")
_COMMA_SPLIT = re.compile(r"(?<!\\),")


def parse_influx_line(line: str):
    """One Influx line: measurement[,tag=v...] field=v[,field=v...] [ts_ns].

    Yields (metric, tags, ts_ms, value) per numeric field; measurement
    becomes the metric prefix for non-'value' fields (reference
    InfluxProtocolParser field handling).
    """
    line = line.strip()
    if not line or line.startswith("#"):
        return
    parts = _INFLUX_SPLIT.split(line)
    if len(parts) < 2:
        raise ValueError(f"bad influx line: {line!r}")
    key_part, field_part = parts[0], parts[1]
    ts_ms = int(parts[2]) // 1_000_000 if len(parts) > 2 else None
    key_items = _COMMA_SPLIT.split(key_part)
    measurement = _unescape(key_items[0])
    tags = {}
    for item in key_items[1:]:
        k, _, v = item.partition("=")
        tags[_unescape(k)] = _unescape(v)
    for fv in _COMMA_SPLIT.split(field_part):
        k, _, v = fv.partition("=")
        k = _unescape(k)
        v = v.strip()
        if v.endswith("i"):
            val = float(v[:-1])
        elif v in ("t", "T", "true", "True"):
            val = 1.0
        elif v in ("f", "F", "false", "False"):
            val = 0.0
        elif v.startswith('"'):
            continue  # string fields are not time series values
        else:
            val = float(v)
        metric = measurement if k == "value" else f"{measurement}_{k}"
        yield metric, dict(tags), ts_ms, val


_PROM_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)(?:\s+(?P<ts>-?\d+))?$"
)
_PROM_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:\\.|[^"\\])*)"')


_EXEMPLAR = re.compile(
    r"^\{(?P<labels>.*)\}\s+(?P<value>\S+)(?:\s+(?P<ts>-?\d+(?:\.\d+)?))?$"
)


def _parse_labels(s: str) -> dict:
    return {
        lm.group(1): lm.group(2).encode().decode("unicode_escape")
        for lm in _PROM_LABEL.finditer(s)
    }


def _parse_sample_line(line: str):
    """One sample line -> (name, tags, ts_ms|None, value, exemplar|None).

    The OpenMetrics exemplar suffix `# {labels} value [ts]` is accepted ONLY
    when both halves parse on their own; otherwise the whole line must match
    (so legal label values containing ' # {' keep working, and a greedy
    label match can never swallow a real exemplar)."""
    exemplar = None
    m = None
    idx = line.rfind(" # {")
    if idx != -1:
        em = _EXEMPLAR.match(line[idx + 3:])
        m2 = _PROM_LINE.match(line[:idx].rstrip())
        if em and m2:
            ex_ts = em.group("ts")
            exemplar = (
                _parse_labels(em.group("labels")),
                float(em.group("value")),
                int(float(ex_ts) * 1000) if ex_ts else None,
            )
            m = m2
    if m is None:
        m = _PROM_LINE.match(line)
    if not m:
        raise ValueError(f"bad prometheus line: {line!r}")
    name = m.group("name")
    tags = _parse_labels(m.group("labels")) if m.group("labels") else {}
    vs = m.group("value")
    val = float("nan") if vs in ("NaN", "nan") else float(vs)
    ts_ms = int(m.group("ts")) if m.group("ts") else None
    return name, tags, ts_ms, val, exemplar


def _series_type(name: str, types: dict[str, str]) -> str:
    """Resolve a sample's type from the TYPE table, understanding family
    suffixes: a ``# TYPE m histogram|summary`` family exposes ``m_bucket``/
    ``m_count``/``m_sum`` series, which are cumulative — counter semantics
    (Prometheus treats them so for rate()); OpenMetrics counters declare the
    family WITHOUT the ``_total`` their samples carry."""
    t = types.get(name)
    if t is not None:
        return t
    for suffix in ("_bucket", "_count", "_sum"):
        if name.endswith(suffix):
            if types.get(name[: -len(suffix)]) in ("histogram", "summary"):
                return "counter"
    if name.endswith("_total") and types.get(name[:-6]) == "counter":
        return "counter"
    return "untyped"


def parse_prom_text(text: str, with_exemplars: bool = False):
    """Prometheus exposition format -> (metric, tags, ts_ms, value, type)
    tuples; with ``with_exemplars`` a sixth element carries the OpenMetrics
    exemplar ``(labels, value, ts_ms|None)`` or None. TYPE comments steer
    counter/gauge schema choice."""
    types: dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE"):
            parts = line.split()
            if len(parts) >= 4:
                types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        name, tags, ts_ms, val, exemplar = _parse_sample_line(line)
        if with_exemplars:
            yield name, tags, ts_ms, val, _series_type(name, types), exemplar
        else:
            yield name, tags, ts_ms, val, _series_type(name, types)


def _native_influx_batch(text: str, default_ts_ms: int, ws: str, ns: str):
    """Native-scanner fast path (see promparse.cpp); None when unavailable.
    Tag/metric dicts come from a memo keyed by the raw (series-key, field)
    byte spans — repeated writers pay label parsing once per series."""
    from .. import native as N

    payload = text.encode()
    recs = N.parse_influx_records(payload)
    if recs is None:
        return None
    if len(_KEY_CACHE) > _KEY_CACHE_CAP:
        _KEY_CACHE.clear()
    tags_list, ts, vals = [], [], []
    for koff, klen, foff, flen, v, t, fl in zip(
        recs["key_off"].tolist(), recs["key_len"].tolist(),
        recs["field_off"].tolist(), recs["field_len"].tolist(),
        recs["value"].tolist(), recs["ts_ms"].tolist(), recs["flags"].tolist(),
    ):
        if fl & 1:  # deferred line: exact Python semantics (may raise)
            line = payload[koff:koff + klen].decode().strip()
            for metric, tags, t2, v2 in parse_influx_line(line) or ():
                full = dict(tags)
                full[METRIC_TAG] = metric
                full.setdefault("_ws_", ws)
                full.setdefault("_ns_", ns)
                tags_list.append(full)
                ts.append(t2 if t2 is not None else default_ts_ms)
                vals.append(v2)
            continue
        ck = (payload[koff:koff + klen], payload[foff:foff + flen], ws, ns)
        tmpl = _KEY_CACHE.get(ck)
        if tmpl is None:
            key_items = _COMMA_SPLIT.split(ck[0].decode())
            measurement = _unescape(key_items[0])
            tags = {}
            for item in key_items[1:]:
                k, _, vv = item.partition("=")
                tags[_unescape(k)] = _unescape(vv)
            field = _unescape(ck[1].decode())
            metric = measurement if field == "value" else f"{measurement}_{field}"
            tmpl = dict(tags)
            tmpl[METRIC_TAG] = metric
            tmpl.setdefault("_ws_", ws)
            tmpl.setdefault("_ns_", ns)
            _KEY_CACHE[ck] = tmpl
        tags_list.append(dict(tmpl))
        ts.append(t if t != N.TS_ABSENT else default_ts_ms)
        vals.append(v)
    return RecordBatch(
        GAUGE, np.asarray(ts, dtype=np.int64), {"value": np.asarray(vals)}, tags_list
    )


def influx_to_batch(lines: "Iterable[str] | str", default_ts_ms: int,
                    ws="default", ns="default") -> RecordBatch:
    """Influx line protocol -> one gauge RecordBatch. A str payload takes
    the native scanner fast path when available."""
    if isinstance(lines, str):
        native = _native_influx_batch(lines, default_ts_ms, ws, ns)
        if native is not None:
            return native
        lines = lines.splitlines()
    tags_list, ts, vals = [], [], []
    for line in lines:
        for metric, tags, t, v in parse_influx_line(line) or ():
            full = dict(tags)
            full[METRIC_TAG] = metric
            full.setdefault("_ws_", ws)
            full.setdefault("_ns_", ns)
            tags_list.append(full)
            ts.append(t if t is not None else default_ts_ms)
            vals.append(v)
    return RecordBatch(
        GAUGE, np.asarray(ts, dtype=np.int64), {"value": np.asarray(vals)}, tags_list
    )


def prom_text_to_batches(text: str, default_ts_ms: int, ws="default", ns="default") -> list[RecordBatch]:
    """Split by schema: counters -> prom-counter, rest -> gauge."""
    return prom_text_to_batches_and_exemplars(text, default_ts_ms, ws, ns)[0]


# cross-call series-key memo for the native scanner: the SAME exposition keys
# arrive every scrape interval, so label parsing is O(new series). Template
# dicts are copied before use; cleared when it outgrows the cap.
_KEY_CACHE: dict[tuple, dict] = {}
_KEY_CACHE_CAP = 500_000


def _native_prom_batches(text: str, default_ts_ms: int, ws: str, ns: str):
    """Native-scanner fast path; None when the lib is unavailable."""
    from .. import native as N

    payload = text.encode()
    recs = N.parse_prom_records(payload)
    if recs is None:
        return None
    if len(_KEY_CACHE) > _KEY_CACHE_CAP:
        _KEY_CACHE.clear()
    gauges, counters = ([], []), ([], [])
    exemplars = []
    for off, ln, v, t, tc, fl in zip(
        recs["key_off"].tolist(), recs["key_len"].tolist(),
        recs["value"].tolist(), recs["ts_ms"].tolist(),
        recs["type_code"].tolist(), recs["flags"].tolist(),
    ):
        ex = None
        if fl & 1:  # deferred line (exemplar/unusual): full Python semantics,
            # including raising for genuinely bad lines. strip() for the wider
            # Unicode whitespace the byte scanner can't trim.
            line = payload[off:off + ln].decode().strip()
            if not line or line.startswith("#"):
                # Unicode whitespace (e.g. U+00A0) can hide a comment/blank
                # from the byte scanner; parse_prom_text skips these.
                continue
            name, tags, t2, v, ex = _parse_sample_line(line)
            t = t2 if t2 is not None else N.TS_ABSENT
            full = dict(tags)
            full[METRIC_TAG] = name
            full.setdefault("_ws_", ws)
            full.setdefault("_ns_", ns)
        else:
            ck = (payload[off:off + ln], ws, ns)
            tmpl = _KEY_CACHE.get(ck)
            if tmpl is None:
                ks = ck[0].decode()
                i = ks.find("{")
                if i == -1:
                    name, tags = ks, {}
                else:
                    name, tags = ks[:i], _parse_labels(ks[i + 1:-1])
                tmpl = dict(tags)
                tmpl[METRIC_TAG] = name
                tmpl.setdefault("_ws_", ws)
                tmpl.setdefault("_ns_", ns)
                _KEY_CACHE[ck] = tmpl
            full = dict(tmpl)
        bucket = counters if tc == 1 else gauges
        ts_ms = t if t != N.TS_ABSENT else default_ts_ms
        bucket[0].append(full)
        bucket[1].append((ts_ms, v))
        if ex is not None:
            ex_labels, ex_val, ex_ts = ex
            exemplars.append(
                (full, ex_ts if ex_ts is not None else ts_ms, ex_val, ex_labels)
            )
    return _assemble_batches(gauges, counters), exemplars


def _assemble_batches(gauges, counters) -> list[RecordBatch]:
    out = []
    for (tags_list, rows), schema, col in (
        (gauges, GAUGE, "value"),
        (counters, PROM_COUNTER, "count"),
    ):
        if tags_list:
            ts = np.asarray([r[0] for r in rows], dtype=np.int64)
            vals = np.asarray([r[1] for r in rows])
            out.append(RecordBatch(schema, ts, {col: vals}, tags_list))
    return out


def prom_text_to_batches_and_exemplars(
    text: str, default_ts_ms: int, ws="default", ns="default"
) -> tuple[list[RecordBatch], list]:
    """One parse of the exposition payload yielding both the schema-split
    sample batches and the OpenMetrics exemplars as
    (full_tags, ts_ms, exemplar_value, exemplar_labels).

    Scans natively when libfilodbprom is available (gateway-parser analog:
    the C++ scanner tokenizes; label dicts come from a per-series-key memo),
    falling back to the pure-Python regex parser — both paths are
    differential-tested against each other."""
    native = _native_prom_batches(text, default_ts_ms, ws, ns)
    if native is not None:
        return native
    gauges, counters = ([], []), ([], [])
    exemplars = []
    for name, tags, t, v, typ, ex in parse_prom_text(text, with_exemplars=True):
        full = dict(tags)
        full[METRIC_TAG] = name
        full.setdefault("_ws_", ws)
        full.setdefault("_ns_", ns)
        bucket = counters if typ == "counter" else gauges
        bucket[0].append(full)
        bucket[1].append((t if t is not None else default_ts_ms, v))
        if ex is not None:
            ex_labels, ex_val, ex_ts = ex
            exemplars.append(
                (full, ex_ts if ex_ts is not None else (t if t is not None else default_ts_ms),
                 ex_val, ex_labels)
            )
    return _assemble_batches(gauges, counters), exemplars
