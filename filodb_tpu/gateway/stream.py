"""Ingestion stream SPI + sources (reference L5/L7:
IngestionStream.scala:74 IngestionStreamFactory, sources/CsvStream.scala:126,
kafka/KafkaIngestionStream.scala:26).

An IngestionStream yields (offset, RecordBatch) in offset order; offsets are
the recovery checkpoint currency (Kafka offsets in the reference). Sources:
in-memory queue (tests / dev gateway), CSV files, JSONL files. A Kafka
consumer slots behind the same SPI when a broker exists.
"""

from __future__ import annotations

import csv
import json
import threading
from typing import Iterable, Iterator

import numpy as np

from ..core.records import RecordBatch
from ..core.schemas import GAUGE, METRIC_TAG, SCHEMAS


class IngestionStream:
    """Iterable of (offset, RecordBatch), replayable from an offset."""

    def batches(self, from_offset: int = 0) -> Iterator[tuple[int, RecordBatch]]:
        raise NotImplementedError


class MemoryStream(IngestionStream):
    """Append-only in-memory log (the test/dev transport)."""

    def __init__(self):
        self._log: list[RecordBatch] = []
        self._lock = threading.Lock()

    def append(self, batch: RecordBatch) -> int:
        with self._lock:
            self._log.append(batch)
            return len(self._log) - 1

    def batches(self, from_offset: int = 0):
        i = max(from_offset, 0)
        while True:
            with self._lock:
                if i >= len(self._log):
                    return
                b = self._log[i]
            yield i, b
            i += 1


class CsvStream(IngestionStream):
    """CSV rows: metric,tags(k=v;k=v),ts_ms,value (reference CsvStream)."""

    def __init__(self, path: str, batch_size: int = 1000, schema=GAUGE):
        self.path = path
        self.batch_size = batch_size
        self.schema = schema

    def batches(self, from_offset: int = 0):
        col = self.schema.value_column
        with open(self.path) as f:
            reader = csv.reader(f)
            rows = []
            offset = 0
            for row in reader:
                if not row or row[0].startswith("#"):
                    continue
                if offset >= from_offset:
                    rows.append(row)
                offset += 1
                if len(rows) >= self.batch_size:
                    yield offset - 1, self._to_batch(rows, col)
                    rows = []
            if rows:
                yield offset - 1, self._to_batch(rows, col)

    def _to_batch(self, rows, col):
        tags_list, ts, vals = [], [], []
        for metric, tagstr, t, v in rows:
            tags = {METRIC_TAG: metric}
            if tagstr:
                for kv in tagstr.split(";"):
                    k, _, val = kv.partition("=")
                    tags[k] = val
            tags_list.append(tags)
            ts.append(int(t))
            vals.append(float(v))
        return RecordBatch(
            self.schema, np.asarray(ts, dtype=np.int64), {col: np.asarray(vals)}, tags_list
        )


class IngestionPipeline:
    """Drives a stream into one shard with checkpointed recovery
    (reference IngestionActor.startIngestion:211 + recovery :36-90)."""

    def __init__(self, memstore, dataset: str, shard_num: int, stream: IngestionStream,
                 flush_coordinator=None, flush_every: int = 0):
        self.memstore = memstore
        self.dataset = dataset
        self.shard_num = shard_num
        self.stream = stream
        self.flush = flush_coordinator
        self.flush_every = flush_every

    def run(self, from_offset: int = 0) -> int:
        """Consume the stream to exhaustion; returns rows ingested."""
        shard = self.memstore.shard(self.dataset, self.shard_num)
        n = 0
        since_flush = 0
        for offset, batch in self.stream.batches(from_offset):
            n += shard.ingest(batch, offset)
            since_flush += 1
            if self.flush and self.flush_every and since_flush >= self.flush_every:
                self.flush.flush_shard(self.dataset, self.shard_num, offset)
                since_flush = 0
        return n

    def recover_and_run(self, store) -> int:
        """Restart path: rebuild from the column store, then replay the
        stream from the min checkpoint (reference createDataRecoveryObservable)."""
        from ..store.flush import recover_shard

        replay_from = recover_shard(self.memstore, store, self.dataset, self.shard_num)
        return self.run(replay_from + 1 if replay_from >= 0 else 0)
