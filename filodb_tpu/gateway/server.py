"""TCP line-protocol gateway (reference L7: GatewayServer.scala:64,124 —
netty TCP server accepting Influx line protocol, converting to records,
sharding by shard-key hash, feeding the ingest pipeline :335; plus
TestTimeseriesProducer load generator).

Stdlib socketserver; each connection streams newline-delimited Influx lines.
Batches accumulate per poll interval and route to shards by spread hashing.
"""

from __future__ import annotations

import socket
import socketserver
import threading
import time

import numpy as np

from ..core.records import RecordBatch
from ..core.schemas import GAUGE, METRIC_TAG
from .parsers import parse_influx_line


class GatewayServer:
    def __init__(self, memstore, dataset: str, spread: int = 3,
                 ws: str = "default", ns: str = "default", batch_lines: int = 1000):
        self.memstore = memstore
        self.dataset = dataset
        self.spread = spread
        self.ws = ws
        self.ns = ns
        self.batch_lines = batch_lines
        self.lines_received = 0
        self.rows_ingested = 0
        self.parse_errors = 0
        self._srv: socketserver.ThreadingTCPServer | None = None
        gateway = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                buf: list[tuple] = []
                for raw in self.rfile:
                    line = raw.decode(errors="replace").strip()
                    if not line:
                        continue
                    gateway.lines_received += 1
                    try:
                        for metric, tags, ts_ms, val in parse_influx_line(line) or ():
                            full = dict(tags)
                            full[METRIC_TAG] = metric
                            full.setdefault("_ws_", gateway.ws)
                            full.setdefault("_ns_", gateway.ns)
                            buf.append((full, ts_ms or int(time.time() * 1000), val))
                    except ValueError:
                        gateway.parse_errors += 1
                    if len(buf) >= gateway.batch_lines:
                        gateway._ingest(buf)
                        buf = []
                if buf:
                    gateway._ingest(buf)

        self._handler = Handler

    def _ingest(self, rows):
        tags_list = [r[0] for r in rows]
        ts = np.asarray([r[1] for r in rows], dtype=np.int64)
        vals = np.asarray([r[2] for r in rows], dtype=np.float64)
        batch = RecordBatch(GAUGE, ts, {"value": vals}, tags_list)
        self.rows_ingested += self.memstore.ingest_routed(self.dataset, batch, self.spread)

    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._srv = socketserver.ThreadingTCPServer((host, port), self._handler)
        self._srv.daemon_threads = True
        t = threading.Thread(target=self._srv.serve_forever, daemon=True)
        t.start()
        return self._srv.server_address[1]

    def stop(self):
        if self._srv:
            self._srv.shutdown()


def produce_load(host: str, port: int, n_series: int, n_samples: int,
                 metric: str = "machine_cpu", start_ms: int | None = None,
                 interval_ms: int = 10_000) -> int:
    """Load generator (reference TestTimeseriesProducer): pushes synthetic
    Influx lines over TCP; returns lines sent."""
    rng = np.random.default_rng(0)
    start_ms = start_ms if start_ms is not None else int(time.time() * 1000)
    sent = 0
    with socket.create_connection((host, port)) as sock:
        f = sock.makefile("wb")
        for t in range(n_samples):
            ts_ns = (start_ms + t * interval_ms) * 1_000_000
            for s in range(n_series):
                v = 50 + 20 * rng.standard_normal()
                f.write(f"{metric},host=host-{s},dc=dc{s % 3} value={v:.4f} {ts_ns}\n".encode())
                sent += 1
        f.flush()
    return sent
