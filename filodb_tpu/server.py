"""Standalone server (reference L7: standalone/.../NewFiloServerMain.scala:25
— boot memstore + shard recovery, start HTTP API, periodic flush + retention
maintenance; v2-style static shard ownership, no cluster singleton).

Config is a JSON dict (HOCON analog), e.g.::

    {
      "dataset": "prometheus",
      "shards": 8,
      "spread": 3,
      "http_port": 9090,
      "store_root": "/var/lib/filodb-tpu",       # omit for memory-only
      "flush_interval_s": 3600,
      "retention_hours": 72,
      "max_chunk_size": 400,
      "downsample": {"enabled": false, "periods_m": [5, 60]}
    }
"""

from __future__ import annotations

import json
import logging
import threading
import time

from .api.http import serve_background
from .coordinator.planner import QueryEngine
from .core.schemas import Dataset
from .memstore.memstore import TimeSeriesMemStore
from .memstore.shard import StoreConfig
from .store.columnstore import LocalColumnStore, NullColumnStore
from .store.flush import FlushCoordinator, recover_shard

log = logging.getLogger("filodb_tpu.server")


class FiloServer:
    def __init__(self, config: dict | None = None):
        from .config import load_config

        cfg = load_config(overrides=config or {})
        self.config = cfg
        # before any jit dispatch: compiled kernels persist across restarts
        # (config "compile_cache_dir"; doc/perf.md)
        from .ops.compile_cache import enable_from_config

        enable_from_config(cfg)
        self.dataset = cfg["dataset"]
        self.n_shards = int(cfg["shards"])
        self.spread = int(cfg["spread"])
        self.http_port = int(cfg["http_port"])
        self.flush_interval_s = float(cfg["flush_interval_s"])
        self.store_config = StoreConfig(
            max_chunk_size=int(cfg["max_chunk_size"]),
            retention_ms=int(float(cfg["retention_hours"]) * 3_600_000),
            groups_per_shard=int(cfg["groups_per_shard"]),
            max_partitions=int(cfg["max_partitions_per_shard"]),
            index_backend=cfg["index_backend"],
            index_device_postings=bool(cfg["index_device_postings"]),
            index_device_min_hits=int(cfg["index_device_min_hits"]),
            index_device_max_bytes=int(cfg["index_device_max_bytes"]),
        )
        # multi-host: join the JAX distributed runtime (no-op single-process)
        # and own only this process's shard slice (reference v2 cluster:
        # ordinal -> shards, FiloDbClusterDiscovery)
        from .parallel.multihost import init_distributed, shards_for_process

        dist_cfg = cfg.get("distributed") or {}
        self.peers = tuple(dist_cfg.get("peers") or ())
        self.seeds = tuple(dist_cfg.get("seeds") or ())
        self.advertise_url = dist_cfg.get("advertise_url")
        self.refresh_interval_s = float(dist_cfg.get("refresh_interval_s") or 30)
        self.is_distributed = init_distributed(
            dist_cfg.get("coordinator"),
            dist_cfg.get("num_processes"),
            dist_cfg.get("process_id"),
        )
        if dist_cfg.get("owned_shards") is not None:
            owned = list(dist_cfg["owned_shards"])  # explicit (k8s static / tests)
        elif self.is_distributed:
            owned = shards_for_process(self.n_shards)
        elif self.peers or self.seeds:
            # peers/seeds configured but nothing assigns THIS process a
            # slice: every host would own (and ingest) everything, and
            # scattered queries would double-count — refuse at startup
            raise ValueError(
                "distributed.peers/seeds requires distributed.owned_shards "
                "or a JAX coordinator to assign this process's shard slice"
            )
        else:
            owned = range(self.n_shards)
        self.memstore = TimeSeriesMemStore(self.store_config)
        # total_shards pins the routing modulus to the CLUSTER size even when
        # this process owns a partial slice
        self.memstore.setup(Dataset(self.dataset), owned, total_shards=self.n_shards)
        # shard plane view for GET /debug/cluster: this process's slice of
        # the static topology, ACTIVE from boot (v2 static ownership). An
        # embedding control plane may attach a ReplicationPlane to
        # self.replication — its richer snapshot (replicas, watermarks,
        # rebalances) takes over the endpoint.
        from .coordinator.cluster import ShardManager, ShardStatus

        self.replication = None
        self.cluster_manager = ShardManager(self.n_shards,
                                            shards_per_node=self.n_shards)
        self.cluster_manager.nodes.append("self")
        for s in owned:
            self.cluster_manager.mapper.update(s, ShardStatus.ACTIVE, "self")
        for q in cfg.get("quotas", []):
            for sh in self.memstore.shards(self.dataset):
                sh.cardinality.set_quota(tuple(q["prefix"]), int(q["quota"]))
        root = cfg.get("store_root")
        self.column_store = LocalColumnStore(root) if root else NullColumnStore()
        if root:
            for sh in self.memstore.shards(self.dataset):
                sh.odp_store = self.column_store
        downsampler = None
        if cfg["downsample"]["enabled"]:
            from .downsample.downsampler import ShardDownsampler

            downsampler = ShardDownsampler(
                self.memstore, self.dataset,
                periods_ms=tuple(int(m) * 60_000 for m in cfg["downsample"]["periods_m"]),
            )
        self.downsampler = downsampler
        preagg = None
        if cfg.get("preagg_rules"):
            from .coordinator.lpopt import AggRuleProvider, ExcludeAggRule, IncludeAggRule
            from .downsample.preagg import PreaggMaintainer

            rules = []
            for i, r in enumerate(cfg["preagg_rules"]):
                if "metric_regex" not in r or ("include_tags" in r) == ("exclude_tags" in r):
                    raise ValueError(
                        f"preagg_rules[{i}] must have metric_regex and exactly one "
                        f"of include_tags/exclude_tags: {r}"
                    )
                if "include_tags" in r:
                    rules.append(IncludeAggRule(r["metric_regex"], frozenset(r["include_tags"])))
                else:
                    rules.append(ExcludeAggRule(r["metric_regex"], frozenset(r["exclude_tags"])))
            self.agg_rules = AggRuleProvider(rules)
            preagg = PreaggMaintainer(self.memstore, self.dataset, self.agg_rules)
        else:
            self.agg_rules = None
        self.preagg = preagg
        self.flusher = FlushCoordinator(self.memstore, self.column_store, downsampler, preagg)
        from .coordinator.planner import PlannerParams

        qcfg = cfg["query"]
        self.scheduler = None
        if int(qcfg.get("parallelism", 0)) > 0:
            from .coordinator.scheduler import QueryScheduler

            self.scheduler = QueryScheduler(
                parallelism=int(qcfg["parallelism"]),
                max_queued=int(qcfg.get("max_queued", 64)),
            )
        # fault tolerance: shared breaker registry + retry budget for remote
        # children (query/faults.py); both engines (scattering + local) share
        # the registry so peer health is judged once per process
        from .config import DEFAULTS
        from .query.faults import BreakerRegistry, RetryPolicy

        # layer user values over config.py DEFAULTS (the single source of
        # truth): a user config providing a partial retry/breaker dict
        # replaces the whole dict in load_config's one-level merge
        rcfg = {**DEFAULTS["query"]["retry"], **(qcfg.get("retry") or {})}
        bcfg = {**DEFAULTS["query"]["breaker"], **(qcfg.get("breaker") or {})}
        self.breakers = BreakerRegistry(
            window=int(bcfg["window"]),
            failure_rate=float(bcfg["failure_rate"]),
            min_calls=int(bcfg["min_calls"]),
            cooldown_s=float(bcfg["cooldown_s"]),
        )
        self.retry_policy = RetryPolicy(
            max_attempts=int(rcfg["max_attempts"]),
            base_backoff_s=float(rcfg["base_backoff_s"]),
            max_backoff_s=float(rcfg["max_backoff_s"]),
        )
        # slow-query log: threshold rides PlannerParams, the ring size is
        # process-global (the log is shared across engines)
        slow_thr = qcfg.get("slow_query_threshold_s", DEFAULTS["query"]["slow_query_threshold_s"])
        from .metrics import SLOW_QUERY_LOG

        SLOW_QUERY_LOG.configure(int(qcfg.get("slow_query_log_max", 64) or 64))
        # query observatory (obs/querylog.py): size the per-query cost
        # record ring and publish its depth at scrape time
        from .obs.querylog import QUERY_LOG
        from .telemetry import register_querylog_collector

        QUERY_LOG.configure(int(qcfg.get("querylog_max", 512) or 512))
        register_querylog_collector()
        # kernel & compile observatory (obs/kernels.py): size the
        # per-executable registry + recompile-storm detector and publish
        # the live executable count at scrape time (/debug/kernels)
        from .obs.kernels import KERNELS, register_kernel_obs_collector

        kcfg = {**DEFAULTS["kernel_obs"], **(cfg.get("kernel_obs") or {})}
        KERNELS.configure(
            max_entries=int(kcfg["max_executables"]),
            storm_threshold=int(kcfg["storm_threshold"]),
            storm_window_s=float(kcfg["storm_window_s"]),
            device_timing=bool(kcfg["device_timing"]),
        )
        register_kernel_obs_collector()
        # work cost model (query/costmodel.py): per-fingerprint predicted
        # device-seconds, fed back from completed querylog records — it
        # prices admission and drives the adaptive batch window below
        from .query.costmodel import COST_MODEL

        cmcfg = {**DEFAULTS["query"]["costmodel"],
                 **(qcfg.get("costmodel") or {})}
        COST_MODEL.configure(
            prior_cost_s=float(cmcfg["prior_cost_s"]),
            alpha=float(cmcfg["alpha"]),
            cold_multiplier=float(cmcfg["cold_multiplier"]),
        )
        prior_cost_s = float(cmcfg["prior_cost_s"])
        # query dispatch scheduler (query/scheduler.py): ONE process-wide
        # micro-batcher + admission controller shared by every engine
        # (scattering, local and _system) so concurrent queries coalesce
        # and tenant quotas act process-wide, whichever engine serves them
        self.dispatch_scheduler = None
        batch_window_ms = float(qcfg.get("batch_window_ms", 0) or 0)
        scfg = {**DEFAULTS["standing"], **(cfg.get("standing") or {})}
        self.standing_config = scfg
        # result plane (doc/perf.md): serving-edge streaming knobs + the
        # node-to-node exchange format. peer_exchange=json pins BOTH sides
        # of this node to decimal JSON (serving edge stops honoring Arrow
        # Accept; outgoing scatter legs stop advertising it).
        rpcfg = {**DEFAULTS["result_plane"], **(cfg.get("result_plane") or {})}
        self.result_plane_config = rpcfg
        from .coordinator import planners as _planners

        _planners.PEER_EXCHANGE = str(rpcfg.get("peer_exchange", "arrow"))
        # standing-query promotion rides the scheduler's per-key recurrence
        # ring, so an enabled standing engine needs the scheduler object
        # even when batching is off (window 0 = ring only, no batching)
        pwcfg = {**DEFAULTS["query"]["prewarm"],
                 **(qcfg.get("prewarm") or {})}
        self.prewarm_config = pwcfg
        if batch_window_ms > 0 or scfg.get("enabled", True):
            from .query.scheduler import DispatchScheduler

            self.dispatch_scheduler = DispatchScheduler(
                batch_window_ms, int(qcfg.get("batch_max", 32) or 32),
                key_ring_max=int(scfg.get("key_ring_max", 512) or 512),
                window_cap_ms=float(
                    qcfg.get("batch_window_cap_ms", 0) or 0),
                load_ref_cost_s=float(
                    qcfg.get("batch_load_ref_cost_s", 0.25) or 0.25),
                prior_cost_s=prior_cost_s,
                prewarm_min_count=int(pwcfg.get("min_count", 3) or 3),
            )
        self.admission = None
        quotas = qcfg.get("tenant_quotas") or {}
        admission_max_queued = int(qcfg.get("admission_max_queued", 0) or 0)
        if quotas or admission_max_queued:
            from .query.scheduler import AdmissionController

            self.admission = AdmissionController(
                quotas, max_queued=admission_max_queued,
                prior_cost_s=prior_cost_s,
            )
        common = dict(
            spread=self.spread,
            lookback_ms=int(qcfg["lookback_ms"]),
            max_series=int(qcfg["max_series"]),
            deadline_s=float(qcfg["timeout_s"]),
            agg_rules=self.agg_rules,
            scheduler=self.scheduler,
            num_shards=self.n_shards,
            allow_partial_results=bool(qcfg.get("allow_partial_results", False)),
            fused_aggregate=bool(qcfg.get("fused_aggregate", True)),
            retry_policy=self.retry_policy,
            breakers=self.breakers,
            slow_query_threshold_s=float(slow_thr) if slow_thr is not None else None,
            batch_window_ms=batch_window_ms,
            dispatch_scheduler=self.dispatch_scheduler,
            admission=self.admission,
        )
        self.engine = QueryEngine(
            self.memstore, self.dataset,
            PlannerParams(
                peer_endpoints=self.peers,
                remote_auth_token=cfg.get("http_auth_token"),
                **common,
            ),
        )
        # peers hit this engine (X-FiloDB-Local): answers from owned shards
        # only, never re-scatters — the multi-host anti-recursion guard.
        # It runs OFF the bounded scheduler: scattering root queries hold
        # scheduler workers while blocking on peer HTTP, so routing the
        # peers' subqueries through the same pool would deadlock the cluster
        # (every worker waiting on the other host). Subquery concurrency is
        # bounded by the peers' own scheduler caps.
        self.local_engine = (
            QueryEngine(
                self.memstore, self.dataset,
                PlannerParams(**{**common, "scheduler": None}),
            )
            if (self.peers or self.seeds) else None
        )
        if (self.peers or self.seeds) and not cfg.get("http_auth_token"):
            log.warning(
                "multi-host peers configured WITHOUT http_auth_token: any "
                "client sending X-FiloDB-Local reaches the shard-local "
                "engine (partial results, no admission control) — set a "
                "token so only peers can"
            )
        # standing-query engine (filodb_tpu/standing/): promotion over the
        # scheduler's recurrence ring, delta-maintained partials on ingest
        # append, SSE push fan-out + the recording-rules API. One per
        # process, bound to the scattering engine (standing queries over
        # this node's primary dataset).
        self.standing = None
        if scfg.get("enabled", True):
            from .standing import StandingEngine

            self.standing = StandingEngine(self.engine, scfg)
        # sketch rollup tier (downsample/rollup.py): standing maintainer
        # folds per-period summary blocks over the ingest path; the
        # planner substitutes them for eligible long-range window queries
        # (params.rollups below); the chooser trains the rollup set on
        # the querylog. /debug/rollups is the admin surface.
        rcfg = {**DEFAULTS["rollup"], **(cfg.get("rollup") or {})}
        self.rollups = None
        self.rollup_chooser = None
        if rcfg.get("enabled", True):
            from .downsample.chooser import RollupChooser
            from .downsample.rollup import RollupManager

            self.rollups = RollupManager(
                self.memstore,
                grace_ms=int(rcfg["grace_ms"]),
                max_entries=int(rcfg["max_entries"]),
                tick_s=float(rcfg["tick_s"]),
            )
            self.engine.planner.params.rollups = self.rollups
            ccfg = {**DEFAULTS["rollup"]["chooser"],
                    **(rcfg.get("chooser") or {})}
            if ccfg.get("enabled", True):
                self.rollup_chooser = RollupChooser(
                    self.rollups,
                    resolutions_ms=tuple(
                        int(r) for r in ccfg["resolutions_ms"]
                    ),
                    min_count=int(ccfg["min_count"]),
                    min_span_ms=int(ccfg["min_span_ms"]),
                    idle_s=float(ccfg["idle_s"]),
                    interval_s=float(ccfg["interval_s"]),
                )
                self.rollups.chooser = self.rollup_chooser
        self.profiler = None
        if cfg["profiler"]["enabled"]:
            from .metrics import SamplingProfiler

            self.profiler = SamplingProfiler(cfg["profiler"]["interval_ms"] / 1000.0)
        # self-telemetry (telemetry.py): config-gated REGISTRY -> _system
        # dataset pipeline + an engine so the server's own metrics answer
        # PromQL through the standard (fused) query path (?dataset=_system)
        tcfg = cfg.get("telemetry") or {}
        self.self_scraper = None
        self.system_engine = None
        scrape_interval = tcfg.get("self_scrape_interval_s")
        if scrape_interval:
            from .telemetry import SYSTEM_DATASET, SelfScraper

            self.memstore.setup(Dataset(SYSTEM_DATASET), owned,
                                total_shards=self.n_shards)
            self.system_engine = QueryEngine(
                self.memstore, SYSTEM_DATASET,
                PlannerParams(**{**common, "scheduler": None}),
            )
            self.self_scraper = SelfScraper(
                self.memstore, SYSTEM_DATASET,
                interval_s=float(scrape_interval),
                spread=int(tcfg.get("self_scrape_spread", 1)),
            )
        # SLO burn-rate recording rules (obs/slo.py): a second standing
        # maintainer bound to the _system engine keeps the observatory's
        # own rollups — availability and latency burn rates — as real
        # series. enabled null = auto (on exactly when _system exists and
        # the standing engine is on).
        from .obs.slo import DEFAULTS as SLO_DEFAULTS

        slo_cfg = {**SLO_DEFAULTS, **(cfg.get("slo") or {})}
        self.slo_config = slo_cfg
        self.system_standing = None
        slo_on = slo_cfg.get("enabled")
        if slo_on is None:
            slo_on = self.system_engine is not None and scfg.get("enabled", True)
        if slo_on and self.system_engine is not None:
            from .standing import StandingEngine

            self.system_standing = StandingEngine(self.system_engine, scfg)
        # alerting plane (obs/alerting.py + obs/notify.py): rule groups
        # evaluated on the _system standing engine, state written back as
        # ALERTS/ALERTS_FOR_STATE, firing alerts fanned out to webhook
        # receivers through the shared breaker/retry plane. enabled null =
        # auto (on exactly when the _system standing engine runs).
        from .obs.alerting import DEFAULTS as ALERT_DEFAULTS

        acfg = {**ALERT_DEFAULTS, **(cfg.get("alerting") or {})}
        self.alerting_config = acfg
        self.alerting = None
        alert_on = acfg.get("enabled")
        if alert_on is None:
            alert_on = self.system_standing is not None
        if alert_on and self.system_standing is not None:
            from .obs.alerting import AlertingEngine
            from .obs.notify import Notifier, Receiver

            notifier = None
            recv = [Receiver.from_config(r)
                    for r in (acfg.get("receivers") or [])]
            if recv:
                notifier = Notifier(
                    recv, breakers=self.breakers, retry=self.retry_policy,
                    deadline_s=float(acfg.get("notify_deadline_s", 10.0)),
                    tick_s=float(acfg.get("notify_tick_s", 1.0)),
                )
            self.alerting = AlertingEngine(self.system_standing, acfg,
                                           notifier=notifier)
        watch_log = tcfg.get("tpu_watch_log", "auto")
        if watch_log:
            import os as _os

            if watch_log == "auto":
                watch_log = _os.path.join(
                    _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
                    "TPU_WATCH_LOG.txt",
                )
                watch_log = watch_log if _os.path.exists(watch_log) else None
            if watch_log:
                from .telemetry import register_tpu_watch_collector

                register_tpu_watch_collector(str(watch_log))
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._http = None
        self._grpc = None
        self.grpc_port = cfg.get("grpc_port")
        self.bootstrapper = None
        self.registry = None

    def _cluster_snapshot(self) -> dict:
        """GET /debug/cluster payload: the replication plane's snapshot when
        one is attached, else the static shard-ownership view."""
        if self.replication is not None:
            return self.replication.snapshot()
        return self.cluster_manager.snapshot()

    # -- lifecycle --------------------------------------------------------

    def recover(self) -> dict[int, int]:
        """Rebuild shards from the column store; returns per-shard replay
        offsets for the ingestion sources. Downsample datasets recover too
        (they have no replay stream — their tail rebuilds from raw flushes)."""
        offsets = {}
        owned = self.memstore.shard_nums(self.dataset)
        for s in owned:
            offsets[s] = recover_shard(self.memstore, self.column_store, self.dataset, s)
        if self.downsampler is not None:
            from .core.schemas import Dataset as _DS
            from .downsample.downsampler import DS_GAUGE

            for period in self.downsampler.periods_ms:
                ds = self.downsampler.dataset_for(period)
                self.memstore.setup(_DS(ds, schemas=[DS_GAUGE]), owned,
                                    total_shards=self.n_shards)
                for s in owned:
                    recover_shard(self.memstore, self.column_store, ds, s)
        log.info("recovered %d shards: %s", len(owned), offsets)
        return offsets

    def start(self, port: int | None = None) -> int:
        self.recover()
        if self.profiler is not None:
            self.profiler.start()
        self._http, actual_port = serve_background(
            self.engine, port=self.http_port if port is None else port,
            auth_token=self.config.get("http_auth_token"),
            local_engine=self.local_engine,
            flush_hook=self.flush_now,
            dataset_engines=(
                {self.system_engine.dataset: self.system_engine}
                if self.system_engine is not None else None
            ),
            standing=self.standing,
            standing_system=self.system_standing,
            rollups=self.rollups,
            alerting=self.alerting,
            cluster=self._cluster_snapshot,
            result_plane=self.result_plane_config,
        )
        if self.standing is not None:
            self.standing.start()
        if self.rollups is not None:
            self.rollups.start()
        if self.rollup_chooser is not None:
            self.rollup_chooser.start()
        if self.system_standing is not None:
            # register + start the SLO maintainer AFTER the HTTP edge is
            # up: rules evaluate from live-traffic metrics the edge emits
            from .obs.slo import register_slo_rules

            self.slo_rules = register_slo_rules(self.system_standing,
                                                self.slo_config)
            if self.alerting is not None:
                # rule files load AFTER the SLO set registers (alert exprs
                # threshold the burn series those rules record) and BEFORE
                # the maintainer thread starts; rehydration restores
                # pending/firing state from the ALERTS_FOR_STATE series a
                # previous process wrote, so a restart never resets a
                # firing alert's for: clock
                self.alerting.load_rule_files()
                self.alerting.rehydrate()
                self.alerting.start()
            self.system_standing.start()
        if self.self_scraper is not None:
            self.self_scraper.start()
        if self.profiler is not None:
            # /debug/profile is config-gated: wired only when the profiler
            # block enables sampling
            self._http.RequestHandlerClass.profiler_hook = staticmethod(
                self.profiler.report
            )
        if self.seeds:
            # seed bootstrap (reference akka-bootstrapper): discover peers
            # via /__members, expose our own membership, keep refreshing so
            # joins propagate and dead peers age out of the scatter set
            from .coordinator.bootstrap import MemberRegistry, SeedBootstrapper

            self_url = self.advertise_url or f"http://127.0.0.1:{actual_port}"
            self.registry = MemberRegistry(self_url)

            def on_change(peers):
                # compose with any statically configured peers (e.g. grpc://
                # endpoints) — discovery must never drop them from scatter
                merged = tuple(dict.fromkeys(self.peers + tuple(peers)))
                log.info("cluster membership changed: peers=%s", list(merged))
                self.engine.planner.params.peer_endpoints = merged

            self.bootstrapper = SeedBootstrapper(
                self.registry, self.seeds,
                auth_token=self.config.get("http_auth_token"),
                on_change=on_change,
            )
            def on_join(url, node_id=None):
                if node_id and node_id == self.registry.node_id:
                    self.registry.mark_self_alias(url)  # our own announce
                    return
                new = self.registry.learn([url])
                self.registry.touch([url])  # they reached us: direct contact
                if new:
                    on_change(self.registry.peers())

            self._http.RequestHandlerClass.members_hook = staticmethod(self.registry.snapshot)
            self._http.RequestHandlerClass.join_hook = staticmethod(on_join)

            def join():
                try:
                    self.bootstrapper.bootstrap()
                except Exception:  # noqa: BLE001
                    log.exception("seed bootstrap failed; refresh loop keeps trying")
                self.bootstrapper.start(self.refresh_interval_s)

            t0 = threading.Thread(target=join, daemon=True, name="filodb-join")
            t0.start()
            self._threads.append(t0)
        if self.grpc_port is not None:
            from .api.grpc_exec import serve_grpc

            self._grpc, self.grpc_port = serve_grpc(
                self.engine, port=int(self.grpc_port),
                auth_token=self.config.get("http_auth_token"),
                local_engine=self.local_engine,
                host=self.config.get("grpc_host") or "127.0.0.1",
            )
            log.info("filodb-tpu gRPC RemoteExec on :%d", self.grpc_port)
        t = threading.Thread(target=self._maintenance_loop, daemon=True)
        t.start()
        self._threads.append(t)
        if (self.dispatch_scheduler is not None
                and self.prewarm_config.get("enabled", True)
                and int(self.prewarm_config.get("per_tick", 2) or 0) > 0):
            tp = threading.Thread(target=self._prewarm_loop, daemon=True,
                                  name="filodb-prewarm")
            tp.start()
            self._threads.append(tp)
        log.info("filodb-tpu serving on :%d (%d shards)", actual_port, self.n_shards)
        return actual_port

    def stop(self):
        self._stop.set()
        if self.rollup_chooser is not None:
            self.rollup_chooser.stop()
        if self.rollups is not None:
            self.rollups.stop()
        if self.standing is not None:
            self.standing.stop()
        if self.alerting is not None:
            self.alerting.stop()
        if self.system_standing is not None:
            self.system_standing.stop()
        if self.self_scraper is not None:
            self.self_scraper.stop()
        if self.bootstrapper is not None:
            self.bootstrapper.stop()
        if self._http:
            self._http.shutdown()
        if self._grpc is not None:
            self._grpc.stop(grace=0.5)
        if self.scheduler is not None:
            self.scheduler.shutdown()

    def _prewarm_loop(self):
        """Background executable pre-warm (query/scheduler.py
        prewarm_tick): trace+compile the programs of recurrence-ring keys
        about to go hot, OFF the serving path, so the first real poll of a
        recurring dashboard pays zero compiles."""
        interval = float(self.prewarm_config.get("interval_s", 5.0) or 5.0)
        limit = int(self.prewarm_config.get("per_tick", 2) or 2)
        while not self._stop.wait(interval):
            try:
                self.dispatch_scheduler.prewarm_tick(limit=limit)
            except Exception:  # noqa: BLE001
                log.exception("prewarm tick failed")

    def _maintenance_loop(self):
        """Periodic flush + retention eviction + tenant metering (reference
        flush timer + evictForHeadroom + TenantIngestionMetering)."""
        from .metering import TenantIngestionMetering

        metering = TenantIngestionMetering(self.memstore, self.dataset)
        last_flush = time.time()
        while not self._stop.wait(min(self.flush_interval_s, 60.0)):
            now = time.time()
            if now - last_flush >= self.flush_interval_s:
                try:
                    self.flush_now()
                except Exception:  # noqa: BLE001
                    log.exception("flush failed")
                last_flush = now
            for ds in list(self.memstore._datasets):
                for sh in self.memstore.shards(ds):
                    sh.evict_for_retention()
                    sh.evict_for_headroom()
            try:
                metering.publish()
            except Exception:  # noqa: BLE001
                log.exception("metering failed")

    def flush_now(self):
        """Flush the primary dataset, then any downsample/aux datasets the
        flush itself populated (so they persist and recover too). Returns
        the TOTAL across all datasets (the /admin/flush contract)."""
        res = self.flusher.flush_all(self.dataset)
        for ds in list(self.memstore._datasets):
            if ds != self.dataset:
                r = self.flusher.flush_all(ds)
                res.chunks_written += r.chunks_written
                res.partkeys_written += r.partkeys_written
                res.groups_flushed += r.groups_flushed
        return res


def main(argv=None):
    import argparse

    from .config import apply_platform_env

    apply_platform_env()
    p = argparse.ArgumentParser("filodb-tpu-server")
    p.add_argument("--config", help="JSON config file")
    p.add_argument("--port", type=int, default=None)
    args = p.parse_args(argv)
    cfg = {}
    if args.config:
        with open(args.config) as f:
            cfg = json.load(f)
    logging.basicConfig(level=logging.INFO)
    srv = FiloServer(cfg)
    port = srv.start(port=args.port)
    print(f"listening on :{port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
