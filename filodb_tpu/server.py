"""Standalone server (reference L7: standalone/.../NewFiloServerMain.scala:25
— boot memstore + shard recovery, start HTTP API, periodic flush + retention
maintenance; v2-style static shard ownership, no cluster singleton).

Config is a JSON dict (HOCON analog), e.g.::

    {
      "dataset": "prometheus",
      "shards": 8,
      "spread": 3,
      "http_port": 9090,
      "store_root": "/var/lib/filodb-tpu",       # omit for memory-only
      "flush_interval_s": 3600,
      "retention_hours": 72,
      "max_chunk_size": 400,
      "downsample": {"enabled": false, "periods_m": [5, 60]}
    }
"""

from __future__ import annotations

import json
import logging
import threading
import time

from .api.http import serve_background
from .coordinator.planner import QueryEngine
from .core.schemas import Dataset
from .memstore.memstore import TimeSeriesMemStore
from .memstore.shard import StoreConfig
from .store.columnstore import LocalColumnStore, NullColumnStore
from .store.flush import FlushCoordinator, recover_shard

log = logging.getLogger("filodb_tpu.server")


class FiloServer:
    def __init__(self, config: dict | None = None):
        cfg = dict(config or {})
        self.dataset = cfg.get("dataset", "prometheus")
        self.n_shards = int(cfg.get("shards", 8))
        self.spread = int(cfg.get("spread", 3))
        self.http_port = int(cfg.get("http_port", 9090))
        self.flush_interval_s = float(cfg.get("flush_interval_s", 3600))
        retention_h = float(cfg.get("retention_hours", 72))
        self.store_config = StoreConfig(
            max_chunk_size=int(cfg.get("max_chunk_size", 400)),
            retention_ms=int(retention_h * 3_600_000),
        )
        self.memstore = TimeSeriesMemStore(self.store_config)
        self.memstore.setup(Dataset(self.dataset), range(self.n_shards))
        root = cfg.get("store_root")
        self.column_store = LocalColumnStore(root) if root else NullColumnStore()
        if root:
            for sh in self.memstore.shards(self.dataset):
                sh.odp_store = self.column_store
        self.flusher = FlushCoordinator(self.memstore, self.column_store)
        self.engine = QueryEngine(self.memstore, self.dataset)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._http = None

    # -- lifecycle --------------------------------------------------------

    def recover(self) -> dict[int, int]:
        """Rebuild shards from the column store; returns per-shard replay
        offsets for the ingestion sources."""
        offsets = {}
        for s in range(self.n_shards):
            offsets[s] = recover_shard(self.memstore, self.column_store, self.dataset, s)
        log.info("recovered %d shards: %s", self.n_shards, offsets)
        return offsets

    def start(self, port: int | None = None) -> int:
        self.recover()
        self._http, actual_port = serve_background(
            self.engine, port=self.http_port if port is None else port
        )
        t = threading.Thread(target=self._maintenance_loop, daemon=True)
        t.start()
        self._threads.append(t)
        log.info("filodb-tpu serving on :%d (%d shards)", actual_port, self.n_shards)
        return actual_port

    def stop(self):
        self._stop.set()
        if self._http:
            self._http.shutdown()

    def _maintenance_loop(self):
        """Periodic flush + retention eviction (reference flush timer +
        evictForHeadroom)."""
        last_flush = time.time()
        while not self._stop.wait(min(self.flush_interval_s, 60.0)):
            now = time.time()
            if now - last_flush >= self.flush_interval_s:
                try:
                    self.flusher.flush_all(self.dataset)
                except Exception:  # noqa: BLE001
                    log.exception("flush failed")
                last_flush = now
            for sh in self.memstore.shards(self.dataset):
                sh.evict_for_retention()

    def flush_now(self):
        return self.flusher.flush_all(self.dataset)


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser("filodb-tpu-server")
    p.add_argument("--config", help="JSON config file")
    p.add_argument("--port", type=int, default=None)
    args = p.parse_args(argv)
    cfg = {}
    if args.config:
        with open(args.config) as f:
            cfg = json.load(f)
    logging.basicConfig(level=logging.INFO)
    srv = FiloServer(cfg)
    port = srv.start(port=args.port)
    print(f"listening on :{port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
