"""Server/config defaults (reference: core/src/main/resources/
filodb-defaults.conf — 1478 lines of HOCON; here a documented JSON-shaped
dict merged with user config files; GlobalConfig analog).
"""

from __future__ import annotations

import json
import os

DEFAULTS: dict = {
    # dataset / sharding (reference filodb.dataset-configs + spread-default)
    "dataset": "prometheus",
    "shards": 8,
    "spread": 3,
    # memstore (reference filodb.memstore block)
    "max_chunk_size": 400,
    "retention_hours": 72,
    "groups_per_shard": 16,
    "max_partitions_per_shard": 1_000_000,
    # "python" = vectorized posting-bitmap index (default), "native" = C++
    # posting lists, "set" = the retained set-arithmetic oracle
    "index_backend": "python",
    # opt-in HBM tier for hot posting bitmaps (doc/perf.md "Vectorized
    # part-key index": all-equality selectors over staged bitmaps resolve
    # as one tiny jit intersection; ledger kind index_postings)
    "index_device_postings": False,
    "index_device_min_hits": 16,
    "index_device_max_bytes": 64 << 20,
    # flush / persistence
    "flush_interval_s": 3600,
    "store_root": None,  # None = memory-only (NullColumnStore)
    # persistent XLA compile cache (ops/compile_cache.py): compiled kernel
    # programs survive process restarts, so a rolling deploy skips the
    # multi-second cold compile. "auto" = <store_root>/jax-compile-cache
    # (or ~/.cache/filodb-tpu/... when memory-only); a path uses it as-is;
    # null disables.
    "compile_cache_dir": "auto",
    # query limits (reference filodb.query circuit breaker / limits)
    "query": {
        "max_series": 1_000_000,
        "max_samples": 500_000_000,
        "lookback_ms": 300_000,
        "timeout_s": 60,
        # bounded shared scheduler (reference query-sched parallelism):
        # 0 = run queries inline on the API edge threads (tests/embedding)
        "parallelism": 8,
        "max_queued": 64,
        # single-dispatch cross-shard aggregates (doc/perf.md): plan
        # sum|avg|min|max|count over range functions as ONE fused kernel
        # dispatch over a device-resident superblock when all shards are
        # local. false forces the reference scatter/partial-merge tree.
        "fused_aggregate": True,
        # fault tolerance (query/faults.py): default partial-results stance
        # (per-request allow_partial_results overrides), remote-child retry
        # budget, and per-endpoint circuit-breaker thresholds
        "allow_partial_results": False,
        "retry": {
            "max_attempts": 3,
            "base_backoff_s": 0.1,
            "max_backoff_s": 2.0,
        },
        "breaker": {
            "window": 16,
            "failure_rate": 0.5,
            "min_calls": 4,
            "cooldown_s": 15.0,
        },
        # observability (metrics.py): queries slower than the threshold
        # record PromQL + rendered trace tree in the slow-query log
        # (/debug/slow_queries, counted as filodb_slow_queries_total).
        # null disables; log size is a ring buffer.
        "slow_query_threshold_s": 10.0,
        "slow_query_log_max": 64,
        # query observatory (obs/querylog.py, doc/observability.md "Query
        # observatory"): every executed query leaves one exemplar-level
        # cost record (phases, path, stats) in a bounded ring served at
        # /debug/querylog and /api/v1/query_profile?id=. This sizes the
        # ring; capture itself is always on (host-side metadata only).
        "querylog_max": 512,
        # cross-query micro-batching (query/scheduler.py): concurrent
        # fused queries sharing a hot superblock + grid/epilogue signature
        # collect for this window and launch as ONE batched kernel (vmap
        # over per-query window/offset/q/group-by). 0 disables. Every
        # fused query pays up to the window in added latency, so this is
        # the high-QPS-serving knob: enable (1-5 ms) when concurrent
        # dashboard fan-out dominates, keep 0 for latency-critical
        # single-user setups. batch_max closes a group early.
        "batch_window_ms": 0.0,
        "batch_max": 32,
        # adaptive batch window (query/scheduler.py, doc/perf.md
        # "Cost-model scheduling"): with a cap > batch_window_ms the
        # effective window scales with predicted queued device-seconds —
        # it collapses toward ZERO when the node idles (no batching tax)
        # and widens toward the cap as predicted load approaches
        # batch_load_ref_cost_s (decayed accumulator of admitted
        # predicted costs). 0 keeps the window fixed at batch_window_ms.
        "batch_window_cap_ms": 0.0,
        "batch_load_ref_cost_s": 0.25,
        # executable pre-warm (doc/perf.md): a background tick scans the
        # scheduler's recurrence ring for keys seen >= prewarm_min_count
        # times (any recurrence during a recompile storm) and trace+
        # compiles their programs off the serving path, so the first real
        # poll of a soon-hot dashboard pays zero compiles. 0 disables the
        # tick; interval_s paces it.
        "prewarm": {
            "enabled": True,
            "min_count": 3,
            "interval_s": 5.0,
            "per_tick": 2,
        },
        # work cost model (query/costmodel.py): predicted device-seconds
        # per query from the normalized-promql fingerprint joined to the
        # kernel registry's warm dispatch stats. prior_cost_s doubles as
        # the legacy query-count -> device-second quota conversion rate;
        # alpha is the online EWMA step; cold fingerprints price at
        # family-mean * cold_multiplier (the compile they may trigger).
        "costmodel": {
            "prior_cost_s": 0.05,
            "alpha": 0.3,
            "cold_multiplier": 2.0,
        },
        # per-tenant admission control (doc/operations.md): maps "ws/ns"
        # (or "*" = default for every tenant, including "unknown") to
        # {"rate_device_s": device-seconds/s, "burst_device_s": bucket,
        # "max_concurrent": n}. Buckets refill in predicted DEVICE-SECONDS
        # (the cost model prices each query), so an expensive query drains
        # proportionally more than a cheap one. Legacy {"rate": queries/s,
        # "burst": n} configs convert via costmodel.prior_cost_s. Over-
        # quota queries shed with HTTP 429 + Retry-After derived from the
        # bucket's actual predicted drain time (gRPC: typed in-band error
        # + retry-after metadata). Empty = no tenant quotas.
        "tenant_quotas": {},
        # global bound on admitted-and-unfinished queries (0 = unbounded);
        # past it every tenant sheds with 429 until in-flight drains
        "admission_max_queued": 0,
    },
    # API
    "http_port": 9090,
    # gRPC RemoteExec service (api/grpc_exec.py; reference PromQLGrpcServer +
    # query_service.proto RemoteExec). null = disabled; 0 = ephemeral port.
    # Peers declared as "grpc://host:port" in distributed.peers use it for
    # binary plan-level scatter instead of PromQL-over-HTTP. grpc_host
    # defaults loopback-only; multi-host deployments set "0.0.0.0" AND an
    # http_auth_token (the service executes arbitrary queries).
    "grpc_port": None,
    "grpc_host": "127.0.0.1",
    # optional bearer token protecting /api/* (remote execs send it via
    # FILODB_REMOTE_TOKEN); null = open
    "http_auth_token": None,
    # multi-host deployment: each process owns a shard slice and scatters
    # queries to its peers over HTTP. "coordinator" joins the JAX
    # distributed runtime for cross-host meshes (null = skip); env
    # FILODB_COORDINATOR/FILODB_NUM_PROCESSES/FILODB_PROCESS_ID override.
    # "peers": base URLs of the OTHER processes; "owned_shards": explicit
    # shard list for this process (default: ordinal slice of "shards").
    # "seeds": bootstrap URLs polled for /__members at startup (the
    # akka-bootstrapper whitelist analog); discovered members become query
    # peers dynamically and a refresh loop ages dead ones out.
    # "advertise_url": this node's URL as peers should reach it (required
    # with seeds unless the default http://127.0.0.1:<port> is reachable).
    "distributed": {
        "coordinator": None, "num_processes": None, "process_id": None,
        "peers": [], "owned_shards": None,
        "seeds": [], "advertise_url": None, "refresh_interval_s": 30,
    },
    # standing-query engine (filodb_tpu/standing/, doc/operations.md
    # "Standing queries & recording rules"): hot recurring live-edge
    # queries promote into registered standing queries whose [G, J]
    # partials are DELTA-maintained on ingest append and served by push
    # (SSE fan-out) — plus the recording-rules API. Promotion needs
    # promote_min_count recurrences inside promote_window_s from a query
    # whose grid end trails wall clock by at most promote_live_lag_ms;
    # auto-promoted queries demote after demote_idle_s of no recurrence
    # and no subscribers (hysteresis). max_subscribers bounds SSE fan-out
    # per standing query; key_ring_max bounds the scheduler's retained
    # per-key recurrence ring; align_ms quantizes staging ranges so every
    # refresh rides ONE extendable superblock cache entry.
    # "serve_range": ordinary /api/v1/query_range requests that match a
    # registered standing query's promql+step serve straight from its
    # retained [G, J] partials (querylog path standing:serve) instead of
    # re-executing.
    "standing": {
        "enabled": True,
        "serve_range": True,
        "promote_min_count": 8,
        "promote_window_s": 120.0,
        "promote_live_lag_ms": 120_000,
        "demote_idle_s": 600.0,
        "demote_retry_s": 3600.0,
        "max_standing": 64,
        "max_subscribers": 64,
        "refresh_debounce_ms": 250,
        "key_ring_max": 512,
        "default_span_ms": 1_800_000,
        "align_ms": 300_000,
        "tick_s": 0.5,
    },
    # result plane (doc/perf.md "Result plane"): how query results leave
    # the node. stream_min_samples: above this, query_range bodies stream
    # chunked with D2H/encode overlap; stream_block_rows: series rows per
    # device->host block on that path (0 pulls whole grids upfront);
    # peer_exchange: "arrow" serves/requests columnar Arrow IPC frames on
    # node-to-node hops (JSON renders exactly once, at the user edge),
    # "json" forces decimal JSON on every hop (debug / rolling downgrade).
    "result_plane": {
        "stream_min_samples": 200_000,
        "stream_block_rows": 512,
        "peer_exchange": "arrow",
    },
    # kernel & compile observatory (obs/kernels.py, doc/observability.md
    # "Kernel & compile observatory"): every jitted kernel dispatch is
    # accounted per executable (compiles, dispatches, device p50/p99,
    # compile-cache provenance) at /debug/kernels — capture is always on,
    # these knobs size the table and the recompile-storm detector. A family
    # compiling more than storm_threshold times inside storm_window_s
    # counts filodb_xla_recompile_storms_total and annotates the unstable
    # key dimension. device_timing adds a block_until_ready around each
    # warm dispatch for exact device cost (bench/attest runs turn it on;
    # serving keeps it off — the sync serializes the dispatch pipeline).
    "kernel_obs": {
        "max_executables": 1024,
        "storm_threshold": 5,
        "storm_window_s": 60.0,
        "device_timing": False,
    },
    # downsampling (reference downsample resolutions)
    "downsample": {"enabled": False, "periods_m": [5, 60]},
    # sketch rollup tier (downsample/rollup.py + downsample/chooser.py,
    # doc/perf.md "Sketch rollup tier"): per-period mergeable summary
    # blocks (log-linear sketch + min/max/sum/count moments) maintained
    # over the ingest path; the planner substitutes them for long-range
    # window queries whose step/window the resolution divides, so a
    # 30-day quantile reads O(periods) instead of O(raw samples). The
    # chooser trains the rollup set on the querylog: a fingerprint
    # recurring >= min_count times with span >= min_span_ms earns a
    # rollup at the coarsest ladder resolution serving its shape;
    # chooser-owned entries idle > idle_s retire. grace_ms holds back
    # the fold watermark so the live edge stays raw-served.
    "rollup": {
        "enabled": True,
        "grace_ms": 120_000,
        "max_entries": 64,
        "tick_s": 5.0,
        "chooser": {
            "enabled": True,
            "resolutions_ms": [300_000, 3_600_000],
            "min_count": 3,
            "min_span_ms": 86_400_000,
            "idle_s": 3600.0,
            "interval_s": 30.0,
        },
    },
    # cardinality quotas: list of {"prefix": ["ws","ns"], "quota": N}
    "quotas": [],
    # streaming preagg rules: [{"metric_regex", "include_tags"|"exclude_tags"}]
    "preagg_rules": [],
    # profiler (reference filodb.profiler)
    "profiler": {"enabled": False, "interval_ms": 10},
    # self-telemetry (telemetry.py): when self_scrape_interval_s is set the
    # server samples its own /metrics registry every interval and ingests
    # the samples as real time series into the "_system" dataset, queryable
    # through the standard query API via ?dataset=_system (so dashboards
    # over the server's own kernel/cache/tenant metrics run through the
    # fused query path). null disables. tpu_watch_log: path of the
    # tools/tpu_watch.py log to surface as filodb_tpu_* gauges ("auto" =
    # <repo>/TPU_WATCH_LOG.txt when present; null disables).
    "telemetry": {
        "self_scrape_interval_s": None,
        "self_scrape_spread": 1,
        "tpu_watch_log": "auto",
    },
    # SLO burn-rate recording rules over the query observatory (obs/slo.py,
    # doc/observability.md "SLO burn-rate rules"): a second standing-query
    # maintainer bound to the _system engine evaluates default availability
    # (non-5xx share of non-shed responses vs the error budget) and latency
    # (p99 vs objective) burn rates and writes them back into _system as
    # real series. enabled null = auto: on exactly when the _system
    # pipeline runs (telemetry.self_scrape_interval_s set) and the
    # standing engine is enabled. latency_objectives_s maps "ws/ns" (or
    # "*" = global) to a p99 objective in seconds.
    "slo": {
        "enabled": None,
        "availability_objective": 0.999,
        "latency_objectives_s": {"*": 2.0},
        "windows": ["5m", "1h"],
        "interval_s": 15.0,
    },
    # alerting plane (obs/alerting.py + obs/notify.py, doc/observability.md
    # "Alerting plane"): Prometheus-compatible alerting rule groups loaded
    # from rule_files (globs) and POST /api/v1/rules/alert, evaluated on
    # the _system standing engine (each rule's expr is a standing query;
    # the newest closed step feeds the inactive→pending→firing state
    # machine), with state written back as ALERTS / ALERTS_FOR_STATE
    # series (restart-safe via rehydrate_lookback_ms) and firing alerts
    # fanned out to Alertmanager-v2 webhook receivers
    # ([{name, url, group_by, group_wait, group_interval, repeat_interval,
    # send_resolved}]). enabled null = auto: on exactly when the _system
    # standing engine runs.
    "alerting": {
        "enabled": None,
        "rule_files": [],
        "default_interval_s": 15.0,
        "rehydrate_lookback_ms": 3_600_000,
        "notify_tick_s": 1.0,
        "notify_deadline_s": 10.0,
        "receivers": [],
    },
}


def force_virtual_devices(n: int) -> None:
    """Ensure ``XLA_FLAGS`` requests at least ``n`` virtual host-platform
    devices — must run BEFORE the first jax backend init. A smaller
    pre-existing count (e.g. inherited from a test harness) is replaced; a
    larger one is kept. The ONE definition of the flag-forcing defense
    shared by the MULTICHIP dryrun (__graft_entry__) and bench.py's
    fused_mesh workload."""
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m and int(m.group(1)) < n:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n}"
        )
    elif not m:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()


def apply_platform_env() -> None:
    """Honor ``FILODB_PLATFORM`` (e.g. "cpu", "tpu"): force the JAX platform
    BEFORE first backend init. Deployment images may preload an accelerator
    plugin via sitecustomize that reads env vars too late and whose backend
    init can wedge indefinitely when the device link is down — the live jax
    config override is the only reliable defense (same as tests/conftest.py
    and __graft_entry__.dryrun_multichip)."""
    plat = os.environ.get("FILODB_PLATFORM")
    if plat:
        os.environ["JAX_PLATFORMS"] = plat
        import jax

        jax.config.update("jax_platforms", plat)


def load_config(path: str | None = None, overrides: dict | None = None) -> dict:
    """defaults <- file <- overrides (later wins, one level deep for dicts)."""
    cfg = json.loads(json.dumps(DEFAULTS))  # deep copy
    layers = []
    if path:
        with open(path) as f:
            layers.append(json.load(f))
    if overrides:
        layers.append(overrides)
    for layer in layers:
        for k, v in layer.items():
            if isinstance(v, dict) and isinstance(cfg.get(k), dict):
                cfg[k].update(v)
            else:
                cfg[k] = v
    return cfg
