"""Alerting plane: rule groups + the pending→firing state machine riding
the standing-query engine (ISSUE 18; doc/observability.md "Alerting
plane").

An alerting rule is a standing query plus a threshold state machine
(Tailwind's explicit-obligation framing — PAPERS.md): the rule's ``expr``
registers on the :class:`~filodb_tpu.standing.maintainer.StandingEngine`
with an ``alert_sink``, so the maintainer's delta-refreshed newest closed
step — never a separate dispatch plane — feeds each evaluation. Every
evaluation therefore already leaves a querylog record
(``path=standing:delta|standing:full``) and alerting cost is attributable
like any other tenant.

Per label set the machine walks ``inactive → pending → firing``
(Prometheus semantics):

- the expr returning a sample CREATES a pending alert (or fires
  immediately when ``for: 0``);
- a pending alert held continuously for ``for:`` promotes to firing;
- absence resolves: a pending alert drops straight back to inactive
  (never notified), a firing one resolves — unless ``keep_firing_for``
  still covers the gap (flap suppression).

State is durable the FiloDB way: every evaluation writes
``ALERTS{alertname,alertstate,...}`` (value 1) and
``ALERTS_FOR_STATE{alertname,...}`` (value = seconds since the alert went
active — an age, not Prometheus's absolute epoch, because the store's f32
value column resolves epochs only to ±64s but holds ages to sub-ms)
back through the production ingest path into the engine's dataset
(``_system`` in the server wiring), so firing state is queryable through
the fused path and :meth:`AlertingEngine.rehydrate` restores it across a
restart from the same series it wrote.

Rule groups load from YAML files (``conf/rules/*.yml``; schema-checked at
load — an invalid file raises :class:`RuleFileError` naming the exact
group/rule) and register at runtime (``POST /api/v1/rules/alert``).
Firing alerts fan out to Alertmanager-v2-compatible webhook receivers via
:class:`~filodb_tpu.obs.notify.Notifier`.
"""

from __future__ import annotations

import glob as _glob
import hashlib
import logging
import re
import threading
import time
from dataclasses import dataclass, field

from ..metrics import REGISTRY

log = logging.getLogger("filodb_tpu.obs.alerting")

# canonical alertstate values (linted by tools/check_metrics.py against
# doc/observability.md): `inactive` never appears on ALERTS series (an
# inactive alert has no series), only in the filodb_alerts gauge + the
# /api/v1/rules state rollup
ALERT_STATES = ("inactive", "pending", "firing")

# the synthetic series families alert state writes back as
ALERTS_SERIES = "ALERTS"
ALERTS_FOR_STATE_SERIES = "ALERTS_FOR_STATE"

# Prometheus rule/metric name charset
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# labels the state machine owns; a rule declaring them would collide with
# its own write-back
_RESERVED_LABELS = ("alertname", "alertstate")

DEFAULTS: dict = {
    # null = auto: on exactly when the _system standing engine runs
    "enabled": None,
    # globs, resolved relative to the process cwd (conf/rules/*.yml)
    "rule_files": [],
    # evaluation cadence for groups that don't set `interval:`
    "default_interval_s": 15.0,
    # how far back rehydrate() searches ALERTS_FOR_STATE on startup
    "rehydrate_lookback_ms": 3_600_000,
    # notifier cadence + per-delivery deadline budget (obs/notify.py)
    "notify_tick_s": 1.0,
    "notify_deadline_s": 10.0,
    # Alertmanager-v2 webhook receivers (obs/notify.py Receiver fields)
    "receivers": [],
}


class RuleFileError(ValueError):
    """A rule file/spec failed schema validation — the message names the
    file, group and rule so a bad deploy is a one-line diagnosis."""


def rfc3339(ms: int) -> str:
    """Prometheus API timestamp rendering; <= 0 is the API's zero time."""
    if ms <= 0:
        return "0001-01-01T00:00:00Z"
    t = time.gmtime(ms / 1000.0)
    return (f"{t.tm_year:04d}-{t.tm_mon:02d}-{t.tm_mday:02d}"
            f"T{t.tm_hour:02d}:{t.tm_min:02d}:{t.tm_sec:02d}"
            f".{int(ms % 1000):03d}Z")


_TMPL = re.compile(
    r"\{\{\s*\$(?:labels\.([a-zA-Z_][a-zA-Z0-9_]*)|(value))\s*\}\}"
    r"|\$(?:labels\.([a-zA-Z_][a-zA-Z0-9_]*)|(value))"
)


def expand_template(text: str, labels: dict, value: float) -> str:
    """Annotation templating: ``{{ $labels.x }}`` / ``{{ $value }}`` (and
    the brace-less shorthand). Unknown labels expand to the empty string —
    an annotation typo must not fail an evaluation."""

    def _sub(m: re.Match) -> str:
        name = m.group(1) or m.group(3)
        if name is not None:
            return str(labels.get(name, ""))
        return f"{float(value):g}"

    return _TMPL.sub(_sub, str(text))


def fingerprint(labels: dict) -> str:
    """Stable per-labelset identity (alertstate excluded — state changes
    must not change identity)."""
    h = hashlib.blake2b(digest_size=8)
    for k, v in sorted(labels.items()):
        if k == "alertstate":
            continue
        h.update(k.encode())
        h.update(b"\x00")
        h.update(str(v).encode())
        h.update(b"\x00")
    return h.hexdigest()


def _duration_s(val, where: str) -> float:
    """Rule-file duration: a bare number is seconds, a string is a PromQL
    duration (``30s``, ``5m``)."""
    if val is None:
        return 0.0
    if isinstance(val, bool):
        raise RuleFileError(f"{where}: expected a duration, got {val!r}")
    if isinstance(val, (int, float)):
        if val < 0:
            raise RuleFileError(f"{where}: duration must be >= 0")
        return float(val)
    from ..query.promql import PromQLError, parse_duration_ms

    try:
        return parse_duration_ms(str(val)) / 1000.0
    except PromQLError as e:
        raise RuleFileError(f"{where}: bad duration {val!r}: {e}") from e


@dataclass
class ActiveAlert:
    """One labelset currently pending or firing for one rule."""

    labels: dict
    annotations: dict
    state: str  # pending | firing
    active_at_ms: int  # when the condition first became true
    value: float
    last_true_ms: int  # newest eval where the condition held (flap clock)
    fired_at_ms: int = 0
    fingerprint: str = ""

    def payload(self) -> dict:
        """Prometheus `/api/v1/alerts` entry shape."""
        return {
            "labels": dict(self.labels),
            "annotations": dict(self.annotations),
            "state": self.state,
            "activeAt": rfc3339(self.active_at_ms),
            "value": f"{self.value:g}",
        }


@dataclass
class AlertRule:
    name: str
    expr: str
    for_s: float = 0.0
    keep_firing_for_s: float = 0.0
    labels: dict = field(default_factory=dict)
    annotations: dict = field(default_factory=dict)
    group: str = ""
    file: str = ""
    # runtime state
    sq: object = field(default=None, repr=False)
    active: dict = field(default_factory=dict, repr=False)  # fp -> ActiveAlert
    eval_duration_s: float = 0.0
    last_eval_s: float = 0.0
    last_error: str | None = None

    def state(self) -> str:
        states = {a.state for a in self.active.values()}
        if "firing" in states:
            return "firing"
        if "pending" in states:
            return "pending"
        return "inactive"


@dataclass
class RecordingRule:
    name: str
    expr: str
    group: str = ""
    file: str = ""
    sq: object = field(default=None, repr=False)


@dataclass
class RuleGroup:
    name: str
    file: str
    interval_s: float
    rules: list = field(default_factory=list)


def _parse_string_map(val, where: str, reserved: tuple = ()) -> dict:
    if val is None:
        return {}
    if not isinstance(val, dict):
        raise RuleFileError(f"{where}: expected a mapping, got "
                            f"{type(val).__name__}")
    out = {}
    for k, v in val.items():
        if not isinstance(k, str) or not _LABEL_RE.match(k):
            raise RuleFileError(f"{where}: bad label name {k!r}")
        if k in reserved:
            raise RuleFileError(
                f"{where}: label {k!r} is reserved for the state machine"
            )
        if isinstance(v, bool) or not isinstance(v, (str, int, float)):
            raise RuleFileError(f"{where}: label {k!r} value must be a "
                                f"string/number, got {type(v).__name__}")
        out[k] = str(v)
    return out


def parse_rule_spec(spec, where: str, group: str = "",
                    file: str = ""):
    """One rule mapping → :class:`AlertRule` | :class:`RecordingRule`,
    schema-checked (shared by file loading and the runtime API)."""
    if not isinstance(spec, dict):
        raise RuleFileError(f"{where}: rule must be a mapping")
    kind = [k for k in ("alert", "record") if k in spec]
    if len(kind) != 1:
        raise RuleFileError(
            f"{where}: rule needs exactly one of `alert:` / `record:`"
        )
    name = spec[kind[0]]
    if not isinstance(name, str) or not _NAME_RE.match(name):
        raise RuleFileError(f"{where}: bad rule name {name!r}")
    expr = spec.get("expr")
    if not isinstance(expr, str) or not expr.strip():
        raise RuleFileError(f"{where}: rule {name!r} needs a non-empty "
                            f"`expr:`")
    if kind[0] == "record":
        extra = set(spec) - {"record", "expr"}
        if "labels" in extra:
            raise RuleFileError(
                f"{where}: recording rule {name!r}: `labels:` is not "
                f"supported (write-back keys series by group labels only)"
            )
        if extra:
            raise RuleFileError(
                f"{where}: recording rule {name!r}: unknown keys "
                f"{sorted(extra)}"
            )
        return RecordingRule(name=name, expr=expr.strip(), group=group,
                             file=file)
    allowed = {"alert", "expr", "for", "keep_firing_for", "labels",
               "annotations"}
    extra = set(spec) - allowed
    if extra:
        raise RuleFileError(
            f"{where}: alerting rule {name!r}: unknown keys {sorted(extra)}"
        )
    return AlertRule(
        name=name, expr=expr.strip(),
        for_s=_duration_s(spec.get("for"), f"{where}: {name} for"),
        keep_firing_for_s=_duration_s(
            spec.get("keep_firing_for"), f"{where}: {name} keep_firing_for"
        ),
        labels=_parse_string_map(spec.get("labels"),
                                 f"{where}: {name} labels",
                                 reserved=_RESERVED_LABELS),
        annotations=_parse_string_map(spec.get("annotations"),
                                      f"{where}: {name} annotations"),
        group=group, file=file,
    )


def parse_rule_groups(doc, file: str = "") -> list[RuleGroup]:
    """One parsed YAML document → schema-checked :class:`RuleGroup` list
    (Prometheus rule-file layout: top-level ``groups:`` only)."""
    where = file or "<rules>"
    if not isinstance(doc, dict):
        raise RuleFileError(f"{where}: rule file must be a mapping")
    extra = set(doc) - {"groups"}
    if extra:
        raise RuleFileError(f"{where}: unknown top-level keys "
                            f"{sorted(extra)}")
    groups_raw = doc.get("groups")
    if not isinstance(groups_raw, list):
        raise RuleFileError(f"{where}: `groups:` must be a list")
    out: list[RuleGroup] = []
    seen: set[str] = set()
    for gi, g in enumerate(groups_raw):
        gwhere = f"{where}: groups[{gi}]"
        if not isinstance(g, dict):
            raise RuleFileError(f"{gwhere}: group must be a mapping")
        extra = set(g) - {"name", "interval", "rules"}
        if extra:
            raise RuleFileError(f"{gwhere}: unknown keys {sorted(extra)}")
        name = g.get("name")
        if not isinstance(name, str) or not name.strip():
            raise RuleFileError(f"{gwhere}: group needs a non-empty "
                                f"`name:`")
        if name in seen:
            raise RuleFileError(f"{gwhere}: duplicate group name {name!r}")
        seen.add(name)
        interval_s = _duration_s(g.get("interval"), f"{gwhere}: interval")
        rules_raw = g.get("rules")
        if not isinstance(rules_raw, list) or not rules_raw:
            raise RuleFileError(f"{gwhere}: group {name!r} needs a "
                                f"non-empty `rules:` list")
        grp = RuleGroup(name=name, file=file, interval_s=interval_s)
        rnames: set[str] = set()
        for ri, spec in enumerate(rules_raw):
            rule = parse_rule_spec(
                spec, f"{where}: group {name!r} rules[{ri}]",
                group=name, file=file,
            )
            if rule.name in rnames:
                raise RuleFileError(
                    f"{gwhere}: duplicate rule name {rule.name!r} in "
                    f"group {name!r}"
                )
            rnames.add(rule.name)
            grp.rules.append(rule)
        out.append(grp)
    return out


def load_rule_file(path: str) -> list[RuleGroup]:
    """Parse + schema-check one YAML rule file."""
    import yaml

    with open(path, encoding="utf-8") as f:
        try:
            doc = yaml.safe_load(f)
        except yaml.YAMLError as e:
            raise RuleFileError(f"{path}: invalid YAML: {e}") from e
    return parse_rule_groups(doc, file=path)


class _Sink:
    """The ``alert_sink`` callable registered on the standing query —
    carries the rule name so the maintainer can label eval failures it
    intercepts before the sink ever runs."""

    def __init__(self, engine: "AlertingEngine", rule: AlertRule):
        self._engine = engine
        self._rule = rule
        self.rule = rule.name

    def __call__(self, sq, end_ms: int, vec: list) -> None:
        self._engine._eval_rule(self._rule, end_ms, vec)


class AlertingEngine:
    """Rule groups + per-labelset state machines, bound to one
    StandingEngine (the server binds the ``_system`` one)."""

    def __init__(self, standing, config: dict | None = None, notifier=None):
        self.cfg = {**DEFAULTS, **(config or {})}
        self.standing = standing
        self.clock = standing.clock
        self.notifier = notifier
        self.groups: dict[str, RuleGroup] = {}
        self._lock = threading.RLock()
        if notifier is not None:
            notifier.alerts_source = self.firing_alerts
        # scrape-time gauge: filodb_alerts{alertstate} mirrors live state
        REGISTRY.register_collector(f"alerting:{id(self)}",
                                    self._publish_gauges)

    # -- rule loading / registration --------------------------------------

    def load_rule_files(self, patterns=None) -> int:
        """Glob + parse + register every configured rule file. Schema
        errors RAISE (a bad rule file is a deploy error, not a runtime
        hiccup); an individual rule failing to PLAN logs and is skipped,
        like the SLO set. Returns the number of rules registered."""
        if patterns is None:
            patterns = self.cfg.get("rule_files") or []
        if isinstance(patterns, str):
            patterns = [patterns]
        n = 0
        for pat in patterns:
            paths = sorted(_glob.glob(pat)) or []
            if not paths:
                log.warning("alerting: rule file pattern %r matched "
                            "nothing", pat)
            for path in paths:
                for grp in load_rule_file(path):
                    n += self._add_group(grp)
        return n

    def _add_group(self, grp: RuleGroup) -> int:
        with self._lock:
            if grp.name in self.groups:
                raise RuleFileError(
                    f"{grp.file or '<rules>'}: duplicate group name "
                    f"{grp.name!r} (already loaded from "
                    f"{self.groups[grp.name].file or '<runtime>'})"
                )
            self.groups[grp.name] = grp
        n = 0
        for rule in grp.rules:
            if self._register(grp, rule):
                n += 1
        return n

    def add_rule(self, spec: dict, group: str = "api",
                 interval_s: float | None = None):
        """Runtime registration (``POST /api/v1/rules/alert``): one rule
        spec in the same shape a rule file carries. Raises
        :class:`RuleFileError` on schema problems, ValueError when the
        expr fails to plan."""
        rule = parse_rule_spec(spec, "<api>", group=group, file="")
        with self._lock:
            grp = self.groups.get(group)
            if grp is None:
                grp = RuleGroup(
                    name=group, file="",
                    interval_s=(float(interval_s) if interval_s
                                else float(self.cfg["default_interval_s"])),
                )
                self.groups[group] = grp
            if any(r.name == rule.name and type(r) is type(rule)
                   for r in grp.rules):
                raise RuleFileError(
                    f"group {group!r} already has a rule named "
                    f"{rule.name!r}"
                )
            grp.rules.append(rule)
        if not self._register(grp, rule, raise_on_error=True):
            with self._lock:
                grp.rules.remove(rule)
                if not grp.rules:
                    self.groups.pop(group, None)
            raise ValueError(f"rule {rule.name!r} failed to register")
        return rule

    def _register(self, grp: RuleGroup, rule,
                  raise_on_error: bool = False) -> bool:
        interval_s = grp.interval_s or float(self.cfg["default_interval_s"])
        step_ms = max(int(interval_s * 1000), 1)
        try:
            if isinstance(rule, AlertRule):
                rule.sq = self.standing.register(
                    rule.expr, step_ms, span_ms=4 * step_ms,
                    source="alert", eval_interval_s=interval_s,
                    alert_sink=_Sink(self, rule),
                )
            else:
                rule.sq = self.standing.register(
                    rule.expr, step_ms, span_ms=4 * step_ms,
                    source="rule", rule_name=rule.name,
                    eval_interval_s=interval_s,
                )
            return True
        except Exception:  # noqa: BLE001 — one sick rule must not kill the set
            if raise_on_error:
                raise
            log.exception("alerting: rule %s (%s) failed to register",
                          rule.name, grp.name)
            return False

    # -- evaluation (called by the standing maintainer's alert_sink) ------

    def _eval_rule(self, rule: AlertRule, end_ms: int, vec: list) -> None:
        """One evaluation tick: walk the state machine over the newest
        closed step's per-group column, write ALERTS/ALERTS_FOR_STATE
        back, and hand resolved alerts to the notifier."""
        t0 = time.perf_counter()
        try:
            with self._lock:
                resolved = self._step_state(rule, end_ms, vec)
                recs = self._state_recs(rule, end_ms)
            self._write_back(recs)
            if resolved and self.notifier is not None:
                self.notifier.note_resolved(resolved)
            rule.last_error = None
        except Exception as e:  # noqa: BLE001 — alerting must not kill refresh
            rule.last_error = f"{type(e).__name__}: {e}"
            REGISTRY.counter("filodb_alert_eval_failures",
                             rule=rule.name).inc()
            log.exception("alert rule %s evaluation failed", rule.name)
        finally:
            rule.eval_duration_s = time.perf_counter() - t0
            rule.last_eval_s = self.clock()
            REGISTRY.histogram("filodb_alert_eval_seconds").observe(
                rule.eval_duration_s
            )

    def _alert_labels(self, rule: AlertRule, series_labels: dict) -> dict:
        from ..core.schemas import METRIC_TAG

        labels = {k: str(v) for k, v in series_labels.items()
                  if k not in (METRIC_TAG, "__name__")}
        labels.update(rule.labels)
        labels["alertname"] = rule.name
        return labels

    def _step_state(self, rule: AlertRule, end_ms: int,
                    vec: list) -> list[dict]:
        """The per-labelset state machine (caller holds self._lock).
        Returns resolved-alert dicts for the notifier."""
        seen: set[str] = set()
        for series_labels, value in vec:
            labels = self._alert_labels(rule, dict(series_labels))
            fp = fingerprint(labels)
            seen.add(fp)
            a = rule.active.get(fp)
            if a is None:
                a = ActiveAlert(
                    labels=labels, annotations={}, state="pending",
                    active_at_ms=end_ms, value=float(value),
                    last_true_ms=end_ms, fingerprint=fp,
                )
                rule.active[fp] = a
            a.value = float(value)
            a.last_true_ms = end_ms
            if (a.state == "pending"
                    and end_ms - a.active_at_ms >= rule.for_s * 1000):
                a.state = "firing"
                a.fired_at_ms = end_ms
            # annotations re-expand every eval: $value tracks the series
            a.annotations = {
                k: expand_template(v, labels, a.value)
                for k, v in rule.annotations.items()
            }
        resolved: list[dict] = []
        for fp in [fp for fp in rule.active if fp not in seen]:
            a = rule.active[fp]
            if a.state == "pending":
                # never fired → never notified: straight back to inactive
                del rule.active[fp]
                continue
            if (rule.keep_firing_for_s > 0
                    and end_ms - a.last_true_ms
                    < rule.keep_firing_for_s * 1000):
                continue  # flap suppression: hold firing through the gap
            del rule.active[fp]
            resolved.append({
                "fingerprint": a.fingerprint,
                "labels": dict(a.labels),
                "annotations": dict(a.annotations),
                "starts_at_ms": a.fired_at_ms or a.active_at_ms,
                "ends_at_ms": end_ms,
            })
        return resolved

    def _state_recs(self, rule: AlertRule, end_ms: int) -> list:
        """(series name, tags, t, v) write-back records for every active
        alert of one rule (caller holds self._lock)."""
        recs = []
        for a in rule.active.values():
            recs.append((ALERTS_SERIES,
                         {**a.labels, "alertstate": a.state},
                         end_ms, 1.0))
            # the value is the alert's AGE in seconds, not the absolute
            # epoch Prometheus stores: the store's value column is f32,
            # where epoch seconds round to ±64s but ages stay sub-ms
            # accurate for days — rehydrate() subtracts the age from the
            # exact int64 sample timestamp to recover active_at
            recs.append((ALERTS_FOR_STATE_SERIES, dict(a.labels),
                         end_ms, (end_ms - a.active_at_ms) / 1000.0))
        return recs

    def _write_back(self, recs: list) -> None:
        """State → series, through the production ingest path: the same
        routing/quota/cardinality machinery every tenant pays."""
        if not recs:
            return
        from ..core.records import gauge_batch

        engine = self.standing.engine
        by_name: dict[str, list] = {}
        for name, tags, t, v in recs:
            by_name.setdefault(name, []).append((tags, int(t), float(v)))
        for name, rows in by_name.items():
            try:
                engine.memstore.ingest_routed(
                    self.standing.dataset, gauge_batch(name, rows),
                    spread=engine.planner.params.spread,
                )
            except Exception:  # noqa: BLE001 — quota/cardinality shed
                log.exception("alert state write-back failed: %s", name)

    # -- restart safety ----------------------------------------------------

    def rehydrate(self, now_ms: int | None = None) -> int:
        """Restore pending/firing state from the ``ALERTS_FOR_STATE``
        series this process (or its predecessor) wrote — an alert that was
        already firing must not restart its ``for:`` clock just because
        the server restarted. Returns the number of alerts restored."""
        import numpy as np

        if now_ms is None:
            now_ms = int(self.clock() * 1000)
        lookback = int(self.cfg["rehydrate_lookback_ms"])
        with self._lock:
            rules = {r.name: r
                     for g in self.groups.values() for r in g.rules
                     if isinstance(r, AlertRule)}
        if not rules:
            return 0
        step_s = min(
            [g.interval_s or float(self.cfg["default_interval_s"])
             for g in self.groups.values()]
            or [float(self.cfg["default_interval_s"])]
        )
        try:
            res = self.standing.engine.query_range(
                ALERTS_FOR_STATE_SERIES,
                (now_ms - lookback) / 1000.0, now_ms / 1000.0,
                max(step_s, 1.0),
            )
        except Exception:  # noqa: BLE001 — a cold store has no state to restore
            log.exception("alert rehydration query failed")
            return 0
        from ..core.schemas import METRIC_TAG

        restored = 0
        with self._lock:
            for g in res.grids:
                vals = np.asarray(g.values_np(), dtype=float)
                times = g.step_times_ms()
                for gi, lbl in enumerate(g.labels):
                    labels = {k: str(v) for k, v in dict(lbl).items()
                              if k not in (METRIC_TAG, "__name__")}
                    rule = rules.get(labels.get("alertname", ""))
                    if rule is None:
                        continue
                    row = vals[gi]
                    ok = ~np.isnan(row)
                    if not ok.any():
                        continue
                    # each written sample satisfies grid_time - age*1000
                    # == active_at exactly; lookback carry-forward only
                    # inflates the difference, so the MINIMUM over the
                    # row recovers active_at to within one grid step
                    active_at_ms = int(
                        np.min(times[ok] - row[ok] * 1000.0)
                    )
                    fp = fingerprint(labels)
                    if fp in rule.active:
                        continue
                    state = ("firing"
                             if now_ms - active_at_ms >= rule.for_s * 1000
                             else "pending")
                    rule.active[fp] = ActiveAlert(
                        labels=labels,
                        annotations={
                            k: expand_template(v, labels, float("nan"))
                            for k, v in rule.annotations.items()
                        },
                        state=state, active_at_ms=active_at_ms,
                        value=float("nan"), last_true_ms=now_ms,
                        fired_at_ms=(active_at_ms if state == "firing"
                                     else 0),
                        fingerprint=fp,
                    )
                    restored += 1
        if restored:
            log.info("alerting: rehydrated %d active alert(s) from %s",
                     restored, ALERTS_FOR_STATE_SERIES)
        return restored

    # -- API payloads ------------------------------------------------------

    def alerts_payload(self, state: str | None = None) -> dict:
        """Prometheus ``GET /api/v1/alerts`` data shape."""
        with self._lock:
            alerts = [a.payload()
                      for g in self.groups.values() for r in g.rules
                      if isinstance(r, AlertRule)
                      for a in r.active.values()]
        if state:
            alerts = [a for a in alerts if a["state"] == state]
        return {"alerts": alerts}

    def rules_payload(self) -> dict:
        """Prometheus ``GET /api/v1/rules`` data shape for the loaded
        groups (both rule types; camelCase eval fields)."""
        groups = []
        with self._lock:
            for g in self.groups.values():
                rules = []
                last_ms = 0
                total_s = 0.0
                for r in g.rules:
                    if isinstance(r, AlertRule):
                        last_ms = max(last_ms, int(r.last_eval_s * 1000))
                        total_s += r.eval_duration_s
                        sq_err = getattr(r.sq, "last_error", None)
                        err = r.last_error or sq_err
                        rules.append({
                            "name": r.name,
                            "query": r.expr,
                            "duration": r.for_s,
                            "keepFiringFor": r.keep_firing_for_s,
                            "labels": dict(r.labels),
                            "annotations": dict(r.annotations),
                            "alerts": [a.payload()
                                       for a in r.active.values()],
                            "state": r.state(),
                            "health": "err" if err else "ok",
                            "lastError": err or "",
                            "evaluationTime": r.eval_duration_s,
                            "lastEvaluation": rfc3339(
                                int(r.last_eval_s * 1000)
                            ),
                            "type": "alerting",
                        })
                    else:
                        sq = r.sq
                        last_s = getattr(sq, "last_refresh_s", 0.0) or 0.0
                        dur = getattr(sq, "last_eval_duration_s", 0.0)
                        err = getattr(sq, "last_error", None)
                        last_ms = max(last_ms, int(last_s * 1000))
                        total_s += dur
                        rules.append({
                            "name": r.name,
                            "query": r.expr,
                            "labels": {},
                            "health": "err" if err else "ok",
                            "lastError": err or "",
                            "evaluationTime": dur,
                            "lastEvaluation": rfc3339(int(last_s * 1000)),
                            "type": "recording",
                        })
                groups.append({
                    "name": g.name,
                    "file": g.file,
                    "interval": g.interval_s
                    or float(self.cfg["default_interval_s"]),
                    "evaluationTime": total_s,
                    "lastEvaluation": rfc3339(last_ms),
                    "rules": rules,
                })
        return {"groups": groups}

    def rule_names(self) -> set[str]:
        """Names this engine owns (the HTTP layer uses this to keep the
        standing engine's synthetic group from double-listing them)."""
        with self._lock:
            return {r.name for g in self.groups.values() for r in g.rules}

    def firing_alerts(self) -> list[dict]:
        """The notifier's pull surface: every currently-firing alert."""
        with self._lock:
            out = []
            for g in self.groups.values():
                for r in g.rules:
                    if not isinstance(r, AlertRule):
                        continue
                    for a in r.active.values():
                        if a.state != "firing":
                            continue
                        out.append({
                            "fingerprint": a.fingerprint,
                            "labels": dict(a.labels),
                            "annotations": dict(a.annotations),
                            "starts_at_ms": a.fired_at_ms
                            or a.active_at_ms,
                        })
            return out

    # -- gauges / lifecycle ------------------------------------------------

    def _publish_gauges(self) -> None:
        counts = dict.fromkeys(ALERT_STATES, 0)
        with self._lock:
            for g in self.groups.values():
                for r in g.rules:
                    if not isinstance(r, AlertRule):
                        continue
                    if not r.active:
                        counts["inactive"] += 1
                        continue
                    for a in r.active.values():
                        counts[a.state] += 1
        for st in ALERT_STATES:
            REGISTRY.gauge("filodb_alerts", alertstate=st).set(
                float(counts[st])
            )

    def start(self) -> None:
        if self.notifier is not None:
            self.notifier.start()

    def stop(self) -> None:
        if self.notifier is not None:
            self.notifier.stop()
        REGISTRY.unregister_collector(f"alerting:{id(self)}")

    def snapshot(self) -> dict:
        """Debug rendering: groups + active alerts + notifier state."""
        with self._lock:
            groups = [{
                "name": g.name, "file": g.file,
                "interval_s": g.interval_s,
                "rules": [{
                    "name": r.name,
                    "type": ("alerting" if isinstance(r, AlertRule)
                             else "recording"),
                    "active": (len(r.active)
                               if isinstance(r, AlertRule) else 0),
                } for r in g.rules],
            } for g in self.groups.values()]
        out = {"groups": groups}
        if self.notifier is not None:
            out["notifier"] = self.notifier.snapshot()
        return out
