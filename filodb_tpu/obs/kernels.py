"""Kernel & compile observatory — the process-global executable registry
(doc/observability.md "Kernel & compile observatory").

PR 12's query observatory decomposes host wall time into phases, but its
``dispatch`` phase is one opaque number conflating XLA compilation, batch
queue skew and actual device execution. The ROADMAP's cost-model-driven
scheduling item needs *measured per-executable device costs* (Tailwind
prices admission by estimated accelerator work, PAPERS.md) and the
workload-chosen-rollup item needs per-kernel-variant costs joined to the
querylog fingerprint — so every jitted kernel entry point in ``ops/``
reports each dispatch here, keyed by the full static signature of the
executable it ran:

    family | variant | epilogue | shapes | mesh | batch

- **family**  — the instrumented entry point's kernel name (the same label
  ``filodb_kernel_dispatch_seconds{kernel=}`` carries), e.g.
  ``fused_sum_rate`` / ``mesh_fused_hist_quantile_sum_rate`` /
  ``batch_fused_sum_rate`` / ``segment_aggregate``.
- **variant** — the grid-class kernel variant the dispatch ladder chose
  (``mxu`` | ``jitter`` | ``masked`` | ``pallas`` | ``general`` |
  ``hist_shared`` | ``hist_jitter`` | ``hist_general`` | ...).
- **epilogue** — the fused epilogue statics (``agg:sum``, ``topk:5:False``,
  ``quantile``, ``hist:quantile``...).
- **shapes**  — the PADDED device shapes that select the XLA executable
  (``S4096xT720xJ64xG2``): padding discipline means a handful of stable
  buckets, so key count stays bounded in steady state.
- **mesh**    — device count under shard_map, ``-`` for single-device.
- **batch**   — batched-lane composition (``Q8U2``: 8 padded lanes, 2
  unique windows), ``-`` for unbatched.

Per key the registry records compile count + compile seconds (the dispatch
that grew the jit cache paid trace+compile inline — that wall time IS the
measurable compile cost), per-dispatch counts and device-time
:class:`~filodb_tpu.metrics.MicroHistogram` p50/p99 (host dispatch wall by
default; with ``kernel_obs.device_timing`` a ``jax.block_until_ready``
delta is folded in for exact device cost on the CPU backend — opt-in
because the sync serializes the async dispatch pipeline), executable bytes
(the persistent compile cache's serialized entry, when one was written)
and compile provenance: ``persistent`` (loaded from the on-disk XLA cache),
``in_process`` (the jit cache hit — the steady state) or ``fresh`` (traced
and compiled from nothing). Provenance reconciles BY CONSTRUCTION with
``filodb_compile_cache_{hits,misses}_total{tier=}`` — both are fed from
the same classification call (ops/compile_cache.classify_dispatch).

**Recompile storms**: a family re-compiling more than
``kernel_obs.storm_threshold`` times inside ``storm_window_s`` is the
SURVEY §7 failure mode (shape churn defeating the padding discipline).
The registry keeps a per-family ring of recent compile keys; on crossing
the threshold it counts ``filodb_xla_recompile_storms_total{family}`` and
annotates the family in ``/debug/kernels`` with the UNSTABLE DIMENSION —
the key component(s) that actually varied across the window's compiles
(``shapes`` churn reads very differently from an ``epilogue`` sweep).

Overhead contract: pure host-side metadata accounting (shape tuples, one
small lock) — no device sync on the default path; the warm canonical query
stays exactly ONE kernel dispatch and records ZERO new compiles with the
observatory on (asserted in tests/test_kernel_obs.py).
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque

from ..metrics import REGISTRY, MicroHistogram

# the ONE canonical key-dimension order (doc/observability.md documents the
# anatomy; tools/check_metrics.py lints that every ops/ jit wrapper
# registers with this registry)
KEY_DIMS = ("family", "variant", "epilogue", "shapes", "mesh", "batch")

_PROVENANCE = ("fresh", "persistent", "in_process")


def _fmt(v) -> str:
    if v is None or v == "" or v == ():
        return "-"
    return str(v)


def executable_key(parts: dict) -> str:
    """Stable ``dim=value|...`` string over :data:`KEY_DIMS` — the join key
    querylog records carry (``executable_key``) and ``/debug/kernels``
    tables are indexed by."""
    return "|".join(f"{d}={_fmt(parts.get(d))}" for d in KEY_DIMS)


def hist_quantile_est(h, q: float) -> float:
    """Linear-interpolated quantile estimate from a fixed-bucket histogram
    (host-side rendering for /debug/kernels — same scheme PromQL's
    histogram_quantile applies to classic buckets)."""
    total = h.total
    if total <= 0:
        return 0.0
    rank = q * total
    cum = 0
    prev_bound = 0.0
    for bound, count in zip(h.BOUNDS, h.counts):
        if count > 0 and cum + count >= rank:
            frac = (rank - cum) / count
            return prev_bound + (bound - prev_bound) * frac
        cum += count
        prev_bound = bound
    return float(h.BOUNDS[-1])


class _ExecRecord:
    """One executable's accounting. Mutated under the registry lock."""

    __slots__ = (
        "key", "parts", "compiles", "compile_seconds", "dispatches",
        "device_hist", "provenance", "executable_bytes", "first_seen_s",
        "last_dispatch_s", "last_compile_s",
    )

    def __init__(self, key: str, parts: dict):
        self.key = key
        self.parts = dict(parts)
        self.compiles = 0
        self.compile_seconds = 0.0
        self.dispatches = 0
        self.device_hist = MicroHistogram()
        self.provenance = {p: 0 for p in _PROVENANCE}
        self.executable_bytes: int | None = None
        self.first_seen_s = time.time()
        # born "just dispatched": a fresh record must never sort below
        # genuinely stale entries in the LRU eviction (it is inserted
        # BEFORE the dispatch stamps it — evicting it would orphan the
        # update and freeze the table at capacity)
        self.last_dispatch_s = self.first_seen_s
        self.last_compile_s = 0.0

    def snapshot(self) -> dict:
        h = self.device_hist
        return {
            "key": self.key,
            **{d: _fmt(self.parts.get(d)) for d in KEY_DIMS},
            "compiles": self.compiles,
            "compile_ms": round(self.compile_seconds * 1e3, 3),
            "dispatches": self.dispatches,
            "device_p50_ms": round(hist_quantile_est(h, 0.5) * 1e3, 4),
            "device_p99_ms": round(hist_quantile_est(h, 0.99) * 1e3, 4),
            "device_total_ms": round(h.sum * 1e3, 3),
            "executable_bytes": self.executable_bytes,
            "cache": dict(self.provenance),
            "first_seen": round(self.first_seen_s, 3),
            "last_dispatch": round(self.last_dispatch_s, 3),
        }


class ExecutableRegistry:
    """Process-global registry of lowered XLA executables and their costs.

    Capture is always on (like the query log); ``configure`` sizes the
    table and the storm detector from the ``kernel_obs`` config block.
    ``observe_dispatch`` is the ONE ingestion point — every
    ``metrics.record_kernel_dispatch`` call forwards here with the key
    parts the dispatch site knows statically."""

    def __init__(self, max_entries: int = 1024, storm_threshold: int = 5,
                 storm_window_s: float = 60.0):
        self._lock = threading.Lock()
        self._records: dict[str, _ExecRecord] = {}
        # registered jit wrappers per ops module: the lint anchor
        # (tools/check_metrics.py) and the snapshot's in-process
        # compile-cache sizes; weakrefs so a registry never pins a module
        self._jits: dict[str, weakref.ref] = {}
        self._jit_meta: dict[str, dict] = {}
        # per-family ring of recent compile events: (monotonic_t, parts)
        self._compile_ring: dict[str, deque] = {}
        self._storm_active: dict[str, bool] = {}
        self._storms: dict[str, dict] = {}
        self.max_entries = int(max_entries)
        self.storm_threshold = int(storm_threshold)
        self.storm_window_s = float(storm_window_s)
        # opt-in exact device timing: block_until_ready around each
        # dispatch (bench/attest runs turn this on; serving keeps it off —
        # the sync would serialize the async dispatch pipeline)
        self.device_timing = False
        self._local = threading.local()

    def configure(self, max_entries: int | None = None,
                  storm_threshold: int | None = None,
                  storm_window_s: float | None = None,
                  device_timing: bool | None = None) -> None:
        with self._lock:
            if max_entries is not None:
                self.max_entries = max(int(max_entries), 16)
            if storm_threshold is not None:
                self.storm_threshold = max(int(storm_threshold), 1)
            if storm_window_s is not None:
                self.storm_window_s = max(float(storm_window_s), 1.0)
            if device_timing is not None:
                self.device_timing = bool(device_timing)

    # -- jit wrapper registration (the lint anchor) -----------------------

    def register_jits(self, module: str, **jits) -> None:
        """Register a module's jit wrappers under stable names.

        Every ``jax.jit`` call site in ``ops/`` must be registered here
        (tools/check_metrics.py AST-lints wrapper names against these
        calls): registration is what lets the observatory report each
        wrapper's live in-process cache size — the ground truth the
        per-dispatch ``compiled`` deltas are measured against — and keeps
        a new kernel from silently dispatching outside the observatory."""
        with self._lock:
            for name, fn in jits.items():
                if fn is None:
                    continue
                full = f"{module}.{name}"
                try:
                    self._jits[full] = weakref.ref(fn)
                except TypeError:
                    # jit wrappers are weakref-able; a plain callable
                    # (tests registering stand-ins) rides a lambda ref
                    self._jits[full] = (lambda f=fn: f)
                self._jit_meta[full] = {
                    "donated": tuple(getattr(fn, "_donate_argnums", ()) or ()),
                }

    def registered_jits(self) -> dict[str, dict]:
        """Live view of registered wrappers: in-process cache sizes plus
        any static metadata (donation) captured at registration."""
        out: dict[str, dict] = {}
        with self._lock:
            items = list(self._jits.items())
            meta = dict(self._jit_meta)
        for full, ref in items:
            fn = ref()
            if fn is None:
                continue
            try:
                size = int(fn._cache_size())
            except Exception:  # noqa: BLE001 — a stand-in without a jit cache
                size = -1
            out[full] = {"cache_size": size,
                         "donated": list(meta.get(full, {}).get("donated", ()))}
        return out

    # -- dispatch ingestion ------------------------------------------------

    def observe_dispatch(self, family: str, seconds: float,
                         compiled: bool | None = None,
                         parts: dict | None = None, result=None) -> str:
        """Account one kernel dispatch (called from
        ``metrics.record_kernel_dispatch`` — the one funnel every ops/
        entry point already routes through). Returns the executable key
        and stashes it thread-locally for the engine's querylog capture
        (``last_dispatch``)."""
        p = dict(parts or {})
        unknown = set(p) - set(KEY_DIMS)
        if unknown:
            # mirror PhaseRecorder: a typo'd dimension must fail loudly,
            # never mint an unjoinable key shape
            raise ValueError(
                f"unknown executable-key dimension(s) {sorted(unknown)} "
                f"(canonical: {KEY_DIMS})"
            )
        p["family"] = family
        key = executable_key(p)
        is_compile = bool(compiled)
        provenance, entry_bytes = "in_process", None
        if compiled is not None:
            from ..ops.compile_cache import classify_dispatch

            provenance, entry_bytes = classify_dispatch(is_compile)
        device_s = float(seconds)
        if self.device_timing and result is not None and not is_compile:
            t0 = time.perf_counter()
            try:
                import jax

                jax.block_until_ready(result)
                device_s += time.perf_counter() - t0
            except Exception:  # noqa: BLE001 — host-only results (np arrays)
                pass
        now = time.time()
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                rec = self._records[key] = _ExecRecord(key, p)
            rec.dispatches += 1
            rec.last_dispatch_s = now
            # evict AFTER the new record carries its dispatch stamp: the
            # LRU min() must only ever pick a genuinely idle entry
            self._evict_locked()
            if compiled is not None:
                rec.provenance[provenance] = rec.provenance.get(provenance, 0) + 1
            if is_compile:
                rec.compiles += 1
                rec.compile_seconds += float(seconds)
                rec.last_compile_s = now
                if entry_bytes is not None:
                    rec.executable_bytes = entry_bytes
                self._note_compile_locked(family, p)
            else:
                rec.device_hist.observe(device_s)
        REGISTRY.counter("filodb_kernel_exec_dispatches", family=family).inc()
        if is_compile:
            REGISTRY.counter("filodb_xla_compiles", family=family).inc()
            REGISTRY.counter("filodb_xla_compile_seconds",
                             family=family).inc(float(seconds))
        else:
            REGISTRY.micro_histogram(
                "filodb_kernel_exec_device_seconds", family=family
            ).observe(device_s)
        self._local.last = {
            "executable_key": key,
            "compile_miss": is_compile,
            "family": family,
        }
        return key

    def _evict_locked(self) -> None:
        while len(self._records) > self.max_entries:
            oldest = min(self._records.values(),
                         key=lambda r: r.last_dispatch_s)
            del self._records[oldest.key]

    def _note_compile_locked(self, family: str, parts: dict) -> None:
        """Recompile-storm detection: slide the family's compile ring and,
        on crossing the threshold, identify which key dimension actually
        churned (the annotation /debug/kernels serves — "shapes keeps
        changing" is actionable; "something recompiles" is not)."""
        ring = self._compile_ring.setdefault(family, deque())
        now = time.monotonic()
        ring.append((now, {d: _fmt(parts.get(d)) for d in KEY_DIMS}))
        horizon = now - self.storm_window_s
        while ring and ring[0][0] < horizon:
            ring.popleft()
        if len(ring) > self.storm_threshold:
            if not self._storm_active.get(family):
                self._storm_active[family] = True
                REGISTRY.counter("filodb_xla_recompile_storms",
                                 family=family).inc()
            unstable = [
                d for d in KEY_DIMS
                if d != "family" and len({p[d] for _, p in ring}) > 1
            ]
            self._storms[family] = {
                "time": time.time(),
                "compiles_in_window": len(ring),
                "window_s": self.storm_window_s,
                "unstable_dims": unstable or ["none (cache churn/eviction)"],
            }
        elif len(ring) <= max(self.storm_threshold // 2, 1):
            self._storm_active[family] = False

    # -- engine-side capture ----------------------------------------------

    def last_dispatch(self) -> dict | None:
        """This thread's most recent dispatch identity: the
        ``{executable_key, compile_miss, family}`` the engine folds into
        the query's cost record (batched launches ride the scheduler's
        request stamping instead — the leader's thread observed them)."""
        return getattr(self._local, "last", None)

    # -- introspection -----------------------------------------------------

    def device_p50_ms(self, key: str) -> float:
        """Warm-dispatch device-time p50 (ms) for one executable key —
        the cost model's registry join: a querylog record with no kernel
        time of its own (fully cache-served) still prices at what its
        executable measurably costs when it does run."""
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                return 0.0
            return hist_quantile_est(rec.device_hist, 0.5) * 1e3

    def storm_annotations(self) -> dict[str, dict]:
        """Copy of the live recompile-storm annotations (family ->
        {time, compiles_in_window, window_s, unstable_dims}) — the
        scheduler's pre-warm trigger reads these without paying for a
        full snapshot."""
        with self._lock:
            return {k: dict(v) for k, v in self._storms.items()}

    def snapshot(self, limit: int | None = None) -> dict:
        """The /debug/kernels (and attestation-artifact) rendering:
        per-executable table sorted by dispatches, storm annotations,
        registered-wrapper cache sizes and the detector config."""
        with self._lock:
            recs = sorted(self._records.values(),
                          key=lambda r: (-r.dispatches, r.key))
            storms = {k: dict(v) for k, v in self._storms.items()}
        if limit is not None:
            recs = recs[: max(int(limit), 0)]
        return {
            "executables": [r.snapshot() for r in recs],
            "storms": storms,
            "jits": self.registered_jits(),
            "config": {
                "max_executables": self.max_entries,
                "storm_threshold": self.storm_threshold,
                "storm_window_s": self.storm_window_s,
                "device_timing": self.device_timing,
            },
        }

    def totals(self) -> dict:
        """Aggregate proof line for attestation: compiles/dispatches and
        the fused/batched/mesh families that actually served traffic."""
        with self._lock:
            recs = list(self._records.values())
        fams = sorted({r.parts.get("family", "") for r in recs})
        return {
            "executables": len(recs),
            "dispatches": sum(r.dispatches for r in recs),
            "compiles": sum(r.compiles for r in recs),
            "compile_ms": round(sum(r.compile_seconds for r in recs) * 1e3, 3),
            "families": fams,
            "fused_families": [f for f in fams if "fused" in f],
        }

    def clear(self) -> None:
        """Test hook: drop accounting state (registered jits are kept —
        module-level registration happens once per process)."""
        with self._lock:
            self._records.clear()
            self._compile_ring.clear()
            self._storms.clear()
            self._storm_active.clear()


KERNELS = ExecutableRegistry()


def register_kernel_obs_collector() -> None:
    """Scrape-time gauge: live registry size (the executables the process
    is serving from — a steadily growing value is the storm detector's
    slow-burn sibling)."""

    def refresh():
        with KERNELS._lock:
            n = len(KERNELS._records)
        REGISTRY.gauge("filodb_xla_executables").set(float(n))

    REGISTRY.register_collector("kernel_obs", refresh)
