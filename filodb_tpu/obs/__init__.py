"""Query observatory (doc/observability.md "Query observatory"):
exemplar-level per-query cost records + per-phase latency decomposition
(`querylog.py`) and the default SLO burn-rate recording rules the standing
engine maintains over the `_system` dataset (`slo.py`)."""

from .querylog import (  # noqa: F401
    QUERY_LOG,
    PhaseRecorder,
    QueryLogRing,
    promql_fingerprint,
)
