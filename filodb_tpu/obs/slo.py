"""Default SLO burn-rate recording rules over the query observatory.

The observatory's exemplar records and phase histograms (querylog.py) feed
the ``_system`` dataset through the self-scraper; these rules make the
standing-query engine (PR 11's recording-rules API) maintain the
observatory's own rollups on top — burn rates land back in ``_system`` as
real series, queryable/alertable like anything else, evaluated by the
standing maintainer on its own clock from live traffic.

Two SLO families (doc/observability.md "SLO burn-rate rules"):

- **availability** — the non-5xx share of non-shed responses. Admission
  sheds (429, class ``shed``) are deliberate load management, not broken
  availability, so they leave both numerator and denominator. The burn
  rate divides the observed error ratio by the error budget
  (``1 - availability_objective``): 1.0 = burning exactly at budget; >1
  sustained over the window means the SLO will be missed.

      slo:availability:burnrate:<w> =
          sum(rate(filodb_http_responses_total{class="5xx"}[w]))
        / sum(rate(filodb_http_responses_total{class!="shed"}[w]))
        / (1 - objective)

  (Prometheus semantics: with zero 5xx responses the numerator selects no
  series and the rule records nothing — absence IS the healthy state.)

- **latency** — observed p99 against the objective, global (from
  ``filodb_query_latency_seconds``) and per tenant with a configured
  objective (from the per-tenant latency histogram
  ``filodb_tenant_query_latency_seconds{ws,ns}``):

      slo:latency:p99:<w>       = histogram_quantile(0.99, sum by (le)
                                    (rate(..._bucket[w])))
      slo:latency:burnrate:<w>  = the same, divided by the objective
                                    (>1 = p99 over objective)

Config block (config.py ``slo``): ``availability_objective``,
``latency_objectives_s`` mapping ``"ws/ns"`` (or ``"*"`` = global) to a
p99 objective in seconds, ``windows`` (PromQL durations), ``interval_s``
(rule evaluation cadence). ``enabled: null`` auto-enables exactly when the
``_system`` pipeline runs (telemetry.self_scrape_interval_s set).
"""

from __future__ import annotations

import logging
import re

log = logging.getLogger("filodb_tpu.obs.slo")

DEFAULTS: dict = {
    # null = auto: on exactly when the _system self-scrape pipeline runs
    "enabled": None,
    "availability_objective": 0.999,
    # "ws/ns" (or "*" for the global objective) -> p99 seconds
    "latency_objectives_s": {"*": 2.0},
    # burn-rate windows (PromQL durations); the classic fast/slow pair
    "windows": ["5m", "1h"],
    "interval_s": 15.0,
}

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def _name_part(s: str) -> str:
    """Sanitize a free-form fragment (tenant key, window) into the rule
    name charset [a-zA-Z0-9_:]."""
    return _NAME_OK.sub("_", str(s))


def default_slo_rules(cfg: dict | None = None) -> list[dict]:
    """The default rule set as ``{"name", "expr", "interval_s"}`` dicts —
    pure config→expressions (unit-testable without a server)."""
    c = {**DEFAULTS, **(cfg or {})}
    interval_s = float(c["interval_s"])
    avail_obj = float(c["availability_objective"])
    if not 0.0 < avail_obj < 1.0:
        raise ValueError(
            f"slo.availability_objective must be in (0, 1), got {avail_obj}"
        )
    budget = 1.0 - avail_obj
    rules: list[dict] = []
    for w in c["windows"]:
        wl = _name_part(w)
        rules.append({
            "name": f"slo:availability:burnrate:{wl}",
            "expr": (
                f'sum(rate(filodb_http_responses_total{{class="5xx"}}[{w}]))'
                f' / sum(rate(filodb_http_responses_total'
                f'{{class!="shed"}}[{w}])) / {budget:g}'
            ),
            "interval_s": interval_s,
        })
        for tenant, obj in (c.get("latency_objectives_s") or {}).items():
            obj = float(obj)
            if obj <= 0:
                raise ValueError(
                    f"slo.latency_objectives_s[{tenant!r}] must be > 0"
                )
            if tenant == "*":
                sel = "filodb_query_latency_seconds_bucket"
                suffix = wl
            else:
                ws, _, ns = str(tenant).partition("/")
                sel = (
                    f"filodb_tenant_query_latency_seconds_bucket"
                    f'{{ws="{ws}",ns="{ns or "unknown"}"}}'
                )
                suffix = f"{_name_part(tenant)}:{wl}"
            p99 = (
                f"histogram_quantile(0.99, sum by (le) "
                f"(rate({sel}[{w}])))"
            )
            if tenant == "*":
                # the raw p99 rollup only once (per window), for dashboards
                rules.append({
                    "name": f"slo:latency:p99:{wl}",
                    "expr": p99,
                    "interval_s": interval_s,
                })
            rules.append({
                "name": f"slo:latency:burnrate:{suffix}",
                "expr": f"{p99} / {obj:g}",
                "interval_s": interval_s,
            })
    return rules


def register_slo_rules(standing, cfg: dict | None = None) -> list:
    """Register the default rules on a StandingEngine bound to the
    ``_system`` engine (server.py wires this when both telemetry
    self-scrape and the standing engine are enabled). Returns the
    registered StandingQuery objects; an individual rule failing to plan
    logs and is skipped — one bad expression must not take the rest of the
    SLO plane down."""
    out = []
    for r in default_slo_rules(cfg):
        step_ms = max(int(r["interval_s"] * 1000), 1)
        try:
            out.append(standing.register(
                r["expr"], step_ms, span_ms=4 * step_ms, source="rule",
                rule_name=r["name"], eval_interval_s=float(r["interval_s"]),
            ))
        except Exception:  # noqa: BLE001 — one sick rule must not kill the set
            log.exception("SLO rule %s failed to register", r["name"])
    return out
