"""Structured query log + per-phase latency decomposition — the query
observatory's exemplar plane (doc/observability.md "Query observatory").

Aggregate counters (PR 7's ledger / tenant totals) answer "how much did
tenant X cost this hour"; the slow-query ring answers "show me the worst
offenders". Neither answers the questions the ROADMAP's cost-model and
workload-chosen-rollup items need: *what did each query actually cost, and
where did its time go?* Every executed query therefore emits ONE compact
cost record — query id (= trace id), tenant, normalized PromQL fingerprint
(the dispatch scheduler's recurrence key shape), grid shape, the path
taken (fused / fallback reason / reference tree, batched or not, grid
class), per-phase wall times, scan/staging/cache stats and result size —
into a bounded in-memory ring served at ``GET /debug/querylog`` and
``GET /api/v1/query_profile?id=``.

The same capture feeds:

- ``filodb_query_phase_seconds{phase,dataset}`` histograms with trace-id
  exemplars (beside ``filodb_query_latency_seconds``), so
  ``histogram_quantile(0.99, rate(..._bucket{phase="render"}[5m]))``
  answers through the fused ``_system`` path once self-scrape ingests it;
- per-tenant/per-path cumulative aggregates
  (``filodb_tenant_phase_seconds_total{phase,ws,ns}``,
  ``filodb_query_path_total{path,dataset}``) that ride the same
  self-scrape into ``_system``;
- the SLO burn-rate recording rules (obs/slo.py).

The phase taxonomy is :data:`filodb_tpu.metrics.QUERY_PHASES` — the ONE
canonical set, linted by tools/check_spans.py (every fused execution path
emits each engine phase exactly once; unknown phase names are rejected
here at runtime and there statically, mirroring the fused-fallback reason
taxonomy).

Overhead contract: capture is host-side metadata only — no device sync is
added anywhere (the warm canonical query stays exactly ONE kernel dispatch
with capture enabled; asserted in tests/test_querylog.py).
"""

from __future__ import annotations

import contextlib
import hashlib
import threading
import time
from collections import deque

from ..metrics import QUERY_PHASES, REGISTRY

_PHASE_SET = frozenset(QUERY_PHASES)

# phases measured inside the engine; transfer/render are added by the
# serving edge after the engine returns, and ``other`` is the computed
# residual — the invariant (tests/test_querylog.py) is
# sum(ENGINE_PHASES + other) == engine duration.
ENGINE_PHASES = ("parse_plan", "admission", "stage", "dispatch")
EDGE_PHASES = ("transfer", "render")


class PhaseRecorder:
    """Lock-cheap per-query phase accumulator. One instance rides the
    QueryContext (``ctx.phases``) and is re-bound per thread by
    ``ExecPlan.execute`` via :func:`filodb_tpu.metrics.activate_phases`,
    so pool workers and the batch scheduler attribute to the right query
    without threading a context through every ops/ signature."""

    __slots__ = ("seconds", "_lock")

    def __init__(self):
        self.seconds: dict[str, float] = {}
        self._lock = threading.Lock()

    def add(self, phase: str, seconds: float) -> None:
        if phase not in _PHASE_SET:
            raise ValueError(
                f"unknown query phase {phase!r} (canonical set: "
                f"{sorted(_PHASE_SET)})"
            )
        with self._lock:
            self.seconds[phase] = (
                self.seconds.get(phase, 0.0) + max(float(seconds), 0.0)
            )

    @contextlib.contextmanager
    def phase(self, name: str):
        """Time a block into ``name`` (the engine-side capture primitive
        for phases that don't already run under a span)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return dict(self.seconds)

    def total(self) -> float:
        with self._lock:
            return sum(self.seconds.values())


def promql_fingerprint(dataset: str, promql: str, step_ms: int,
                       span_ms: int) -> str:
    """Stable fingerprint of the NORMALIZED query: dataset + PromQL text +
    grid shape (step, span), with the sliding live-edge start/end
    normalized away — the same shape the dispatch scheduler's recurrence
    ring keys on, so a dashboard panel re-issuing ``end=now`` every 15 s
    is ONE fingerprint. This is the join key the future cost model and
    Storyboard-style rollup chooser train on."""
    raw = f"{dataset}\x00{promql}\x00{int(step_ms)}\x00{int(span_ms)}"
    return hashlib.sha1(raw.encode()).hexdigest()[:16]


def observe_phase(dataset: str, phase: str, seconds: float,
                  trace_id: str | None = None) -> None:
    """One phase observation into the operator-facing histogram
    (``filodb_query_phase_seconds{phase,dataset}``) with a trace-id
    exemplar — the bucket a spiking phase lands in links straight to its
    query-log record (same id)."""
    if phase not in _PHASE_SET:
        raise ValueError(f"unknown query phase {phase!r}")
    REGISTRY.histogram(
        "filodb_query_phase_seconds", phase=phase, dataset=dataset
    ).observe(float(seconds), exemplar={"trace_id": trace_id} if trace_id
              else None)


def _record_tenant_phases(ws: str, ns: str, phases: dict[str, float]) -> None:
    """Cumulative per-tenant phase seconds
    (``filodb_tenant_phase_seconds_total{phase,ws,ns}``), cardinality
    bounded by the metering overflow-bucket cap — the per-tenant half of
    the ``_system`` phase aggregates."""
    from ..metering import bounded_tenant_pair

    ws, ns = bounded_tenant_pair(ws, ns)
    for phase, s in phases.items():
        if s > 0.0:
            REGISTRY.counter(
                "filodb_tenant_phase_seconds", phase=phase, ws=ws, ns=ns
            ).inc(float(s))


class QueryLogRing:
    """Bounded ring of per-query cost records, newest last; lock-cheap
    (one mutex around a deque + an id index — the record itself is built
    outside the lock). Mirrors SlowQueryLog's concurrency contract:
    ``record`` vs ``configure`` resize races are safe, ``entries`` returns
    copies newest-first."""

    def __init__(self, max_entries: int = 512):
        self._max = max(int(max_entries), 1)
        self._entries: deque = deque()
        self._by_id: dict[str, dict] = {}
        self._lock = threading.Lock()

    def configure(self, max_entries: int) -> None:
        with self._lock:
            self._max = max(int(max_entries), 1)
            self._evict_locked()

    def _evict_locked(self) -> None:
        while len(self._entries) > self._max:
            gone = self._entries.popleft()
            if self._by_id.get(gone.get("id")) is gone:
                del self._by_id[gone["id"]]

    def record(self, entry: dict) -> dict:
        with self._lock:
            self._entries.append(entry)
            qid = entry.get("id")
            if qid:
                self._by_id[qid] = entry
            self._evict_locked()
        return entry

    @staticmethod
    def _copy(e: dict) -> dict:
        # records are finished in place by the serving edge
        # (finish_serving) — readers must get copies, nested mutable
        # fields included
        out = dict(e)
        for k in ("phases_ms", "stats", "result", "grid"):
            if isinstance(out.get(k), dict):
                out[k] = dict(out[k])
        return out

    def get(self, query_id: str) -> dict | None:
        with self._lock:
            e = self._by_id.get(query_id)
            return self._copy(e) if e is not None else None

    def entries(self, limit: int | None = None) -> list[dict]:
        """Newest first; ``limit`` caps the page (0 = empty, not all)."""
        with self._lock:
            out = [self._copy(e) for e in reversed(self._entries)]
        if limit is None:
            return out
        return out[: max(int(limit), 0)]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._by_id.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- record lifecycle --------------------------------------------------

    def publish(self, *, query_id: str, dataset: str, promql: str,
                ws: str, ns: str, step_ms: int, span_ms: int,
                start_s: float, end_s: float,
                phases: PhaseRecorder, elapsed_s: float,
                stats=None, path_info: dict | None = None,
                result_series: int = 0, result_samples: int = 0,
                status: str = "ok", error: str | None = None,
                predicted_cost_s: float | None = None,
                realized_cost_s: float | None = None) -> dict:
        """Build + ring one query's cost record and feed the aggregate
        planes (phase histograms with trace-id exemplars, per-tenant phase
        counters, per-path counter). The engine calls this once per
        EXECUTION at the query's origin (coalesced followers share the
        leader's record; remote-child legs don't publish — the origin
        accounts the whole query, mirroring tenant metering)."""
        ph = phases.snapshot()
        # the residual: engine wall time the named phases don't cover
        # (transformer folding, result assembly, scatter overhead) — makes
        # the engine-phase sum equal wall time by construction
        other = max(float(elapsed_s) - sum(ph.values()), 0.0)
        if other > 0.0:
            ph["other"] = ph.get("other", 0.0) + other
        info = path_info or {}
        path = info.get("path", "tree")
        entry = {
            "id": query_id,
            "time": time.time(),
            "dataset": dataset,
            "promql": promql,
            "fingerprint": promql_fingerprint(dataset, promql, step_ms,
                                              span_ms),
            "ws": ws,
            "ns": ns,
            "grid": {
                "start_s": round(float(start_s), 3),
                "end_s": round(float(end_s), 3),
                "step_ms": int(step_ms),
                "steps": (int((end_s - start_s) * 1000 // step_ms) + 1
                          if step_ms > 0 else 1),
            },
            "path": path,
            "fallback_reason": info.get("fallback"),
            "grid_class": info.get("grid_class"),
            "batched": info.get("batched"),
            # kernel-observatory join (obs/kernels.py): the executable that
            # served the fused dispatch and whether that launch compiled —
            # the cost model joins phase data to kernel identity through
            # this key (/debug/kernels indexes by it)
            "executable_key": info.get("executable_key"),
            "compile_miss": info.get("compile_miss"),
            # replicated shard plane: the remote endpoint(s) that served the
            # query's scatter legs — a failover shows up as the sibling's
            # endpoint here (and in /api/v1/query_profile)
            "endpoint": ",".join(info["endpoints"]) if info.get("endpoints") else None,
            "status": status,
            "error": error,
            # cost-model plane (query/costmodel.py): what admission PRICED
            # this execution at vs. what the device actually charged —
            # the pair every prediction-quality surface joins on
            "predicted_cost_s": (round(float(predicted_cost_s), 6)
                                 if predicted_cost_s is not None else None),
            "realized_cost_s": (round(float(realized_cost_s), 6)
                                if realized_cost_s is not None else None),
            "duration_ms": round(float(elapsed_s) * 1e3, 3),
            "phases_ms": {k: round(v * 1e3, 3) for k, v in ph.items()},
            "stats": {
                "series_scanned": getattr(stats, "series_scanned", 0),
                "samples_scanned": getattr(stats, "samples_scanned", 0),
                "bytes_staged": getattr(stats, "bytes_staged", 0),
                "kernel_ms": round(getattr(stats, "kernel_ns", 0) / 1e6, 3),
                "cache_hits": getattr(stats, "cache_hits", 0),
                "cache_misses": getattr(stats, "cache_misses", 0),
                "cache_extends": getattr(stats, "cache_extends", 0),
            },
            "result": {"series": int(result_series),
                       "samples": int(result_samples), "bytes": None},
        }
        for phase, s in ph.items():
            observe_phase(dataset, phase, s, trace_id=query_id)
        _record_tenant_phases(ws, ns, ph)
        REGISTRY.counter("filodb_query_path", path=path,
                         dataset=dataset).inc()
        return self.record(entry)

    def finish_serving(self, entry: dict, transfer_s: float, render_s: float,
                       body_bytes: int | None = None,
                       code: int | None = None,
                       render_format: str | None = None) -> None:
        """Edge-side completion: fold the serving phases (device→host
        transfer, encode+write) into the record and the aggregate planes.
        Histograms/tenant counters observe for EVERY caller (each
        coalesced follower pays its own render); the record itself is
        finished FIRST-WINS — followers sharing the leader's record must
        not accumulate their renders into its phase sums."""
        dataset = entry.get("dataset", "")
        qid = entry.get("id")
        for phase, s in (("transfer", transfer_s), ("render", render_s)):
            observe_phase(dataset, phase, s, trace_id=qid)
        _record_tenant_phases(entry.get("ws", "unknown"),
                              entry.get("ns", "unknown"),
                              {"transfer": transfer_s, "render": render_s})
        with self._lock:
            ph = entry.get("phases_ms")
            if isinstance(ph, dict) and "render" not in ph:
                ph["transfer"] = round(float(transfer_s) * 1e3, 3)
                ph["render"] = round(float(render_s) * 1e3, 3)
                if body_bytes is not None:
                    entry.setdefault("result", {})["bytes"] = int(body_bytes)
                if code is not None:
                    entry["code"] = int(code)
                if render_format is not None:
                    # which encoder tier served the body (native/numpy JSON
                    # fragments, arrow peer frames) — joins the record to
                    # filodb_render_seconds{format}
                    entry["render_format"] = render_format


QUERY_LOG = QueryLogRing()
