"""Deduplicated notification fan-out for the alerting plane
(doc/observability.md "Alerting plane — notification lifecycle").

Firing alerts pull from the :class:`~filodb_tpu.obs.alerting.AlertingEngine`
and fan out to Alertmanager-v2-compatible webhook receivers. Per receiver,
alerts group by the receiver's ``group_by`` labels; per group the notifier
keeps exactly the Alertmanager timing contract:

- a NEW group waits ``group_wait`` before its first notification (so a
  burst of related alerts lands as ONE payload);
- a group whose membership changed (new firing fingerprint, or a resolved
  one to report) re-notifies after ``group_interval``;
- an UNCHANGED group re-notifies only after ``repeat_interval``.

Dedup is by grouped fingerprint content hash: evaluating the same firing
alert every interval produces exactly one delivery until the group's
membership changes or ``repeat_interval`` elapses — the e2e test drives
repeated evaluations and asserts the single delivery.

Delivery reuses the fault-tolerance plane (query/faults.py): a per-receiver
circuit breaker gates sends (a dead receiver stops consuming the notify
thread), and each delivery gets a deadline-budgeted retry loop with
exponential backoff. Outcomes land in
``filodb_alert_notify_total{receiver,outcome}`` with the taxonomy
``ok | retry | error | breaker_open``.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from dataclasses import dataclass, field

from ..metrics import REGISTRY
from .alerting import _duration_s, rfc3339

log = logging.getLogger("filodb_tpu.obs.notify")

# delivery outcome taxonomy (linted against doc/observability.md):
# ok           — payload accepted by the receiver
# retry        — one failed attempt that will be retried within budget
# error        — delivery abandoned (attempts or deadline exhausted)
# breaker_open — skipped: the receiver's circuit breaker is open
NOTIFY_OUTCOMES = ("ok", "retry", "error", "breaker_open")

_ZERO_TIME = "0001-01-01T00:00:00Z"


@dataclass
class Receiver:
    """One webhook destination + its grouping/timing knobs (Alertmanager
    route semantics, flattened: one receiver = one route)."""

    name: str
    url: str
    group_by: tuple = ("alertname",)
    group_wait_s: float = 30.0
    group_interval_s: float = 300.0
    repeat_interval_s: float = 14400.0
    send_resolved: bool = True

    @classmethod
    def from_config(cls, cfg: dict) -> "Receiver":
        if not isinstance(cfg, dict):
            raise ValueError("alerting.receivers entries must be mappings")
        allowed = {"name", "url", "group_by", "group_wait",
                   "group_interval", "repeat_interval", "send_resolved"}
        extra = set(cfg) - allowed
        if extra:
            raise ValueError(f"receiver: unknown keys {sorted(extra)}")
        name = cfg.get("name")
        url = cfg.get("url")
        if not name or not isinstance(name, str):
            raise ValueError("receiver needs a non-empty `name`")
        if not url or not isinstance(url, str):
            raise ValueError(f"receiver {name!r} needs a non-empty `url`")
        gb = cfg.get("group_by", ["alertname"])
        if isinstance(gb, str):
            gb = [gb]
        kw = {}
        for key, attr in (("group_wait", "group_wait_s"),
                          ("group_interval", "group_interval_s"),
                          ("repeat_interval", "repeat_interval_s")):
            if key in cfg:
                kw[attr] = _duration_s(cfg[key], f"receiver {name}: {key}")
        return cls(name=name, url=url, group_by=tuple(str(g) for g in gb),
                   send_resolved=bool(cfg.get("send_resolved", True)), **kw)


@dataclass
class _Group:
    """Per-(receiver, group-key) dispatch state."""

    key: tuple
    group_labels: dict
    first_seen_s: float
    last_notify_s: float = 0.0
    last_hash: str = ""
    resolved: dict = field(default_factory=dict)  # fp -> resolved dict


def _default_transport(url: str, body: bytes, timeout_s: float) -> None:
    """POST the JSON payload; any HTTP error status raises (urllib)."""
    req = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        resp.read()


class Notifier:
    """Grouping + dedup + breaker/retry delivery over a set of webhook
    receivers. ``alerts_source`` is a zero-arg callable returning the
    currently-firing alert dicts (the AlertingEngine binds itself)."""

    def __init__(self, receivers, alerts_source=None, breakers=None,
                 retry=None, deadline_s: float = 10.0, tick_s: float = 1.0,
                 clock=time.time, transport=None):
        from ..query.faults import BreakerRegistry, RetryPolicy

        self.receivers = list(receivers)
        self.alerts_source = alerts_source
        self.breakers = breakers if breakers is not None else \
            BreakerRegistry()
        self.retry = retry if retry is not None else RetryPolicy()
        self.deadline_s = float(deadline_s)
        self.tick_s = float(tick_s)
        self.clock = clock
        self.transport = transport or _default_transport
        self._lock = threading.Lock()
        # (receiver name, group key) -> _Group
        self._groups: dict[tuple, _Group] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- grouping / dedup --------------------------------------------------

    @staticmethod
    def _group_key(receiver: Receiver, labels: dict) -> tuple:
        return tuple((g, str(labels.get(g, ""))) for g in receiver.group_by)

    @staticmethod
    def _content_hash(firing: list, resolved: list) -> str:
        return "|".join(
            sorted(a["fingerprint"] for a in firing)
        ) + "//" + "|".join(sorted(a["fingerprint"] for a in resolved))

    def note_resolved(self, alerts: list) -> None:
        """Queue resolved alerts onto the groups that previously notified
        them — a group nobody was ever told about has nothing to resolve."""
        with self._lock:
            for r in self.receivers:
                if not r.send_resolved:
                    continue
                for a in alerts:
                    key = (r.name, self._group_key(r, a["labels"]))
                    g = self._groups.get(key)
                    if g is None or g.last_notify_s <= 0:
                        continue
                    g.resolved[a["fingerprint"]] = a

    def tick(self, now_s: float | None = None) -> int:
        """One dispatch pass; returns the number of deliveries attempted.
        The background thread calls this every ``tick_s``; tests drive it
        directly with an injected clock."""
        if now_s is None:
            now_s = self.clock()
        firing = list(self.alerts_source() if self.alerts_source else [])
        attempted = 0
        for r in self.receivers:
            by_key: dict[tuple, list] = {}
            for a in firing:
                by_key.setdefault(self._group_key(r, a["labels"]),
                                  []).append(a)
            plans = []
            with self._lock:
                # register/refresh group state for every live group
                for gkey, members in by_key.items():
                    key = (r.name, gkey)
                    g = self._groups.get(key)
                    if g is None:
                        g = _Group(key=gkey, group_labels=dict(gkey),
                                   first_seen_s=now_s)
                        self._groups[key] = g
                # decide which groups flush this tick
                for (rname, gkey), g in list(self._groups.items()):
                    if rname != r.name:
                        continue
                    members = by_key.get(gkey, [])
                    resolved = list(g.resolved.values())
                    if not members and not resolved:
                        # nothing firing, nothing to resolve: forget it
                        del self._groups[(rname, gkey)]
                        continue
                    h = self._content_hash(members, resolved)
                    if g.last_notify_s <= 0:
                        if not members:
                            continue  # resolved-only, never notified
                        due = now_s - g.first_seen_s >= r.group_wait_s
                    elif h != g.last_hash:
                        due = (now_s - g.last_notify_s
                               >= r.group_interval_s)
                    else:
                        due = (now_s - g.last_notify_s
                               >= r.repeat_interval_s)
                    if due:
                        plans.append((g, members, resolved, h))
            for g, members, resolved, h in plans:
                attempted += 1
                ok = self._deliver(r, g, members, resolved)
                with self._lock:
                    g.last_notify_s = now_s
                    if ok:
                        g.last_hash = h
                        for a in resolved:
                            g.resolved.pop(a["fingerprint"], None)
                        if not members and not g.resolved:
                            self._groups.pop((r.name, g.key), None)
        return attempted

    # -- delivery ----------------------------------------------------------

    def _count(self, receiver: Receiver, outcome: str) -> None:
        REGISTRY.counter("filodb_alert_notify", receiver=receiver.name,
                         outcome=outcome).inc()

    def _deliver(self, receiver: Receiver, g: _Group, firing: list,
                 resolved: list) -> bool:
        """One deadline-budgeted delivery: breaker gate, then retry with
        backoff until the payload lands or the budget is gone."""
        breaker = self.breakers.breaker_for(("notify", receiver.name))
        if not breaker.allow():
            self._count(receiver, "breaker_open")
            return False
        body = json.dumps(self.build_payload(
            receiver, g.group_labels, firing, resolved
        )).encode()
        deadline = time.monotonic() + self.deadline_s
        rng = self.retry.rng()
        attempts = max(int(self.retry.max_attempts), 1)
        last_err: Exception | None = None
        for i in range(attempts):
            budget = deadline - time.monotonic()
            if budget <= 0:
                break
            try:
                self.transport(receiver.url, body, min(budget,
                                                       self.deadline_s))
                breaker.record_success()
                self._count(receiver, "ok")
                return True
            except Exception as e:  # noqa: BLE001 — any failure is retryable here
                last_err = e
                if i + 1 >= attempts:
                    break
                backoff = self.retry.backoff_s(i, rng)
                if time.monotonic() + backoff >= deadline:
                    break
                self._count(receiver, "retry")
                self.retry.sleep(backoff)
        breaker.record_failure()
        self._count(receiver, "error")
        log.warning("alert delivery to %s failed: %s", receiver.name,
                    last_err)
        return False

    def build_payload(self, receiver: Receiver, group_labels: dict,
                      firing: list, resolved: list) -> dict:
        """Alertmanager v2 webhook payload (version "4" wire format)."""
        alerts = []
        for a in firing:
            alerts.append({
                "status": "firing",
                "labels": dict(a["labels"]),
                "annotations": dict(a.get("annotations") or {}),
                "startsAt": rfc3339(int(a.get("starts_at_ms", 0))),
                "endsAt": _ZERO_TIME,
                "generatorURL": "",
                "fingerprint": a["fingerprint"],
            })
        for a in resolved:
            alerts.append({
                "status": "resolved",
                "labels": dict(a["labels"]),
                "annotations": dict(a.get("annotations") or {}),
                "startsAt": rfc3339(int(a.get("starts_at_ms", 0))),
                "endsAt": rfc3339(int(a.get("ends_at_ms", 0))),
                "generatorURL": "",
                "fingerprint": a["fingerprint"],
            })

        def _common(key: str) -> dict:
            if not alerts:
                return {}
            out = dict(alerts[0][key])
            for a in alerts[1:]:
                for k in list(out):
                    if a[key].get(k) != out[k]:
                        del out[k]
            return out

        gl = ",".join(f'{k}="{v}"' for k, v in sorted(group_labels.items()))
        return {
            "version": "4",
            "groupKey": f"{{}}:{{{gl}}}",
            "truncatedAlerts": 0,
            "status": "firing" if firing else "resolved",
            "receiver": receiver.name,
            "groupLabels": dict(group_labels),
            "commonLabels": _common("labels"),
            "commonAnnotations": _common("annotations"),
            "externalURL": "",
            "alerts": alerts,
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if not self.receivers:
            return
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="filodb-notify"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the notify loop must not die
                log.exception("notifier tick failed")

    def snapshot(self) -> dict:
        with self._lock:
            groups = [{
                "receiver": rname,
                "group": dict(g.key),
                "last_notify_s": g.last_notify_s,
                "pending_resolved": len(g.resolved),
            } for (rname, _k), g in self._groups.items()]
        return {
            "receivers": [r.name for r in self.receivers],
            "groups": groups,
            "breakers": self.breakers.states(),
        }
