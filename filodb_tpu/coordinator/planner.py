"""Query planner: LogicalPlan -> ExecPlan (reference L5:
queryplanner/SingleClusterPlanner.scala:55 materialize:310 — shard fan-out,
transformer pushdown onto leaves, aggregate pushdown :1137).

Planning strategy (mirrors the reference):
- selectors fan out one leaf per shard; transformers (periodic samples,
  instant fns, scalar ops) are pushed onto every leaf so they run where the
  data is (on device, per shard block);
- mergeable aggregations (sum/min/max/count/avg/stddev/stdvar/group) push
  their map phase onto the leaves and reduce at the root
  (AggregateMapReduce -> ReduceAggregateExec, the psum path once shards are
  mesh-resident);
- non-mergeable aggregations (topk/quantile/count_values) and joins gather
  full series at the root.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from ..core.filters import ColumnFilter
from ..query import logical as L
from ..query.exec.joins import (
    BinaryJoinExec,
    ScalarPlanExec,
    ScalarVaryingExec,
    ScalarVectorOpExec,
    SetOperatorExec,
    SubqueryWindowExec,
)
from ..query.exec.plans import (
    _PARTIAL_COMPONENTS,
    AggregateMapReduce,
    AggregatePresentExec,
    DistConcatExec,
    EmptyResultExec,
    ExecPlan,
    QueryContext,
    RawChunkExportExec,
    ReduceAggregateExec,
    SelectRawPartitionsExec,
    StitchRvsExec,
)
from ..query.exec.transformers import (
    AbsentFunctionMapper,
    InstantVectorFunctionMapper,
    LimitFunctionMapper,
    MiscellaneousFunctionMapper,
    PeriodicSamplesMapper,
    QueryError,
    ScalarOperationMapper,
    SortFunctionMapper,
)
from ..query.functions import RANGE_FUNCTIONS
from ..query.promql import query_range_to_logical_plan, query_to_logical_plan


def _filters_to_selector(filters) -> str:
    """Serialize ColumnFilters back to a PromQL matcher set for peers'
    ``match[]`` params."""
    import re as _re

    from ..core.schemas import METRIC_TAG

    parts = []
    for f in filters:
        col = "__name__" if f.column == METRIC_TAG else f.column
        if f.op in ("=", "!=", "=~", "!~"):
            v = str(f.value).replace("\\", "\\\\").replace('"', '\\"')
            parts.append(f'{col}{f.op}"{v}"')
        elif f.op == "in":
            parts.append(f'{col}=~"{"|".join(_re.escape(v) for v in f.value)}"')
    return "{" + ",".join(parts) + "}"


def _scatter_call(thunks, prefix: str):
    """Run peer-call thunks concurrently; yields each result."""
    from concurrent.futures import ThreadPoolExecutor

    if not thunks:
        return
    with ThreadPoolExecutor(max_workers=min(8, len(thunks)),
                            thread_name_prefix=prefix) as pool:
        yield from pool.map(lambda t: t(), thunks)


class TsCardinalitiesExec(ExecPlan):
    """Cardinality scan by shard-key prefix (reference TsCardinalities
    metadata plan / TsCardExec): merges every owned shard's cardinality trie
    and, multi-host, the peers' locally-pinned scans."""

    def __init__(self, prefix: Sequence[str], depth: int | None = None,
                 peers: tuple = (), auth_token: str | None = None):
        super().__init__()
        self.prefix = tuple(prefix)
        self.depth = depth if depth is not None else len(self.prefix) + 1
        self.peers = tuple(peers)
        self.auth_token = auth_token

    def args_str(self) -> str:
        return f"prefix={','.join(self.prefix)} depth={self.depth}"

    def do_execute(self, ctx: QueryContext):
        from ..query.rangevector import QueryResult

        merged: dict[tuple, dict] = {}

        def add(prefix: tuple, ts_count: int, active: int, children: int):
            slot = merged.setdefault(
                prefix, {"prefix": list(prefix), "ts_count": 0, "active": 0, "children": 0}
            )
            slot["ts_count"] += ts_count
            slot["active"] += active
            slot["children"] = max(slot["children"], children)

        for sh in ctx.memstore.shards(ctx.dataset):
            for rec in sh.cardinality.scan(list(self.prefix), self.depth):
                add(rec.prefix, rec.ts_count, rec.active_ts_count, rec.children)
        if self.peers:
            import urllib.parse

            from .planners import fetch_json

            q = f"prefix={urllib.parse.quote(','.join(self.prefix))}&depth={self.depth}"
            plan = L.TsCardinalities(self.prefix, self.depth)
            thunks = []
            for ep in self.peers:  # one pool across BOTH transports
                if ep.startswith("grpc://"):
                    from ..api.grpc_exec import remote_metadata

                    thunks.append(lambda ep=ep: remote_metadata(ep, plan, self.auth_token))
                else:
                    url = f"{ep}/api/v1/cardinality?{q}"
                    thunks.append(lambda url=url: fetch_json(
                        url, auth_token=self.auth_token, local_only=True))
            for data in _scatter_call(thunks, "filodb-card"):
                for rec in data:
                    add(tuple(rec["prefix"]), rec["ts_count"], rec["active"], rec["children"])
        res = QueryResult()
        res.metadata = sorted(merged.values(), key=lambda r: -r["ts_count"])
        res.result_type = "metadata"
        return res


class MetadataExec(ExecPlan):
    """Label values/names & series metadata queries (reference
    MetadataExecPlan execs). With ``peers`` configured (multi-host), the
    same query scatters to every peer (locally pinned) and the disjoint
    per-host answers union — otherwise label/series browsing would silently
    show only this host's shard slice."""

    is_remote = False

    def __init__(self, kind: str, filters, start_ms, end_ms, label: str | None = None,
                 limit=None, peers: tuple = (), auth_token: str | None = None):
        super().__init__()
        self.kind = kind
        self.filters = tuple(filters)
        self.start_ms = start_ms
        self.end_ms = end_ms
        self.label = label
        self.limit = limit
        self.peers = tuple(peers)
        self.auth_token = auth_token

    def _grpc_plan(self):
        if self.kind == "label_values":
            return L.LabelValues(self.label, self.filters, self.start_ms, self.end_ms)
        if self.kind == "label_names":
            return L.LabelNames(self.filters, self.start_ms, self.end_ms)
        return L.SeriesKeysByFilters(self.filters, self.start_ms, self.end_ms)

    def _peer_metadata(self) -> list:
        """Concurrent per-peer fetch on ONE pool across both transports —
        HTTP peers over the shared retrying transport (results normalized
        from __name__ to internal tags), gRPC peers via plan-level
        executePlan (already internal-tag form)."""
        import urllib.parse

        from ..core.schemas import METRIC_TAG
        from .planners import fetch_json

        def http_thunk(url):
            def go():
                data = fetch_json(url, auth_token=self.auth_token, local_only=True)
                if self.kind == "series":
                    return [
                        {(METRIC_TAG if k == "__name__" else k): v for k, v in d.items()}
                        for d in data
                    ]
                return list(data)
            return go

        t = f"start={self.start_ms / 1000}&end={self.end_ms / 1000}"
        match = urllib.parse.quote(_filters_to_selector(self.filters)) if self.filters else None
        thunks = []
        for ep in self.peers:
            if ep.startswith("grpc://"):
                from ..api.grpc_exec import remote_metadata

                plan = self._grpc_plan()
                thunks.append(lambda ep=ep, plan=plan: remote_metadata(ep, plan, self.auth_token))
                continue
            if self.kind == "label_values":
                label = "__name__" if self.label == METRIC_TAG else self.label
                url = f"{ep}/api/v1/label/{urllib.parse.quote(label)}/values?{t}"
                if match:
                    url += f"&match[]={match}"
            elif self.kind == "label_names":
                url = f"{ep}/api/v1/labels?{t}"
                if match:
                    url += f"&match[]={match}"
            else:  # series
                url = f"{ep}/api/v1/series?{t}&match[]={match or urllib.parse.quote('{}')}"
            thunks.append(http_thunk(url))
        out: list = []
        for data in _scatter_call(thunks, "filodb-meta"):
            out.extend(data)
        return out

    def do_execute(self, ctx: QueryContext):
        from ..query.rangevector import QueryResult

        ms = ctx.memstore
        res = QueryResult()
        if self.kind == "label_values":
            vals = ms.label_values(ctx.dataset, self.filters, self.label, self.start_ms, self.end_ms, self.limit)
            if self.peers:
                vals = sorted(set(vals) | set(self._peer_metadata()))
                if self.limit:
                    vals = vals[: self.limit]
            res.metadata = vals
        elif self.kind == "label_names":
            names = ms.label_names(ctx.dataset, self.filters, self.start_ms, self.end_ms)
            if self.peers:
                names = sorted(
                    set(names)
                    | {"_metric_" if n == "__name__" else n for n in self._peer_metadata()}
                )
            res.metadata = names
        elif self.kind == "series":
            series = [dict(t) for t in ms.series(ctx.dataset, self.filters, self.start_ms, self.end_ms, self.limit)]
            if self.peers:
                series.extend(self._peer_metadata())  # shard-disjoint: no dedup needed
                if self.limit:
                    series = series[: self.limit]
            res.metadata = series
        else:
            raise QueryError(f"unknown metadata query {self.kind}")
        res.result_type = "metadata"
        return res


@dataclass
class PlannerParams:
    """Per-planner config (reference PlannerParams / QueryConfig)."""

    spread: int = 3
    lookback_ms: int = 300_000
    max_series: int = 1_000_000
    deadline_s: float = 60.0
    # optional jax.sharding.Mesh: distributed aggregations compile to one
    # psum program over the shard axis instead of host-side merging
    mesh: object | None = None
    # optional lpopt AggRuleProvider: sum-by queries rewrite onto maintained
    # :agg series before planning
    agg_rules: object | None = None
    # total shards in the CLUSTER (the ingest-routing modulus). None = the
    # memstore owns the whole cluster and the modulus is inferred from it;
    # multi-node deployments MUST set this from the ShardMapper so query-side
    # pruning enumerates the same shard group ingest routing used.
    num_shards: int | None = None
    # optional shared QueryScheduler: execution runs on its bounded pool with
    # fail-fast admission + deadline abort (reference QueryScheduler.scala)
    scheduler: object | None = None
    # multi-host scatter: base URLs of PEER processes owning the other shard
    # slices of this cluster. Selector-level subqueries fan out to every peer
    # (reference: ActorPlanDispatcher scatter to peer nodes' QueryActors) and
    # concatenate with the local leaves; peers execute locally-only (the
    # remote exec pins X-FiloDB-Local so scatter never recurses).
    peer_endpoints: tuple = ()
    # bearer token for peer requests (the cluster's http_auth_token)
    remote_auth_token: str | None = None
    # coalesce concurrent IDENTICAL queries into one execution (dashboard
    # fan-out: one kernel launch serves every copy). In-flight sharing only,
    # never a cache — see coordinator.scheduler.SingleFlight.
    coalesce_identical: bool = True
    # single-dispatch cross-shard aggregates (FusedAggregateExec): when every
    # shard is local, `sum|avg|min|max|count by (...) (range_fn(...))`
    # concatenates the per-shard staged blocks into one device-resident
    # superblock and runs ONE compiled range_fn -> segment_aggregate program
    # (doc/perf.md). False forces the reference scatter/partial-merge tree.
    fused_aggregate: bool = True
    # fault tolerance (query/faults.py): default for per-query
    # allow_partial_results (merge nodes tolerate lost shards/peers,
    # tagging results with structured warnings); retry_policy / breakers
    # override the module defaults (None = DEFAULT_RETRY_POLICY /
    # GLOBAL_BREAKERS); dispatcher wraps child execution (fault injection)
    allow_partial_results: bool = False
    retry_policy: object | None = None
    breakers: object | None = None
    dispatcher: object | None = None
    # observability (metrics.py): queries slower than this record their
    # rendered trace tree + PromQL in the global slow-query log
    # (/debug/slow_queries). None disables.
    slow_query_threshold_s: float | None = 10.0
    # cross-query micro-batching (query/scheduler.DispatchScheduler):
    # concurrent fused queries sharing a hot superblock + grid/epilogue
    # signature collect for batch_window_ms and launch as ONE batched
    # kernel (vmap over per-query params). 0 disables — every dispatch
    # runs exactly like the pre-scheduler path. A shared scheduler object
    # may be passed explicitly (server: one per process, shared by the
    # scattering + local engines); else the engine builds one when the
    # window is positive.
    batch_window_ms: float = 0.0
    batch_max: int = 32
    dispatch_scheduler: object | None = None
    # per-tenant admission control (query/scheduler.AdmissionController):
    # consulted BEFORE execution with the tenant resolved from the plan's
    # selector filters (metering.tenant_of_plan); over-quota queries raise
    # AdmissionRejected (HTTP 429 + Retry-After). None = no admission.
    admission: object | None = None
    # sketch rollup tier (downsample/rollup.RollupManager): long-range
    # queries whose step/window are multiples of a registered rollup's
    # resolution substitute O(periods) summary blocks for the raw scan
    # (doc/perf.md "Sketch rollup tier"). None = no substitution; every
    # plan is byte-identical to the pre-rollup planner.
    rollups: object | None = None
    # replicated shard plane (coordinator/replication.ReplicaRouter):
    # selector scatter consults it for per-shard replica endpoints — each
    # dispatch leg pins ONE replica (x-filodb-shards) and carries its
    # sibling endpoints so the dispatch layer can fail over before
    # allow_partial_results is even considered. None = legacy peer scatter.
    replica_router: object | None = None


class SingleClusterPlanner:
    """Plans against the shards of one memstore cluster."""

    def __init__(self, memstore, dataset: str, shard_nums: Sequence[int] | None = None,
                 params: PlannerParams | None = None):
        self.memstore = memstore
        self.dataset = dataset
        self.params = params or PlannerParams()
        self._shards = shard_nums

    def shards_for(self, filters) -> list[int]:
        """Shard fan-out for a selector (reference shardsFromFilters,
        SingleClusterPlanner.scala:424): when every shard-key column is
        constrained by equality filters, only the ``2^spread`` shards the
        ingest router can place those series on are queried; otherwise all
        owned shards are scanned. Pruning with planner spread >= ingest
        spread is always a superset of the shards holding the data (the low
        ``spread`` bits range over the whole group), so a too-large spread is
        safe; configs must never shrink spread below what ingest used."""
        owned = list(self._shards) if self._shards is not None else self.memstore.shard_nums(self.dataset)
        if not filters:
            return owned
        num_shards = self.params.num_shards
        if num_shards is None:
            try:
                num_shards = self.memstore.total_shards(self.dataset)
            except (KeyError, AttributeError):
                all_nums = self.memstore.shard_nums(self.dataset)
                if not all_nums:
                    return owned
                num_shards = max(all_nums) + 1
        if not num_shards:
            return owned
        cand = self._shards_from_filters(filters, num_shards)
        if cand is None:
            return owned
        owned_set = set(owned)
        return [s for s in cand if s in owned_set]

    _MAX_SHARDKEY_COMBOS = 64

    def _shards_from_filters(self, filters, num_shards: int) -> list[int] | None:
        """Candidate shards from shard-key equality filters, or None when the
        filters don't pin every shard-key column (scan-all). Matches the
        ingest-side routing exactly: the shard-key hash fixes the high bits,
        the low ``spread`` bits range over the full 2^spread group."""
        import itertools

        from ..core.schemas import (
            METRIC_TAG, PROM_METRIC_TAG, shard_group, shardkey_hash,
        )

        from ..memstore.index import _LITERAL_ALT

        options = self._options()
        skc = tuple(options.shard_key_columns)
        eq: dict[str, set[str]] = {}
        for f in filters:
            col = METRIC_TAG if f.column == PROM_METRIC_TAG else f.column
            if f.op == "=":
                eq.setdefault(col, set()).add(f.value)
            elif f.op == "in":
                eq.setdefault(col, set()).update(f.value)
            elif (f.op == "=~" and isinstance(f.value, str)
                  and _LITERAL_ALT.match(f.value)):
                # literal-alternation regex on a shard-key column (the
                # Grafana variable-storm shape {_ns_=~"App-1|App-2"}) pins
                # it to an explicit value set exactly like `in` — same
                # dictionary-batched expansion the index applies. An empty
                # alternation part would also match a MISSING tag, which
                # routing can't pin, so it falls back to scan-all.
                parts = f.value.split("|")
                if all(parts):
                    eq.setdefault(col, set()).update(parts)
        keysets = []
        for c in skc:
            vals = eq.get(c)
            if not vals:
                return None
            keysets.append(sorted(vals))
        n_combos = 1
        for ks in keysets:
            n_combos *= len(ks)
        if n_combos > self._MAX_SHARDKEY_COMBOS:
            return None
        shards: set[int] = set()
        for combo in itertools.product(*keysets):
            skh = shardkey_hash(dict(zip(skc, combo)), options)
            shards |= shard_group(skh, self.params.spread, num_shards)
        return sorted(shards)

    def _options(self):
        from ..core.schemas import DatasetOptions

        try:
            return self.memstore.dataset(self.dataset).options
        except KeyError:
            return DatasetOptions()

    # -- entry -----------------------------------------------------------

    def materialize(self, plan: L.LogicalPlan) -> ExecPlan:
        slices = self._wide_range_slices(plan)
        if slices is None:
            return self._materialize(plan)
        # over-wide range: the raw selector span exceeds the staged int32
        # ms-offset representation (ops/staging.MAX_STAGE_SPAN_MS, ~24.8
        # days) — offsets would wrap and every windowing path over the
        # staged block (fused searchsorted precompute, tree kernels alike)
        # silently empties or corrupts late windows. Rollup substitution
        # still gets first refusal over the WHOLE range (summary blocks
        # index by period number, no span limit); only the raw serving —
        # including a rollup serve's runtime fallback — is time-sliced
        # into per-slice staged bases and stitched.
        from ..query.exec.plans import RollupServeExec

        exec_plan = self._materialize(plan)
        if isinstance(exec_plan, RollupServeExec):
            exec_plan._fallback_factory = (
                lambda: self._materialize_sliced(plan, slices)
            )
            return exec_plan
        return self._materialize_sliced(plan, slices)

    def _wide_range_slices(self, plan) -> list[tuple[int, int]] | None:
        """(delta_start_ms, delta_end_ms) trims cutting an over-wide range
        query into slices whose raw selector span each fits the staged
        int32 offset representation — or None when the plan fits as-is (or
        has no range grid to slice along, e.g. instant subqueries)."""
        from ..ops import staging as ST

        raws = L.leaf_raw_series(plan)
        if not raws:
            return None
        raw_lo = min(r.start_ms for r in raws)
        raw_hi = max(r.end_ms for r in raws)
        span = raw_hi - raw_lo
        if span <= ST.MAX_STAGE_SPAN_MS:
            return None
        # grid params live on the topmost periodic node (Aggregate and the
        # function wrappers don't carry times themselves)
        node = plan
        while node is not None and not isinstance(
            getattr(node, "start_ms", None), int
        ):
            node = getattr(node, "inner", None) or getattr(
                node, "vectors", None
            )
        start = getattr(node, "start_ms", None)
        end = getattr(node, "end_ms", None)
        step = getattr(node, "step_ms", None) or 0
        if not isinstance(start, int) or not isinstance(end, int) \
                or step <= 0 or end <= start:
            return None
        # per-slice budget: the window/lookback/offset margins around the
        # grid ride along with EVERY slice
        margin = span - (end - start)
        per = ST.MAX_STAGE_SPAN_MS - margin
        if per < step:
            return None  # window alone overflows; unsliceable
        k = int(per // step) + 1  # steps per slice: (k-1)*step <= per
        n = int((end - start) // step) + 1
        if k >= n:
            return None
        out = []
        for a in range(0, n, k):
            b = min(a + k, n) - 1
            out.append((a * step, (b - (n - 1)) * step))
        return out

    def _materialize_sliced(self, plan, slices) -> ExecPlan:
        children = [
            self._materialize(L.narrow_time(plan, ds, de))
            for ds, de in slices
        ]
        return StitchRvsExec(children)

    def _fanout(self, make_leaf, transformers, filters=None, logical=None) -> ExecPlan:
        leaves = []
        for s in self.shards_for(filters):
            leaf = make_leaf(s)
            leaf.transformers.extend(transformers)
            leaves.append(leaf)
        leaves.extend(self._peer_leaves(logical))
        if not leaves:
            return EmptyResultExec()
        if len(leaves) == 1:
            return leaves[0]
        return DistConcatExec(leaves)

    def _peer_leaves(self, logical) -> list:
        """Multi-host scatter: one locally-pinned remote exec per peer for
        this selector-level subtree. Series are disjoint across hosts (shard
        ownership), so concatenation is exact; upper transformers/aggregates
        apply to the union at this node's parent, identically to local
        leaves."""
        if logical is None:
            return []
        if not isinstance(logical, (L.PeriodicSeries, L.PeriodicSeriesWithWindowing)):
            return []
        router = self.params.replica_router
        if router is not None:
            return self._router_leaves(router, logical)
        if not self.params.peer_endpoints:
            return []
        from ..query.unparse import to_promql
        from .planners import PromQlRemoteExec

        q = None
        leaves = []
        for ep in self.params.peer_endpoints:
            if ep.startswith("grpc://"):
                # binary plan transport (reference executePlan): the logical
                # subtree ships as protobuf — no unparse round-trip
                from ..api.grpc_exec import GrpcPlanRemoteExec

                r = GrpcPlanRemoteExec(
                    ep, logical, auth_token=self.params.remote_auth_token,
                    local_only=True,
                )
            else:
                if q is None:
                    q = to_promql(logical)
                r = PromQlRemoteExec(
                    ep, q, logical.start_ms, logical.end_ms, logical.step_ms or 1,
                    auth_token=self.params.remote_auth_token, local_only=True,
                )
            r.peer_logical = logical  # for aggregate pushdown rewriting
            leaves.append(r)
        return leaves

    def _router_leaves(self, router, logical) -> list:
        """Replica-routed scatter: the router groups non-local shards into
        dispatch legs of (shards, candidate endpoints). Each leg becomes ONE
        shard-pinned remote exec against the selected replica, carrying its
        sibling endpoints for dispatch-layer failover (query/faults.py)."""
        from ..api.grpc_exec import GrpcPlanRemoteExec

        local = set(self.shards_for(None))
        num = getattr(router.plane.mapper, "num_shards", 0)
        remote = [s for s in range(num) if s not in local]
        leaves = []
        for shards, endpoints in router.legs(remote, end_ms=logical.end_ms):
            r = GrpcPlanRemoteExec(
                endpoints[0], logical,
                auth_token=self.params.remote_auth_token,
                local_only=True, shard_subset=shards,
                sibling_endpoints=endpoints[1:],
            )
            r.peer_logical = logical  # for aggregate pushdown rewriting
            leaves.append(r)
        return leaves

    def _materialize(self, p: L.LogicalPlan) -> ExecPlan:
        if isinstance(p, L.PeriodicSeries):
            mapper = PeriodicSamplesMapper(
                p.start_ms, p.end_ms, p.step_ms, None, None, p.lookback_ms, p.offset_ms, p.at_ms
            )
            raw = p.raw
            return self._fanout(
                lambda s: SelectRawPartitionsExec(s, raw.filters, raw.start_ms, raw.end_ms, raw.column),
                [mapper],
                filters=raw.filters,
                logical=p,
            )
        if isinstance(p, L.PeriodicSeriesWithWindowing):
            ts_plan = self._try_time_shard(p)
            if ts_plan is not None:
                return ts_plan
            rollup_plan = self._try_rollup_windowing(p)
            if rollup_plan is not None:
                return rollup_plan
            mapper = PeriodicSamplesMapper(
                p.start_ms, p.end_ms, p.step_ms, p.function, p.window_ms,
                offset_ms=p.offset_ms, at_ms=p.at_ms, args=p.function_args,
            )
            raw = p.raw
            return self._fanout(
                lambda s: SelectRawPartitionsExec(s, raw.filters, raw.start_ms, raw.end_ms, raw.column),
                [mapper],
                filters=raw.filters,
                logical=p,
            )
        if isinstance(p, L.RawSeries):
            # raw chunk export stays host-local (remote read serves peers'
            # raw data from their own processes)
            return self._fanout(
                lambda s: RawChunkExportExec(s, p.filters, p.start_ms, p.end_ms, p.column), [],
                filters=p.filters,
            )
        if isinstance(p, L.Aggregate):
            return self._materialize_aggregate(p)
        if isinstance(p, L.PartialAggregate):
            return self._materialize_partial_aggregate(p)
        if isinstance(p, L.BinaryJoin):
            pushed = self._try_join_pushdown(p)
            if pushed is not None:
                return pushed
            lhs = self._materialize(p.lhs)
            rhs = self._materialize(p.rhs)
            if p.op in ("and", "or", "unless"):
                return SetOperatorExec(lhs, rhs, p.op, p.on, p.ignoring)
            return BinaryJoinExec(
                lhs, rhs, p.op, p.cardinality, p.on, p.ignoring, p.include, p.return_bool
            )
        if isinstance(p, L.ScalarVectorBinaryOperation):
            vec = self._materialize(p.vector)
            sc = p.scalar
            if isinstance(sc, (L.ScalarFixedDoublePlan, L.ScalarTimeBasedPlan, L.ScalarBinaryOperation)):
                # push the mapper onto the vector subtree; scalar evaluated at
                # execution against the vector's own grid
                times = _plan_times(p.vector)
                if times is not None:
                    start, end, step = times
                    nsteps = int((end - start) // step) + 1
                    sexec = ScalarPlanExec(sc, start, step, nsteps)
                    return ScalarVectorOpExec(vec, sexec, p.op, p.scalar_is_lhs, p.return_bool)
                sexec = ScalarPlanExec(sc, getattr(sc, "start_ms", 0), getattr(sc, "step_ms", 1) or 1, 1)
                return ScalarVectorOpExec(vec, sexec, p.op, p.scalar_is_lhs, p.return_bool)
            if isinstance(sc, L.ScalarVaryingDoublePlan):
                sexec = ScalarVaryingExec(self._materialize(sc.inner), sc.function)
                return ScalarVectorOpExec(vec, sexec, p.op, p.scalar_is_lhs, p.return_bool)
            raise QueryError(f"unsupported scalar operand {sc}")
        if isinstance(p, L.ApplyInstantFunction):
            if (
                p.function == "histogram_quantile"
                and len(p.args) == 1
                and isinstance(p.args[0], (int, float))
                and isinstance(p.inner, L.Aggregate)
                and p.inner.op == "sum"
            ):
                # the canonical SRE chain histogram_quantile(q, sum by (le)
                # (rate(m_bucket[w]))): fuse the interpolation epilogue into
                # the single-dispatch aggregate program (doc/perf.md)
                fused = self._try_fused_aggregate(
                    p.inner, hist_quantile=float(p.args[0])
                )
                if fused is not None:
                    return fused
            inner = self._materialize(p.inner)
            inner.transformers.append(InstantVectorFunctionMapper(p.function, p.args))
            return inner
        if isinstance(p, L.ApplyMiscellaneousFunction):
            if p.function == "_filodb_chunkmeta_all":
                from ..query.exec.plans import ChunkMetaExec

                leaves = L.leaf_raw_series(p)
                if len(leaves) != 1:
                    raise QueryError(
                        "_filodb_chunkmeta_all needs exactly one selector, "
                        f"got {len(leaves)}"
                    )
                raw = leaves[0]
                plans = [
                    ChunkMetaExec(s, raw.filters, raw.start_ms, raw.end_ms)
                    for s in self.shards_for(raw.filters)
                ]
                return plans[0] if len(plans) == 1 else DistConcatExec(plans)
            inner = self._materialize(p.inner)
            inner.transformers.append(MiscellaneousFunctionMapper(p.function, p.str_args))
            return inner
        if isinstance(p, L.ApplySortFunction):
            inner = self._materialize(p.inner)
            inner.transformers.append(SortFunctionMapper(p.descending))
            return inner
        if isinstance(p, L.ApplyAbsentFunction):
            inner = self._materialize(p.inner)
            nsteps = int((p.end_ms - p.start_ms) // p.step_ms) + 1 if p.step_ms else 1
            inner.transformers.append(
                AbsentFunctionMapper(p.filters, p.start_ms, p.step_ms or 1, nsteps)
            )
            return inner
        if isinstance(p, L.ApplyLimitFunction):
            inner = self._materialize(p.inner)
            inner.transformers.append(LimitFunctionMapper(p.limit))
            return inner
        if isinstance(p, (L.ScalarFixedDoublePlan, L.ScalarTimeBasedPlan, L.ScalarBinaryOperation)):
            nsteps = int((p.end_ms - p.start_ms) // p.step_ms) + 1 if p.step_ms else 1
            return ScalarPlanExec(p, p.start_ms, p.step_ms or 1, nsteps)
        if isinstance(p, L.ScalarVaryingDoublePlan):
            return ScalarVaryingExec(self._materialize(p.inner), p.function)
        if isinstance(p, L.SubqueryWithWindowing):
            inner = self._materialize(p.inner)
            return SubqueryWindowExec(
                inner, p.function, p.window_ms, p.sub_step_ms,
                p.start_ms, p.end_ms, p.step_ms, p.offset_ms, p.function_args,
            )
        if isinstance(p, L.TopLevelSubquery):
            return self._materialize(p.inner)
        if isinstance(p, L.TsCardinalities):
            return TsCardinalitiesExec(
                p.shard_key_prefix, p.num_groups,
                peers=self.params.peer_endpoints,
                auth_token=self.params.remote_auth_token,
            )
        if isinstance(p, (L.LabelValues, L.LabelNames, L.SeriesKeysByFilters)):
            kind = {"LabelValues": "label_values", "LabelNames": "label_names",
                    "SeriesKeysByFilters": "series"}[type(p).__name__]
            return MetadataExec(
                kind, p.filters, p.start_ms, p.end_ms,
                label=getattr(p, "label", None),
                peers=self.params.peer_endpoints,
                auth_token=self.params.remote_auth_token,
            )
        raise QueryError(f"cannot materialize {type(p).__name__}")

    def _materialize_partial_aggregate(self, p: "L.PartialAggregate") -> ExecPlan:
        """Execute the map phase only and return __comp__-labeled mergeable
        components — what a federation peer runs for a pushed-down
        aggregate (reference partial AggregateItem exchange,
        RowAggregator.scala:28,114)."""
        from ..query.exec.plans import (
            PartialReduceExec,
            SketchMapReduce,
        )

        inner = self._materialize(p.inner)
        if p.op == "quantile":
            mapper = SketchMapReduce(p.by, p.without)
        elif p.op in _PARTIAL_COMPONENTS:
            mapper = AggregateMapReduce(p.op, p.by, p.without)
        else:
            raise QueryError(f"no mergeable partial form for {p.op}")
        if isinstance(inner, DistConcatExec) and not inner.transformers:
            for child in inner.child_plans:
                child.transformers.append(mapper)
            return PartialReduceExec(inner.child_plans, p.op, p.by, p.without)
        inner.transformers.append(mapper)
        return PartialReduceExec([inner], p.op, p.by, p.without)

    def _materialize_aggregate(self, p: L.Aggregate) -> ExecPlan:
        mesh_plan = self._try_mesh_aggregate(p)
        if mesh_plan is not None:
            return mesh_plan
        fused = self._try_fused_aggregate(p)
        if fused is not None:
            return fused
        return self._materialize_aggregate_tree(p)

    def _try_fused_aggregate(self, p: L.Aggregate,
                             hist_quantile: float | None = None):
        """Single-dispatch path: `op by (...) (range_fn(selector[w]))` with
        every shard local plans to a FusedAggregateExec over one
        device-resident superblock (O(1) kernel launches) — including 3-D
        histogram superblocks, fused ``topk``/``bottomk``/``quantile``
        epilogues, and (via ``hist_quantile``) the device-side
        ``histogram_quantile`` interpolation epilogue. Grid SHAPE is not a
        plan-time concern: the dispatch classifies the staged superblock's
        grid (regular | jitter | holes | irregular, staging.grid_class)
        and selects the matching kernel variant — jittered and holey
        scrape grids stay single-dispatch (doc/perf.md "Jitter-tolerant
        fused path"), with the ``grid_jitter``/``grid_holes`` taxonomy
        entries reserved for shapes the jitter variants truly can't model
        (degraded to the general fused kernel, never to the tree). The
        reference scatter tree is built alongside as the runtime fallback
        (partial results, mixed schemas, unsupported hist shapes)."""
        from ..query.exec.plans import (
            FUSED_AGG_OPS,
            FUSED_EPI_OPS,
            FUSED_FUNCS,
            FusedAggregateExec,
        )

        params = self.params
        if not params.fused_aggregate or params.peer_endpoints or params.replica_router is not None:
            return None
        if p.op in FUSED_AGG_OPS:
            if p.params:
                return None
        elif p.op in FUSED_EPI_OPS:
            if len(p.params) != 1 or not isinstance(p.params[0], (int, float)):
                return None
            if p.op in ("topk", "bottomk") and (p.by or p.without):
                # the compact [k, J] device epilogue is global-only; grouped
                # topk keeps the per-shard candidate pre-reduction tree
                return None
        else:
            return None
        inner = p.inner
        if isinstance(inner, L.PeriodicSeriesWithWindowing):
            if (
                inner.function not in FUSED_FUNCS
                or inner.function_args
                or inner.at_ms is not None
            ):
                return None
            func, window = inner.function, inner.window_ms
        elif isinstance(inner, L.PeriodicSeries):
            if inner.at_ms is not None:
                return None
            func, window = None, inner.lookback_ms
        else:
            return None
        shards = self.shards_for(inner.raw.filters)
        if not shards:
            return None
        mesh = None
        if params.mesh is not None:
            # a configured device mesh rides the SAME fused path: the
            # superblock series axis partitions across it and the program
            # runs under shard_map (ONE multi-chip dispatch). Simple
            # aggregates reach here via the mesh engines' delegation
            # (_try_mesh_aggregate wins for them); this branch covers the
            # epilogue ops and fused histogram_quantile, which the legacy
            # mesh kernels never modeled.
            from ..parallel.mesh import series_mesh
            from ..query.exec.plans import fused_mesh_supported

            mesh = series_mesh(params.mesh)
            if not fused_mesh_supported(mesh, p.op, func):
                return None
        if hist_quantile is not None:
            # the fallback must reproduce the WHOLE fused subtree — the
            # aggregate tree plus the histogram_quantile mapper on top
            def fallback():
                tree = self._materialize_aggregate_tree(p)
                tree.transformers.append(
                    InstantVectorFunctionMapper(
                        "histogram_quantile", (hist_quantile,)
                    )
                )
                return tree
        else:
            def fallback():
                return self._materialize_aggregate_tree(p)
        raw_start, raw_end = self._fused_raw_range(
            inner.raw.start_ms, inner.raw.end_ms
        )
        fused = FusedAggregateExec(
            shards, inner.raw.filters, raw_start, raw_end,
            inner.raw.column, p.op, p.by, p.without, func,
            inner.start_ms, inner.end_ms, inner.step_ms or 1, window,
            inner.offset_ms,
            # lazy: the O(shards) reference tree only materializes if a
            # runtime condition actually falls back to it
            fallback=fallback,
            params=p.params,
            hist_quantile=hist_quantile,
            mesh=mesh,
        )
        rollup = self._try_rollup_aggregate(
            p, inner, func, window, hist_quantile, fused, mesh
        )
        return rollup if rollup is not None else fused

    def _try_rollup_aggregate(self, p: "L.Aggregate", inner, func,
                              window_ms: int, hist_quantile, fused, mesh):
        """Rollup substitution over the fused aggregate shape: when a
        registered rollup's resolution divides this query's step AND
        window and its closed coverage spans the grid, the [G, J] answer
        comes from O(periods) summary blocks — moments for
        sum/count/avg/min/max, merged sketches for the quantile epilogue,
        per-``le`` counter rollups for classic histogram_quantile. The
        already-built FusedAggregateExec IS the fallback, so plan-time
        ineligibility (returning None) and runtime ineligibility
        (``rollup_ineligible``) are both bit-identical to today's path."""
        from ..query.exec.plans import RollupServeExec
        from ..downsample.rollup import ROLLUP_AGG_OPS, ROLLUP_FUNCS

        rollups = self.params.rollups
        if rollups is None or func is None or inner.raw.column is not None:
            return None
        if func not in ROLLUP_FUNCS or inner.offset_ms:
            return None
        if hist_quantile is not None:
            # classic bucket series only: the interpolation needs the
            # per-``le`` rate partials in the grouping
            if p.op != "sum" or "le" not in tuple(p.by or ()):
                return None
        elif p.op not in ROLLUP_AGG_OPS and p.op != "quantile":
            return None
        key = rollups.plan(
            self.dataset, inner.raw.filters, func, inner.step_ms or 1,
            window_ms, inner.start_ms, inner.end_ms, inner.offset_ms,
        )
        if key is None:
            return None
        return RollupServeExec(
            rollups, key, inner.raw.filters, func, (),
            inner.start_ms, inner.end_ms, inner.step_ms or 1, window_ms,
            fallback=lambda: fused, op=p.op, by=p.by, without=p.without,
            params=p.params, hist_quantile=hist_quantile, mesh=mesh,
        )

    def _try_rollup_windowing(self, p: "L.PeriodicSeriesWithWindowing"):
        """Rollup substitution for a bare range function (no aggregate):
        ``quantile_over_time`` reads the per-period sketch blocks, the
        moment functions and counter rate/increase read the [S, P]
        moments. Ineligible shapes return None and the caller builds the
        raw mapper+fanout plan exactly as before (bit-identical)."""
        from ..query.exec.plans import (
            RollupServeExec,
            SelectRawPartitionsExec,
        )
        from ..downsample.rollup import ROLLUP_FUNCS

        rollups = self.params.rollups
        if rollups is None or p.raw.column is not None:
            return None
        if (p.function not in ROLLUP_FUNCS or p.at_ms is not None
                or p.offset_ms):
            return None
        if p.function_args and not (
            p.function == "quantile_over_time"
            and len(p.function_args) == 1
            and isinstance(p.function_args[0], (int, float))
        ):
            return None
        key = rollups.plan(
            self.dataset, p.raw.filters, p.function, p.step_ms or 1,
            p.window_ms, p.start_ms, p.end_ms, p.offset_ms,
        )
        if key is None:
            return None

        def fallback():
            mapper = PeriodicSamplesMapper(
                p.start_ms, p.end_ms, p.step_ms, p.function, p.window_ms,
                offset_ms=p.offset_ms, at_ms=p.at_ms, args=p.function_args,
            )
            raw = p.raw
            return self._fanout(
                lambda s: SelectRawPartitionsExec(
                    s, raw.filters, raw.start_ms, raw.end_ms, raw.column
                ),
                [mapper],
                filters=raw.filters,
                logical=p,
            )

        return RollupServeExec(
            rollups, key, p.raw.filters, p.function, p.function_args,
            p.start_ms, p.end_ms, p.step_ms or 1, p.window_ms,
            fallback=fallback,
        )

    # superblock staging-range alignment under cross-query batching: the
    # coalescing key is the superblock itself, but two dashboard panels
    # differing only in window (rate[3m] vs rate[5m]), offset, or the
    # live-edge "end=now" instant derive different raw selector ranges and
    # would stage two byte-near-identical superblocks that can never share
    # a batched launch. Aligning the staged range (start floored, end
    # ceiled) makes them resolve to ONE cached superblock — staging a
    # superset is always safe because result windows derive from the query
    # params (out_t/window), never from block bounds; the wider selection
    # can at most add series whose samples miss every window (NaN rows =
    # absence, same as the reference tree over the same range).
    FUSED_ALIGN_MS = 300_000

    def _fused_raw_range(self, start_ms: int, end_ms: int) -> tuple[int, int]:
        """Quantize a fused exec's staging range when (and only when)
        cross-query batching is enabled — with batching off, plans are
        byte-identical to the pre-scheduler planner."""
        if self.params.batch_window_ms <= 0:
            return start_ms, end_ms
        a = self.FUSED_ALIGN_MS
        return start_ms - start_ms % a, end_ms + (-end_ms) % a

    def _materialize_aggregate_tree(self, p: L.Aggregate) -> ExecPlan:
        inner = self._materialize(p.inner)
        simple = p.op in _PARTIAL_COMPONENTS
        if simple and isinstance(inner, DistConcatExec) and not inner.transformers:
            # push map phase onto each shard subtree (reference agg pushdown
            # SingleClusterPlanner.scala:1137)
            pushed_partial = self._push_peer_aggregate(inner.child_plans, p)
            for child in inner.child_plans:
                if id(child) not in pushed_partial:
                    child.transformers.append(
                        AggregateMapReduce(p.op, p.by, p.without)
                    )
            return ReduceAggregateExec(inner.child_plans, p.op, p.by, p.without)
        if simple and not isinstance(inner, DistConcatExec):
            inner.transformers.append(AggregateMapReduce(p.op, p.by, p.without))
            return ReduceAggregateExec([inner], p.op, p.by, p.without)
        if (p.op in ("topk", "bottomk") and p.params
                and isinstance(inner, DistConcatExec) and not inner.transformers):
            # per-shard candidate pre-reduction (exact; see
            # TopkCandidateFilter): root gathers O(shards*k), not O(series).
            # Peer leaves ship the topk ITSELF (the peer's per-step winners
            # are the exact candidate set) so O(k) rows cross the wire, not
            # the peer's full matching series.
            from ..query.exec.transformers import TopkCandidateFilter

            k = max(int(p.params[0]), 1)
            for child in inner.child_plans:
                if getattr(child, "peer_logical", None) is not None:
                    self._rewrite_peer_leaf(child, p)
                else:
                    child.transformers.append(
                        TopkCandidateFilter(k, p.op == "bottomk", p.by, p.without)
                    )
        elif (p.op == "count_values" and p.params
              and isinstance(inner, DistConcatExec) and not inner.transformers):
            # per-shard counting (exact: disjoint series sum at the root;
            # see CountValuesMapReduce) — O(groups x values) crosses the
            # gather, not O(series). Peers ship count_values itself: their
            # partial count rows merge by sum like local partials.
            from ..query.exec.plans import CountValuesMergeExec
            from ..query.exec.transformers import CountValuesMapReduce

            for child in inner.child_plans:
                if getattr(child, "peer_logical", None) is not None:
                    self._rewrite_peer_leaf(child, p)
                else:
                    child.transformers.append(
                        CountValuesMapReduce(str(p.params[0]), p.by, p.without)
                    )
            return CountValuesMergeExec(inner.child_plans)
        elif (p.op == "quantile" and p.params
              and isinstance(inner, DistConcatExec) and not inner.transformers):
            # distributed quantile over plan-transport peers: everyone ships
            # per-group mergeable sketch counts, O(groups x B) on the wire
            # instead of O(series) raw rows (reference QuantileRowAggregator
            # t-digest exchange). Local-only quantile stays on the exact
            # path below; HTTP peers can't ship sketches (PromQL transport).
            peers = [c for c in inner.child_plans
                     if getattr(c, "peer_logical", None) is not None]
            if peers and all(hasattr(c, "push_aggregate") for c in peers):
                from ..query.exec.plans import QuantileMergeExec, SketchMapReduce

                for child in inner.child_plans:
                    if getattr(child, "peer_logical", None) is not None:
                        child.push_aggregate(L.PartialAggregate(
                            "quantile", child.peer_logical, (), p.by, p.without
                        ))
                    else:
                        child.transformers.append(
                            SketchMapReduce(p.by, p.without)
                        )
                return QuantileMergeExec(
                    inner.child_plans, float(p.params[0]), p.by, p.without
                )
        return AggregatePresentExec([inner], p.op, p.params, p.by, p.without)

    def _rewrite_peer_leaf(self, child, p: "L.Aggregate") -> None:
        """Ship the whole aggregate to a peer leaf instead of its raw
        series (plan-level for gRPC, unparsed PromQL for HTTP)."""
        from ..query.unparse import to_promql

        wrapped = L.Aggregate(p.op, child.peer_logical, p.params, p.by, p.without)
        if hasattr(child, "push_aggregate"):
            child.push_aggregate(wrapped)
        else:
            child.promql = to_promql(wrapped)

    # aggregation ops where re-aggregating per-peer FINAL rows with the
    # same op is exact: sum of sums, min of mins, max of maxes, group of
    # groups — the only pushdown expressible over the PromQL (HTTP)
    # transport. count/avg/stddev over HTTP peers still return raw series.
    _PEER_PUSH_OPS = {"sum", "min", "max", "group"}

    def _push_peer_aggregate(self, children, p: "L.Aggregate") -> set:
        """Rewrite peer remote leaves to ship the aggregate instead of
        every raw series — the cross-host analog of the per-shard map-phase
        pushdown: O(groups) rows over the wire, not O(series).

        Plan-transport (gRPC) peers receive L.PartialAggregate and return
        mergeable __comp__ components, so count/avg/stddev/stdvar push too
        (reference RowAggregator.scala:28,114 AggregateItem exchange);
        PromQL (HTTP) peers can only express the exact-re-aggregation ops
        (_PEER_PUSH_OPS) and ship final rows. Returns the id()s of children
        now returning PARTIAL components (they must not get the local
        AggregateMapReduce transformer — their grids are already partials).
        """
        pushed_partial: set = set()
        if p.params:
            return pushed_partial
        for child in children:
            if getattr(child, "peer_logical", None) is None:
                continue
            if hasattr(child, "push_aggregate") and p.op in _PARTIAL_COMPONENTS:
                child.push_aggregate(L.PartialAggregate(
                    p.op, child.peer_logical, p.params, p.by, p.without
                ))
                pushed_partial.add(id(child))
            elif p.op in self._PEER_PUSH_OPS:
                self._rewrite_peer_leaf(child, p)
        return pushed_partial

    def _try_join_pushdown(self, p: "L.BinaryJoin"):
        """Per-shard binary-join pushdown (reference materializeBinaryJoin
        pushdown, SingleClusterPlanner.scala:640-760, gated there by
        target-schema colocation). The join runs inside each shard and the
        results concatenate — no cross-shard gather of full series.

        Sound ONLY when every pair of series that can match is guaranteed to
        live on the same shard. With our routing
        (shard = f(shard-key hash | partkey-hash low spread bits)) that means:

        - spread == 0: placement is a pure function of the shard-key columns;
        - the matching keys preserve every shard-key column: ``on`` ⊇ shard
          keys, or default matching with ignoring ∩ shard keys = ∅ AND the
          metric column NOT a shard key (default matching ignores __name__,
          so a metric-keyed placement would let cross-metric matches cross
          shards — the reference's target-schema gate is exactly this);
        - plain selector sides, one-to-one or set-op cardinality.

        Beneficiary: datasets sharded purely by (_ws_, _ns_) — the
        target-schema analog — where ``foo_bucket / foo_count`` and error
        ratios join shard-locally."""
        if self.params.spread != 0:
            return None
        if self.params.peer_endpoints or self.params.replica_router is not None:
            return None  # matching pairs may span hosts
        if p.op not in ("and", "or", "unless") and p.cardinality not in (None, "one-to-one"):
            return None
        if not isinstance(p.lhs, (L.PeriodicSeries, L.PeriodicSeriesWithWindowing)):
            return None
        if not isinstance(p.rhs, (L.PeriodicSeries, L.PeriodicSeriesWithWindowing)):
            return None
        options = self._options()
        skc = set(options.shard_key_columns)
        if p.on is not None:
            # explicit on-list (including the empty `on()`) must cover every
            # shard-key column or pairs can cross shards
            if not skc <= set(p.on):
                return None
        else:
            if options.metric_column in skc:
                return None  # default matching ignores the metric name
            if p.ignoring and set(p.ignoring) & skc:
                return None
        shards = sorted(set(self.shards_for(p.lhs.raw.filters))
                        | set(self.shards_for(p.rhs.raw.filters)))
        if len(shards) <= 1:
            return None  # single shard: the root join is already local
        per_shard = []
        for s in shards:
            sub = SingleClusterPlanner(self.memstore, self.dataset, [s], self.params)
            lhs = sub._materialize(p.lhs)
            rhs = sub._materialize(p.rhs)
            if p.op in ("and", "or", "unless"):
                per_shard.append(SetOperatorExec(lhs, rhs, p.op, p.on, p.ignoring))
            else:
                per_shard.append(BinaryJoinExec(
                    lhs, rhs, p.op, p.cardinality, p.on, p.ignoring,
                    p.include, p.return_bool,
                ))
        return DistConcatExec(per_shard)

    def _try_time_shard(self, p: "L.PeriodicSeriesWithWindowing"):
        """Long non-aggregated range queries shard the TIME axis over the
        mesh with a ring halo exchange (parallel/timeshard.py)."""
        mesh = self.params.mesh
        if mesh is None or self.params.peer_endpoints or self.params.replica_router is not None:
            return None
        from ..ops.kernels import SORTED_FUNCS
        from ..parallel.exec import TIME_SHARD_MIN_STEPS, TimeShardRangeExec

        num_steps = int((p.end_ms - p.start_ms) // (p.step_ms or 1)) + 1
        if (
            num_steps < TIME_SHARD_MIN_STEPS
            or p.offset_ms
            or p.at_ms is not None
            or p.function_args
            or p.function in SORTED_FUNCS
            or p.raw.column is not None
        ):
            return None
        # histograms stay on the standard path (plan-time schema peek)
        shards = self.shards_for(p.raw.filters)
        for s in shards:
            pids = self.memstore.shard(self.dataset, s).lookup_partitions(
                p.raw.filters, p.raw.start_ms, p.raw.end_ms, limit=1
            )
            if len(pids):
                part = self.memstore.shard(self.dataset, s).partition(int(pids[0]))
                if part.schema.has_histogram:
                    return None
                break
        is_counter = p.function in ("rate", "increase", "irate")
        return TimeShardRangeExec(
            mesh, shards, p.raw.filters, p.raw.start_ms, p.raw.end_ms,
            p.function, p.start_ms, p.end_ms, p.step_ms, p.window_ms,
            is_counter=is_counter,
        )

    def _try_mesh_aggregate(self, p: L.Aggregate):
        """Mesh path: aggregate-of-range-function compiles to one psum
        program when a device mesh is configured."""
        mesh = self.params.mesh
        if mesh is None or self.params.peer_endpoints or self.params.replica_router is not None:
            # peer scatter runs through the standard leaf fan-out; the mesh
            # single-psum program would aggregate local shards only
            return None
        from ..parallel.exec import MESH_OPS, MeshAggregateExec

        inner = p.inner
        if p.op not in MESH_OPS and p.op != "quantile":
            return None
        if not isinstance(inner, L.PeriodicSeriesWithWindowing):
            return None
        from ..ops.kernels import SORTED_FUNCS

        if (
            inner.offset_ms
            or inner.at_ms is not None
            or inner.function in SORTED_FUNCS
            or inner.function_args
        ):
            return None
        shards = self.shards_for(inner.raw.filters)
        # counter-ness resolved at execution from schemas; assume cumulative
        # counter when the function is the counter family
        is_counter = inner.function in ("rate", "increase", "irate")
        raw_start, raw_end = self._fused_raw_range(
            inner.raw.start_ms, inner.raw.end_ms
        )
        common = dict(
            mesh=mesh, shard_nums=shards, filters=inner.raw.filters,
            raw_start_ms=raw_start, raw_end_ms=raw_end,
            by=p.by, without=p.without, function=inner.function,
            start_ms=inner.start_ms, end_ms=inner.end_ms,
            step_ms=inner.step_ms, window_ms=inner.window_ms,
            is_counter=is_counter,
            # sharded-fused delegation (parallel/exec.py): the mesh engines
            # run the fused superblock kernels under shard_map when the
            # op/function allows, falling back to their legacy per-shard
            # stack (reason mesh_unsupported) otherwise. The delegate's own
            # runtime fallback is the reference tree.
            fused=self.params.fused_aggregate,
            fused_fallback=lambda: self._materialize_aggregate_tree(p),
        )
        axes = set(getattr(mesh, "axis_names", ()))
        if axes == {"shard", "time"}:
            from ..parallel.exec import Mesh2DAggregateExec

            if p.op in ("sum", "count", "avg"):
                return Mesh2DAggregateExec(op=p.op, **common)
            return None
        if "shard" not in axes:
            # e.g. a time-only mesh: the 1D aggregation program psums over
            # 'shard', which doesn't exist there — use the host path
            return None
        if p.op == "quantile":
            from ..parallel.exec import MeshQuantileExec

            return MeshQuantileExec(float(p.params[0]), **common)
        return MeshAggregateExec(op=p.op, **common)


def _plan_times(p: L.LogicalPlan):
    for attr in ("start_ms",):
        if hasattr(p, "start_ms") and hasattr(p, "step_ms") and hasattr(p, "end_ms"):
            return p.start_ms, p.end_ms, p.step_ms or 1
    for f in getattr(p, "__dataclass_fields__", {}):
        v = getattr(p, f)
        if isinstance(v, L.LogicalPlan):
            t = _plan_times(v)
            if t is not None:
                return t
    return None


class QueryEngine:
    """Top-level facade: PromQL string -> executed result (the in-process
    analog of QueryActor -> planner.materialize -> execute)."""

    def __init__(self, memstore, dataset: str, params: PlannerParams | None = None,
                 shard_nums: Sequence[int] | None = None):
        from .scheduler import SingleFlight

        self.memstore = memstore
        self.dataset = dataset
        self.planner = SingleClusterPlanner(memstore, dataset,
                                            shard_nums=shard_nums, params=params)
        self._single_flight = SingleFlight()
        p = self.planner.params
        if p.dispatch_scheduler is None and p.batch_window_ms > 0:
            from ..query.scheduler import DispatchScheduler

            p.dispatch_scheduler = DispatchScheduler(
                p.batch_window_ms, p.batch_max
            )
        if p.dispatch_scheduler is not None:
            # executable pre-warm (query/costmodel plane): the scheduler's
            # background tick traces+compiles about-to-be-hot recurrence
            # keys through this engine, off the serving path
            reg = getattr(p.dispatch_scheduler, "register_prewarmer", None)
            if reg is not None:
                reg(self._prewarm_key)

    def context(self, allow_partial_results: bool | None = None) -> QueryContext:
        params = self.planner.params
        ctx = QueryContext(self.memstore, self.dataset)
        ctx.max_series = params.max_series
        ctx.deadline_s = params.deadline_s
        ctx.allow_partial_results = (
            params.allow_partial_results if allow_partial_results is None
            else bool(allow_partial_results)
        )
        ctx.retry_policy = params.retry_policy
        ctx.breakers = params.breakers
        ctx.dispatcher = params.dispatcher
        ctx.dispatch_scheduler = params.dispatch_scheduler
        return ctx

    def _start_trace(self, ctx, promql: str, trace_id: str | None = None,
                     parent_span_id: str | None = None):
        """Open the query's root span. ``trace_id``/``parent_span_id`` come
        from an upstream origin (gRPC metadata / HTTP headers) so this
        process's spans — and its slow-query entries — join that trace."""
        import time as _time

        from ..metrics import Span, new_trace_id

        root = Span("query", _time.perf_counter_ns())
        root.trace_id = trace_id or new_trace_id()
        root.parent_id = parent_span_id
        root.tags["promql"] = promql
        root.tags["dataset"] = self.dataset
        ctx.trace_root = root
        return root

    def _observe_slow(self, promql: str, elapsed_s: float, res,
                      query_id: str | None = None) -> None:
        """Record queries over the slow-query threshold with their rendered
        trace (the observability substrate for "why was THIS query slow").
        ``query_id`` links the entry to the same execution's query-log
        record (``/api/v1/query_profile?id=``) so the two debug surfaces
        join instead of being disjoint rings."""
        thr = self.planner.params.slow_query_threshold_s
        if thr is None or elapsed_s < thr:
            return
        from ..metrics import SLOW_QUERY_LOG

        SLOW_QUERY_LOG.record(
            promql, elapsed_s, dataset=self.dataset, trace=res.trace,
            stats=res.stats.as_dict() if res.stats is not None else None,
            query_id=query_id,
        )

    def _observe_querylog(self, promql: str, ctx, rec, elapsed_s: float,
                          start_s: float, end_s: float, step_ms: int,
                          res=None, error=None, tenant=None):
        """Publish one exemplar-level cost record for this execution into
        the query observatory (obs/querylog.py): phases, path, stats,
        result size, status. Returns the record (None for remote-child
        legs — the ORIGIN records the whole query exactly once, mirroring
        tenant metering) and attaches it to the result so the serving edge
        can fold in its transfer/render phases."""
        root = getattr(ctx, "trace_root", None)
        if rec is None or root is None or root.parent_id is not None:
            return None
        from ..obs.querylog import QUERY_LOG
        from ..query.scheduler import AdmissionRejected

        ws, ns = (tenant or getattr(ctx, "_tenant", None)
                  or (root.tags.get("ws", "unknown"),
                      root.tags.get("ns", "unknown")))
        status, err = "ok", None
        if error is not None:
            status = ("shed" if isinstance(error, AdmissionRejected)
                      else "error")
            err = f"{type(error).__name__}: {error}"
        result_series = result_samples = 0
        if res is not None:
            for g in res.grids:
                result_series += g.n_series
                result_samples += g.n_series * g.num_steps
            if res.raw is not None:
                result_series += len(res.raw)
                result_samples += sum(len(t) for _, t, _ in res.raw)
        # cost-model plane: what admission priced the query at vs. the
        # device time it actually consumed; the completed record feeds the
        # predictor's online update (EWMA per fingerprint + family)
        predicted = getattr(ctx, "predicted_cost_s", None)
        realized = ctx.stats.kernel_ns / 1e9 if ctx.stats is not None else 0.0
        record = QUERY_LOG.publish(
            query_id=root.trace_id, dataset=self.dataset, promql=promql,
            ws=ws, ns=ns, step_ms=int(step_ms),
            span_ms=max(int((end_s - start_s) * 1000), 0),
            start_s=start_s, end_s=end_s, phases=rec, elapsed_s=elapsed_s,
            stats=ctx.stats, path_info=getattr(ctx, "obs", None),
            result_series=result_series, result_samples=result_samples,
            status=status, error=err,
            predicted_cost_s=predicted,
            realized_cost_s=realized if realized > 0 else None,
        )
        if status == "ok":
            from ..query.costmodel import COST_MODEL

            COST_MODEL.observe(record)
        if res is not None:
            res.query_log = record
        return record

    def _finish(self, res, ctx):
        """Attach per-query stats + partial-result warnings collected on the
        context during scatter-gather (query/faults.py), and close + attach
        the trace root span."""
        res.stats = ctx.stats  # per-query scan/latency stats ride in responses
        root = getattr(ctx, "trace_root", None)
        if root is not None:
            import time as _time

            if not root.end_ns:
                root.end_ns = _time.perf_counter_ns()
            root.stats = ctx.stats.as_dict()
            res.trace = root
        if ctx.warnings:
            from ..metrics import record_partial_result

            # order-preserving dedup: a remote child's warnings can be seen
            # both in its own result and hoisted onto the context
            deduped: list = []
            for w in ctx.warnings:
                if w not in deduped:
                    deduped.append(w)
            res.warnings = deduped
            res.partial = True
            record_partial_result(self.dataset)
        return res

    def query_range(self, promql: str, start_s: float, end_s: float, step_s: float,
                    allow_partial_results: bool | None = None,
                    trace_id: str | None = None,
                    parent_span_id: str | None = None):
        """PromQL range query. Concurrent identical queries coalesce into
        ONE plan+stage+kernel execution (reference: the shared
        QueryScheduler pool, QueryScheduler.scala:29-73, plus single-flight
        result sharing for the dashboard fan-out pattern). Serving metrics
        count every CALLER (followers included), not executions — the
        coalescing factor must not deflate served QPS or the latency
        histogram."""
        import time as _time

        from ..metrics import REGISTRY

        t0 = _time.perf_counter()
        # resolve the tri-state BEFORE keying: "absent" and "explicitly the
        # engine default" are the same query and must coalesce together
        allow_partial = (
            self.planner.params.allow_partial_results
            if allow_partial_results is None else bool(allow_partial_results)
        )
        # trace linkage is NOT part of the coalescing key: followers share
        # the leader's execution and therefore the leader's trace tree
        if self.planner.params.coalesce_identical:
            res = self._single_flight.run(
                (self.dataset, promql, float(start_s), float(end_s), float(step_s),
                 allow_partial),
                lambda: self._query_range_uncoalesced(
                    promql, start_s, end_s, step_s, allow_partial,
                    trace_id=trace_id, parent_span_id=parent_span_id,
                ),
                timeout_s=self.planner.params.deadline_s,
            )
        else:
            res = self._query_range_uncoalesced(promql, start_s, end_s, step_s,
                                                allow_partial, trace_id=trace_id,
                                                parent_span_id=parent_span_id)
        REGISTRY.counter("filodb_queries", dataset=self.dataset).inc()
        # trace-id exemplar: the OpenMetrics exposition attaches it to the
        # latency bucket this query landed in, so a spiking bucket links
        # straight to its trace / slow-query-log entry
        tid = getattr(res.trace, "trace_id", None) if res.trace is not None \
            else None
        if tid is None and isinstance(res.trace, dict):
            tid = res.trace.get("trace_id")
        REGISTRY.histogram("filodb_query_latency_seconds", dataset=self.dataset).observe(
            _time.perf_counter() - t0,
            exemplar={"trace_id": tid} if tid else None,
        )
        return res

    def _meter_tenant(self, plan, ctx, elapsed_s: float) -> None:
        """Attribute the finished query's resources to the tenant resolved
        from its selector filters (metering.py — the admission-control
        accounting), and tag the trace root so ?trace=true shows it.

        Child executions (a parent span rides the request: remote-exec from
        another node, or a peer's scatter leg) only TAG — the origin meters
        the whole query once, from its merged query-wide stats; metering
        here too would double-count every remote child's resources."""
        from ..metering import record_tenant_query, tenant_of_plan

        ws, ns = getattr(ctx, "_tenant", None) or tenant_of_plan(plan)
        root = getattr(ctx, "trace_root", None)
        if root is not None:
            root.tags["ws"] = ws
            root.tags["ns"] = ns
            if root.parent_id is not None:
                return ws, ns
        record_tenant_query(
            ws, ns, elapsed_s, ctx.stats.kernel_ns / 1e9,
            ctx.stats.bytes_staged,
        )
        return ws, ns

    def _query_range_uncoalesced(self, promql: str, start_s: float,
                                 end_s: float, step_s: float,
                                 allow_partial_results: bool | None = None,
                                 trace_id: str | None = None,
                                 parent_span_id: str | None = None):
        import time as _time

        from ..obs.querylog import PhaseRecorder

        rec = PhaseRecorder()
        t0 = _time.perf_counter()
        with rec.phase("parse_plan"):
            plan = query_range_to_logical_plan(
                promql, start_s, end_s, step_s,
                self.planner.params.lookback_ms,
            )
            if self.planner.params.agg_rules is not None:
                from .lpopt import optimize_with_preagg

                plan = optimize_with_preagg(plan,
                                            self.planner.params.agg_rules)
            exec_plan = self.planner.materialize(plan)
        ctx = self.context(allow_partial_results)
        ctx.phases = rec
        self._start_trace(ctx, promql, trace_id, parent_span_id)
        step_ms = int(step_s * 1000)
        try:
            with rec.phase("admission"):
                adm = self._admit(
                    plan, ctx, promql=promql, step_ms=step_ms,
                    span_ms=max(int((end_s - start_s) * 1000), 0),
                )
            with adm:
                res = self._run(exec_plan, ctx)
        except Exception as e:
            # shed / errored queries are cost records too (status =
            # shed|error): the observatory must see what the tenant PAID
            # for, not only what succeeded
            self._observe_querylog(
                promql, ctx, rec, _time.perf_counter() - t0, start_s,
                end_s, step_ms, error=e,
            )
            raise
        self._finish(res, ctx)
        if res.result_type == "matrix" or res.grids:
            res.result_type = "matrix"
        elapsed_s = _time.perf_counter() - t0
        tenant = self._meter_tenant(plan, ctx, elapsed_s)
        record = self._observe_querylog(promql, ctx, rec, elapsed_s,
                                        start_s, end_s, step_ms, res=res,
                                        tenant=tenant)
        self._observe_slow(promql, elapsed_s, res,
                           query_id=record["id"] if record else None)
        return res

    def _admit(self, plan, ctx, promql: str | None = None,
               step_ms: int = 0, span_ms: int = 0):
        """Admission-control gate (query/scheduler.AdmissionController):
        resolve the tenant from the plan's selector filters, PRICE the
        query through the cost model (query/costmodel.py — fingerprint
        EWMA, family prior for cold fingerprints) and claim its
        concurrency/rate slots for the duration of execution, draining the
        tenant's device-second bucket by the prediction. Raises
        AdmissionRejected (HTTP 429 + Retry-After = the bucket's predicted
        drain time) when the tenant is over quota or the global
        queue-depth bound is hit; a no-op context when no controller is
        configured. The prediction + resolved tenant are stashed on the
        context: _observe_querylog stamps ``predicted_cost_s`` onto the
        cost record, and _meter_tenant doesn't walk the plan's leaves a
        second time per query. Coalesced identical-query followers never
        reach this point (they share the leader's execution AND its
        admission slot — sharing an answer costs the tenant nothing)."""
        params = self.planner.params
        cost_s = None
        if promql is not None:
            from ..obs.querylog import promql_fingerprint
            from ..query.costmodel import COST_MODEL, family_of

            steps = (int(span_ms // step_ms) + 1) if step_ms > 0 else 1
            fp = promql_fingerprint(self.dataset, promql, step_ms, span_ms)
            cost_s, source = COST_MODEL.predict(
                fp, steps=steps, family=family_of(promql)
            )
            ctx.predicted_cost_s = cost_s
            ctx.cost_fingerprint = fp
            ctx.cost_source = source
        if params.admission is None:
            import contextlib

            return contextlib.nullcontext()
        from ..metering import tenant_of_plan

        ws, ns = tenant_of_plan(plan)
        ctx._tenant = (ws, ns)
        return params.admission.admit(ws, ns, cost_s=cost_s)

    def _prewarm_key(self, desc: dict) -> None:
        """Background trace+compile of a predicted-hot recurrence key
        (DispatchScheduler.prewarm_tick): run the ring descriptor's query
        end-to-end OFF the serving path — no admission (the server's own
        standing obligation, like maintainer refreshes), no querylog or
        recurrence-ring feedback (``standing_refresh`` flag), no batch
        window — so its executables and superblock are warm before the
        first real poll pays the compile in its p99."""
        import time as _time

        promql = desc.get("promql")
        step_ms = int(desc.get("step_ms") or 0)
        span_ms = int(desc.get("span_ms") or 0)
        if not promql or step_ms <= 0 or span_ms <= 0:
            return
        end_s = _time.time() - float(desc.get("end_lag_ms") or 0) / 1e3
        start_s = end_s - span_ms / 1e3
        plan = query_range_to_logical_plan(
            promql, start_s, end_s, step_ms / 1e3,
            self.planner.params.lookback_ms,
        )
        if self.planner.params.agg_rules is not None:
            from .lpopt import optimize_with_preagg

            plan = optimize_with_preagg(plan, self.planner.params.agg_rules)
        exec_plan = self.planner.materialize(plan)
        ctx = self.context()
        ctx.standing_refresh = True  # keep prewarm out of the ring
        # solo-path compile is the one a dashboard's first poll would pay:
        # don't route the warmup through the batch window it exists to dodge
        ctx.dispatch_scheduler = None
        exec_plan.execute(ctx)

    def _run(self, exec_plan, ctx):
        """Execute on the shared bounded scheduler when configured, else
        inline on the caller's thread."""
        sched = self.planner.params.scheduler
        if sched is None:
            return exec_plan.execute(ctx)
        return sched.run(lambda: exec_plan.execute(ctx), deadline_s=ctx.deadline_s)

    def execute_plan(self, plan, deadline_s: float = 0.0, max_series: int = 0,
                     allow_partial_results: bool | None = None,
                     trace_id: str | None = None,
                     parent_span_id: str | None = None):
        """Execute an already-built LogicalPlan — THE entry for plan-level
        remote transports (gRPC ExecutePlan, Flight plan tickets), so every
        transport shares the same pre-agg rewrite, limits, and scheduler
        path as PromQL queries."""
        import time as _time

        from ..obs.querylog import PhaseRecorder

        rec = PhaseRecorder()
        t0 = _time.perf_counter()
        with rec.phase("parse_plan"):
            if self.planner.params.agg_rules is not None:
                from .lpopt import optimize_with_preagg

                plan = optimize_with_preagg(plan,
                                            self.planner.params.agg_rules)
            exec_plan = self.planner.materialize(plan)
        ctx = self.context(allow_partial_results)
        ctx.phases = rec
        if deadline_s:
            ctx.deadline_s = min(ctx.deadline_s, deadline_s)
        if max_series:
            ctx.max_series = min(ctx.max_series, max_series)
        try:
            from ..query.unparse import to_promql

            qname = to_promql(plan)
        except Exception:  # noqa: BLE001 — metadata plans have no PromQL form
            qname = type(plan).__name__
        self._start_trace(ctx, qname, trace_id, parent_span_id)
        times = _plan_times(plan)
        g_start, g_end, g_step = (
            (times[0] / 1000.0, times[1] / 1000.0, times[2])
            if times else (0.0, 0.0, 0)
        )
        try:
            with rec.phase("admission"):
                adm = self._admit(
                    plan, ctx, promql=qname, step_ms=g_step,
                    span_ms=max(int((g_end - g_start) * 1000), 0),
                )
            with adm:
                res = self._run(exec_plan, ctx)
        except Exception as e:
            self._observe_querylog(qname, ctx, rec,
                                   _time.perf_counter() - t0, g_start,
                                   g_end, g_step, error=e)
            raise
        self._finish(res, ctx)
        elapsed_s = _time.perf_counter() - t0
        tenant = self._meter_tenant(plan, ctx, elapsed_s)
        record = self._observe_querylog(qname, ctx, rec, elapsed_s,
                                        g_start, g_end, g_step, res=res,
                                        tenant=tenant)
        self._observe_slow(qname, elapsed_s, res,
                           query_id=record["id"] if record else None)
        return res

    def label_values(self, filters, label: str, start_ms: int, end_ms: int, limit=None):
        """Metadata through the planner so multi-host peers scatter too."""
        plan = L.LabelValues(label, tuple(filters), start_ms, end_ms)
        ep = self.planner.materialize(plan)
        if limit:
            ep.limit = int(limit)
        return ep.execute(self.context()).metadata

    def label_names(self, filters, start_ms: int, end_ms: int):
        ep = self.planner.materialize(L.LabelNames(tuple(filters), start_ms, end_ms))
        return ep.execute(self.context()).metadata

    def series(self, filters, start_ms: int, end_ms: int, limit=None):
        ep = self.planner.materialize(L.SeriesKeysByFilters(tuple(filters), start_ms, end_ms))
        if limit:
            ep.limit = int(limit)
        return ep.execute(self.context()).metadata

    def ts_cardinalities(self, prefix, depth: int | None = None):
        plan = L.TsCardinalities(tuple(prefix), depth if depth is not None else len(tuple(prefix)) + 1)
        return self.planner.materialize(plan).execute(self.context()).metadata

    def query_instant(self, promql: str, time_s: float,
                      allow_partial_results: bool | None = None,
                      trace_id: str | None = None,
                      parent_span_id: str | None = None):
        import time as _time

        from ..obs.querylog import PhaseRecorder

        rec = PhaseRecorder()
        t0 = _time.perf_counter()
        with rec.phase("parse_plan"):
            plan = query_to_logical_plan(promql, time_s,
                                         self.planner.params.lookback_ms)
            exec_plan = self.planner.materialize(plan)
        ctx = self.context(allow_partial_results)
        ctx.phases = rec
        self._start_trace(ctx, promql, trace_id, parent_span_id)
        try:
            with rec.phase("admission"):
                adm = self._admit(plan, ctx, promql=promql)
            with adm:
                res = self._run(exec_plan, ctx)
        except Exception as e:
            self._observe_querylog(promql, ctx, rec,
                                   _time.perf_counter() - t0, time_s,
                                   time_s, 0, error=e)
            raise
        self._finish(res, ctx)
        if res.result_type == "matrix":
            res.result_type = "vector"
        elapsed_s = _time.perf_counter() - t0
        tenant = self._meter_tenant(plan, ctx, elapsed_s)
        record = self._observe_querylog(promql, ctx, rec, elapsed_s,
                                        time_s, time_s, 0, res=res,
                                        tenant=tenant)
        self._observe_slow(promql, elapsed_s, res,
                           query_id=record["id"] if record else None)
        return res
