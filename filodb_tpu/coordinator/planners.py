"""Planner hierarchy (reference L5 queryplanner/: LongTimeRangePlanner,
HighAvailabilityPlanner.scala:491, MultiPartitionPlanner.scala:1445,
ShardKeyRegexPlanner.scala:500, SinglePartitionPlanner.scala:129,
FailureRoutingStrategy.scala).

Composition (same layering as the reference):

  SinglePartitionPlanner        picks a planner per metric/dataset
    MultiPartitionPlanner       federates across clusters ("partitions")
      ShardKeyRegexPlanner      fans out regex shard keys
        HighAvailabilityPlanner fails over to a buddy cluster
          LongTimeRangePlanner  raw vs downsample + stitch
            SingleClusterPlanner  (planner.py)

Cross-cluster execution ships subplans as PromQL over HTTP
(PromQlRemoteExec analog) — the reference does the same for federation;
its gRPC path is an optimization we don't need host-side.
"""

from __future__ import annotations

import itertools
import json
import urllib.parse
import urllib.request
from dataclasses import dataclass, field, replace
from typing import Callable, Sequence

import numpy as np

from ..core.filters import ColumnFilter
from ..core.schemas import METRIC_TAG
from ..query import logical as L
from ..query.exec.plans import DistConcatExec, EmptyResultExec, ExecPlan, StitchRvsExec
from ..query.exec.transformers import QueryError
from ..query.rangevector import Grid, QueryResult
from ..query.unparse import to_promql
from .planner import SingleClusterPlanner


# ---------------------------------------------------------------------------
# Remote execution over HTTP (reference PromQlRemoteExec)
# ---------------------------------------------------------------------------


_RETRIES = 3
_BACKOFF_S = (0.2, 0.8)


class RemoteFetchError(QueryError):
    """Transport-level remote HTTP failure (exhausted retries / 5xx). Counts
    against the endpoint's circuit breaker (query/faults.py) but is NOT
    re-retried at the dispatch layer — fetch_json already retried."""

    endpoint_failure = True


def fetch_raw(url: str, auth_token: str | None = None, local_only: bool = False,
              timeout: float = 60, data: dict | None = None,
              extra_headers: dict | None = None) -> tuple:
    """Transport layer under :func:`fetch_json` / :func:`fetch_result`:
    gzip transport, bearer auth, X-FiloDB-Local pinning, bounded retries
    with backoff on transient failures (5xx / connection errors / timeouts;
    4xx fails fast). ``data`` switches to a JSON POST. Returns
    ``(body_bytes, response_headers)`` with gzip already undone.

    ``timeout`` is a TOTAL budget: per-attempt socket timeouts shrink to the
    remaining budget and retries/backoffs never run past it, so a hung peer
    cannot stall a deadline-budgeted caller for retries x timeout."""
    import gzip
    import time as _time
    import urllib.error
    import urllib.request

    headers = {"Accept-Encoding": "gzip"}
    if auth_token:
        headers["Authorization"] = f"Bearer {auth_token}"
    if local_only:
        headers["X-FiloDB-Local"] = "1"
    if extra_headers:
        headers.update(extra_headers)
    body = None
    if data is not None:
        body = json.dumps(data).encode()
        headers["Content-Type"] = "application/json"
    deadline = _time.monotonic() + timeout
    last_err: Exception | None = None
    for attempt in range(_RETRIES):
        per_attempt = deadline - _time.monotonic()
        if per_attempt <= 0:
            break
        try:
            req = urllib.request.Request(url, data=body, headers=headers)
            with urllib.request.urlopen(req, timeout=per_attempt) as r:
                raw = r.read()
                if r.headers.get("Content-Encoding") == "gzip":
                    raw = gzip.decompress(raw)
                return raw, r.headers
        except urllib.error.HTTPError as e:
            if e.code == 429:
                # the peer's admission control shed this scatter leg: honor
                # its Retry-After instead of retrying into the shed window,
                # and surface the typed rejection so partial-results merges
                # degrade it exactly like a faulted child (its
                # endpoint_failure classification feeds the peer's breaker)
                from ..query.scheduler import AdmissionRejected

                try:
                    retry_after = float(e.headers.get("Retry-After") or 1.0)
                except (TypeError, ValueError):
                    retry_after = 1.0
                raise AdmissionRejected(
                    f"remote peer shed request: HTTP 429 {e.reason}",
                    retry_after_s=retry_after, outcome="shed_remote",
                ) from e
            if e.code < 500:
                raise QueryError(f"remote request failed: HTTP {e.code} {e.reason}") from e
            last_err = e  # 5xx: transient, retry
        except (urllib.error.URLError, TimeoutError, ConnectionError) as e:
            last_err = e
        if attempt < _RETRIES - 1:
            backoff = _BACKOFF_S[min(attempt, len(_BACKOFF_S) - 1)]
            if _time.monotonic() + backoff >= deadline:
                break  # budget exhausted: surface the last error now
            _time.sleep(backoff)
    raise RemoteFetchError(f"remote request failed after retries: {last_err}")


def fetch_json(url: str, auth_token: str | None = None, local_only: bool = False,
               timeout: float = 60, data: dict | None = None,
               want_envelope: bool = False,
               extra_headers: dict | None = None) -> dict | list:
    """THE remote-HTTP fetch used by every cross-host path (query scatter,
    federation, metadata, membership) — :func:`fetch_raw` plus the
    Prometheus-envelope decode. Returns the parsed ``data`` payload of a
    successful response (``want_envelope=True`` returns the whole envelope —
    the partial-results scatter reads top-level ``warnings``/``partial``)."""
    raw, _hdrs = fetch_raw(url, auth_token=auth_token, local_only=local_only,
                           timeout=timeout, data=data, extra_headers=extra_headers)
    payload = json.loads(raw)
    if payload.get("status") != "success":
        raise QueryError(f"remote request failed: {payload}")
    return payload if want_envelope else payload["data"]


# node-to-node result hops default to columnar Arrow frames; "json" forces
# the legacy decimal-JSON legs everywhere (config [result_plane] wires this)
PEER_EXCHANGE = "arrow"


def fetch_result(url: str, auth_token: str | None = None, local_only: bool = False,
                 timeout: float = 60, extra_headers: dict | None = None):
    """Columnar-first result fetch for node-to-node hops: advertises the
    Arrow media type via Accept and decodes the peer's IPC frames (floats
    cross bit-exact, no decimal render/parse). A peer that answers JSON —
    older build, arrow-less install, or non-matrix result — falls back to
    the envelope path: returns a ``QueryResult`` when the peer spoke Arrow,
    else the parsed JSON envelope dict."""
    AE = None
    if PEER_EXCHANGE == "arrow":
        try:
            from ..api import arrow_edge as AE  # noqa: N813 (pyarrow gate)
        except Exception:
            AE = None
    headers = dict(extra_headers or {})
    if AE is not None:
        headers["Accept"] = AE.ARROW_CONTENT_TYPE + ", application/json"
    raw, hdrs = fetch_raw(url, auth_token=auth_token, local_only=local_only,
                          timeout=timeout, extra_headers=headers)
    ctype = (hdrs.get("Content-Type") or "").split(";")[0].strip()
    if AE is not None and ctype == AE.ARROW_CONTENT_TYPE:
        return AE.ipc_to_result(raw)
    payload = json.loads(raw)
    if payload.get("status") != "success":
        raise QueryError(f"remote request failed: {payload}")
    return payload


class PromQlRemoteExec(ExecPlan):
    """Cross-cluster exec as PromQL-over-HTTP (reference PromQlRemoteExec —
    which also ships retries/timeouts via sttp), over :func:`fetch_json`."""

    is_remote = True  # network-bound: NonLeafExecPlan overlaps these children

    def __init__(self, endpoint: str, promql: str, start_ms: int, end_ms: int, step_ms: int,
                 auth_token: str | None = None, local_only: bool = False):
        super().__init__()
        self.endpoint = endpoint
        self.promql = promql
        self.start_ms = start_ms
        self.end_ms = end_ms
        self.step_ms = step_ms
        # multi-host scatter anti-recursion: the peer must answer from its
        # OWN shards only (X-FiloDB-Local), never re-scatter to its peers
        self.local_only = local_only
        import os as _os

        self.auth_token = auth_token or _os.environ.get("FILODB_REMOTE_TOKEN")

    def args_str(self) -> str:
        return f"endpoint={self.endpoint} promql={self.promql}"

    def do_execute(self, ctx) -> QueryResult:
        q = urllib.parse.quote(self.promql)
        url = (
            f"{self.endpoint}/api/v1/query_range?query={q}"
            f"&start={self.start_ms / 1000}&end={self.end_ms / 1000}&step={self.step_ms / 1000}"
        )
        # forward the origin's RESOLVED stance explicitly (true or false) so
        # it overrides the peer's own configured default either way; a
        # partial peer's top-level warnings fold into this child's result
        allow_partial = getattr(ctx, "allow_partial_results", False)
        url += f"&allow_partial_results={'true' if allow_partial else 'false'}"
        # trace propagation: request the peer's span tree and hand it our
        # span identity so its spans join this query's trace; the tree comes
        # back in the envelope and ExecPlan.execute stitches it in
        from ..metrics import TraceContext, current_span

        sp = current_span()
        headers = None
        if sp is not None:
            url += "&trace=true"
            headers = {
                TraceContext.TRACE_ID_HEADER: sp.trace_id,
                TraceContext.PARENT_SPAN_HEADER: sp.span_id,
            }
        fetched = fetch_result(
            url, auth_token=self.auth_token, local_only=self.local_only,
            timeout=max(ctx.remaining_deadline_s(), 0.1),
            extra_headers=headers,
        )
        if isinstance(fetched, QueryResult):
            # columnar leg: the Arrow envelope already carried grids
            # (bit-exact float payloads), warnings/partial, the peer's span
            # tree and its QueryStats — no O(series x steps) JSON re-parse
            return fetched
        envelope = fetched
        data = envelope["data"]
        result = data["result"]
        num_steps = int((self.end_ms - self.start_ms) // self.step_ms) + 1
        times = self.start_ms + np.arange(num_steps, dtype=np.int64) * self.step_ms
        labels, rows = [], []
        t2i = {int(t): i for i, t in enumerate(times)}
        for series in result:
            lbls = {
                (METRIC_TAG if k == "__name__" else k): v
                for k, v in series["metric"].items()
            }
            row = np.full(num_steps, np.nan, np.float32)
            for t, v in series.get("values", []):
                # round, don't truncate: the peer renders t/1000.0 and the
                # nearest double of e.g. ...400.123 is ...400.12299999
                i = t2i.get(round(float(t) * 1000))
                if i is not None:
                    row[i] = float(v)
            labels.append(lbls)
            rows.append(row)
        vals = np.stack(rows) if rows else np.zeros((0, num_steps), np.float32)
        out = QueryResult(grids=[Grid(labels, self.start_ms, self.step_ms, num_steps, vals)])
        if envelope.get("warnings"):
            out.warnings = list(envelope["warnings"])
            out.partial = True
        if isinstance(data, dict) and data.get("trace") is not None:
            out.trace = data["trace"]  # peer span tree; stitched by execute
        st = data.get("stats") if isinstance(data, dict) else None
        if st:
            # the peer's QueryStats fold into the origin's query-wide stats
            # (ExecPlan.execute merges a remote child's stats exactly once)
            from ..query.rangevector import QueryStats

            out.stats = QueryStats(
                series_scanned=int(st.get("seriesScanned", 0)),
                samples_scanned=int(st.get("samplesScanned", 0)),
                cpu_ns=int(st.get("cpuNanos", 0)),
                bytes_staged=int(st.get("bytesStaged", 0)),
                # resource attribution (doc/observability.md): remote kernel
                # and cache work must fold into the origin's query totals
                kernel_ns=int(round(float(st.get("kernelSeconds", 0.0)) * 1e9)),
                cache_hits=int(st.get("cacheHits", 0)),
                cache_misses=int(st.get("cacheMisses", 0)),
                cache_extends=int(st.get("cacheExtends", 0)),
            )
        return out


# ---------------------------------------------------------------------------
# LongTimeRangePlanner
# ---------------------------------------------------------------------------


class LongTimeRangePlanner:
    """Routes old time ranges to the downsample cluster, recent ones to raw,
    stitching at the boundary (reference LongTimeRangePlanner +
    materializeTimeSplitPlan)."""

    def __init__(self, raw_planner, downsample_planner, earliest_raw_ms: Callable[[], int]):
        self.raw = raw_planner
        self.ds = downsample_planner
        self.earliest_raw_ms = earliest_raw_ms

    def materialize(self, plan: L.LogicalPlan) -> ExecPlan:
        times = _plan_range(plan)
        if times is None:
            return self.raw.materialize(plan)
        start, end, step = times
        boundary = self.earliest_raw_ms()
        lookback = _max_lookback(plan)
        if start >= boundary:
            return self.raw.materialize(plan)
        if end < boundary:
            return self.ds.materialize(plan)
        # split on the step grid: last ds step < first raw step
        first_raw_step = boundary + lookback
        first_raw_step = start + ((first_raw_step - start + step - 1) // step) * step
        if first_raw_step > end:
            return self.ds.materialize(plan)
        ds_end = first_raw_step - step
        parts = []
        if ds_end >= start:
            parts.append(self.ds.materialize(_with_range(plan, start, ds_end)))
        parts.append(self.raw.materialize(_with_range(plan, first_raw_step, end)))
        if len(parts) == 1:
            return parts[0]
        return StitchRvsExec(parts)


class DownsampleClusterPlanner:
    """Plans against a downsample dataset: rewrites the selected column by
    range function (reference DownsampledTimeSeriesShard column rewrite,
    ``min_over_time(m) -> m::min``, doc/downsampling.md:89-96)."""

    def __init__(self, memstore, dataset: str, params=None):
        from .planner import SingleClusterPlanner

        self.inner = SingleClusterPlanner(memstore, dataset, params=params)

    def materialize(self, plan: L.LogicalPlan) -> ExecPlan:
        return self.inner.materialize(self._rewrite(plan))

    def _rewrite(self, p: L.LogicalPlan) -> L.LogicalPlan:
        from ..downsample.downsampler import FUNC_TO_DS_COLUMN

        if isinstance(p, L.PeriodicSeriesWithWindowing):
            col = FUNC_TO_DS_COLUMN.get(p.function)
            if col:
                return replace(p, raw=replace(p.raw, column=col))
            return p
        if isinstance(p, L.PeriodicSeries):
            return replace(p, raw=replace(p.raw, column="avg"))
        kw = {}
        for f in getattr(p, "__dataclass_fields__", {}):
            v = getattr(p, f)
            if isinstance(v, L.LogicalPlan):
                kw[f] = self._rewrite(v)
        return replace(p, **kw) if kw else p


# ---------------------------------------------------------------------------
# HighAvailabilityPlanner
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class FailureTimeRange:
    """A known-bad window of the local cluster (reference FailureProvider)."""

    start_ms: int
    end_ms: int


class HighAvailabilityPlanner:
    """Routes query sub-ranges overlapping local failures to a buddy cluster
    as PromQL remote execs (reference HighAvailabilityPlanner +
    FailureRoutingStrategy)."""

    def __init__(self, local_planner, buddy_endpoint: str,
                 failure_provider: Callable[[], Sequence[FailureTimeRange]]):
        self.local = local_planner
        self.buddy = buddy_endpoint
        self.failures = failure_provider

    def materialize(self, plan: L.LogicalPlan) -> ExecPlan:
        times = _plan_range(plan)
        failures = [f for f in self.failures()]
        if times is None or not failures:
            return self.local.materialize(plan)
        start, end, step = times
        lookback = _max_lookback(plan)
        overlapping = [f for f in failures if f.end_ms >= start - lookback and f.start_ms <= end]
        if not overlapping:
            return self.local.materialize(plan)
        # route whole steps whose lookback window touches a failure remotely
        remote_steps = np.zeros(int((end - start) // step) + 1, dtype=bool)
        times_arr = start + np.arange(len(remote_steps), dtype=np.int64) * step
        for f in overlapping:
            remote_steps |= (times_arr >= f.start_ms) & (times_arr - lookback <= f.end_ms)
        parts: list[ExecPlan] = []
        for is_remote, group in itertools.groupby(
            enumerate(remote_steps), key=lambda kv: bool(kv[1])
        ):
            idx = [i for i, _ in group]
            seg_start = int(times_arr[idx[0]])
            seg_end = int(times_arr[idx[-1]])
            sub = _with_range(plan, seg_start, seg_end)
            if is_remote:
                parts.append(
                    PromQlRemoteExec(self.buddy, to_promql(sub), seg_start, seg_end, step)
                )
            else:
                parts.append(self.local.materialize(sub))
        return parts[0] if len(parts) == 1 else StitchRvsExec(parts)


# ---------------------------------------------------------------------------
# MultiPartitionPlanner (federation)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PartitionAssignment:
    """Which cluster owns a shard-key prefix (reference PartitionLocator)."""

    name: str
    endpoint: str | None  # None = local


class MultiPartitionPlanner:
    """Federates across FiloDB clusters keyed by shard keys (_ws_/_ns_):
    local selectors plan locally, foreign ones become PromQL remote execs
    (reference MultiPartitionPlanner.scala:1445)."""

    def __init__(self, local_planner, locate: Callable[[dict], PartitionAssignment]):
        self.local = local_planner
        self.locate = locate

    def _partition_of(self, plan: L.LogicalPlan) -> set[str]:
        out = set()
        for rs in L.leaf_raw_series(plan):
            keys = {
                f.column: f.value for f in rs.filters if f.op == "=" and f.column in ("_ws_", "_ns_")
            }
            out.add(self.locate(keys).name)
        return out

    def materialize(self, plan: L.LogicalPlan) -> ExecPlan:
        parts = self._partition_of(plan)
        if not parts:
            return self.local.materialize(plan)
        assignments = {}
        for rs in L.leaf_raw_series(plan):
            keys = {f.column: f.value for f in rs.filters if f.op == "="}
            a = self.locate(keys)
            assignments[a.name] = a
        if len(assignments) == 1:
            a = next(iter(assignments.values()))
            if a.endpoint is None:
                return self.local.materialize(plan)
            if a.endpoint.startswith("grpc://"):
                # federation over the binary plan transport (reference
                # MultiPartitionPlanner's gRPC remote exec path)
                from ..api.grpc_exec import GrpcPlanRemoteExec

                return GrpcPlanRemoteExec(a.endpoint, plan)
            times = _plan_range(plan)
            if times is None:
                raise QueryError("cannot remote-execute a plan without a time range")
            start, end, step = times
            return PromQlRemoteExec(a.endpoint, to_promql(plan), start, end, step)
        # cross-partition expression: only joins/set-ops between single-
        # partition subtrees are supported (reference behaves likewise)
        if isinstance(plan, (L.BinaryJoin,)):
            from ..query.exec.joins import SetOperatorExec
            from ..query.exec.plans import ExecPlan as _EP

            lhs = self.materialize(plan.lhs)
            rhs = self.materialize(plan.rhs)
            from ..query.exec.joins import BinaryJoinExec

            if plan.op in ("and", "or", "unless"):
                return SetOperatorExec(lhs, rhs, plan.op, plan.on, plan.ignoring)
            return BinaryJoinExec(
                lhs, rhs, plan.op, plan.cardinality, plan.on, plan.ignoring,
                plan.include, plan.return_bool,
            )
        if isinstance(plan, L.Aggregate):
            from ..query.exec.plans import AggregatePresentExec

            inner = self.materialize(plan.inner)
            return AggregatePresentExec([inner], plan.op, plan.params, plan.by, plan.without)
        raise QueryError("cross-partition query shape not supported")


class ShardKeyRegexPlanner:
    """Expands regex/multi-value shard-key matchers into concrete key
    combinations and fans out (reference ShardKeyRegexPlanner.scala:500)."""

    def __init__(self, inner_planner, shard_key_values: Callable[[str], Sequence[str]],
                 keys: Sequence[str] = ("_ws_", "_ns_")):
        self.inner = inner_planner
        self.values_of = shard_key_values
        self.keys = keys

    def materialize(self, plan: L.LogicalPlan) -> ExecPlan:
        expansions = self._expand(plan)
        if expansions is None:
            return self.inner.materialize(plan)
        plans = [self.inner.materialize(p) for p in expansions]
        if not plans:
            return EmptyResultExec()
        if len(plans) == 1:
            return plans[0]
        if isinstance(plan, L.Aggregate) and plan.op in (
            "sum", "min", "max", "count", "group"
        ):
            from ..query.exec.plans import AggregatePresentExec

            return AggregatePresentExec(plans, plan.op, plan.params, plan.by, plan.without)
        return DistConcatExec(plans)

    def _expand(self, plan: L.LogicalPlan) -> list[L.LogicalPlan] | None:
        leaves = L.leaf_raw_series(plan)
        if not leaves:
            return None
        regex_keys: dict[str, list[str]] = {}
        for rs in leaves:
            for f in rs.filters:
                if f.column in self.keys and f.op in ("=~", "in"):
                    vals = (
                        [v for v in self.values_of(f.column) if f.matches(v)]
                        if f.op == "=~"
                        else list(f.value)
                    )
                    regex_keys[f.column] = vals
        if not regex_keys:
            return None
        combos = [
            dict(zip(regex_keys.keys(), combo))
            for combo in itertools.product(*regex_keys.values())
        ]
        return [_replace_shard_keys(plan, combo) for combo in combos]


class SinglePartitionPlanner:
    """Dispatches to a named planner per dataset/metric (reference
    SinglePartitionPlanner.scala:129)."""

    def __init__(self, planners: dict[str, object], pick: Callable[[L.LogicalPlan], str],
                 default: str):
        self.planners = planners
        self.pick = pick
        self.default = default

    def materialize(self, plan: L.LogicalPlan) -> ExecPlan:
        name = self.pick(plan) or self.default
        return self.planners.get(name, self.planners[self.default]).materialize(plan)


# ---------------------------------------------------------------------------
# plan tree rewrites
# ---------------------------------------------------------------------------


def _plan_range(p: L.LogicalPlan):
    if hasattr(p, "start_ms") and hasattr(p, "end_ms") and hasattr(p, "step_ms"):
        if not isinstance(p, L.RawSeries):
            return p.start_ms, p.end_ms, p.step_ms or 1
    for f in getattr(p, "__dataclass_fields__", {}):
        v = getattr(p, f)
        if isinstance(v, L.LogicalPlan):
            t = _plan_range(v)
            if t is not None:
                return t
    return None


def _max_lookback(p: L.LogicalPlan) -> int:
    out = 0
    if isinstance(p, L.PeriodicSeries):
        out = max(out, p.lookback_ms)
    if isinstance(p, (L.PeriodicSeriesWithWindowing, L.SubqueryWithWindowing)):
        out = max(out, p.window_ms)
    for f in getattr(p, "__dataclass_fields__", {}):
        v = getattr(p, f)
        if isinstance(v, L.LogicalPlan):
            out = max(out, _max_lookback(v))
    return out


def _with_range(p: L.LogicalPlan, start_ms: int, end_ms: int) -> L.LogicalPlan:
    """Rewrite every node's evaluation range (RawSeries windows shift to
    cover the new grid's lookback)."""
    if isinstance(p, L.RawSeries):
        return p  # adjusted by parent
    kw = {}
    for f in p.__dataclass_fields__:
        v = getattr(p, f)
        if isinstance(v, L.RawSeries):
            window = 0
            if isinstance(p, L.PeriodicSeriesWithWindowing):
                window = p.window_ms
            elif isinstance(p, L.PeriodicSeries):
                window = p.lookback_ms
            off = getattr(p, "offset_ms", 0)
            kw[f] = replace(v, start_ms=start_ms - window - off, end_ms=end_ms - off)
        elif isinstance(v, L.LogicalPlan):
            kw[f] = _with_range(v, start_ms, end_ms)
    if hasattr(p, "start_ms") and hasattr(p, "end_ms") and not isinstance(p, L.RawSeries):
        kw["start_ms"] = start_ms
        kw["end_ms"] = end_ms
    return replace(p, **kw) if kw else p


def _replace_shard_keys(p: L.LogicalPlan, combo: dict) -> L.LogicalPlan:
    if isinstance(p, L.RawSeries):
        new_filters = tuple(
            ColumnFilter(f.column, "=", combo[f.column]) if f.column in combo else f
            for f in p.filters
        )
        return replace(p, filters=new_filters)
    kw = {}
    for f in p.__dataclass_fields__:
        v = getattr(p, f)
        if isinstance(v, L.LogicalPlan):
            kw[f] = _replace_shard_keys(v, combo)
    return replace(p, **kw) if kw else p
