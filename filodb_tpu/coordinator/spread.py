"""Spread provider (reference L5: spread-assignment config in
filodb-defaults.conf + SpreadChange/SpreadProvider — per-shard-key spread
overrides so high-volume tenants fan out over more shards than the default).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence


@dataclass(frozen=True)
class SpreadChange:
    """Spread override for an exact shard-key match (e.g. one _ws_/_ns_)."""

    keys: tuple[tuple[str, str], ...]  # ((label, value), ...)
    spread: int


class SpreadProvider:
    def __init__(self, default_spread: int = 3, overrides: Sequence[SpreadChange] = ()):
        self.default_spread = default_spread
        self._overrides = list(overrides)

    @classmethod
    def from_config(cls, cfg: Mapping) -> "SpreadProvider":
        """cfg: {"default": 3, "overrides": [{"keys": {"_ws_": "w", "_ns_": "n"}, "spread": 5}]}"""
        overrides = [
            SpreadChange(tuple(sorted(o["keys"].items())), int(o["spread"]))
            for o in cfg.get("overrides", ())
        ]
        return cls(int(cfg.get("default", 3)), overrides)

    def spread_for(self, tags: Mapping[str, str]) -> int:
        for o in self._overrides:
            if all(tags.get(k) == v for k, v in o.keys):
                return o.spread
        return self.default_spread
