"""Cluster seed bootstrap + membership (reference akka-bootstrapper:
``ClusterSeedDiscovery.scala:13,70`` — a joining node asks configured seeds
for the current members via the ``/__members`` HTTP contract, joins them,
or self-seeds when it is the head of the whitelist and nobody answers).

TPU-repo reframing: there is no Akka cluster to join — membership IS the
peer list the query planners scatter to. So bootstrap resolves straight to
``PlannerParams.peer_endpoints``: a node polls seed URLs (the whitelist
analog; consul/DNS sources would plug in behind ``fetch``), unions the
member lists, advertises itself, and a refresh loop keeps polling members
so joins propagate gossip-style and dead nodes age out of the scatter set
(the failure-detector analog of the reference's retries + health checks).
"""

from __future__ import annotations

import logging
import threading
import time

log = logging.getLogger("filodb_tpu.bootstrap")


class BootstrapError(RuntimeError):
    pass


class MemberRegistry:
    """Known cluster members with last-seen times; thread-safe.

    ``node_id`` is a process-unique identity carried in the /__members
    payload: URL string equality cannot detect a node reaching ITSELF under
    an alias (advertise_url vs 127.0.0.1), which would make it scatter to
    its own shards and double-count — the id comparison can."""

    def __init__(self, self_url: str, prune_after_s: float = 90.0):
        import uuid

        self.self_url = self_url.rstrip("/")
        self.node_id = uuid.uuid4().hex
        self.prune_after_s = prune_after_s
        self._seen: dict[str, float] = {self.self_url: float("inf")}
        self._aliases: set[str] = set()  # other URLs that turned out to be US
        self._lock = threading.Lock()

    def mark_self_alias(self, url: str) -> None:
        """This url answered with OUR node id: it is this node under another
        name. Exclude it from membership forever (hearsay re-mentions are
        ignored too) — scattering to ourselves would double-count shards."""
        url = url.rstrip("/")
        with self._lock:
            self._aliases.add(url)
            self._seen.pop(url, None)

    def _is_self(self, url: str) -> bool:
        return url == self.self_url or url in self._aliases

    def touch(self, urls, now: float | None = None) -> None:
        """DIRECT contact (they answered us, or they reached us): refreshes
        liveness. Hearsay must go through :meth:`learn` instead — otherwise
        nodes keep re-reporting a dead member to each other and it never
        ages out."""
        now = time.time() if now is None else now
        with self._lock:
            for u in urls:
                u = str(u).rstrip("/")
                if u and u not in self._aliases:
                    self._seen[u] = max(self._seen.get(u, 0.0), now)

    def learn(self, urls, now: float | None = None) -> list[str]:
        """Indirect mention: adds unknown members (so we start polling them)
        without refreshing known ones. Returns newly-learned members."""
        now = time.time() if now is None else now
        new = []
        with self._lock:
            for u in urls:
                u = str(u).rstrip("/")
                if u and u not in self._seen and u not in self._aliases:
                    new.append(u)
                    self._seen[u] = now
        return new

    def prune(self, now: float | None = None) -> list[str]:
        """Drop members not seen within the window; returns the dropped."""
        now = time.time() if now is None else now
        with self._lock:
            dead = [u for u, ts in self._seen.items()
                    if now - ts > self.prune_after_s]
            for u in dead:
                del self._seen[u]
        return dead

    def members(self) -> list[str]:
        with self._lock:
            return sorted(self._seen)

    def peers(self) -> tuple[str, ...]:
        """Everyone but self — the planner scatter set."""
        return tuple(u for u in self.members() if u != self.self_url)

    def snapshot(self) -> dict:
        """The /__members payload."""
        return {"self": self.self_url, "id": self.node_id,
                "members": self.members()}


class SeedBootstrapper:
    """Join (or found) a cluster from a static seed list."""

    def __init__(self, registry: MemberRegistry, seeds, auth_token: str | None = None,
                 fetch=None, on_change=None, poll_timeout_s: float = 5.0):
        self.registry = registry
        self.seeds = [s.rstrip("/") for s in seeds]
        self.auth_token = auth_token
        if fetch is None:
            from .planners import fetch_json

            fetch = fetch_json
        self._fetch = fetch  # url -> decoded /__members "data" payload
        self.on_change = on_change  # called with registry.peers() on change
        # short per-member timeout: a blackholed member must not stall the
        # refresh loop past the prune window
        self.poll_timeout_s = poll_timeout_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- discovery --------------------------------------------------------

    def _poll(self, urls) -> tuple[list[str], list[str]]:
        """Announce ourselves to each url (concurrently, short timeout) and
        collect its member list — the one-RTT join: the peer learns us from
        the POST body, we learn the cluster from the response. An answer
        carrying OUR node id is ourselves under an alias and is dropped
        (and remembered, so we never poll that alias again).
        Returns (responders, mentioned)."""
        from concurrent.futures import ThreadPoolExecutor

        targets = [u for u in urls if not self.registry._is_self(u)]

        def ask(u):
            try:
                return u, self._fetch(
                    f"{u}/__members", auth_token=self.auth_token,
                    data={"url": self.registry.self_url,
                          "id": self.registry.node_id},
                    timeout=self.poll_timeout_s,
                )
            except Exception as e:  # noqa: BLE001 — unreachable seed is normal
                log.debug("seed %s unreachable: %s", u, e)
                return u, None

        responders: list[str] = []
        mentioned: list[str] = []
        if not targets:
            return responders, mentioned
        with ThreadPoolExecutor(max_workers=min(8, len(targets)),
                                thread_name_prefix="filodb-seed") as pool:
            for u, data in pool.map(ask, targets):
                if data is None:
                    continue
                if data.get("id") == self.registry.node_id:
                    log.warning("seed %s is this node under an alias; ignoring", u)
                    self.registry.mark_self_alias(u)
                    continue
                responders.append(u)
                mentioned.extend(data.get("members", ()))
        return responders, mentioned

    def _absorb(self, responders, mentioned) -> None:
        before = self.registry.peers()
        self.registry.touch(responders)
        self.registry.learn(mentioned)
        self.registry.prune()
        after = self.registry.peers()
        if after != before and self.on_change:
            self.on_change(after)

    def bootstrap(self, retries: int = 5, backoff_s: float = 1.0) -> list[str]:
        """Reference join flow: poll seeds; join whoever answers. When nobody
        answers and we are the HEAD of the seed list, found a new cluster
        (self-seed); otherwise retry — a non-head node must not split-brain
        a fresh cluster into existence (ClusterSeedDiscovery:70)."""
        for attempt in range(max(1, retries)):
            responders, mentioned = self._poll(self.seeds)
            if responders:
                self._absorb(responders, mentioned)
                return self.registry.members()
            head = self.seeds[0] if self.seeds else self.registry.self_url
            if self.registry._is_self(head):
                log.info("self-seeding new cluster as %s", head)
                return self.registry.members()
            if attempt < retries - 1:
                self._stop.wait(backoff_s * (attempt + 1))
        raise BootstrapError(
            f"no seed answered after {retries} attempts: {self.seeds}"
        )

    # -- refresh loop ------------------------------------------------------

    def refresh_once(self) -> None:
        """Poll every known member AND the configured seeds (gossip-style:
        joins propagate without every node listing every seed; re-polling
        seeds lets a node that bootstrapped alone — or whose whole peer set
        was pruned during a rolling restart — rejoin when seeds return),
        absorb answers, prune the dead."""
        targets = list(dict.fromkeys(self.registry.members() + self.seeds))
        responders, mentioned = self._poll(targets)
        self._absorb(responders, mentioned)

    def start(self, interval_s: float = 30.0) -> None:
        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.refresh_once()
                except Exception:  # noqa: BLE001
                    log.exception("membership refresh failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="filodb-bootstrap")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
