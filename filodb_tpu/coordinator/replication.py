"""Replicated shard plane: ingest fan-out, lag watermarks, replica routing,
and live rebalancing with state handoff (doc/robustness.md "Replicated shard
plane"; reference FiloDB's peer-to-peer ingestion replication + Tailwind's
explicit dispatch-boundary dataflow from PAPERS.md).

The ReplicationPlane owns the data motion the ShardManager only *maps*:

- ``append`` splits a batch by shard and fans each sub-batch to every live
  replica, tracking per-replica acks (sequence numbers against a retained
  per-shard append log) and a lag watermark (max acked sample timestamp) so
  a recovering replica serves only behind its watermark.
- ``set_node_down`` / ``recover`` drive the membership events and replay the
  retained log tail to catch a returning replica up.
- ``rebalance`` moves a shard by rebuild-on-arrival: replay the retained log
  into the new owner, then use the source shard's effect log
  (``ingest_effects_interval_since``) to PROVE nothing landed on the old
  owner mid-copy (reason None = clean cutover; "overlap" = replay the tail
  and re-check; "full_clear"/"log_truncated" = full rebuild). Standing
  queries homed on the shard re-register on the new owner so delta refreshes
  resume within one align bucket.

The ReplicaRouter is the query-side view: per shard it offers the live
replica endpoints primary-first (rotated per shard to spread load), filtered
by watermark, grouped into dispatch legs the planner turns into one remote
exec per distinct endpoint set. Failover between a leg's candidates lives in
query/faults.dispatch_child — a breaker-open or endpoint-failure signal
re-pins to the next sibling before allow_partial_results is even considered.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Sequence

from ..core.records import RecordBatch
from ..core.schemas import Dataset
from ..metrics import (
    record_rebalance,
    record_rebalance_standing_move,
    record_replica_ack,
    record_replica_watermark,
)
from .cluster import QUERYABLE, ShardManager, ShardStatus

# replica statuses that receive live appends: queryable ones plus freshly
# ASSIGNED followers (they must not fall behind while warming up)
_APPENDABLE = QUERYABLE | {ShardStatus.ASSIGNED}


@dataclass
class NodeHandle:
    """One data node the plane can reach: its memstore (in-process) and the
    gRPC endpoint queries dial, plus an optional StandingEngine."""

    name: str
    memstore: object
    endpoint: str | None = None
    standing: object = None
    alive: bool = True


@dataclass
class StandingSpec:
    """A standing query homed on a shard — enough to re-register it on a new
    owner after a rebalance."""

    promql: str
    step_ms: int
    shard: int
    kwargs: dict = field(default_factory=dict)
    owner: str | None = None
    qid: str | None = None


class ReplicationPlane:
    def __init__(self, manager: ShardManager, dataset: str = "prometheus",
                 spread: int = 2, retain: int = 1024):
        self.manager = manager
        self.mapper = manager.mapper
        self.dataset = dataset
        self.spread = spread
        self.nodes: dict[str, NodeHandle] = {}
        # per-shard retained append log [(seq, sub_batch)] — the replay
        # source for recovery and rebuild-on-arrival
        self._log: dict[int, deque] = {
            s: deque(maxlen=retain) for s in range(self.mapper.num_shards)
        }
        self._seq: dict[int, int] = {s: 0 for s in range(self.mapper.num_shards)}
        self._acks: dict[tuple[int, str], int] = {}
        self._watermarks: dict[tuple[int, str], int] = {}
        self._standing: list[StandingSpec] = []

    # -- membership -------------------------------------------------------

    def add_node(self, name: str, memstore, endpoint: str | None = None,
                 standing=None) -> NodeHandle:
        h = NodeHandle(name, memstore, endpoint, standing)
        self.nodes[name] = h
        return h

    def endpoint_of(self, node: str) -> str | None:
        h = self.nodes.get(node)
        return h.endpoint if h else None

    def set_node_down(self, name: str) -> None:
        """Node failure: mark the handle dead and let the manager promote
        live followers / reassign shards with no survivor."""
        h = self.nodes.get(name)
        if h is not None:
            h.alive = False
        if name in self.manager.nodes:
            self.manager.node_left(name)

    def recover(self, name: str) -> list[int]:
        """Node return: rejoin, replay the retained log tail past each
        replica's last ack, and flip replicas ACTIVE once caught up.
        Returns the shards replayed."""
        h = self.nodes[name]
        h.alive = True
        self.manager.node_joined(name)
        caught_up = []
        for s in self.mapper.replica_shards_of_node(name):
            self.mapper.set_replica(s, name, ShardStatus.RECOVERY)
            self._replay(s, name, since_seq=self._acks.get((s, name), 0))
            self.mapper.set_replica(s, name, ShardStatus.ACTIVE)
            caught_up.append(s)
        return caught_up

    # -- ingest fan-out ---------------------------------------------------

    def append(self, batch: RecordBatch) -> dict[int, list[str]]:
        """Fan a batch out to all live replicas of each destination shard.
        Returns {shard: [nodes acked]}."""
        options = None
        for h in self.nodes.values():
            try:
                options = h.memstore.dataset(self.dataset).options
                break
            except KeyError:
                continue
        acked: dict[int, list[str]] = {}
        split = batch.shard_split(
            self.spread, self.mapper.num_shards, options
        )
        for snum, sub in split.items():
            seq = self._seq[snum] + 1
            self._seq[snum] = seq
            self._log[snum].append((seq, sub))
            acked[snum] = []
            for node, status in self.mapper.replicas_of(snum).items():
                h = self.nodes.get(node)
                if h is None or not h.alive or status not in _APPENDABLE:
                    record_replica_ack("skipped")
                    continue
                try:
                    self._ensure_shard(h, snum)
                    h.memstore.ingest(self.dataset, snum, sub)
                except Exception:
                    record_replica_ack("error")
                    self.mapper.set_replica(snum, node, ShardStatus.ERROR)
                    continue
                self._ack(snum, node, seq, sub)
                acked[snum].append(node)
        return acked

    def _ack(self, shard: int, node: str, seq: int, sub: RecordBatch) -> None:
        self._acks[(shard, node)] = max(self._acks.get((shard, node), 0), seq)
        if len(sub):
            wm = max(
                self._watermarks.get((shard, node), 0),
                int(sub.timestamps.max()),
            )
            self._watermarks[(shard, node)] = wm
            record_replica_watermark(shard, node, wm)
        record_replica_ack("ok")

    def lag_watermark(self, shard: int, node: str) -> int:
        """Max sample timestamp (ms) the replica has acked; 0 = nothing."""
        return self._watermarks.get((shard, node), 0)

    def _ensure_shard(self, h: NodeHandle, snum: int) -> None:
        try:
            owned = h.memstore.shard_nums(self.dataset)
        except KeyError:
            owned = []
        if snum not in owned:
            h.memstore.setup(
                Dataset(self.dataset), [snum],
                total_shards=self.mapper.num_shards,
            )

    def _replay(self, shard: int, node: str, since_seq: int = 0) -> int:
        """Replay retained log entries with seq > since_seq into a node.
        Returns the number of entries replayed."""
        h = self.nodes[node]
        n = 0
        for seq, sub in list(self._log[shard]):
            if seq <= since_seq:
                continue
            self._ensure_shard(h, shard)
            h.memstore.ingest(self.dataset, shard, sub)
            self._ack(shard, node, seq, sub)
            n += 1
        return n

    # -- standing queries -------------------------------------------------

    def register_standing(self, promql: str, step_ms: int, shard: int,
                          **kwargs) -> StandingSpec:
        """Register a standing query homed on a shard — it lives on the
        shard's current primary and follows the shard across rebalances."""
        spec = StandingSpec(promql, step_ms, shard, dict(kwargs))
        self._register_on(spec, self.mapper.node_of(shard))
        self._standing.append(spec)
        return spec

    def _register_on(self, spec: StandingSpec, node: str | None) -> None:
        h = self.nodes.get(node) if node else None
        if h is None or h.standing is None:
            spec.owner, spec.qid = node, None
            return
        sq = h.standing.register(spec.promql, spec.step_ms, **spec.kwargs)
        spec.owner, spec.qid = node, sq.qid

    def standing_query(self, spec: StandingSpec):
        """The live StandingQuery object behind a spec, on its current
        owner's StandingEngine (None when the owner has none)."""
        h = self.nodes.get(spec.owner) if spec.owner else None
        if h is None or h.standing is None or not spec.qid:
            return None
        return h.standing.registry.get(spec.qid)

    def standing_specs(self, shard: int | None = None) -> list[StandingSpec]:
        if shard is None:
            return list(self._standing)
        return [sp for sp in self._standing if sp.shard == shard]

    # -- live rebalancing -------------------------------------------------

    def rebalance(self, shard: int, to_node: str) -> str:
        """Move a shard's primary to ``to_node`` by rebuild-on-arrival.
        Returns the cutover outcome: clean | replayed | rebuilt | damped |
        failed (also counted in filodb_rebalance{outcome})."""
        src_name = self.mapper.node_of(shard)
        src = self.nodes.get(src_name) if src_name else None
        src_shard = None
        if src is not None:
            try:
                src_shard = src.memstore.shard(self.dataset, shard)
            except KeyError:
                src_shard = None
        v0 = src_shard.version if src_shard is not None else None
        seq0 = self._seq[shard]

        if not self.manager.rebalance(shard, to_node):
            record_rebalance("damped")
            return "damped"

        dst = self.nodes.get(to_node)
        if dst is None:
            record_rebalance("failed")
            return "failed"
        # rebuild-on-arrival: full retained-log replay into the new owner
        # (idempotent for a node that already held a follower replica only
        # in the sense that the memstore dedupes per-series timestamps;
        # a fresh owner rebuilds from scratch)
        self._replay(shard, to_node, since_seq=self._acks.get((shard, to_node), 0))

        outcome = "clean"
        if src_shard is not None and v0 is not None:
            # effect-log cutover proof: did ANY ingest land on the source
            # after we snapshotted? (doc/robustness.md effect-log taxonomy)
            for _ in range(3):
                reason, _lo, _hi = src_shard.ingest_effects_interval_since(
                    v0, 0, 1 << 62
                )
                if reason is None:
                    break
                if reason == "overlap":
                    # a tail landed on the source mid-copy: replay the tail
                    # (it is in the retained log) and re-check
                    v0 = src_shard.version
                    self._replay(shard, to_node, since_seq=seq0)
                    seq0 = self._seq[shard]
                    outcome = "replayed"
                else:  # full_clear | log_truncated — no interval proof left
                    v0 = src_shard.version
                    self._replay(shard, to_node, since_seq=0)
                    outcome = "rebuilt"
        self.manager.shard_active(shard)
        record_rebalance(outcome)

        # standing queries follow the shard: unregister on the old owner,
        # re-register on the new one so delta refreshes resume there
        for spec in self.standing_specs(shard):
            old = self.nodes.get(spec.owner) if spec.owner else None
            if old is not None and old.standing is not None and spec.qid:
                try:
                    old.standing.unregister(spec.qid, reason="rebalanced")
                except Exception:
                    pass
            self._register_on(spec, to_node)
            record_rebalance_standing_move()
        return outcome

    # -- observability ----------------------------------------------------

    def snapshot(self) -> dict:
        """Cluster + replication state for GET /debug/cluster."""
        snap = self.manager.snapshot()
        for row in snap["shards"]:
            s = row["shard"]
            row["watermarks_ms"] = {
                n: self.lag_watermark(s, n) for n in row["replicas"]
            }
            row["log_seq"] = self._seq[s]
            row["acks"] = {
                n: self._acks.get((s, n), 0) for n in row["replicas"]
            }
        snap["nodes"] = [
            {
                "name": h.name,
                "endpoint": h.endpoint,
                "alive": h.alive,
                "standing": h.standing is not None,
            }
            for h in self.nodes.values()
        ] or snap["nodes"]
        snap["standing"] = [
            {"shard": sp.shard, "promql": sp.promql, "owner": sp.owner}
            for sp in self._standing
        ]
        return snap


class ReplicaRouter:
    """Query-side replica selection: shards -> dispatch legs.

    A *leg* is (shards, endpoints): one remote exec covering ``shards`` on
    ``endpoints[0]``, with ``endpoints[1:]`` the sibling replicas the
    dispatch layer may fail over to. Candidates are live replicas
    primary-first, rotated per shard to spread read load, and a RECOVERY
    replica is excluded for queries ending past its lag watermark."""

    def __init__(self, plane: ReplicationPlane, local_node: str | None = None):
        self.plane = plane
        self.local_node = local_node

    def candidates(self, shard: int, end_ms: int | None = None) -> list[str]:
        """Live replica endpoints for one shard, primary first, watermark-
        filtered, rotated by shard index."""
        mapper = self.plane.mapper
        out = []
        for node, status in mapper.replicas_of(shard).items():
            if status not in QUERYABLE:
                continue
            h = self.plane.nodes.get(node)
            if h is None or not h.alive or h.endpoint is None:
                continue
            if (status is ShardStatus.RECOVERY and end_ms is not None
                    and self.plane.lag_watermark(shard, node) < end_ms):
                continue
            out.append(h.endpoint)
        if len(out) > 1:
            k = shard % len(out)
            out = out[k:] + out[:k]
        return out

    def legs(self, shards: Sequence[int] | None = None,
             end_ms: int | None = None) -> list[tuple[tuple, tuple]]:
        """One dispatch leg PER SHARD, in shard order. Per-shard legs keep
        the merge tree's structure identical across failovers: re-pinning a
        leg to a sibling swaps only the endpoint, never the partial-merge
        grouping, so a failed-over query is bit-equal to the pre-kill one
        (replicas hold identical fan-out data; float reduction order is a
        function of tree structure). ``shards`` defaults to every shard."""
        if shards is None:
            shards = range(self.plane.mapper.num_shards)
        legs = []
        for s in shards:
            cands = tuple(self.candidates(s, end_ms))
            if not cands:
                continue
            legs.append(((s,), cands))
        return legs
