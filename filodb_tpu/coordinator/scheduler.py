"""Bounded shared query scheduler (reference QueryScheduler.scala:29-73 —
one instrumented ForkJoinPool shared by all query execution, sized to the
host, so N concurrent queries cannot each grab the device/compile pipeline
at once).

Semantics:
- at most ``parallelism`` queries execute concurrently; up to ``max_queued``
  more wait for a slot;
- beyond that, submission fails fast with :class:`QueryRejected` (the HTTP
  edge maps it to 503, matching Prometheus' overload behavior);
- a query whose caller stops waiting (deadline) keeps its worker only until
  the next ``ctx.check_deadline()`` between plan nodes, then aborts — device
  work in flight cannot be interrupted, exactly the reference's cooperative
  cancellation model.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout

from ..metrics import REGISTRY
from ..query.exec.transformers import QueryDeadlineExceeded, QueryError


class QueryRejected(QueryError):
    """Admission control: pool and queue are full."""


class SingleFlight:
    """Coalesce concurrent IDENTICAL queries into one execution.

    Dashboards fan the same panel query out N times within milliseconds;
    without coalescing each copy pays its own staging lookup + kernel
    launch + render. The first arrival for a key becomes the leader and
    executes; followers that arrive while it runs share its result (and its
    exception). In-flight only — nothing is cached after completion, so a
    shared answer is exactly as fresh as the followers' own execution would
    have been. Compatible-query batching beyond exact identity happens
    below this layer: the mesh stage cache shares staged blocks and window
    matrices across queries that differ only in function/aggregation.

    Caveat: a follower whose deadline exceeds the leader's inherits the
    leader's deadline failure; identical queries almost always carry
    identical deadlines (same dashboard), so this trade is taken for the
    16x fan-out win."""

    def __init__(self):
        self._lock = threading.Lock()
        self._flights: dict = {}

    def run(self, key, fn, timeout_s: float):
        from concurrent.futures import Future

        with self._lock:
            fut = self._flights.get(key)
            leader = fut is None
            if leader:
                fut = Future()
                self._flights[key] = fut
        if not leader:
            REGISTRY.counter("filodb_queries_coalesced").inc()
            try:
                return fut.result(timeout=timeout_s)
            except FutureTimeout:
                REGISTRY.counter("filodb_queries_deadline_exceeded").inc()
                raise QueryDeadlineExceeded(
                    f"query exceeded deadline: {timeout_s:.1f}s (coalesced)"
                ) from None
        try:
            result = fn()
        except BaseException as e:
            with self._lock:
                self._flights.pop(key, None)
            fut.set_exception(e)
            raise
        # deregister BEFORE resolving: an arrival after completion must run
        # its own flight (sharing is for concurrent queries, never a cache)
        with self._lock:
            self._flights.pop(key, None)
        fut.set_result(result)
        return result


class QueryScheduler:
    def __init__(self, parallelism: int | None = None, max_queued: int = 64):
        self.parallelism = parallelism or min(8, os.cpu_count() or 4)
        self.max_queued = max_queued
        self._pool = ThreadPoolExecutor(
            max_workers=self.parallelism, thread_name_prefix="filodb-query"
        )
        # slots = running + queued; acquired non-blocking at submission
        self._slots = threading.BoundedSemaphore(self.parallelism + max_queued)
        self._in_flight = 0
        self.peak_in_flight = 0
        self._lock = threading.Lock()

    @property
    def in_flight(self) -> int:
        return self._in_flight

    def run(self, fn, deadline_s: float):
        """Run ``fn()`` on the shared pool; wait at most ``deadline_s``.
        Raises QueryRejected when saturated, QueryError on deadline."""
        if not self._slots.acquire(blocking=False):
            REGISTRY.counter("filodb_queries_rejected").inc()
            raise QueryRejected(
                f"query rejected: {self.parallelism} running + {self.max_queued} queued"
            )

        def _job():
            with self._lock:
                self._in_flight += 1
                self.peak_in_flight = max(self.peak_in_flight, self._in_flight)
            try:
                return fn()
            finally:
                with self._lock:
                    self._in_flight -= 1
                self._slots.release()

        fut = self._pool.submit(_job)
        try:
            return fut.result(timeout=deadline_s)
        except FutureTimeout:
            # the worker aborts at its next check_deadline(); stop waiting now
            if fut.cancel():
                # never started: _job's finally will not run — free the slot
                self._slots.release()
            REGISTRY.counter("filodb_queries_deadline_exceeded").inc()
            raise QueryDeadlineExceeded(
                f"query exceeded deadline: {deadline_s:.1f}s"
            ) from None

    def shutdown(self):
        self._pool.shutdown(wait=False, cancel_futures=True)
