"""Bounded shared query scheduler (reference QueryScheduler.scala:29-73 —
one instrumented ForkJoinPool shared by all query execution, sized to the
host, so N concurrent queries cannot each grab the device/compile pipeline
at once).

Semantics:
- at most ``parallelism`` queries execute concurrently; up to ``max_queued``
  more wait for a slot;
- beyond that, submission fails fast with :class:`QueryRejected` (the HTTP
  edge maps it to 503, matching Prometheus' overload behavior);
- a query whose caller stops waiting (deadline) keeps its worker only until
  the next ``ctx.check_deadline()`` between plan nodes, then aborts — device
  work in flight cannot be interrupted, exactly the reference's cooperative
  cancellation model.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout

from ..metrics import REGISTRY
from ..query.exec.transformers import QueryDeadlineExceeded, QueryError


class QueryRejected(QueryError):
    """Admission control: pool and queue are full."""


class QueryScheduler:
    def __init__(self, parallelism: int | None = None, max_queued: int = 64):
        self.parallelism = parallelism or min(8, os.cpu_count() or 4)
        self.max_queued = max_queued
        self._pool = ThreadPoolExecutor(
            max_workers=self.parallelism, thread_name_prefix="filodb-query"
        )
        # slots = running + queued; acquired non-blocking at submission
        self._slots = threading.BoundedSemaphore(self.parallelism + max_queued)
        self._in_flight = 0
        self.peak_in_flight = 0
        self._lock = threading.Lock()

    @property
    def in_flight(self) -> int:
        return self._in_flight

    def run(self, fn, deadline_s: float):
        """Run ``fn()`` on the shared pool; wait at most ``deadline_s``.
        Raises QueryRejected when saturated, QueryError on deadline."""
        if not self._slots.acquire(blocking=False):
            REGISTRY.counter("filodb_queries_rejected").inc()
            raise QueryRejected(
                f"query rejected: {self.parallelism} running + {self.max_queued} queued"
            )

        def _job():
            with self._lock:
                self._in_flight += 1
                self.peak_in_flight = max(self.peak_in_flight, self._in_flight)
            try:
                return fn()
            finally:
                with self._lock:
                    self._in_flight -= 1
                self._slots.release()

        fut = self._pool.submit(_job)
        try:
            return fut.result(timeout=deadline_s)
        except FutureTimeout:
            # the worker aborts at its next check_deadline(); stop waiting now
            if fut.cancel():
                # never started: _job's finally will not run — free the slot
                self._slots.release()
            REGISTRY.counter("filodb_queries_deadline_exceeded").inc()
            raise QueryDeadlineExceeded(
                f"query exceeded deadline: {deadline_s:.1f}s"
            ) from None

    def shutdown(self):
        self._pool.shutdown(wait=False, cancel_futures=True)
