"""Cluster shard coordination (reference L5: ShardMapper.scala,
ShardManager.scala, ShardAssignmentStrategy.scala:265, ShardStatus.scala ADT,
v2 FiloDbClusterDiscovery.scala:6 ordinal assignment + peer health checks,
doc/sharding.md:157-189 auto-reassignment with 2h damper).

Single-process-friendly: nodes are logical endpoints; the event-driven state
machine (status transitions, subscriptions, reassignment policy) matches the
reference so a networked control plane can drive it later.

Replication (doc/robustness.md "Replicated shard plane"): each shard may have
R replicas — one primary (the legacy node_of/shards_of_node view, unchanged)
plus followers, each with its own ShardStatus. Placement keeps replicas on
distinct nodes; node_left promotes a live follower instead of unassigning.
"""

from __future__ import annotations

import enum
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Sequence


class ShardStatus(enum.Enum):
    UNASSIGNED = "unassigned"
    ASSIGNED = "assigned"
    RECOVERY = "recovery"
    ACTIVE = "active"
    ERROR = "error"
    DOWN = "down"
    STOPPED = "stopped"


QUERYABLE = {ShardStatus.ACTIVE, ShardStatus.RECOVERY}


@dataclass
class ShardEvent:
    shard: int
    status: ShardStatus
    node: str | None
    ts: float = field(default_factory=time.time)


class ShardMapper:
    """shard -> (node, status) map + query routing (reference
    ShardMapper.scala: status tracking, activeShards, queryShards).

    The primary view (node_of/status_of/shards_of_node) is unchanged from the
    single-replica days; replicas_of exposes the full ordered replica set
    (primary first) with a per-replica status.
    """

    def __init__(self, num_shards: int):
        self.num_shards = num_shards
        self._node: list[str | None] = [None] * num_shards
        self._status: list[ShardStatus] = [ShardStatus.UNASSIGNED] * num_shards
        # per-shard ordered replica map {node: status}; first key is the
        # primary and mirrors _node/_status exactly (dicts keep insertion
        # order, so "first key" is well-defined)
        self._replicas: list[dict[str, ShardStatus]] = [
            {} for _ in range(num_shards)
        ]
        self._subscribers: list[Callable[[ShardEvent], None]] = []

    def subscribe(self, fn: Callable[[ShardEvent], None]) -> None:
        self._subscribers.append(fn)

    def update(self, shard: int, status: ShardStatus, node: str | None = None) -> None:
        self._status[shard] = status
        if node is not None or status in (ShardStatus.UNASSIGNED, ShardStatus.DOWN):
            old = self._node[shard]
            self._node[shard] = node
            if node != old:
                # primary moved (or cleared): rebuild the replica map with
                # the new primary in front, keeping surviving followers
                rest = {
                    n: st for n, st in self._replicas[shard].items()
                    if n not in (old, node)
                }
                if node is None:
                    self._replicas[shard] = rest
                else:
                    self._replicas[shard] = {node: status, **rest}
        primary = self._node[shard]
        if primary is not None:
            self._replicas[shard][primary] = status
        ev = ShardEvent(shard, status, primary)
        for fn in self._subscribers:
            fn(ev)

    def node_of(self, shard: int) -> str | None:
        return self._node[shard]

    def status_of(self, shard: int) -> ShardStatus:
        return self._status[shard]

    def active_shards(self) -> list[int]:
        return [s for s in range(self.num_shards) if self._status[s] in QUERYABLE]

    def shards_of_node(self, node: str) -> list[int]:
        return [s for s in range(self.num_shards) if self._node[s] == node]

    def unassigned(self) -> list[int]:
        return [s for s in range(self.num_shards) if self._status[s] == ShardStatus.UNASSIGNED]

    # -- replicas ---------------------------------------------------------

    def set_replica(self, shard: int, node: str, status: ShardStatus) -> None:
        """Add or update one replica. When the node is (or becomes) the
        primary this delegates to update() so the legacy view and the
        subscriber stream stay the single source of truth."""
        primary = self._node[shard]
        if primary is None or primary == node:
            self.update(shard, status, node)
            return
        self._replicas[shard][node] = status
        ev = ShardEvent(shard, status, node)
        for fn in self._subscribers:
            fn(ev)

    def remove_replica(self, shard: int, node: str) -> None:
        """Drop a replica; a removed primary promotes the first live
        follower (RECOVERY if it was not already queryable)."""
        reps = self._replicas[shard]
        if node not in reps:
            return
        if self._node[shard] != node:
            del reps[node]
            return
        # primary removal: promote the first surviving follower
        del reps[node]
        for cand, st in reps.items():
            promoted = st if st in QUERYABLE else ShardStatus.RECOVERY
            self.update(shard, promoted, cand)
            return
        self.update(shard, ShardStatus.UNASSIGNED, None)

    def promote(self, shard: int, node: str) -> None:
        """Make an existing follower the primary (status carries over)."""
        reps = self._replicas[shard]
        if node not in reps or self._node[shard] == node:
            return
        self.update(shard, reps[node], node)

    def replicas_of(self, shard: int) -> dict[str, ShardStatus]:
        """Ordered {node: status}, primary first (copy)."""
        return dict(self._replicas[shard])

    def nodes_of(self, shard: int) -> list[str]:
        """Replica nodes, primary first."""
        return list(self._replicas[shard])

    def live_replicas(self, shard: int) -> list[str]:
        """Replica nodes currently queryable, primary first."""
        return [n for n, st in self._replicas[shard].items() if st in QUERYABLE]

    def replica_status_of(self, shard: int, node: str) -> ShardStatus | None:
        return self._replicas[shard].get(node)

    def replica_shards_of_node(self, node: str) -> list[int]:
        """Shards holding ANY replica (primary or follower) on the node."""
        return [s for s in range(self.num_shards) if node in self._replicas[s]]

    def query_shards(self, shard_key_hash: int | None = None, spread: int | None = None) -> list[int]:
        """Shards a query must touch; with a shard-key hash + spread the set
        prunes to the 2^spread shards that key maps to (reference
        queryShardsFromShardKey)."""
        if shard_key_hash is None or spread is None:
            return self.active_shards()
        from ..core.schemas import shard_group

        cands = shard_group(shard_key_hash, spread, self.num_shards)
        return sorted(s for s in cands if self._status[s] in QUERYABLE)


class ShardAssignmentStrategy:
    """Even spread of shards over nodes respecting capacity (reference
    DefaultShardAssignmentStrategy)."""

    def assign(self, mapper: ShardMapper, nodes: Sequence[str], shards_per_node: int):
        out: dict[str, list[int]] = {n: [] for n in nodes}
        load = {n: len(mapper.shards_of_node(n)) for n in nodes}
        for s in mapper.unassigned():
            node = min(nodes, key=lambda n: load[n]) if nodes else None
            if node is None or load[node] >= shards_per_node:
                continue
            out[node].append(s)
            load[node] += 1
        return out

    def place_replicas(self, mapper: ShardMapper, nodes: Sequence[str],
                       shards_per_node: int, num_replicas: int):
        """Follower placement: for each shard with a primary but fewer than
        num_replicas replicas, pick the least-loaded nodes NOT already
        holding a replica of it (replicas land on distinct nodes, always).
        Follower capacity counts against the same shards_per_node budget.
        Returns {node: [shards]} of new follower placements."""
        out: dict[str, list[int]] = {n: [] for n in nodes}
        load = {n: len(mapper.replica_shards_of_node(n)) for n in nodes}
        for s in range(mapper.num_shards):
            have = mapper.nodes_of(s)
            if not have:
                continue  # no primary yet — assign() owns that
            need = num_replicas - len(have)
            for _ in range(max(0, need)):
                cands = [n for n in nodes
                         if n not in have and load[n] < shards_per_node]
                if not cands:
                    break
                node = min(cands, key=lambda n: load[n])
                out[node].append(s)
                have.append(node)
                load[node] += 1
        return out


class ShardManager:
    """Cluster-singleton shard coordinator: node join/leave, ingestion-error
    reassignment with a damper window (reference ShardManager.scala +
    doc/sharding.md: a shard reassigned within the damper period is marked
    DOWN instead of bounced again)."""

    def __init__(self, num_shards: int, shards_per_node: int,
                 reassignment_damper_s: float = 7200.0,
                 clock: Callable[[], float] = time.time,
                 num_replicas: int = 1):
        self.mapper = ShardMapper(num_shards)
        self.strategy = ShardAssignmentStrategy()
        self.shards_per_node = shards_per_node
        self.damper_s = reassignment_damper_s
        self.num_replicas = max(1, num_replicas)
        self._clock = clock  # injectable for deterministic chaos tests
        self.nodes: list[str] = []
        self._last_reassign: dict[int, float] = {}
        # ring of recent placement decisions for /debug/cluster
        self.recent: deque = deque(maxlen=64)

    def damper_active(self, shard: int) -> bool:
        """True while a recent reassignment suppresses another bounce."""
        last = self._last_reassign.get(shard)
        return last is not None and self._clock() - last < self.damper_s

    def _note(self, shard: int, node: str | None, event: str) -> None:
        self.recent.append(
            {"shard": shard, "node": node, "event": event, "ts": self._clock()}
        )

    # -- membership -------------------------------------------------------

    def node_joined(self, node: str) -> list[int]:
        if node not in self.nodes:
            self.nodes.append(node)
        assigned = self.strategy.assign(self.mapper, [node], self.shards_per_node)[node]
        for s in assigned:
            self.mapper.update(s, ShardStatus.ASSIGNED, node)
            self._note(s, node, "assigned")
        if self.num_replicas > 1:
            self._place_followers()
        return assigned

    def _place_followers(self) -> None:
        placed = self.strategy.place_replicas(
            self.mapper, self.nodes, self.shards_per_node, self.num_replicas
        )
        for node, got in placed.items():
            for s in got:
                self.mapper.set_replica(s, node, ShardStatus.ASSIGNED)
                self._note(s, node, "follower")

    def node_left(self, node: str) -> list[int]:
        self.nodes = [n for n in self.nodes if n != node]
        primaried = self.mapper.shards_of_node(node)
        # strip the dead node's follower entries first so promotion and
        # attribution never point at it (satellite: stale-node attribution)
        for s in self.mapper.replica_shards_of_node(node):
            if s not in primaried:
                self.mapper.remove_replica(s, node)
        lost: list[int] = []
        for s in primaried:
            survivors = [n for n in self.mapper.nodes_of(s) if n != node]
            if survivors:
                # promote a live follower in place — no reassignment churn,
                # no damper interaction (reference: replica failover)
                self.mapper.remove_replica(s, node)
                self._note(s, self.mapper.node_of(s), "promoted")
            else:
                self.mapper.update(s, ShardStatus.UNASSIGNED, None)
                lost.append(s)
        moved = self._reassign(lost)
        if self.num_replicas > 1 and self.nodes:
            self._place_followers()
        return primaried

    def _reassign(self, shards: Sequence[int]) -> list[int]:
        from ..metrics import record_shard_reassignment

        moved = []
        now = self._clock()
        eligible = []
        for s in shards:
            # a shard never reassigned before is infinitely old — the damper
            # only suppresses REPEAT bounces (clocks may start near zero)
            last = self._last_reassign.get(s)
            if last is not None and now - last < self.damper_s:
                # bounced too recently -> stop flapping (reference damper)
                self.mapper.update(s, ShardStatus.DOWN, None)
                record_shard_reassignment(s, damped=True)
                self._note(s, None, "damped")
                continue
            eligible.append(s)
        if not eligible:
            return moved
        # ONE batch assignment for every eligible shard: re-running
        # strategy.assign per shard is quadratic and lets a later iteration
        # skip shards an earlier call already placed
        per_node = self.strategy.assign(self.mapper, self.nodes, self.shards_per_node)
        placed = {s: node for node, got in per_node.items() for s in got}
        for s in eligible:
            node = placed.get(s)
            if node is None:
                continue
            self.mapper.update(s, ShardStatus.ASSIGNED, node)
            self._last_reassign[s] = now
            moved.append(s)
            record_shard_reassignment(s, damped=False)
            self._note(s, node, "moved")
        return moved

    # -- shard lifecycle events (from ingestion) --------------------------

    def shard_active(self, shard: int) -> None:
        self.mapper.update(shard, ShardStatus.ACTIVE, self.mapper.node_of(shard))

    def shard_recovering(self, shard: int) -> None:
        self.mapper.update(shard, ShardStatus.RECOVERY, self.mapper.node_of(shard))

    def ingestion_error(self, shard: int) -> bool:
        """IngestionError -> reassign elsewhere unless dampered (reference
        doc/sharding.md:157-167). Returns True if reassigned."""
        self.mapper.update(shard, ShardStatus.ERROR, self.mapper.node_of(shard))
        self.mapper.update(shard, ShardStatus.UNASSIGNED, None)
        return bool(self._reassign([shard]))

    # -- live rebalancing -------------------------------------------------

    def rebalance(self, shard: int, to_node: str) -> bool:
        """Deliberate shard move (operator- or balancer-driven). The damper
        gates it exactly like failure reassignment — a shard that just
        bounced will not bounce again. The new owner starts in RECOVERY;
        the state-handoff layer (coordinator/replication.py) replays data
        and flips it ACTIVE once the effect log proves cutover. Returns
        True when the mapping moved."""
        if to_node not in self.nodes:
            raise ValueError(f"unknown node {to_node!r}")
        if self.mapper.node_of(shard) == to_node:
            return False
        if self.damper_active(shard):
            from ..metrics import record_shard_reassignment

            record_shard_reassignment(shard, damped=True)
            self._note(shard, to_node, "damped")
            return False
        self.mapper.update(shard, ShardStatus.RECOVERY, to_node)
        self._last_reassign[shard] = self._clock()
        self._note(shard, to_node, "rebalanced")
        return True

    def snapshot(self) -> dict:
        """Cluster state for GET /debug/cluster."""
        shards = []
        for s in range(self.mapper.num_shards):
            shards.append({
                "shard": s,
                "primary": self.mapper.node_of(s),
                "status": self.mapper.status_of(s).value,
                "replicas": {
                    n: st.value for n, st in self.mapper.replicas_of(s).items()
                },
                "damper_active": self.damper_active(s),
            })
        return {
            "nodes": list(self.nodes),
            "num_replicas": self.num_replicas,
            "shards": shards,
            "recent_reassignments": list(self.recent),
        }


class ClusterDiscovery:
    """v2-style deterministic ordinal assignment + peer health tracking
    (reference FiloDbClusterDiscovery: stateful-set ordinal -> shard range
    :37-47, periodic peer pings)."""

    def __init__(self, num_shards: int, num_nodes: int, failure_detection_interval_s: float = 30.0):
        self.num_shards = num_shards
        self.num_nodes = num_nodes
        self.interval_s = failure_detection_interval_s
        self._heartbeat: dict[int, float] = {}

    def shards_for_ordinal(self, ordinal: int) -> list[int]:
        if not (0 <= ordinal < self.num_nodes):
            raise ValueError(f"ordinal {ordinal} out of range")
        per = self.num_shards // self.num_nodes
        extra = self.num_shards % self.num_nodes
        start = ordinal * per + min(ordinal, extra)
        n = per + (1 if ordinal < extra else 0)
        return list(range(start, start + n))

    def heartbeat(self, ordinal: int, ts: float | None = None) -> None:
        self._heartbeat[ordinal] = ts if ts is not None else time.time()

    def healthy_nodes(self, now: float | None = None) -> list[int]:
        now = now if now is not None else time.time()
        return [
            o for o in range(self.num_nodes)
            if now - self._heartbeat.get(o, 0) <= self.interval_s
        ]

    def down_nodes(self, now: float | None = None) -> list[int]:
        healthy = set(self.healthy_nodes(now))
        return [o for o in range(self.num_nodes) if o not in healthy]
