"""Cluster shard coordination (reference L5: ShardMapper.scala,
ShardManager.scala, ShardAssignmentStrategy.scala:265, ShardStatus.scala ADT,
v2 FiloDbClusterDiscovery.scala:6 ordinal assignment + peer health checks,
doc/sharding.md:157-189 auto-reassignment with 2h damper).

Single-process-friendly: nodes are logical endpoints; the event-driven state
machine (status transitions, subscriptions, reassignment policy) matches the
reference so a networked control plane can drive it later.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Callable, Sequence


class ShardStatus(enum.Enum):
    UNASSIGNED = "unassigned"
    ASSIGNED = "assigned"
    RECOVERY = "recovery"
    ACTIVE = "active"
    ERROR = "error"
    DOWN = "down"
    STOPPED = "stopped"


QUERYABLE = {ShardStatus.ACTIVE, ShardStatus.RECOVERY}


@dataclass
class ShardEvent:
    shard: int
    status: ShardStatus
    node: str | None
    ts: float = field(default_factory=time.time)


class ShardMapper:
    """shard -> (node, status) map + query routing (reference
    ShardMapper.scala: status tracking, activeShards, queryShards)."""

    def __init__(self, num_shards: int):
        self.num_shards = num_shards
        self._node: list[str | None] = [None] * num_shards
        self._status: list[ShardStatus] = [ShardStatus.UNASSIGNED] * num_shards
        self._subscribers: list[Callable[[ShardEvent], None]] = []

    def subscribe(self, fn: Callable[[ShardEvent], None]) -> None:
        self._subscribers.append(fn)

    def update(self, shard: int, status: ShardStatus, node: str | None = None) -> None:
        self._status[shard] = status
        if node is not None or status in (ShardStatus.UNASSIGNED, ShardStatus.DOWN):
            self._node[shard] = node
        ev = ShardEvent(shard, status, self._node[shard])
        for fn in self._subscribers:
            fn(ev)

    def node_of(self, shard: int) -> str | None:
        return self._node[shard]

    def status_of(self, shard: int) -> ShardStatus:
        return self._status[shard]

    def active_shards(self) -> list[int]:
        return [s for s in range(self.num_shards) if self._status[s] in QUERYABLE]

    def shards_of_node(self, node: str) -> list[int]:
        return [s for s in range(self.num_shards) if self._node[s] == node]

    def unassigned(self) -> list[int]:
        return [s for s in range(self.num_shards) if self._status[s] == ShardStatus.UNASSIGNED]

    def query_shards(self, shard_key_hash: int | None = None, spread: int | None = None) -> list[int]:
        """Shards a query must touch; with a shard-key hash + spread the set
        prunes to the 2^spread shards that key maps to (reference
        queryShardsFromShardKey)."""
        if shard_key_hash is None or spread is None:
            return self.active_shards()
        from ..core.schemas import shard_group

        cands = shard_group(shard_key_hash, spread, self.num_shards)
        return sorted(s for s in cands if self._status[s] in QUERYABLE)


class ShardAssignmentStrategy:
    """Even spread of shards over nodes respecting capacity (reference
    DefaultShardAssignmentStrategy)."""

    def assign(self, mapper: ShardMapper, nodes: Sequence[str], shards_per_node: int):
        out: dict[str, list[int]] = {n: [] for n in nodes}
        load = {n: len(mapper.shards_of_node(n)) for n in nodes}
        for s in mapper.unassigned():
            node = min(nodes, key=lambda n: load[n]) if nodes else None
            if node is None or load[node] >= shards_per_node:
                continue
            out[node].append(s)
            load[node] += 1
        return out


class ShardManager:
    """Cluster-singleton shard coordinator: node join/leave, ingestion-error
    reassignment with a damper window (reference ShardManager.scala +
    doc/sharding.md: a shard reassigned within the damper period is marked
    DOWN instead of bounced again)."""

    def __init__(self, num_shards: int, shards_per_node: int,
                 reassignment_damper_s: float = 7200.0,
                 clock: Callable[[], float] = time.time):
        self.mapper = ShardMapper(num_shards)
        self.strategy = ShardAssignmentStrategy()
        self.shards_per_node = shards_per_node
        self.damper_s = reassignment_damper_s
        self._clock = clock  # injectable for deterministic chaos tests
        self.nodes: list[str] = []
        self._last_reassign: dict[int, float] = {}

    def damper_active(self, shard: int) -> bool:
        """True while a recent reassignment suppresses another bounce."""
        last = self._last_reassign.get(shard)
        return last is not None and self._clock() - last < self.damper_s

    # -- membership -------------------------------------------------------

    def node_joined(self, node: str) -> list[int]:
        if node not in self.nodes:
            self.nodes.append(node)
        assigned = self.strategy.assign(self.mapper, [node], self.shards_per_node)[node]
        for s in assigned:
            self.mapper.update(s, ShardStatus.ASSIGNED, node)
        return assigned

    def node_left(self, node: str) -> list[int]:
        shards = self.mapper.shards_of_node(node)
        self.nodes = [n for n in self.nodes if n != node]
        for s in shards:
            self.mapper.update(s, ShardStatus.UNASSIGNED, None)
        return self._reassign(shards)

    def _reassign(self, shards: Sequence[int]) -> list[int]:
        from ..metrics import record_shard_reassignment

        moved = []
        now = self._clock()
        for s in shards:
            # a shard never reassigned before is infinitely old — the damper
            # only suppresses REPEAT bounces (clocks may start near zero)
            last = self._last_reassign.get(s)
            if last is not None and now - last < self.damper_s:
                # bounced too recently -> stop flapping (reference damper)
                self.mapper.update(s, ShardStatus.DOWN, None)
                record_shard_reassignment(s, damped=True)
                continue
            per_node = self.strategy.assign(self.mapper, self.nodes, self.shards_per_node)
            for node, got in per_node.items():
                if s in got:
                    self.mapper.update(s, ShardStatus.ASSIGNED, node)
                    self._last_reassign[s] = now
                    moved.append(s)
                    record_shard_reassignment(s, damped=False)
                    break
        return moved

    # -- shard lifecycle events (from ingestion) --------------------------

    def shard_active(self, shard: int) -> None:
        self.mapper.update(shard, ShardStatus.ACTIVE, self.mapper.node_of(shard))

    def shard_recovering(self, shard: int) -> None:
        self.mapper.update(shard, ShardStatus.RECOVERY, self.mapper.node_of(shard))

    def ingestion_error(self, shard: int) -> bool:
        """IngestionError -> reassign elsewhere unless dampered (reference
        doc/sharding.md:157-167). Returns True if reassigned."""
        self.mapper.update(shard, ShardStatus.ERROR, self.mapper.node_of(shard))
        self.mapper.update(shard, ShardStatus.UNASSIGNED, None)
        return bool(self._reassign([shard]))


class ClusterDiscovery:
    """v2-style deterministic ordinal assignment + peer health tracking
    (reference FiloDbClusterDiscovery: stateful-set ordinal -> shard range
    :37-47, periodic peer pings)."""

    def __init__(self, num_shards: int, num_nodes: int, failure_detection_interval_s: float = 30.0):
        self.num_shards = num_shards
        self.num_nodes = num_nodes
        self.interval_s = failure_detection_interval_s
        self._heartbeat: dict[int, float] = {}

    def shards_for_ordinal(self, ordinal: int) -> list[int]:
        if not (0 <= ordinal < self.num_nodes):
            raise ValueError(f"ordinal {ordinal} out of range")
        per = self.num_shards // self.num_nodes
        extra = self.num_shards % self.num_nodes
        start = ordinal * per + min(ordinal, extra)
        n = per + (1 if ordinal < extra else 0)
        return list(range(start, start + n))

    def heartbeat(self, ordinal: int, ts: float | None = None) -> None:
        self._heartbeat[ordinal] = ts if ts is not None else time.time()

    def healthy_nodes(self, now: float | None = None) -> list[int]:
        now = now if now is not None else time.time()
        return [
            o for o in range(self.num_nodes)
            if now - self._heartbeat.get(o, 0) <= self.interval_s
        ]

    def down_nodes(self, now: float | None = None) -> list[int]:
        healthy = set(self.healthy_nodes(now))
        return [o for o in range(self.num_nodes) if o not in healthy]
