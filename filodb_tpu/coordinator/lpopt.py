"""Pre-aggregation rewrite (reference L4 lpopt/:
AggLpOptimization.optimizeWithPreaggregatedDataset (AggLpOptimization.scala:36),
rule model IncludeAggRule/ExcludeAggRule
(query/util/HierarchicalQueryExperience.scala:28)).

When an aggregation's grouping labels are covered by a pre-aggregated
dataset's dimensions (maintained by streaming aggregation jobs), the query
can read the much-smaller preagg metric instead of raw series. Example rule:
metric ``http_requests_total`` preaggregated over {job, code} as
``http_requests_total:agg`` — then ``sum by (job) (rate(m[5m]))`` rewrites
the selector to the preagg metric; ``sum by (instance) (...)`` does not
(instance isn't a preagg dimension).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Sequence

from ..core.filters import ColumnFilter
from ..core.schemas import METRIC_TAG
from ..query import logical as L

# aggregation ops servable from the maintained preagg series. The
# maintainer (downsample/preagg.py) materializes cross-series SUMS, so only
# sum-rewrites are sound; per-op preagg datasets (min/max/count) are a
# later-round extension.
_REWRITABLE_OPS = {"sum"}


@dataclass(frozen=True)
class IncludeAggRule:
    """Metric is preaggregated retaining ONLY these tags."""

    metric_regex: str
    include_tags: frozenset[str]
    suffix: str = ":agg"

    def dims(self):
        return self.include_tags

    def covers(self, labels: Sequence[str]) -> bool:
        return set(labels) <= self.include_tags


@dataclass(frozen=True)
class ExcludeAggRule:
    """Metric is preaggregated dropping these tags (keeps the rest)."""

    metric_regex: str
    exclude_tags: frozenset[str]
    suffix: str = ":agg"

    def covers(self, labels: Sequence[str]) -> bool:
        return not (set(labels) & self.exclude_tags)


@dataclass
class AggRuleProvider:
    rules: list = None
    enabled: bool = True  # global gate; optimize_with_agg() overrides per-query

    def __post_init__(self):
        self.rules = self.rules or []

    def rule_for(self, metric: str):
        import re

        for r in self.rules:
            if re.fullmatch(r.metric_regex, metric):
                return r
        return None


def _metric_of(filters) -> str | None:
    for f in filters:
        if f.column == METRIC_TAG and f.op == "=":
            return f.value
    return None


def _filters_covered(rule, filters) -> bool:
    """Every non-shard-key filter tag must survive preaggregation."""
    for f in filters:
        if f.column in (METRIC_TAG, "_ws_", "_ns_"):
            continue
        if isinstance(rule, IncludeAggRule) and f.column not in rule.include_tags:
            return False
        if isinstance(rule, ExcludeAggRule) and f.column in rule.exclude_tags:
            return False
    return True


def optimize_with_preagg(
    plan: L.LogicalPlan, provider: AggRuleProvider, force: bool = False
) -> L.LogicalPlan:
    """Rewrite Aggregate(RawSeries...) subtrees to preagg metrics when the
    rule covers both the grouping labels and the filters. ``no_optimize(...)``
    opts a subtree out; ``optimize_with_agg(...)`` forces the rewrite even
    when the provider is globally disabled (reference NoOptimize /
    OptimizeWithAgg markers)."""
    if isinstance(plan, L.ApplyMiscellaneousFunction):
        if plan.function == "no_optimize":
            return plan
        if plan.function == "optimize_with_agg":
            return replace(plan, inner=optimize_with_preagg(plan.inner, provider, force=True))
    if isinstance(plan, L.Aggregate):
        if (provider.enabled or force) and plan.op in _REWRITABLE_OPS and plan.by is not None:
            rewritten = _try_rewrite(plan, provider)
            if rewritten is not None:
                return rewritten
        return replace(plan, inner=optimize_with_preagg(plan.inner, provider, force))
    kw = {}
    for f in getattr(plan, "__dataclass_fields__", {}):
        v = getattr(plan, f)
        if isinstance(v, L.LogicalPlan) and not isinstance(v, L.RawSeries):
            kw[f] = optimize_with_preagg(v, provider, force)
    return replace(plan, **kw) if kw else plan


def _try_rewrite(agg: L.Aggregate, provider: AggRuleProvider) -> L.LogicalPlan | None:
    inner = agg.inner
    if isinstance(inner, (L.PeriodicSeries, L.PeriodicSeriesWithWindowing)):
        raw = inner.raw
        metric = _metric_of(raw.filters)
        if metric is None:
            return None
        rule = provider.rule_for(metric)
        if rule is None:
            return None
        if not rule.covers(agg.by or ()):
            return None
        if not _filters_covered(rule, raw.filters):
            return None
        new_filters = tuple(
            ColumnFilter(METRIC_TAG, "=", metric + rule.suffix) if f.column == METRIC_TAG and f.op == "=" else f
            for f in raw.filters
        )
        new_raw = replace(raw, filters=new_filters)
        return replace(agg, inner=replace(inner, raw=new_raw))
    return None
