"""Distributed batch downsampler: many worker PROCESSES over one store.

The reference distributes DownsamplerMain over Spark executors by Cassandra
token range (spark-jobs/.../chunk/DownsamplerMain.scala,
CassandraColumnStore.getScanSplits:500) with userTimeStart checkpoints.
Here the work unit is the shard and the coordination substrate is the
column store's filesystem root (the analog of the reference's checkpoint
tables), so any number of workers on any host sharing the store can run
the job with NO coordinator process:

- work assignment: each worker walks the shard list and atomically CLAIMS
  a shard (O_EXCL claim file naming the worker); the bootstrap cluster's
  ``/__members`` list, when given, orders each worker's walk by member
  ordinal so workers start on disjoint slices and claim contention is the
  exception, not the rule;
- per-worker checkpoints: a shard's downsampled output is flushed to a
  worker-private staging directory and atomically renamed into place, then
  a ``done`` marker commits it — a crash at ANY point leaves either
  nothing or a committed shard, never a half-read double-count;
- straggler reassignment: claim files carry a heartbeat (mtime, refreshed
  by the worker); a claim older than ``stale_s`` is broken by any other
  worker and the shard is redone (safe: commit is atomic, redo overwrites).

Run via ``python -m filodb_tpu.cli downsample-batch --distributed`` in N
processes, or call :func:`run_worker` directly.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from dataclasses import dataclass, field

from ..core.records import SeriesBatch
from ..core.schemas import SCHEMAS, Dataset
from .downsampler import DS_GAUGE


@dataclass
class WorkerReport:
    worker_id: str
    shards_done: list = field(default_factory=list)
    shards_skipped: list = field(default_factory=list)
    shards_failed: list = field(default_factory=list)
    claims_broken: list = field(default_factory=list)
    samples: int = 0


def _job_dir(store_root: str, dataset: str, label: str) -> str:
    return os.path.join(store_root, dataset, f"downsample-job-{label}")


def _claim_path(job: str, shard: int) -> str:
    return os.path.join(job, f"shard-{shard}.claim")


def _done_path(job: str, shard: int) -> str:
    return os.path.join(job, f"shard-{shard}.done")


def _try_claim(job: str, shard: int, worker_id: str, stale_s: float,
               report: WorkerReport) -> bool:
    """Atomically claim a shard; break claims whose heartbeat went stale
    (the straggler-reassignment path)."""
    path = _claim_path(job, shard)
    payload = json.dumps({"worker": worker_id, "t": time.time()}).encode()
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        os.write(fd, payload)
        os.close(fd)
        return True
    except FileExistsError:
        pass
    try:
        age = time.time() - os.path.getmtime(path)
    except OSError:
        return False  # claim vanished: owner just finished or released
    if age <= stale_s:
        return False
    # stale heartbeat: STEAL the claim with an atomic rename of the stale
    # file — rename of one source path succeeds for exactly ONE of several
    # concurrent breakers (the losers get FileNotFoundError), so two
    # breakers can never both claim the shard (unlink+recreate could)
    stolen = path + f".stolen-{worker_id}-{os.getpid()}"
    try:
        os.rename(path, stolen)
    except OSError:
        return False  # another breaker won (or the owner just finished)
    try:
        os.unlink(stolen)
    except OSError:
        pass
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        os.write(fd, payload)
        os.close(fd)
        report.claims_broken.append(shard)
        return True
    except FileExistsError:
        return False


def _release(job: str, shard: int, worker_id: str) -> None:
    """Release a claim ONLY if we still own it — a worker whose stale claim
    was broken must not delete the new owner's claim (which would re-open
    the shard to a third worker mid-redo)."""
    path = _claim_path(job, shard)
    try:
        with open(path) as f:
            owner = json.load(f).get("worker")
        if owner == worker_id:
            os.unlink(path)
    except (OSError, ValueError):
        pass


def member_ordered_shards(shard_nums, members, self_url: str | None):
    """Order a worker's shard walk by its ``/__members`` ordinal so workers
    start on disjoint slices (assignment hint; claims stay the correctness
    mechanism). Unknown membership degrades to the natural order."""
    shard_nums = list(shard_nums)
    if not members or self_url is None:
        return shard_nums
    ring = sorted(members)
    if self_url not in ring:
        return shard_nums
    k = ring.index(self_url)
    n = len(ring)
    mine = [s for s in shard_nums if s % n == k]
    rest = [s for s in shard_nums if s % n != k]
    return mine + rest


def _flush_shard_output(store_root: str, dataset: str, shard: int,
                        periods_ms, value_cols, worker_id: str,
                        downsample_resolution_names) -> int:
    """Read one shard's raw chunks, reduce, and COMMIT the downsample
    datasets for that shard via staging-dir + atomic rename."""
    from ..memstore.memstore import TimeSeriesMemStore
    from ..store.columnstore import LocalColumnStore
    from ..store.flush import FlushCoordinator
    from .downsampler import _downsample_shard_records

    store = LocalColumnStore(store_root)
    records = _downsample_shard_records(store, dataset, shard,
                                        tuple(periods_ms), value_cols)
    staging_root = os.path.join(store_root, f".ds-staging-{worker_id}")
    shutil.rmtree(staging_root, ignore_errors=True)
    staging = LocalColumnStore(staging_root)
    ms = TimeSeriesMemStore()
    by_ds: dict[str, int] = {}
    n = 0
    for period, tags, out_ts, reduced in records:
        ds = downsample_resolution_names[int(period)]
        if ds not in by_ds:
            ms.setup(Dataset(ds, schemas=[DS_GAUGE]), [shard])
            by_ds[ds] = 1
        ms.shard(ds, shard).ingest_series(SeriesBatch(DS_GAUGE, tags, out_ts, reduced))
        n += len(out_ts)
    fc = FlushCoordinator(ms, staging)
    for ds in by_ds:
        fc.flush_shard(ds, shard)
        src = os.path.join(staging_root, ds, f"shard-{shard}")
        dst = os.path.join(store_root, ds, f"shard-{shard}")
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        # a stalled-but-alive previous owner can commit concurrently with a
        # redo (its heartbeat went stale, its claim was stolen, but its
        # process survived): rmtree+rename can then race another committer
        # and rename hits a re-created non-empty dst — retry a few times;
        # both candidate outputs are equivalent (same input chunks)
        for attempt in range(4):
            shutil.rmtree(dst, ignore_errors=True)
            try:
                os.rename(src, dst)
                break
            except OSError:
                if attempt == 3:
                    raise
                time.sleep(0.05 * (attempt + 1))
    shutil.rmtree(staging_root, ignore_errors=True)
    return n


def run_worker(store_root: str, dataset: str, shard_nums, periods_ms,
               worker_id: str | None = None, label: str = "default",
               stale_s: float = 30.0, heartbeat_s: float = 5.0,
               members=None, self_url: str | None = None) -> WorkerReport:
    """Claim-process-commit loop over the shard list; returns the worker's
    report. Run one of these per process; re-running after ANY crash
    resumes exactly where the job left off (done markers skip committed
    shards, stale claims get broken and redone)."""
    from .downsampler import _value_columns

    worker_id = worker_id or f"{os.uname().nodename}-{os.getpid()}"
    report = WorkerReport(worker_id=worker_id)
    job = _job_dir(store_root, dataset, label)
    os.makedirs(job, exist_ok=True)
    value_cols = _value_columns(SCHEMAS)
    # resolution naming matches ShardDownsampler.dataset_for so batch output
    # lands in the same datasets the ingest-time downsampler feeds
    res_names = {
        int(p): f"{dataset}_{int(p) // 60_000}m" for p in periods_ms
    }
    order = member_ordered_shards(shard_nums, members, self_url)
    stop_hb = threading.Event()

    def heartbeat(path: str):
        while not stop_hb.wait(heartbeat_s):
            try:
                os.utime(path)
            except FileNotFoundError:
                return  # claim broken by another worker: stop beating
            except OSError:
                continue  # transient FS error must not kill the heartbeat

    crash_after = os.environ.get("FILODB_DS_CRASH_AFTER_CLAIM")
    for shard in order:
        if os.path.exists(_done_path(job, shard)):
            report.shards_skipped.append(shard)
            continue
        if not _try_claim(job, shard, worker_id, stale_s, report):
            report.shards_skipped.append(shard)
            continue
        if crash_after is not None and int(crash_after) == shard:
            os._exit(17)  # test hook: die holding the claim (straggler)
        stop_hb.clear()
        hb = threading.Thread(target=heartbeat,
                              args=(_claim_path(job, shard),), daemon=True)
        hb.start()
        try:
            n = _flush_shard_output(store_root, dataset, shard, periods_ms,
                                    value_cols, worker_id, res_names)
            with open(_done_path(job, shard), "w") as f:
                json.dump({"worker": worker_id, "samples": n,
                           "t": time.time()}, f)
            report.shards_done.append(shard)
            report.samples += n
        except Exception:
            # one shard's failure (e.g. losing a concurrent-commit race to
            # a stalled-but-alive previous owner) must not abort the whole
            # worker: no done marker is left, so the shard gets redone.
            # The cause is logged — a deterministic failure (corrupt chunk)
            # must be distinguishable from the benign race
            import logging
            import traceback

            logging.getLogger(__name__).error(
                "downsample worker %s: shard %s failed\n%s",
                worker_id, shard, traceback.format_exc(),
            )
            report.shards_failed.append(shard)
        finally:
            stop_hb.set()
            hb.join(timeout=heartbeat_s + 1)
            _release(job, shard, worker_id)
    return report


def job_complete(store_root: str, dataset: str, shard_nums,
                 label: str = "default") -> bool:
    job = _job_dir(store_root, dataset, label)
    return all(os.path.exists(_done_path(job, s)) for s in shard_nums)
