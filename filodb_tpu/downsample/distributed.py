"""Distributed batch downsampler: many worker PROCESSES over one store.

The reference distributes DownsamplerMain over Spark executors by Cassandra
token range (spark-jobs/.../chunk/DownsamplerMain.scala,
CassandraColumnStore.getScanSplits:500) with userTimeStart checkpoints.
Here the work unit is the shard and the coordination substrate is the
column store's filesystem root (the analog of the reference's checkpoint
tables), so any number of workers on any host sharing the store can run
the job with NO coordinator process:

- work assignment: each worker walks the shard list and atomically CLAIMS
  a shard (O_EXCL claim file naming the worker); the bootstrap cluster's
  ``/__members`` list, when given, orders each worker's walk by member
  ordinal so workers start on disjoint slices and claim contention is the
  exception, not the rule;
- per-worker checkpoints: a shard's downsampled output is flushed to a
  worker-private staging directory and atomically renamed into place, then
  a ``done`` marker commits it — a crash at ANY point leaves either
  nothing or a committed shard, never a half-read double-count;
- straggler reassignment: claim files carry a heartbeat (mtime, refreshed
  by the worker); a claim older than ``stale_s`` is broken by any other
  worker and the shard is redone (safe: commit is atomic, redo overwrites).

Run via ``python -m filodb_tpu.cli downsample-batch --distributed`` in N
processes, or call :func:`run_worker` directly.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from dataclasses import dataclass, field

from ..core.records import SeriesBatch
from ..core.schemas import SCHEMAS, Dataset
from .downsampler import DS_GAUGE


@dataclass
class WorkerReport:
    worker_id: str
    shards_done: list = field(default_factory=list)
    shards_skipped: list = field(default_factory=list)
    shards_failed: list = field(default_factory=list)
    claims_broken: list = field(default_factory=list)
    samples: int = 0


def _job_dir(store_root: str, dataset: str, label: str) -> str:
    return os.path.join(store_root, dataset, f"downsample-job-{label}")


def _claim_path(job: str, shard: int) -> str:
    return os.path.join(job, f"shard-{shard}.claim")


def _done_path(job: str, shard: int) -> str:
    return os.path.join(job, f"shard-{shard}.done")


def _try_claim(job: str, shard: int, worker_id: str, stale_s: float,
               report: WorkerReport) -> bool:
    """Atomically claim a shard; break claims whose heartbeat went stale
    (the straggler-reassignment path)."""
    path = _claim_path(job, shard)
    payload = json.dumps({"worker": worker_id, "t": time.time()}).encode()
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        os.write(fd, payload)
        os.close(fd)
        return True
    except FileExistsError:
        pass
    try:
        age = time.time() - os.path.getmtime(path)
    except OSError:
        return False  # claim vanished: owner just finished or released
    if age <= stale_s:
        return False
    # stale heartbeat: STEAL the claim. Exactly one of N racing breakers
    # may ever rename the claim file per stale epoch — an O_EXCL ``.break``
    # marker elects it. Without this, a second breaker whose age check read
    # the ORIGINAL stale mtime can rename the first breaker's freshly
    # re-created claim (its rename source is no longer the file it judged
    # stale), and any restore of that claim clobbers whatever a third
    # worker O_EXCL-created while the path was transiently missing — the
    # two-winner storms the chaos suite pins.
    brk = path + ".break"
    try:
        bfd = os.open(brk, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        os.write(bfd, worker_id.encode())
        os.close(bfd)
    except FileExistsError:
        # another breaker is mid-steal; reap its marker only if IT died
        try:
            if time.time() - os.path.getmtime(brk) > stale_s:
                os.unlink(brk)
        except OSError:
            pass
        return False
    own_marker = True
    try:
        # sole breaker: re-verify from the claim itself — the owner's
        # heartbeat may have revived it since the age check above
        try:
            if time.time() - os.path.getmtime(path) <= stale_s:
                return False
        except OSError:
            return False  # vanished: owner just finished or released
        # a breaker stalled here longer than stale_s loses its marker to
        # the reap above; re-checking ownership narrows the resulting
        # double-breaker window to the microseconds between this read and
        # the rename (the residual, like the heartbeat TOCTOU, costs only
        # duplicate redo work — commits are idempotent)
        try:
            with open(brk) as bf:
                if bf.read().strip() != worker_id:
                    own_marker = False
                    return False
        except OSError:
            own_marker = False
            return False
        stolen = path + f".stolen-{worker_id}-{os.getpid()}"
        try:
            os.rename(path, stolen)
        except OSError:
            return False  # owner finished/released concurrently
        try:
            os.unlink(stolen)
        except OSError:
            pass
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, payload)
            os.close(fd)
            report.claims_broken.append(shard)
            _record_claim("steal")
            return True
        except FileExistsError:
            # a fresh claimant slipped into the rename->recreate gap via
            # the top O_EXCL path: it is the single winner, we yield
            return False
    finally:
        if own_marker:  # never remove a successor breaker's marker
            try:
                os.unlink(brk)
            except OSError:
                pass


def _record_claim(event: str) -> None:
    from ..metrics import record_downsample_claim

    record_downsample_claim(event)


# test hook: called between _release's ownership pre-read and its rename,
# so the chaos suite can deterministically interleave a steal into the
# exact TOCTOU window the tombstone discipline closes
_release_race_hook = None


def _release(job: str, shard: int, worker_id: str) -> None:
    """Release a claim ONLY if we still own it — a worker whose stale claim
    was broken must not delete the new owner's claim (which would re-open
    the shard to a third worker mid-redo).

    Uses the same atomic-rename discipline as the steal path instead of
    check-then-unlink: rename the claim to a worker-suffixed tombstone
    (exactly one process can win the rename), verify ownership from the
    RENAMED file, then unlink. If the tombstone turns out not to be ours —
    our claim was stolen and re-created between the pre-read and the
    rename, the old code's TOCTOU that deleted the NEW owner's claim — the
    tombstone is renamed back into place and the new owner keeps the
    shard (worst case: its redo duplicates work; commit stays atomic)."""
    path = _claim_path(job, shard)
    try:
        with open(path) as f:
            owner = json.load(f).get("worker")
    except (OSError, ValueError):
        return  # claim vanished (or unreadable): nothing of ours to release
    if owner != worker_id:
        return
    if _release_race_hook is not None:
        _release_race_hook(shard)
    tomb = path + f".release-{worker_id}-{os.getpid()}"
    try:
        os.rename(path, tomb)
    except OSError:
        return  # a concurrent stealer renamed it first: not ours anymore
    try:
        with open(tomb) as f:
            owner = json.load(f).get("worker")
    except (OSError, ValueError):
        owner = None
    if owner == worker_id:
        _record_claim("release")
        try:
            os.unlink(tomb)
        except OSError:
            pass
    else:
        # the TOCTOU window closed on us: we yanked the new owner's claim —
        # put it back exactly as it was. link (not rename) so a THIRD
        # worker's claim O_EXCL-created while the path was transiently
        # missing is never clobbered by the restore (EEXIST → the newer
        # claim stands; the yanked owner redoes work, commit is idempotent)
        _record_claim("tombstone_restored")
        try:
            os.link(tomb, path)
        except OSError:
            pass
        try:
            os.unlink(tomb)
        except OSError:
            pass


def member_ordered_shards(shard_nums, members, self_url: str | None):
    """Order a worker's shard walk by its ``/__members`` ordinal so workers
    start on disjoint slices (assignment hint; claims stay the correctness
    mechanism). Unknown membership degrades to the natural order."""
    shard_nums = list(shard_nums)
    if not members or self_url is None:
        return shard_nums
    ring = sorted(members)
    if self_url not in ring:
        return shard_nums
    k = ring.index(self_url)
    n = len(ring)
    mine = [s for s in shard_nums if s % n == k]
    rest = [s for s in shard_nums if s % n != k]
    return mine + rest


def _append_jsonl(dst_path: str, blob: str) -> None:
    """Append newline-terminated jsonl records in ONE write, sharing the
    store layer's torn-final-line guard (columnstore.torn_final_line)."""
    from ..store.columnstore import torn_final_line

    if not blob.endswith("\n"):
        blob += "\n"
    if torn_final_line(dst_path):
        blob = "\n" + blob
    with open(dst_path, "a") as f:
        f.write(blob)


def _commit_shard_dir(src: str, dst: str, label: str) -> None:
    """MERGE one staged downsample shard dir into the LIVE shard dir —
    never deleting it, so a batch commit can no longer wipe newer
    ingest-time streaming-downsampled segments (the old rmtree+rename race,
    ADVICE round 5).

    Batch segments land under DETERMINISTIC ``chunks-batch-<label>-*``
    names via atomic ``os.replace``: a redo after a claim steal (or a
    stalled-but-alive previous owner committing late) overwrites its own
    previous output in place — last writer wins, and both candidates are
    equivalent (same input chunks) — while ``chunks-gN.seg`` files written
    by the streaming downsampler are never touched. Where batch and
    streaming output overlap in time, the read side reconciles
    (store/flush._reconcile_chunks: later-end chunk wins per timestamp).
    Manifest entries for the committed segments are appended in ONE write
    (a concurrently appending streaming flush cannot tear a line);
    partkeys append likewise (recovery dedups by partkey)."""
    os.makedirs(dst, exist_ok=True)
    seg_map = {}
    for fn in sorted(os.listdir(src)):
        if fn.startswith("chunks-") and fn.endswith(".seg"):
            new = f"chunks-batch-{label}-{fn[len('chunks-'):]}"
            os.replace(os.path.join(src, fn), os.path.join(dst, new))
            seg_map[fn] = new
    man = os.path.join(src, "manifest.jsonl")
    if seg_map and os.path.exists(man):
        out = []
        with open(man) as f:
            for line in f:
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if e.get("seg") in seg_map:
                    e["seg"] = seg_map[e["seg"]]
                    out.append(json.dumps(e))
        if out:
            _append_jsonl(os.path.join(dst, "manifest.jsonl"),
                          "\n".join(out))
    pk = os.path.join(src, "partkeys.jsonl")
    if os.path.exists(pk):
        with open(pk) as f:
            data = f.read()
        if data:
            _append_jsonl(os.path.join(dst, "partkeys.jsonl"), data)


def _flush_shard_output(store_root: str, dataset: str, shard: int,
                        periods_ms, value_cols, worker_id: str,
                        downsample_resolution_names,
                        label: str = "default") -> int:
    """Read one shard's raw chunks, reduce, and COMMIT the downsample
    datasets for that shard by MERGING the staged output into the live
    shard dirs (_commit_shard_dir)."""
    from ..memstore.memstore import TimeSeriesMemStore
    from ..store.columnstore import LocalColumnStore
    from ..store.flush import FlushCoordinator
    from .downsampler import _downsample_shard_records

    store = LocalColumnStore(store_root)
    records = _downsample_shard_records(store, dataset, shard,
                                        tuple(periods_ms), value_cols)
    staging_root = os.path.join(store_root, f".ds-staging-{worker_id}")
    shutil.rmtree(staging_root, ignore_errors=True)
    staging = LocalColumnStore(staging_root)
    ms = TimeSeriesMemStore()
    by_ds: dict[str, int] = {}
    n = 0
    for period, tags, out_ts, reduced in records:
        ds = downsample_resolution_names[int(period)]
        if ds not in by_ds:
            ms.setup(Dataset(ds, schemas=[DS_GAUGE]), [shard])
            by_ds[ds] = 1
        ms.shard(ds, shard).ingest_series(SeriesBatch(DS_GAUGE, tags, out_ts, reduced))
        n += len(out_ts)
    fc = FlushCoordinator(ms, staging)
    crash_mid = os.environ.get("FILODB_DS_CRASH_MID_COMMIT")
    for i, ds in enumerate(by_ds):
        fc.flush_shard(ds, shard)
        src = os.path.join(staging_root, ds, f"shard-{shard}")
        dst = os.path.join(store_root, ds, f"shard-{shard}")
        _commit_shard_dir(src, dst, label)
        if crash_mid is not None and int(crash_mid) == shard:
            os._exit(19)  # test hook: die between commit and done marker
    shutil.rmtree(staging_root, ignore_errors=True)
    return n


def run_worker(store_root: str, dataset: str, shard_nums, periods_ms,
               worker_id: str | None = None, label: str = "default",
               stale_s: float = 30.0, heartbeat_s: float = 5.0,
               members=None, self_url: str | None = None) -> WorkerReport:
    """Claim-process-commit loop over the shard list; returns the worker's
    report. Run one of these per process; re-running after ANY crash
    resumes exactly where the job left off (done markers skip committed
    shards, stale claims get broken and redone)."""
    from .downsampler import _value_columns

    worker_id = worker_id or f"{os.uname().nodename}-{os.getpid()}"
    report = WorkerReport(worker_id=worker_id)
    job = _job_dir(store_root, dataset, label)
    os.makedirs(job, exist_ok=True)
    value_cols = _value_columns(SCHEMAS)
    # resolution naming matches ShardDownsampler.dataset_for so batch output
    # lands in the same datasets the ingest-time downsampler feeds
    res_names = {
        int(p): f"{dataset}_{int(p) // 60_000}m" for p in periods_ms
    }
    order = member_ordered_shards(shard_nums, members, self_url)
    stop_hb = threading.Event()

    def heartbeat(path: str):
        while not stop_hb.wait(heartbeat_s):
            try:
                os.utime(path)
            except FileNotFoundError:
                return  # claim broken by another worker: stop beating
            except OSError:
                continue  # transient FS error must not kill the heartbeat

    crash_after = os.environ.get("FILODB_DS_CRASH_AFTER_CLAIM")
    for shard in order:
        if os.path.exists(_done_path(job, shard)):
            report.shards_skipped.append(shard)
            continue
        if not _try_claim(job, shard, worker_id, stale_s, report):
            report.shards_skipped.append(shard)
            continue
        if crash_after is not None and int(crash_after) == shard:
            os._exit(17)  # test hook: die holding the claim (straggler)
        stop_hb.clear()
        hb = threading.Thread(target=heartbeat,
                              args=(_claim_path(job, shard),), daemon=True)
        hb.start()
        try:
            n = _flush_shard_output(store_root, dataset, shard, periods_ms,
                                    value_cols, worker_id, res_names,
                                    label=label)
            with open(_done_path(job, shard), "w") as f:
                json.dump({"worker": worker_id, "samples": n,
                           "t": time.time()}, f)
            report.shards_done.append(shard)
            report.samples += n
        except Exception:
            # one shard's failure (e.g. losing a concurrent-commit race to
            # a stalled-but-alive previous owner) must not abort the whole
            # worker: no done marker is left, so the shard gets redone.
            # The cause is logged — a deterministic failure (corrupt chunk)
            # must be distinguishable from the benign race
            import logging
            import traceback

            logging.getLogger(__name__).error(
                "downsample worker %s: shard %s failed\n%s",
                worker_id, shard, traceback.format_exc(),
            )
            report.shards_failed.append(shard)
        finally:
            stop_hb.set()
            hb.join(timeout=heartbeat_s + 1)
            _release(job, shard, worker_id)
    return report


def job_complete(store_root: str, dataset: str, shard_nums,
                 label: str = "default") -> bool:
    job = _job_dir(store_root, dataset, label)
    return all(os.path.exists(_done_path(job, s)) for s in shard_nums)
