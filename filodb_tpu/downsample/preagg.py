"""Streaming pre-aggregation maintenance (the producer side of lpopt:
reference operators run streaming aggregation jobs that materialize
``metric:agg`` series with reduced tag sets; AggRuleProvider's rules then
let the planner serve ``sum by`` queries from them — AggLpOptimization).

The maintainer consumes flushed chunks: samples bucket onto a fixed preagg
resolution grid, accumulate per (reduced-tags, period) across ALL matching
series, and periods older than the watermark emit (append-only, so late
series must flush before the watermark passes — bounded by flush cadence).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..core.records import SeriesBatch
from ..core.schemas import GAUGE, METRIC_TAG, canonical_partkey
from ..coordinator.lpopt import AggRuleProvider, ExcludeAggRule, IncludeAggRule


@dataclass
class PreaggMaintainer:
    """Accumulates sum/count preaggregates per rule into the target
    memstore's ``<metric>:agg`` series."""

    memstore: object
    dataset: str
    provider: AggRuleProvider
    resolution_ms: int = 60_000
    # (shard, reduced_pk) -> {"tags", "sums": {period -> [sum, count]}}
    _acc: dict = field(default_factory=dict)
    _watermark: dict = field(default_factory=dict)  # shard -> emitted-until period

    def _reduced_tags(self, rule, tags: dict) -> dict:
        metric = tags.get(METRIC_TAG, "")
        if isinstance(rule, IncludeAggRule):
            out = {k: v for k, v in tags.items() if k in rule.include_tags or k == METRIC_TAG}
        else:
            out = {k: v for k, v in tags.items() if k not in rule.exclude_tags}
        out[METRIC_TAG] = metric + rule.suffix
        return out

    def process_chunks(self, shard_num: int, part, chunks) -> int:
        """Fold one partition's flushed chunks into the accumulators."""
        metric = part.tags.get(METRIC_TAG)
        if metric is None:
            return 0
        rule = self.provider.rule_for(metric)
        if rule is None:
            return 0
        col = part.schema.value_column
        c0 = part.schema.column(col)
        from ..core.schemas import ColumnType

        if c0.ctype != ColumnType.DOUBLE:
            return 0
        reduced = self._reduced_tags(rule, dict(part.tags))
        key = (shard_num, canonical_partkey(reduced))
        slot = self._acc.setdefault(key, {"tags": reduced, "sums": {}})
        n = 0
        for c in chunks:
            ts = c.column("timestamp")
            vals = c.column(col).astype(np.float64)
            periods = (ts // self.resolution_ms).astype(np.int64)
            keep = ~np.isnan(vals)
            idx = np.nonzero(np.diff(periods, prepend=periods[0] - 1))[0]
            sums = np.add.reduceat(np.where(keep, vals, 0.0), idx)
            counts = np.add.reduceat(keep.astype(np.float64), idx)
            for p, s, cnt in zip(periods[idx], sums, counts):
                cur = slot["sums"].setdefault(int(p), [0.0, 0.0])
                cur[0] += float(s)
                cur[1] += float(cnt)
                n += 1
        return n

    def emit(self, shard_num: int, up_to_ms: int | None = None) -> int:
        """Flush accumulated periods older than the watermark into the
        memstore as ``metric:agg`` gauge series (value = period sum)."""
        emitted = 0
        cutoff = (up_to_ms // self.resolution_ms) if up_to_ms is not None else None
        for (s, pk), slot in list(self._acc.items()):
            if s != shard_num:
                continue
            ready = sorted(
                p for p in slot["sums"] if cutoff is None or p < cutoff
            )
            if not ready:
                continue
            ts = np.asarray(
                [(p + 1) * self.resolution_ms - 1 for p in ready], dtype=np.int64
            )
            vals = np.asarray([slot["sums"][p][0] for p in ready])
            sb = SeriesBatch(GAUGE, dict(slot["tags"]), ts, {"value": vals})
            self.memstore.shard(self.dataset, shard_num).ingest_series(sb)
            for p in ready:
                del slot["sums"][p]
            emitted += len(ready)
        return emitted
