"""Streaming pre-aggregation maintenance (the producer side of lpopt:
reference operators run streaming aggregation jobs that materialize
``metric:agg`` series with reduced tag sets; AggRuleProvider's rules then
let the planner serve ``sum by`` queries from them — AggLpOptimization).

Semantics (what makes ``sum by`` substitutable): at each preagg period, a
series contributes its LAST sample in the period (gauge instant value /
cumulative counter reading); the :agg sample is the CROSS-SERIES SUM of
those contributions. For gauges that is the instant sum at preagg
resolution; for cumulative counters the summed series is itself a valid
counter, so ``rate`` over the :agg series approximates the sum of rates.
Contributions key by source series and REPLACE on later flushes, so a
period only emits once its watermark passes (all contributors flushed past
it). Only ``sum`` rewrites are enabled in lpopt — the maintainer
materializes sums, not per-op datasets.

Durability note: :agg samples are emitted into normal partitions and
persist on the NEXT flush; a crash between emit and that flush loses them
(raw data recovers via stream replay, but :agg has no replay). Bounded by
flush cadence; an idempotent rebuild is `cli downsample-batch`-style work
for a later round.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..core.records import SeriesBatch
from ..core.schemas import GAUGE, METRIC_TAG, canonical_partkey
from ..coordinator.lpopt import AggRuleProvider, ExcludeAggRule, IncludeAggRule
from .downsampler import last_per_period


@dataclass
class PreaggMaintainer:
    memstore: object
    dataset: str
    provider: AggRuleProvider
    resolution_ms: int = 60_000
    # (shard, reduced_pk) -> {"tags", "periods": {p: {src_pk: last_val}},
    #                         "src_max_ts": {src_pk: max processed ts}}
    _acc: dict = field(default_factory=dict)

    def _reduced_tags(self, rule, tags: dict) -> dict:
        metric = tags.get(METRIC_TAG, "")
        if isinstance(rule, IncludeAggRule):
            out = {k: v for k, v in tags.items() if k in rule.include_tags or k == METRIC_TAG}
        else:
            out = {k: v for k, v in tags.items() if k not in rule.exclude_tags}
        out[METRIC_TAG] = metric + rule.suffix
        return out

    def process_chunks(self, shard_num: int, part, chunks) -> int:
        """Fold one partition's flushed chunks into the accumulators."""
        metric = part.tags.get(METRIC_TAG)
        if metric is None:
            return 0
        rule = self.provider.rule_for(metric)
        if rule is None or metric.endswith(rule.suffix):
            return 0  # never re-aggregate :agg output (unbounded recursion)
        from ..core.schemas import ColumnType

        col = part.schema.value_column
        if part.schema.column(col).ctype != ColumnType.DOUBLE:
            return 0
        reduced = self._reduced_tags(rule, dict(part.tags))
        key = (shard_num, canonical_partkey(reduced))
        slot = self._acc.setdefault(
            key, {"tags": reduced, "periods": {}, "src_max_ts": {}}
        )
        src = part.partkey
        n = 0
        for c in chunks:
            ts = c.column("timestamp")
            vals = c.column(col).astype(np.float64)
            keep = ~np.isnan(vals)
            ts, vals = ts[keep], vals[keep]
            if not len(ts):
                continue
            last_idx, _ = last_per_period(ts, self.resolution_ms)
            for i in last_idx:
                p = int(ts[i]) // self.resolution_ms
                # later flushes REPLACE this series' contribution
                slot["periods"].setdefault(p, {})[src] = float(vals[i])
                n += 1
            slot["src_max_ts"][src] = max(
                slot["src_max_ts"].get(src, 0), int(ts[-1])
            )
        return n

    def emit(self, shard_num: int, up_to_ms: int | None = None) -> int:
        """Emit closed periods as :agg samples (cross-series sums).

        A period is closed when every known contributor has flushed data
        past its end (or when ``up_to_ms`` forces a cutoff)."""
        emitted = 0
        for (s, pk), slot in list(self._acc.items()):
            if s != shard_num or not slot["periods"]:
                continue
            if up_to_ms is not None:
                watermark = up_to_ms
            elif slot["src_max_ts"]:
                watermark = min(slot["src_max_ts"].values())
            else:
                continue
            cutoff = watermark // self.resolution_ms
            ready = sorted(p for p in slot["periods"] if p < cutoff)
            if not ready:
                continue
            ts = np.asarray(
                [(p + 1) * self.resolution_ms - 1 for p in ready], dtype=np.int64
            )
            vals = np.asarray(
                [sum(slot["periods"][p].values()) for p in ready]
            )
            sb = SeriesBatch(GAUGE, dict(slot["tags"]), ts, {"value": vals})
            self.memstore.shard(self.dataset, shard_num).ingest_series(sb)
            for p in ready:
                del slot["periods"][p]
            emitted += len(ready)
        return emitted
