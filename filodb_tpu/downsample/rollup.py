"""Workload-chosen sketch rollup tier — the maintenance side
(doc/perf.md "Sketch rollup tier").

A *rollup entry* is a selector-scoped summary block at one resolution:
per series per period it holds min/max/sum/count moments, a
reset-corrected last (counters — period-aligned ``rate``/``increase``
read off as diffs), and a COMPACTED log-linear sketch (ops/sketch.py bin
ids, stored as the populated ``[bin_lo, bin_hi]`` slice of the full bin
axis — exact-equivalent to the full sketch because bins stay sorted by
value). Long-range ``quantile_over_time``/``histogram_quantile``/
``*_over_time`` queries whose step and window are multiples of the
resolution read O(periods) summaries instead of O(raw samples)
(coordinator/planner substitution -> query/exec RollupServeExec).

Maintenance reuses the PR-6 race-free pattern: each entry records the
member shards' version vectors and closes periods up to a graced
watermark; on refresh, the shard effect log (``ingest_effects_since``)
proves whether ingest since the stamped versions touched the CLOSED
region — disjoint effects fold forward incrementally, overlapping or
unclassifiable effects (out-of-order writes, eviction, truncation) force
a full rebuild, so rollups stay live under production ingest without
ever serving a torn period. Device copies stage lazily at first serve
and are accounted in the device ledger under the ``rollup`` kind;
``/debug/rollups`` serves :meth:`RollupManager.snapshot`.

WHICH selectors get rollups at WHAT resolutions is workload-chosen:
downsample/chooser.py trains on querylog fingerprints and drives
:meth:`RollupManager.ensure` / :meth:`RollupManager.retire`.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ..core.schemas import ColumnType
from ..ledger import LEDGER
from ..metrics import REGISTRY, record_rollup_event
from ..ops import sketch as SK

# range functions servable from moments (plus counter-only rate/increase;
# quantile_over_time serves from the sketch block)
ROLLUP_MOMENT_FUNCS = frozenset({
    "min_over_time", "max_over_time", "sum_over_time", "count_over_time",
    "avg_over_time",
})
ROLLUP_COUNTER_FUNCS = frozenset({"rate", "increase"})
ROLLUP_SKETCH_FUNCS = frozenset({"quantile_over_time"})
ROLLUP_FUNCS = ROLLUP_MOMENT_FUNCS | ROLLUP_COUNTER_FUNCS | ROLLUP_SKETCH_FUNCS

# aggregate ops the rollup aggregate path computes (one masked segment
# reduce over the per-series moment values; quantile goes through the
# merge-sketches -> epilogue program)
ROLLUP_AGG_OPS = frozenset({"sum", "count", "avg", "min", "max"})


def filters_key(filters) -> tuple:
    """Canonical selector identity: rollups are selector-scoped and matched
    exactly (order-insensitive)."""
    return tuple(sorted((f.column, f.op, str(f.value)) for f in filters))


def _ffill(arr: np.ndarray, seed: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """Row-wise forward fill of ``arr`` [S, P] where ``mask`` is False,
    seeded per row (the value 'before' column 0)."""
    S, P = arr.shape
    if P == 0:
        return arr
    a = np.concatenate([seed[:, None], arr], axis=1)
    m = np.concatenate([np.ones((S, 1), bool), mask], axis=1)
    idx = np.where(m, np.arange(P + 1)[None, :], 0)
    np.maximum.accumulate(idx, axis=1, out=idx)
    return a[np.arange(S)[:, None], idx][:, 1:]


@dataclass
class RollupEntry:
    """One selector x resolution summary block (host mirrors + lazily
    staged device copies)."""

    dataset: str
    filters: tuple
    resolution_ms: int
    origin: str = "config"
    # period coverage: periods [p0, watermark_p) are closed and folded.
    # Arrays are allocated only up to the DATA edge (local period count
    # ``alloc_p``); closed periods past it are implicitly empty — identity
    # values the serve path pads in — so a stale selector costs O(data),
    # not O(wall-clock since p0)
    p0: int | None = None
    watermark_p: int | None = None
    alloc_p: int = 0
    # per-series identity, in row order
    labels: list = field(default_factory=list)
    part_refs: list = field(default_factory=list)  # [(shard_num, pid)]
    col_name: str | None = None
    is_counter: bool = False
    # host moment arrays [S, P]
    mn: np.ndarray | None = None
    mx: np.ndarray | None = None
    sm: np.ndarray | None = None
    cnt: np.ndarray | None = None
    clast: np.ndarray | None = None  # corrected last, forward-filled (f64)
    # compacted sketch block [S, P, Bc] over full-bin ids [bin_lo, bin_hi]
    sketch: np.ndarray | None = None
    bin_lo: int | None = None
    bin_hi: int | None = None
    # per-series counter-correction carry across folds
    carry_last_raw: np.ndarray | None = None
    carry_base: np.ndarray | None = None
    carry_clast: np.ndarray | None = None
    # freshness: per-shard versions stamped BEFORE the last fold's reads
    versions: dict = field(default_factory=dict)
    # stats
    created_s: float = field(default_factory=time.time)
    last_hit_s: float = 0.0
    last_refresh_s: float = 0.0
    builds: int = 0
    folds: int = 0
    serves: int = 0
    # device staging (protected by the manager lock)
    _dev: dict | None = None
    _dev_nbytes: int = 0

    @property
    def n_series(self) -> int:
        return len(self.labels)

    @property
    def n_periods(self) -> int:
        if self.p0 is None or self.watermark_p is None:
            return 0
        return self.watermark_p - self.p0

    def host_nbytes(self) -> int:
        total = 0
        for a in (self.mn, self.mx, self.sm, self.cnt, self.clast,
                  self.sketch):
            if a is not None:
                total += a.nbytes
        return total

    def describe(self) -> dict:
        res = self.resolution_ms
        return {
            "dataset": self.dataset,
            "selector": [list(f) for f in self.filters_key()],
            "resolution_ms": res,
            "origin": self.origin,
            "series": self.n_series,
            "periods": self.n_periods,
            "alloc_periods": self.alloc_p,
            "coverage_ms": (
                [self.p0 * res, self.watermark_p * res]
                if self.p0 is not None else None
            ),
            "is_counter": self.is_counter,
            "column": self.col_name,
            "sketch_bins": (
                self.bin_hi - self.bin_lo + 1 if self.bin_lo is not None
                else 0
            ),
            "host_bytes": self.host_nbytes(),
            "device_bytes": self._dev_nbytes,
            "builds": self.builds,
            "folds": self.folds,
            "serves": self.serves,
            "last_hit_s": self.last_hit_s,
            "last_refresh_s": self.last_refresh_s,
        }

    def filters_key(self) -> tuple:
        return filters_key(self.filters)


class RollupManager:
    """Owns the rollup entry set for one memstore: maintenance (standing
    thread or explicit :meth:`tick`), plan-time eligibility, and the
    serve-time views RollupServeExec dispatches on."""

    def __init__(self, memstore, grace_ms: int = 0, max_entries: int = 64,
                 tick_s: float = 5.0):
        self.memstore = memstore
        self.grace_ms = int(grace_ms)
        self.max_entries = int(max_entries)
        self.tick_s = float(tick_s)
        self._entries: dict[tuple, RollupEntry] = {}
        self._lock = threading.RLock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.ledger = LEDGER.register(
            self, "rollup", _rollup_ledger_walker, name="rollup-blocks",
        )
        REGISTRY.register_collector(
            f"rollup_manager_{id(self)}", self._publish_gauges,
        )

    # -- entry lifecycle ---------------------------------------------------

    def _key(self, dataset: str, filters, resolution_ms: int) -> tuple:
        return (dataset, filters_key(filters), int(resolution_ms))

    def ensure(self, dataset: str, filters, resolution_ms: int,
               origin: str = "config", build: bool = False) -> RollupEntry:
        """Idempotently register a rollup for (selector, resolution).
        ``build=True`` folds synchronously (tests, chooser warm-add)."""
        key = self._key(dataset, filters, resolution_ms)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                if len(self._entries) >= self.max_entries:
                    raise ValueError(
                        f"rollup entry limit {self.max_entries} reached"
                    )
                entry = RollupEntry(
                    dataset=dataset, filters=tuple(filters),
                    resolution_ms=int(resolution_ms), origin=origin,
                )
                self._entries[key] = entry
                record_rollup_event("add")
        if build:
            self.refresh(entry)
        return entry

    def retire(self, dataset: str, filters, resolution_ms: int,
               reason: str = "idle") -> bool:
        key = self._key(dataset, filters, resolution_ms)
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                return False
            self._drop_device_locked(entry)
        record_rollup_event("retire")
        return True

    def has(self, dataset: str, filters, resolution_ms: int) -> bool:
        with self._lock:
            return self._key(dataset, filters, resolution_ms) in self._entries

    def entries(self) -> list[RollupEntry]:
        with self._lock:
            return list(self._entries.values())

    def snapshot(self) -> dict:
        with self._lock:
            entries = [e.describe() for e in self._entries.values()]
        out = {
            "entries": entries,
            "count": len(entries),
            "max_entries": self.max_entries,
            "grace_ms": self.grace_ms,
        }
        # the chooser (when attached by the server) contributes its latest
        # decision pass so /debug/rollups tells WHY the set looks like this
        chooser = getattr(self, "chooser", None)
        if chooser is not None:
            out["chooser_decisions"] = list(chooser.decisions)
        return out

    def _publish_gauges(self) -> None:
        with self._lock:
            per_ds: dict[str, int] = {}
            for e in self._entries.values():
                per_ds[e.dataset] = per_ds.get(e.dataset, 0) + 1
        for ds, n in per_ds.items():
            REGISTRY.gauge("filodb_rollup_entries", dataset=ds).set(float(n))

    # -- maintenance -------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="rollup-maintainer", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — maintenance must not die
                record_rollup_event("error")

    def tick(self, now_ms: int | None = None) -> int:
        """One maintenance pass over every entry; returns entries
        refreshed. Synchronous entry point for tests."""
        n = 0
        for entry in self.entries():
            try:
                if self.refresh(entry, now_ms=now_ms):
                    n += 1
            except Exception:  # noqa: BLE001 — one sick entry must not stall the rest
                record_rollup_event("error")
        return n

    def refresh(self, entry: RollupEntry, now_ms: int | None = None) -> bool:
        """Fold newly closed periods into ``entry``; full rebuild when the
        effect log can't prove the closed region untouched."""
        if now_ms is None:
            now_ms = int(time.time() * 1000)
        res = entry.resolution_ms
        upto_p = (now_ms - self.grace_ms) // res
        with self._lock:
            needs_rebuild = False
            if entry.watermark_p is not None:
                closed_lo = (entry.p0 or 0) * res
                closed_hi = entry.watermark_p * res
                for s in self.memstore.shard_nums(entry.dataset):
                    shard = self.memstore.shard(entry.dataset, s)
                    old_v = entry.versions.get(s)
                    if old_v is None:
                        continue
                    if shard.version == old_v:
                        continue
                    reason = shard.ingest_effects_since(
                        old_v, closed_lo, closed_hi - 1
                    )
                    if reason is not None:
                        # overlap / full_clear / log_truncated: closed
                        # periods may be stale — rebuild from scratch
                        needs_rebuild = True
                        break
            if needs_rebuild:
                self._rebuild_locked(entry, upto_p, now_ms)
                record_rollup_event("rebuild")
                return True
            if entry.watermark_p is not None and upto_p <= entry.watermark_p:
                entry.last_refresh_s = time.time()
                return False
            self._fold_locked(entry, upto_p, now_ms)
            if entry.builds == 1 and entry.folds == 1:
                record_rollup_event("build")
            else:
                record_rollup_event("fold")
            return True

    def _rebuild_locked(self, entry: RollupEntry, upto_p: int,
                        now_ms: int) -> None:
        entry.p0 = None
        entry.watermark_p = None
        entry.alloc_p = 0
        entry.labels = []
        entry.part_refs = []
        entry.mn = entry.mx = entry.sm = entry.cnt = None
        entry.clast = entry.sketch = None
        entry.bin_lo = entry.bin_hi = None
        entry.carry_last_raw = entry.carry_base = entry.carry_clast = None
        entry.versions = {}
        self._drop_device_locked(entry)
        self._fold_locked(entry, upto_p, now_ms)

    def _fold_locked(self, entry: RollupEntry, upto_p: int,
                     now_ms: int) -> None:
        """Fold samples from closed periods [watermark_p, upto_p) into the
        entry's arrays (first call establishes p0 from the data)."""
        res = entry.resolution_ms
        # versions stamped BEFORE the reads: a racing in-range append bumps
        # them, so the next refresh sees an overlap against the (by then
        # closed) region and rebuilds — never a torn period served
        versions = {
            s: self.memstore.shard(entry.dataset, s).version
            for s in self.memstore.shard_nums(entry.dataset)
        }
        fold_from = (entry.watermark_p * res
                     if entry.watermark_p is not None else 0)
        fold_to = upto_p * res
        if fold_to <= fold_from:
            entry.versions = versions
            entry.last_refresh_s = time.time()
            return
        # gather (row, ts, vals) per series; discover new series as we go
        ref_row = {r: i for i, r in enumerate(entry.part_refs)}
        gathered: list[tuple[int, np.ndarray, np.ndarray]] = []
        for s in self.memstore.shard_nums(entry.dataset):
            shard = self.memstore.shard(entry.dataset, s)
            pids = shard.lookup_partitions(entry.filters, fold_from,
                                           fold_to - 1)
            for pid in pids:
                part = shard.partition(int(pid))
                schema = part.schema
                col_name = entry.col_name or schema.value_column
                try:
                    col = schema.column(col_name)
                except KeyError:
                    continue
                if col.ctype != ColumnType.DOUBLE:
                    continue  # native-histogram columns: not rolled up
                ts, vals = part.samples_in_range(fold_from, fold_to - 1,
                                                 col_name)
                keep = ~np.isnan(np.asarray(vals, dtype=np.float64))
                ts = np.asarray(ts, dtype=np.int64)[keep]
                vals = np.asarray(vals, dtype=np.float64)[keep]
                ref = (s, int(pid))
                row = ref_row.get(ref)
                if row is None:
                    row = len(entry.part_refs)
                    ref_row[ref] = row
                    entry.part_refs.append(ref)
                    entry.labels.append(dict(part.tags))
                    if entry.col_name is None:
                        entry.col_name = col_name
                        entry.is_counter = bool(col.is_counter
                                                and not col.is_delta)
                if len(ts):
                    gathered.append((row, ts, vals))
        if entry.p0 is None:
            if not gathered:
                entry.versions = versions
                entry.last_refresh_s = time.time()
                return
            entry.p0 = int(min(ts[0] for _, ts, _ in gathered)) // res
            entry.watermark_p = entry.p0
        p_off = entry.p0
        wm_old = entry.watermark_p - p_off  # fold range, local periods
        wm_new = upto_p - p_off
        old_alloc = entry.alloc_p
        data_hi = old_alloc
        kept: list[tuple[int, np.ndarray, np.ndarray, np.ndarray]] = []
        for row, ts, vals in gathered:
            periods = ts // res - p_off
            inrange = (periods >= wm_old) & (periods < wm_new)
            if not inrange.all():
                ts, vals, periods = ts[inrange], vals[inrange], periods[inrange]
            if not len(ts):
                continue
            kept.append((row, ts, vals, periods))
            data_hi = max(data_hi, int(periods.max()) + 1)
        S = len(entry.part_refs)
        self._grow_arrays_locked(entry, S, data_hi)
        entry.alloc_p = data_hi
        mn, mx, sm, cnt = entry.mn, entry.mx, entry.sm, entry.cnt
        for row, ts, vals, periods in kept:
            np.minimum.at(mn[row], periods, vals)
            np.maximum.at(mx[row], periods, vals)
            np.add.at(sm[row], periods, vals)
            np.add.at(cnt[row], periods, 1.0)
            # sketch: full-bin ids, folded into the compacted slice
            bins = SK.bin_of_np(vals)
            self._fold_sketch_locked(entry, row, periods, bins)
            if entry.is_counter:
                self._fold_counter_locked(entry, row, ts, vals, periods)
        # forward-fill corrected last across empty periods of the fold
        if entry.is_counter and data_hi > old_alloc:
            seg = entry.clast[:, old_alloc:data_hi]
            mask = cnt[:, old_alloc:data_hi] > 0
            seed = (entry.carry_clast.copy() if old_alloc == 0
                    else entry.clast[:, old_alloc - 1])
            entry.clast[:, old_alloc:data_hi] = _ffill(seg, seed, mask)
            entry.carry_clast = entry.clast[:, data_hi - 1].copy()
        entry.watermark_p = upto_p
        entry.versions = versions
        entry.builds += 1 if entry.folds == 0 else 0
        entry.folds += 1
        entry.last_refresh_s = time.time()
        self._drop_device_locked(entry)

    def _grow_arrays_locked(self, entry: RollupEntry, S: int,
                            P: int) -> None:
        """Resize host arrays to [S, P] (rows append, periods extend)."""
        def grow2(a, fill, dtype=np.float64):
            if a is None:
                return np.full((S, P), fill, dtype)
            s0, p0 = a.shape
            if s0 == S and p0 == P:
                return a
            out = np.full((S, P), fill, dtype)
            out[:s0, :p0] = a
            return out

        entry.mn = grow2(entry.mn, np.inf)
        entry.mx = grow2(entry.mx, -np.inf)
        entry.sm = grow2(entry.sm, 0.0)
        entry.cnt = grow2(entry.cnt, 0.0)
        entry.clast = grow2(entry.clast, 0.0)
        Bc = (entry.bin_hi - entry.bin_lo + 1
              if entry.bin_lo is not None else 0)
        if entry.sketch is None:
            entry.sketch = np.zeros((S, P, Bc), np.float32)
        elif entry.sketch.shape[:2] != (S, P):
            out = np.zeros((S, P, Bc), np.float32)
            s0, p0, _ = entry.sketch.shape
            out[:s0, :p0] = entry.sketch
            entry.sketch = out

        def grow1(a, fill):
            if a is None:
                return np.full(S, fill, np.float64)
            if len(a) == S:
                return a
            out = np.full(S, fill, np.float64)
            out[: len(a)] = a
            return out

        entry.carry_last_raw = grow1(entry.carry_last_raw, np.nan)
        entry.carry_base = grow1(entry.carry_base, 0.0)
        entry.carry_clast = grow1(entry.carry_clast, 0.0)

    def _fold_sketch_locked(self, entry: RollupEntry, row: int,
                            periods: np.ndarray, bins: np.ndarray) -> None:
        ok = bins >= 0
        if not ok.all():
            periods, bins = periods[ok], bins[ok]
        if not len(bins):
            return
        lo, hi = int(bins.min()), int(bins.max())
        if entry.bin_lo is None:
            entry.bin_lo, entry.bin_hi = lo, hi
            S, P = entry.cnt.shape
            entry.sketch = np.zeros((S, P, hi - lo + 1), np.float32)
        elif lo < entry.bin_lo or hi > entry.bin_hi:
            new_lo = min(lo, entry.bin_lo)
            new_hi = max(hi, entry.bin_hi)
            pad_l = entry.bin_lo - new_lo
            pad_r = new_hi - entry.bin_hi
            entry.sketch = np.pad(
                entry.sketch, ((0, 0), (0, 0), (pad_l, pad_r))
            )
            entry.bin_lo, entry.bin_hi = new_lo, new_hi
        np.add.at(entry.sketch[row], (periods, bins - entry.bin_lo), 1.0)

    def _fold_counter_locked(self, entry: RollupEntry, row: int,
                             ts: np.ndarray, vals: np.ndarray,
                             periods: np.ndarray) -> None:
        """Reset-corrected cumulative last per period (vectorized; carry
        crosses folds so corrections stay consistent over time)."""
        last_raw = entry.carry_last_raw[row]
        prev = np.concatenate(
            [[vals[0] if np.isnan(last_raw) else last_raw], vals[:-1]]
        )
        drops = vals < prev
        base = entry.carry_base[row] + np.cumsum(np.where(drops, prev, 0.0))
        corrected = base + vals
        # last sample of each period present in this fold
        uniq, last_idx = np.unique(periods[::-1], return_index=True)
        last_idx = len(periods) - 1 - last_idx
        entry.clast[row, uniq] = corrected[last_idx]
        entry.carry_last_raw[row] = vals[-1]
        entry.carry_base[row] = base[-1]

    # -- device staging ----------------------------------------------------

    def _drop_device_locked(self, entry: RollupEntry) -> None:
        if entry._dev is not None:
            self.ledger.free(entry._dev_nbytes, reason="invalidate")
            entry._dev = None
            entry._dev_nbytes = 0

    def device_arrays(self, entry: RollupEntry) -> dict:
        """Lazily staged device copies of the entry's arrays (f32 moments
        with the counter baseline shifted out, compacted sketch counts).
        Ledger-accounted under kind ``rollup``."""
        import jax.numpy as jnp

        with self._lock:
            if entry._dev is not None:
                return entry._dev
            clast = entry.clast
            baseline = (clast[:, 0].copy() if clast is not None
                        and clast.shape[1] else None)
            dev = {
                "mn": jnp.asarray(entry.mn, jnp.float32),
                "mx": jnp.asarray(entry.mx, jnp.float32),
                "sm": jnp.asarray(entry.sm, jnp.float32),
                "cnt": jnp.asarray(entry.cnt, jnp.float32),
                # baseline-shifted corrected last: exact f32 diffs even on
                # 1e15-magnitude counters (the staging "shifted" trick)
                "clast": jnp.asarray(
                    clast - baseline[:, None] if baseline is not None
                    else entry.clast, jnp.float32,
                ),
                "sketch": jnp.asarray(entry.sketch, jnp.float32),
                "centers": jnp.asarray(
                    SK.bin_centers()[entry.bin_lo: entry.bin_hi + 1]
                    if entry.bin_lo is not None else np.zeros(0),
                    jnp.float32,
                ),
            }
            nbytes = sum(int(v.nbytes) for v in dev.values())
            entry._dev = dev
            entry._dev_nbytes = nbytes
            self.ledger.alloc(nbytes)
            return dev

    # -- plan-time eligibility + serve-time views --------------------------

    def plan(self, dataset: str, filters, func: str | None, step_ms: int,
             window_ms: int, start_ms: int, end_ms: int,
             offset_ms: int = 0, need_counter: bool | None = None):
        """Plan-time substitution check: the most coarse registered rollup
        whose resolution divides step AND window, with the query's period
        range inside the entry's closed coverage. Returns the entry key or
        None (the planner keeps the raw plan — bit-identical fallback)."""
        if offset_ms or step_ms <= 0 or window_ms <= 0:
            return None
        if func is not None and func not in ROLLUP_FUNCS:
            return None
        # clamp to the last evaluated grid step: coverage is only needed up
        # to start + floor((end-start)/step)*step, and the serve-time slice
        # then yields exactly num_steps windows
        end_ms = start_ms + ((end_ms - start_ms) // step_ms) * step_ms
        fkey = filters_key(filters)
        best = None
        with self._lock:
            for key, entry in self._entries.items():
                if key[0] != dataset or key[1] != fkey:
                    continue
                res = entry.resolution_ms
                if (step_ms % res or window_ms % res or window_ms < res
                        or start_ms % res):
                    continue
                if self._eligible_locked(entry, func, window_ms, start_ms,
                                         end_ms) is None:
                    continue
                if best is None or res > best[2]:
                    best = key
        if best is not None:
            with self._lock:
                e = self._entries.get(best)
                if e is not None:
                    e.last_hit_s = time.time()
        return best

    def _eligible_locked(self, entry: RollupEntry, func: str | None,
                         window_ms: int, start_ms: int, end_ms: int):
        """Runtime-shared eligibility: coverage + func/schema fit. Returns
        the (p_lo, p_hi) period range or None."""
        if entry.p0 is None or entry.watermark_p is None:
            return None
        res = entry.resolution_ms
        p_lo = (start_ms - window_ms) // res
        p_hi = end_ms // res
        if p_lo < entry.p0 or p_hi > entry.watermark_p:
            return None
        if func in ROLLUP_COUNTER_FUNCS:
            if not entry.is_counter or p_lo - 1 < entry.p0:
                return None
        return (p_lo, p_hi)

    def serve_view(self, key: tuple, func: str | None, window_ms: int,
                   start_ms: int, end_ms: int, step_ms: int):
        """Serve-time view for RollupServeExec: re-checks coverage against
        the LIVE entry (it may have been rebuilt, retired, or its
        watermark may no longer cover a moved live edge) and returns the
        device arrays plus local period indexing, or None -> the exec
        falls back to the raw path."""
        end_ms = start_ms + ((end_ms - start_ms) // step_ms) * step_ms
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            rng = self._eligible_locked(entry, func, window_ms, start_ms,
                                        end_ms)
            if rng is None:
                return None
            res = entry.resolution_ms
            if step_ms % res or window_ms % res or start_ms % res:
                return None
            entry.serves += 1
            entry.last_hit_s = time.time()
        p_lo, p_hi = rng
        # device arrays are NOT fetched here: the exec stages them under
        # its "stage" phase span so upload cost lands in the decomposition
        return {
            "entry": entry,
            "labels": list(entry.labels),
            "resolution_ms": res,
            "p_lo": p_lo,
            "p_hi": p_hi,
            "p0": entry.p0,
            "alloc_p": entry.alloc_p,
            "win_p": window_ms // res,
            "step_p": step_ms // res,
        }


def _rollup_ledger_walker(manager: "RollupManager") -> int:
    with manager._lock:
        return sum(e._dev_nbytes for e in manager._entries.values())
