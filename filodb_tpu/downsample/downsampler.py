"""Downsampling (reference core/.../downsample/: ChunkDownsampler.scala:38
dMin/dMax/dSum/dCount/dAvg/tTime ADT, ShardDownsampler.scala:40 ingest-time
emission at flush, DownsampledTimeSeriesStore query-side column rewrite
``min_over_time(m) -> m::min``; batch job: spark-jobs DownsamplerMain).

TPU-native reframing: downsampling a chunk is a vectorized period-reduce
over its sample arrays (numpy host-side at flush; the data is already
columnar). Downsampled series land in a separate dataset (e.g. ``ds_5m``)
with a gauge-like multi-column schema {min,max,sum,count,avg}; the query
planner picks the column by function (column rewrite) when serving from a
downsample dataset.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..core.records import RecordBatch, SeriesBatch
from ..core.schemas import Column, ColumnType, Schema

# the downsample schema: one row per period with all reduced columns
DS_GAUGE = Schema(
    "ds-gauge",
    [
        Column("timestamp", ColumnType.TIMESTAMP),
        Column("min", ColumnType.DOUBLE),
        Column("max", ColumnType.DOUBLE),
        Column("sum", ColumnType.DOUBLE),
        Column("count", ColumnType.DOUBLE),
        Column("avg", ColumnType.DOUBLE),
    ],
    "avg",
)

# register in the global schema registry so persisted ds chunks recover
# (recover_shard resolves schemas by name)
from ..core.schemas import SCHEMAS as _SCHEMAS

_SCHEMAS.setdefault(DS_GAUGE.name, DS_GAUGE)

# query-side column rewrite (reference DownsampledTimeSeriesShard column
# selection, doc/downsampling.md:89-96)
FUNC_TO_DS_COLUMN = {
    "min_over_time": "min",
    "max_over_time": "max",
    "sum_over_time": "sum",
    "count_over_time": "count",
    "avg_over_time": "avg",
    "last": "avg",
    "last_over_time": "avg",
}


def downsample_samples(ts: np.ndarray, vals: np.ndarray, period_ms: int):
    """Reduce one series' samples into per-period rows.

    Periods are aligned to epoch multiples of period_ms; the emitted
    timestamp is the period end (reference tTime semantics). Vectorized via
    np.add.reduceat on period boundaries.
    """
    if len(ts) == 0:
        empty = np.empty(0)
        return np.empty(0, dtype=np.int64), {k: empty for k in ("min", "max", "sum", "count", "avg")}
    period = (ts // period_ms).astype(np.int64)
    # boundaries where the period changes
    idx = np.nonzero(np.diff(period, prepend=period[0] - 1))[0]
    keep = ~np.isnan(vals)
    # reduceat needs NaN-safe values
    v0 = np.where(keep, vals, 0.0)
    sums = np.add.reduceat(v0, idx)
    counts = np.add.reduceat(keep.astype(np.float64), idx)
    mins = np.minimum.reduceat(np.where(keep, vals, np.inf), idx)
    maxs = np.maximum.reduceat(np.where(keep, vals, -np.inf), idx)
    out_ts = (period[idx] + 1) * period_ms - 1
    has = counts > 0
    avg = np.where(has, sums / np.maximum(counts, 1), np.nan)
    return out_ts[has], {
        "min": mins[has],
        "max": maxs[has],
        "sum": sums[has],
        "count": counts[has],
        "avg": avg[has],
    }


@dataclass
class ShardDownsampler:
    """Ingest-time downsampler: at flush, reduce each sealed chunk and feed
    the downsample dataset (reference ShardDownsampler emits downsample
    records during doFlushSteps)."""

    target_memstore: object
    target_dataset: str
    periods_ms: tuple[int, ...] = (300_000, 3_600_000)  # 5m, 1h

    def dataset_for(self, period_ms: int) -> str:
        return f"{self.target_dataset}_{period_ms // 60000}m"

    def _shard(self, ds: str, shard_num: int):
        from ..core.schemas import Dataset

        try:
            return self.target_memstore.shard(ds, shard_num)
        except KeyError:
            self.target_memstore.setup(Dataset(ds, schemas=[DS_GAUGE]), [shard_num])
            return self.target_memstore.shard(ds, shard_num)

    def downsample_chunks(self, shard_num: int, part, chunks) -> int:
        if part.schema.has_histogram:
            return self._downsample_histogram(shard_num, part, chunks)
        n = 0
        col = part.schema.value_column
        c0 = part.schema.column(col)
        if c0.ctype != ColumnType.DOUBLE:
            return 0
        for period in self.periods_ms:
            ts_parts, val_parts = [], []
            for c in chunks:
                ts_parts.append(c.column("timestamp"))
                val_parts.append(c.column(col).astype(np.float64))
            ts = np.concatenate(ts_parts)
            vals = np.concatenate(val_parts)
            out_ts, cols = downsample_samples(ts, vals, period)
            if len(out_ts) == 0:
                continue
            ds = self.dataset_for(period)
            sb = SeriesBatch(DS_GAUGE, dict(part.tags), out_ts, cols)
            self._shard(ds, shard_num).ingest_series(sb)
            n += len(out_ts)
        return n


def last_per_period(ts: np.ndarray, period_ms: int):
    """Indices of the last sample in each aligned period + period-end ts
    (reference hLast/dLast downsamplers for cumulative schemas)."""
    if len(ts) == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    period = (ts // period_ms).astype(np.int64)
    starts = np.nonzero(np.diff(period, prepend=period[0] - 1))[0]
    last_idx = np.concatenate([starts[1:] - 1, [len(ts) - 1]])
    out_ts = (period[last_idx] + 1) * period_ms - 1
    return last_idx, out_ts


def _downsample_histogram(self, shard_num: int, part, chunks) -> int:
    """Cumulative histograms downsample by taking the LAST sample of each
    period for every column (hLast/dLast — cumulative values carry the
    whole period's information); emitted into the same prom-histogram
    schema so quantile queries work unchanged on downsample datasets."""
    ts_parts = [c.column("timestamp") for c in chunks]
    if not ts_parts:
        return 0
    ts = np.concatenate(ts_parts)
    col_names = [c.name for c in part.schema.columns if c.name != "timestamp"]
    cols = {
        name: np.concatenate([c.column(name) for c in chunks]) for name in col_names
    }
    n = 0
    for period in self.periods_ms:
        last_idx, out_ts = last_per_period(ts, period)
        if len(out_ts) == 0:
            continue
        values = {name: arr[last_idx] for name, arr in cols.items()}
        sb = SeriesBatch(part.schema, dict(part.tags), out_ts, values,
                         bucket_les=part.bucket_les)
        self._shard(self.dataset_for(period), shard_num).ingest_series(sb)
        n += len(out_ts)
    return n


ShardDownsampler._downsample_histogram = _downsample_histogram


def _value_columns(schemas: dict) -> dict[str, str]:
    """{schema_name: value_column} for DOUBLE-valued schemas — the only
    schema facts the scan+reduce phase needs, shipped to workers explicitly
    so runtime-registered schemas survive the spawn boundary."""
    return {
        name: s.value_column
        for name, s in schemas.items()
        if s.value_column and s.column(s.value_column).ctype == ColumnType.DOUBLE
    }


def _downsample_shard_records(store, dataset: str, shard_num: int, periods_ms,
                              value_cols: dict[str, str]):
    """Scan one shard's persisted chunks and reduce each into downsample
    records: [(period_ms, tags, out_ts, reduced_columns)]. Pure read+compute
    — safe to run in a worker process (the Spark-executor analog)."""
    from ..core.encodings import decode

    out = []
    for header, schema_name, encs in store.read_chunks(dataset, shard_num):
        vcol = value_cols.get(schema_name)
        if vcol is None:
            continue
        cols = dict(zip(header["cols"], encs))
        if vcol not in cols:
            continue
        ts = decode(cols["timestamp"])
        vals = decode(cols[vcol]).astype(np.float64)
        for period in periods_ms:
            out_ts, reduced = downsample_samples(ts, vals, period)
            if len(out_ts):
                out.append((period, dict(header["tags"]), out_ts, reduced))
    return out


def _downsample_shard_worker(store_root: str, dataset: str, shard_num: int,
                             periods_ms, value_cols: dict[str, str]):
    """Process-pool entry: opens its own store handle (file-backed, read
    path is process-safe) and returns the reduced records."""
    from ..store.columnstore import LocalColumnStore

    return shard_num, _downsample_shard_records(
        LocalColumnStore(store_root), dataset, shard_num, tuple(periods_ms), value_cols
    )


def batch_downsample(store, memstore, dataset: str, shard_nums, target_memstore,
                     downsampler: ShardDownsampler, processes: int = 0) -> int:
    """Batch job analog of spark-jobs DownsamplerMain: scan persisted chunks
    from the column store and (re)build downsample datasets.

    ``processes`` >= 1 distributes the scan+reduce phase over a spawn-based
    process pool, one task per shard (the reference distributes Cassandra
    token ranges over Spark executors); each shard's records ingest as its
    worker finishes. Requires a LocalColumnStore (workers reopen it by root
    path); other stores fall back in-process with a warning."""
    import logging

    from ..core.schemas import SCHEMAS

    shard_nums = list(shard_nums)
    value_cols = _value_columns(SCHEMAS)
    n = 0

    def ingest(shard_num, records):
        nonlocal n
        for period, tags, out_ts, reduced in records:
            ds = downsampler.dataset_for(period)
            sb = SeriesBatch(DS_GAUGE, tags, out_ts, reduced)
            downsampler._shard(ds, shard_num).ingest_series(sb)
            n += len(out_ts)

    use_pool = processes >= 1
    if use_pool and getattr(store, "root", None) is None:
        logging.getLogger(__name__).warning(
            "batch_downsample: store has no filesystem root; --processes "
            "requested but running in-process"
        )
        use_pool = False
    if use_pool:
        import multiprocessing
        from concurrent.futures import ProcessPoolExecutor, as_completed

        # spawn, not fork: a forked child inherits the parent's initialized
        # JAX/TPU backend state and can wedge on first device touch
        ctx = multiprocessing.get_context("spawn")
        with ProcessPoolExecutor(max_workers=min(max(processes, 1), len(shard_nums) or 1),
                                 mp_context=ctx) as pool:
            futs = [
                pool.submit(_downsample_shard_worker, store.root, dataset, s,
                            tuple(downsampler.periods_ms), value_cols)
                for s in shard_nums
            ]
            for f in as_completed(futs):
                ingest(*f.result())
    else:
        for s in shard_nums:
            ingest(s, _downsample_shard_records(
                store, dataset, s, downsampler.periods_ms, value_cols))
    return n
