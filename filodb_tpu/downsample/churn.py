"""Label-churn finder (reference spark-jobs
``LabelChurnFinder``: a Spark job that scans the partkey tables and builds
HyperLogLog sketches of per-label distinct-value counts — total vs active —
to find labels whose values churn, the classic cardinality-killer).

Batch-job shape mirrors the batch downsampler: per-shard scans build local
sketches concurrently; HLL registers merge associatively at the driver
(numpy ``maximum``), exactly the Spark executor → driver merge. Output is a
report of ``(workspace, namespace, label)`` rows where
``total_distinct / active_distinct`` exceeds a churn threshold: a label
with 50k historical values but 200 live ones is re-keying itself (pod
hashes, build ids) and deserves a quota or a drop rule.
"""

from __future__ import annotations

import hashlib
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Iterable, Sequence

import numpy as np

from ..core.schemas import METRIC_TAG

DEFAULT_PRECISION = 12  # 4096 registers, ~1.6% standard error


class HllSketch:
    """Vectorized HyperLogLog over uint8 registers (stable 64-bit hashes via
    blake2b so sketches merge across processes/hosts)."""

    __slots__ = ("p", "m", "regs")

    def __init__(self, precision: int = DEFAULT_PRECISION):
        self.p = precision
        self.m = 1 << precision
        self.regs = np.zeros(self.m, np.uint8)

    @staticmethod
    def _hash64(value: str) -> int:
        return int.from_bytes(
            hashlib.blake2b(value.encode(), digest_size=8).digest(), "little"
        )

    def add(self, value: str) -> None:
        h = self._hash64(value)
        idx = h >> (64 - self.p)
        rest = h & ((1 << (64 - self.p)) - 1)
        # rank = leading zeros of the remaining bits + 1
        rank = (64 - self.p) - rest.bit_length() + 1
        if rank > self.regs[idx]:
            self.regs[idx] = rank

    def add_all(self, values: Iterable[str]) -> None:
        for v in values:
            self.add(v)

    def merge(self, other: "HllSketch") -> "HllSketch":
        assert self.p == other.p
        np.maximum(self.regs, other.regs, out=self.regs)
        return self

    def estimate(self) -> float:
        m = float(self.m)
        alpha = 0.7213 / (1.0 + 1.079 / m)
        est = alpha * m * m / float(np.sum(np.exp2(-self.regs.astype(np.float64))))
        if est <= 2.5 * m:
            zeros = int(np.count_nonzero(self.regs == 0))
            if zeros:
                return m * float(np.log(m / zeros))  # linear counting
        return est


@dataclass
class ChurnRecord:
    """One (shard-key prefix, label) churn finding."""

    prefix: tuple[str, ...]  # (_ws_, _ns_)
    label: str
    total: int  # distinct values over all series ever persisted
    active: int  # distinct values among currently-live series

    @property
    def ratio(self) -> float:
        return self.total / max(self.active, 1)


class LabelChurnFinder:
    """Scan a column store's partkeys and sketch per-label churn.

    ``active_ms`` defines liveness: a series is active when its persisted
    end time is within ``active_ms`` of ``now_ms`` (end-time updates ride
    the flush path, store/flush.py)."""

    def __init__(self, store, dataset: str, shard_nums: Sequence[int],
                 now_ms: int, active_ms: int = 2 * 3_600_000,
                 precision: int = DEFAULT_PRECISION,
                 shard_key_columns: tuple[str, ...] = ("_ws_", "_ns_")):
        self.store = store
        self.dataset = dataset
        self.shard_nums = list(shard_nums)
        self.now_ms = now_ms
        self.active_ms = active_ms
        self.precision = precision
        self.skc = shard_key_columns

    # -- per-shard map phase ---------------------------------------------

    def _scan_shard(self, shard: int) -> dict[tuple, tuple[HllSketch, HllSketch]]:
        """(prefix, label) -> (total sketch, active sketch) for one shard."""
        out: dict[tuple, tuple[HllSketch, HllSketch]] = {}
        cutoff = self.now_ms - self.active_ms
        for rec in self.store.read_partkeys(self.dataset, shard):
            tags = rec["tags"]
            prefix = tuple(tags.get(c, "") for c in self.skc)
            is_active = rec.get("end", 0) >= cutoff
            for label, value in tags.items():
                if label in self.skc or label == METRIC_TAG:
                    continue
                key = (prefix, label)
                pair = out.get(key)
                if pair is None:
                    pair = (HllSketch(self.precision), HllSketch(self.precision))
                    out[key] = pair
                pair[0].add(value)
                if is_active:
                    pair[1].add(value)
        return out

    # -- driver-side reduce phase ----------------------------------------

    def scan(self, workers: int = 4) -> dict[tuple, tuple[HllSketch, HllSketch]]:
        merged: dict[tuple, tuple[HllSketch, HllSketch]] = {}
        with ThreadPoolExecutor(max_workers=max(1, min(workers, len(self.shard_nums) or 1)),
                                thread_name_prefix="filodb-churn") as pool:
            for shard_map in pool.map(self._scan_shard, self.shard_nums):
                for key, (tot, act) in shard_map.items():
                    have = merged.get(key)
                    if have is None:
                        merged[key] = (tot, act)
                    else:
                        have[0].merge(tot)
                        have[1].merge(act)
        return merged

    def report(self, min_total: int = 100, min_ratio: float = 2.0,
               workers: int = 4) -> list[ChurnRecord]:
        """Labels with ≥min_total distinct values and total/active ≥
        min_ratio, worst churn first."""
        out = []
        for (prefix, label), (tot, act) in self.scan(workers).items():
            total = int(round(tot.estimate()))
            active = int(round(act.estimate()))
            if total < min_total:
                continue
            rec = ChurnRecord(prefix, label, total, active)
            if rec.ratio >= min_ratio:
                out.append(rec)
        out.sort(key=lambda r: -r.ratio)
        return out
