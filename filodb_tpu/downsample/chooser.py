"""Workload-chosen rollup set (Storyboard's framing): the summary tier is
trained on the OBSERVED workload, not a fixed 5m/1h ladder.

The chooser is a standing job over the querylog ring (obs/querylog.py —
exemplar-level records with PromQL fingerprints and per-phase costs). A
fingerprint that keeps re-appearing with a long span (a dashboard panel
refreshing a month-long quantile, say) earns a rollup: the chooser
re-parses the recorded PromQL into its logical plan, extracts the
selector + range-function shape, picks the COARSEST ladder resolution
that divides both the query's step and window (maximum summary
compression that still serves the shape exactly), and registers it with
:class:`~filodb_tpu.downsample.rollup.RollupManager`. Chooser-owned
entries whose selectors stop being queried are retired after an idle
period, so the rollup set tracks dashboards as they change.

Config-pinned entries (``origin != "chooser"``) are never retired here.
"""

from __future__ import annotations

import threading
import time

from ..metrics import record_rollup_chooser
from ..obs.querylog import QUERY_LOG
from ..query import logical as L
from ..query.promql import query_range_to_logical_plan
from .rollup import ROLLUP_FUNCS, RollupManager


class RollupChooser:
    """Decides WHICH selectors get rollups at WHAT resolutions from
    querylog evidence. ``tick()`` is the synchronous decision pass (tests
    call it directly); ``start()`` runs it on a standing thread."""

    def __init__(self, manager: RollupManager,
                 resolutions_ms=(300_000, 3_600_000),
                 min_count: int = 3, min_span_ms: int = 86_400_000,
                 idle_s: float = 3600.0, interval_s: float = 30.0,
                 log_limit: int = 512):
        self.manager = manager
        self.resolutions_ms = tuple(sorted(int(r) for r in resolutions_ms))
        self.min_count = int(min_count)
        self.min_span_ms = int(min_span_ms)
        self.idle_s = float(idle_s)
        self.interval_s = float(interval_s)
        self.log_limit = int(log_limit)
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.decisions: list[dict] = []  # most recent pass, for /debug

    # -- standing thread ---------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="rollup-chooser", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the chooser must not die
                pass

    # -- decision pass -----------------------------------------------------

    def tick(self, now_s: float | None = None) -> list[dict]:
        """One decision pass: add rollups for repeated long-range
        fingerprints, retire idle chooser-owned entries. Returns the
        decisions made (also kept on ``self.decisions``)."""
        if now_s is None:
            now_s = time.time()
        decisions: list[dict] = []
        by_fp: dict[str, list[dict]] = {}
        for rec in QUERY_LOG.entries(limit=self.log_limit):
            grid = rec.get("grid") or {}
            span_ms = (grid.get("end_s", 0) - grid.get("start_s", 0)) * 1000
            if span_ms < self.min_span_ms or rec.get("status") == "error":
                continue
            by_fp.setdefault(rec.get("fingerprint", ""), []).append(rec)
        for fp, recs in by_fp.items():
            if not fp or len(recs) < self.min_count:
                continue
            rec = recs[0]  # newest
            shape = self._servable_shape(rec)
            if shape is None:
                continue
            filters, step_ms, window_ms = shape
            res = self._pick_resolution(step_ms, window_ms)
            if res is None:
                continue
            dataset = rec.get("dataset", "")
            if self.manager.has(dataset, filters, res):
                continue
            try:
                self.manager.ensure(dataset, filters, res,
                                    origin="chooser", build=True)
            except ValueError:
                continue  # entry limit — keep what we have
            record_rollup_chooser("add")
            decisions.append({
                "action": "add", "fingerprint": fp, "dataset": dataset,
                "resolution_ms": res, "count": len(recs),
                "promql": rec.get("promql"),
            })
        # retire chooser-owned entries that went idle
        for entry in self.manager.entries():
            if entry.origin != "chooser":
                continue
            last = max(entry.last_hit_s, entry.created_s)
            if now_s - last > self.idle_s:
                if self.manager.retire(entry.dataset, entry.filters,
                                       entry.resolution_ms, reason="idle"):
                    record_rollup_chooser("retire")
                    decisions.append({
                        "action": "retire",
                        "dataset": entry.dataset,
                        "selector": [list(f) for f in entry.filters_key()],
                        "resolution_ms": entry.resolution_ms,
                        "idle_s": now_s - last,
                    })
        self.decisions = decisions
        return decisions

    def _pick_resolution(self, step_ms: int, window_ms: int) -> int | None:
        """Coarsest ladder resolution that divides step AND window — the
        same divisibility rule the planner's substitution check applies,
        so a chosen rollup is guaranteed eligible for the training
        fingerprint's shape."""
        best = None
        for res in self.resolutions_ms:
            if (step_ms % res == 0 and window_ms % res == 0
                    and window_ms >= res):
                best = res
        return best

    def _servable_shape(self, rec: dict):
        """Re-parse the recorded PromQL and extract (filters, step_ms,
        window_ms) when the plan is a rollup-servable shape: a range
        function in ROLLUP_FUNCS under any stack of aggregates / instant
        functions (histogram_quantile over rate'd buckets included).
        Returns None for everything else."""
        grid = rec.get("grid") or {}
        promql = rec.get("promql")
        if not promql or not grid:
            return None
        try:
            plan = query_range_to_logical_plan(
                promql, grid["start_s"], grid["end_s"],
                max(grid.get("step_ms", 0) // 1000, 1),
            )
        except Exception:  # noqa: BLE001 — unparsable record, skip
            return None
        node = plan
        while isinstance(node, (L.Aggregate, L.ApplyInstantFunction)):
            node = node.inner
        if not isinstance(node, L.PeriodicSeriesWithWindowing):
            return None
        if node.function not in ROLLUP_FUNCS or node.offset_ms:
            return None
        if node.function_args and node.function != "quantile_over_time":
            return None
        return (node.raw.filters, int(node.step_ms), int(node.window_ms))
