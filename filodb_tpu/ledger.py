"""Device-resource ledger: ONE live accounting object every HBM consumer
debits and credits (reference analog: MemFactory/BlockManager free-block
accounting surfaced through FilodbMetrics gauges — resource attribution
lives INSIDE the allocation boundary, never bolted on after the fact).

Consumers register an *account* per cache (per-shard staging caches, the
cross-shard ``SuperblockCache``, the persistent XLA compile cache) with:

- a ``kind`` label — the ``filodb_device_bytes{kind=...}`` dimension;
- a *walker*: a function recomputing the owner's TRUE footprint from the
  cache itself (``staged_nbytes`` over live entries). The ledger's
  ``verify()`` compares every live account's running balance against a cold
  walk — the drift check the soak test pins to zero, and the leak detector
  ``/debug/resources`` serves in production.

Accounts hold their owner only through a weakref: a shut-down memstore's
caches must not be pinned by process-global accounting (same discipline as
``register_shard_stats_collector``). An account collected while still
holding bytes is itself the signal a cache died without releasing — counted
in ``filodb_device_leaked_bytes_total{kind}``.

Nothing here touches device values: balances come from ``.nbytes`` metadata
and the walkers read ``.nbytes`` only, so accounting adds zero host syncs —
the warm fused query stays exactly ONE kernel dispatch with accounting on.
"""

from __future__ import annotations

import threading
import time
import weakref

from .metrics import REGISTRY


class LedgerAccount:
    """One consumer's balance within the ledger. ``alloc``/``free`` are the
    debit/credit pair; ``sync()`` (self-syncing accounts only, e.g. the XLA
    compile cache whose writes we don't control) re-reads the walker."""

    __slots__ = ("kind", "name", "synced", "_owner_ref", "_walker",
                 "_device_walker", "_lock",
                 "bytes", "allocs", "frees", "created")

    def __init__(self, kind: str, name: str, owner_ref, walker, synced: bool,
                 device_walker=None):
        self.kind = kind
        self.name = name
        self.synced = synced
        self._owner_ref = owner_ref
        self._walker = walker
        self._device_walker = device_walker
        self._lock = threading.Lock()
        self.bytes = 0
        self.allocs = 0
        self.frees = 0
        self.created = time.time()

    def alloc(self, nbytes: int, count: int = 1) -> None:
        if nbytes <= 0 and count <= 0:
            return
        with self._lock:
            self.bytes += int(nbytes)
            self.allocs += count
        REGISTRY.counter("filodb_device_alloc", kind=self.kind).inc(count)
        REGISTRY.counter("filodb_device_alloc_bytes", kind=self.kind).inc(int(nbytes))

    def free(self, nbytes: int, reason: str = "drop", count: int = 1) -> None:
        """Credit released bytes. ``reason``: ``evict`` (budget eviction),
        ``invalidate`` (ingest invalidation / wholesale clear), ``replace``
        (entry superseded by a rebuild/repair), ``drop`` (explicit
        removal)."""
        if nbytes <= 0 and count <= 0:
            return
        with self._lock:
            self.bytes -= int(nbytes)
            self.frees += count
        REGISTRY.counter("filodb_device_free", kind=self.kind, reason=reason).inc(count)
        REGISTRY.counter(
            "filodb_device_free_bytes", kind=self.kind, reason=reason
        ).inc(int(nbytes))

    def walk(self) -> int | None:
        """Cold recount of the owner's true footprint (None when the owner
        is gone or has no walker)."""
        if self._walker is None:
            return None
        owner = self._owner_ref() if self._owner_ref is not None else None
        if self._owner_ref is not None and owner is None:
            return None
        try:
            return int(self._walker(owner) if self._owner_ref is not None
                       else self._walker())
        except Exception:  # noqa: BLE001 — a sick walker must not kill /metrics
            return None

    def sync(self) -> None:
        """Self-syncing accounts: balance = walker() (the compile cache —
        jax writes it, we only observe)."""
        got = self.walk()
        if got is not None:
            with self._lock:
                self.bytes = got

    def walk_devices(self) -> dict | None:
        """Per-device byte split of this account's balance (sharded caches
        only; None when the owner is gone or the account has no device
        walker). Metadata-only, like walk()."""
        if self._device_walker is None:
            return None
        owner = self._owner_ref() if self._owner_ref is not None else None
        if self._owner_ref is not None and owner is None:
            return None
        try:
            return self._device_walker(owner)
        except Exception:  # noqa: BLE001 — a sick walker must not kill /metrics
            return None

    def alive(self) -> bool:
        return self._owner_ref is None or self._owner_ref() is not None


class DeviceLedger:
    """Process-global registry of LedgerAccounts; exposes the per-kind
    ``filodb_device_bytes`` gauges as a scrape-time collector and serves
    the drift check (``verify``) behind ``/debug/resources``."""

    KINDS = ("staged_block", "superblock", "compile_cache",
             "standing_state", "index_postings", "rollup")

    def __init__(self):
        self._lock = threading.Lock()
        self._accounts: dict[int, LedgerAccount] = {}
        self._next_id = 0
        self._seen_kinds: set[str] = set(self.KINDS)
        self._seen_devices: set[tuple[str, str]] = set()
        # dead-owner notices: weakref callbacks run mid-GC (possibly inside
        # OTHER locks), so they only append to this list — list.append is
        # atomic under the GIL — and real cleanup happens lazily in _reap()
        self._dead: list[tuple[int, str, int]] = []

    def register(self, owner, kind: str, walker=None, name: str = "",
                 synced: bool = False, device_walker=None) -> LedgerAccount:
        """Create an account for ``owner`` (held weakly). ``walker(owner)``
        recomputes the true byte footprint for the drift check. ``owner``
        may be None for keyed module-level accounts (pass ``synced=True``
        and a zero-arg walker). ``device_walker(owner)`` optionally returns
        a per-device byte split (mesh-sharded caches) published as
        ``filodb_device_bytes{kind,device}``."""
        with self._lock:
            aid = self._next_id
            self._next_id += 1
        acct_holder: list[LedgerAccount] = []

        def on_dead(_ref, _aid=aid):
            acct = acct_holder[0] if acct_holder else None
            # self-syncing accounts only OBSERVE external storage (e.g. the
            # compile-cache dir); their owner dying releases nothing, so a
            # replaced probe must not fire the leak alarm
            leaked = acct.bytes if acct is not None and not acct.synced else 0
            self._dead.append((_aid, kind, leaked))

        ref = weakref.ref(owner, on_dead) if owner is not None else None
        acct = LedgerAccount(kind, name, ref, walker, synced,
                             device_walker=device_walker)
        acct_holder.append(acct)
        with self._lock:
            self._accounts[aid] = acct
        return acct

    def _reap(self) -> None:
        """Lazily process dead-owner notices: drop their accounts and count
        any unreleased balance as leaked bytes."""
        while self._dead:
            try:
                aid, kind, leaked = self._dead.pop()
            except IndexError:  # racer drained it
                return
            with self._lock:
                self._accounts.pop(aid, None)
            if leaked > 0:
                REGISTRY.counter("filodb_device_leaked_bytes", kind=kind).inc(leaked)

    def _live_accounts(self) -> list[LedgerAccount]:
        self._reap()
        with self._lock:
            accts = list(self._accounts.values())
        return [a for a in accts if a.alive()]

    def balances(self) -> dict[str, int]:
        """Per-kind byte balance over live accounts (self-syncing accounts
        refresh first)."""
        out: dict[str, int] = {}
        for a in self._live_accounts():
            if a.synced:
                a.sync()
            out[a.kind] = out.get(a.kind, 0) + a.bytes
        return out

    def verify(self) -> dict:
        """Drift check: ledger balance vs a cold walk of every live cache.
        Returns ``{"kinds": {kind: {"ledger": b, "actual": b, "drift": d}},
        "accounts": [...]}`` — drift must be zero for debit/credit kinds
        (self-syncing kinds are zero by construction)."""
        kinds: dict[str, dict] = {}
        accounts = []
        for a in self._live_accounts():
            if a.synced:
                a.sync()
            actual = a.walk()
            slot = kinds.setdefault(a.kind, {"ledger": 0, "actual": 0, "drift": 0})
            slot["ledger"] += a.bytes
            if actual is not None:
                slot["actual"] += actual
                slot["drift"] += a.bytes - actual
            accounts.append({
                "kind": a.kind,
                "name": a.name,
                "bytes": a.bytes,
                "actual": actual,
                "allocs": a.allocs,
                "frees": a.frees,
            })
        return {"kinds": kinds, "accounts": accounts}

    def device_balances(self) -> dict[tuple[str, str], int]:
        """Per-(kind, device) byte balances over live accounts that expose a
        device split (mesh-sharded caches)."""
        out: dict[tuple[str, str], int] = {}
        for a in self._live_accounts():
            split = a.walk_devices()
            if not split:
                continue
            for dev, b in split.items():
                key = (a.kind, str(dev))
                out[key] = out.get(key, 0) + int(b)
        return out

    def publish(self) -> None:
        """Scrape-time collector: refresh the per-kind gauges — plus the
        per-device breakdown for kinds whose caches hold mesh-sharded
        entries. Kinds/devices seen once keep publishing (possibly 0) so
        dashboards don't see series vanish when a cache empties."""
        balances = self.balances()
        self._seen_kinds |= set(balances)
        for kind in self._seen_kinds:
            REGISTRY.gauge("filodb_device_bytes", kind=kind).set(
                float(balances.get(kind, 0))
            )
        dev_balances = self.device_balances()
        self._seen_devices |= set(dev_balances)
        for kind, dev in self._seen_devices:
            REGISTRY.gauge("filodb_device_bytes", kind=kind, device=dev).set(
                float(dev_balances.get((kind, dev), 0))
            )


LEDGER = DeviceLedger()
REGISTRY.register_collector("device_ledger", LEDGER.publish)
