"""Metrics, tracing, profiling (reference aux subsystems, SURVEY.md §5:
core/.../metrics/FilodbMetrics.scala Kamon facade + OTel export;
Kamon spans threading ExecPlan.execute; standalone SimpleProfiler.java:19
sampling profiler).

- ``Registry``: counters / gauges / histograms with Prometheus text
  exposition (served at /metrics by the HTTP API), plus scrape-time
  collectors for gauges that must be refreshed on demand.
- ``span`` / ``Span`` / ``TraceContext``: real tracing. Spans carry
  (trace_id, span_id, parent_id) plus tags and per-node QueryStats; the
  context is explicitly capturable (``current_span``) and re-activatable
  (``activate``) so a trace survives thread-pool hops, and serializable
  (``Span.to_dict`` / ``from_dict``) so remote children return their span
  trees in-band and the origin stitches them under the dispatching span.
- ``SlowQueryLog``: ring buffer of queries exceeding a configured
  threshold, each entry carrying the rendered trace tree (served at
  /debug/slow_queries and counted in /metrics).
- ``SamplingProfiler``: periodic stack sampler over all threads (the
  SimpleProfiler analog) with top-of-stack aggregation.
"""

from __future__ import annotations

import bisect
import contextlib
import sys
import threading
import time
import traceback
import uuid
from collections import Counter, deque
from dataclasses import dataclass, field


class Counter_:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0):
        with self._lock:
            self.value += amount


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float):
        self.value = v


class Histogram:
    """Fixed-bucket latency histogram (seconds)."""

    BOUNDS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

    def __init__(self):
        self.counts = [0] * (len(self.BOUNDS) + 1)
        self.sum = 0.0
        self.total = 0
        # last exemplar per bucket: (labels dict, value, unix_ts) — rendered
        # on OpenMetrics bucket lines so a spiking latency bucket links
        # straight to its trace (and through it the slow-query log)
        self.exemplars: list = [None] * (len(self.BOUNDS) + 1)
        self._lock = threading.Lock()

    def observe(self, v: float, exemplar: dict | None = None):
        i = bisect.bisect_left(self.BOUNDS, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.total += 1
            if exemplar:
                self.exemplars[i] = (dict(exemplar), float(v), time.time())


class MicroHistogram(Histogram):
    """Histogram with sub-millisecond bounds for host paths that complete in
    microseconds (index lookups): the standard bounds start at 1ms and would
    collapse the whole distribution into the first bucket."""

    BOUNDS = (5e-6, 1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
              1e-3, 5e-3, 2.5e-2, 0.1, 0.5)


def escape_label_value(v) -> str:
    """Prometheus text-format label escaping: backslash, double-quote and
    newline must be escaped or the exposition line is unparseable."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def escape_help(v: str) -> str:
    """# HELP line escaping (backslash and newline only, per the spec)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


# help text per metric family (the *registered* name: counters WITHOUT the
# _total suffix the exposition appends). tools/check_metrics.py lints that
# every family emitted in code is documented in doc/observability.md —
# this table feeds the # HELP lines of the same families.
HELP_TEXTS: dict[str, str] = {
    "filodb_queries": "Queries served, per dataset (coalesced followers included).",
    "filodb_query_latency_seconds": "End-to-end query latency.",
    "filodb_slow_queries": "Queries over the slow-query threshold (see /debug/slow_queries).",
    "filodb_breaker_transitions": "Circuit-breaker state transitions per endpoint.",
    "filodb_breaker_state": "Breaker state per endpoint: 0 closed, 0.5 half-open, 1 open.",
    "filodb_remote_retries": "Remote-child dispatch retries per endpoint.",
    "filodb_partial_results": "Queries answered with merged partials (children lost).",
    "filodb_shard_reassignments": "Shard reassignment outcomes from ingestion errors.",
    "filodb_fused_fallback": "Fused single-dispatch aggregates delegated to the reference tree, by reason.",
    "filodb_stage_cache_insert_dropped": "Staged blocks not cached because ingest effects touched their range.",
    "filodb_superblock_maintenance": "Version-stale superblock maintenance outcomes (revalidate|extend|extend_abort|restage).",
    "filodb_downsample_claims": "Distributed-downsample claim lifecycle events.",
    "filodb_kernel_dispatch_seconds": "ops/ kernel dispatch latency, per kernel.",
    "filodb_jit_cache": "JIT compile-cache hits/misses per kernel.",
    "filodb_shard_partitions": "Live partitions per shard.",
    "filodb_shard_rows_ingested": "Rows ingested per shard.",
    "filodb_shard_rows_skipped": "Rows skipped per shard.",
    "filodb_shard_partitions_evicted": "Partitions evicted per shard.",
    "filodb_shard_chunks_flushed": "Chunks flushed per shard.",
    "filodb_tenant_ts_total": "Total series per tenant (ws/ns).",
    "filodb_tenant_ts_active": "Actively ingesting series per tenant (ws/ns).",
    "filodb_tenant_queries": "Queries attributed to the tenant resolved from query filters.",
    "filodb_admission": "Admission-control outcomes per tenant (admitted|shed_rate|shed_concurrency|shed_queue).",
    "filodb_batch_queries": "Fused dispatches submitted to the cross-query batching scheduler, per epilogue family.",
    "filodb_batch_dispatches": "Batching-scheduler group executions per family and outcome (batched|solo|fallback).",
    "filodb_batch_merged_windows": "Compatible window-groups re-merged into one mixed-window batched launch, per family.",
    "filodb_batch_queue_depth": "Fused dispatches currently collecting in open batch windows.",
    "filodb_tenant_query_seconds": "Wall-clock query seconds per tenant.",
    "filodb_tenant_kernel_seconds": "Device kernel-dispatch seconds per tenant.",
    "filodb_tenant_bytes_staged": "Bytes staged to device per tenant.",
    "filodb_device_bytes": "Live device bytes per ledger kind (staged_block|superblock|compile_cache|standing_state|index_postings|rollup).",
    "filodb_device_alloc": "Ledger debits (entries pinned) per kind.",
    "filodb_device_alloc_bytes": "Bytes debited to the device ledger per kind.",
    "filodb_device_free": "Ledger credits per kind and reason (evict|invalidate|replace|drop).",
    "filodb_device_free_bytes": "Bytes credited back to the device ledger per kind and reason.",
    "filodb_device_leaked_bytes": "Bytes held by ledger accounts whose cache died without releasing.",
    "filodb_self_scrapes": "Self-scrape cycles into the _system dataset.",
    "filodb_self_scrape_samples": "Samples ingested into the _system dataset by the self-scraper.",
    "filodb_standing_queries": "Registered standing queries by maintenance mode (delta|full).",
    "filodb_standing_refreshes": "Standing-query refreshes by outcome (retained|delta|full|reset|error).",
    "filodb_standing_refresh_seconds": "Standing-query refresh latency (classify + dispatch + render + fan-out).",
    "filodb_standing_steps": "Standing-query grid steps per refresh disposition (computed|retained).",
    "filodb_standing_subscribers": "Live push subscribers across all standing queries.",
    "filodb_standing_pushes": "Per-subscriber payload deliveries (sent) and stall drops (dropped).",
    "filodb_standing_promotions": "Standing-query lifecycle events (register|promote|demote).",
    "filodb_standing_rule_samples": "Samples written back into the memstore by recording rules.",
    "filodb_tpu_probe_healthy": "Last tpu-watch probe outcome (1 healthy, 0 not).",
    "filodb_tpu_probe_age_seconds": "Seconds since the last tpu-watch probe.",
    "filodb_tpu_probes": "tpu-watch probes attempted (from the watch log).",
    "filodb_tpu_probes_ok": "tpu-watch probes that found a healthy device.",
    "filodb_tpu_bench_attested": "tpu-watch attested benchmark measurements.",
    "filodb_query_phase_seconds": "Per-phase query latency decomposition (parse_plan|admission|stage|dispatch|transfer|render|other).",
    "filodb_query_path": "Queries by execution path (fused|fallback|tree|standing:delta|standing:full|standing:serve) per dataset.",
    "filodb_tenant_phase_seconds": "Per-phase query wall seconds attributed to the tenant (ws/ns).",
    "filodb_tenant_query_latency_seconds": "End-to-end query latency per tenant (the latency-SLO feed).",
    "filodb_http_responses": "HTTP API responses by status code and class (2xx|4xx|shed|5xx|stream_abort).",
    "filodb_render_seconds": "Result-body encode seconds per format (json-native|json-numpy JSON tiers, arrow peer frames).",
    "filodb_response_bytes": "Uncompressed result-body bytes sent per format (json|arrow).",
    "filodb_render_stream_stalls": "Streamed-render encoder waits on a device->host block (D2H the double-buffer failed to hide).",
    "filodb_querylog_entries": "Query-log ring depth (exemplar-level cost records retained).",
    "filodb_index_lookup_seconds": "Part-key index lookup latency by matcher cost class (eq|in|prefix|regex|neg).",
    "filodb_xla_compiles": "XLA compile events per kernel family (a dispatch that grew the jit cache).",
    "filodb_xla_compile_seconds": "Wall seconds spent in dispatches that compiled (trace+compile inclusive), per kernel family.",
    "filodb_xla_recompile_storms": "Recompile storms detected per kernel family (same family re-lowering past the threshold inside the window; /debug/kernels names the unstable dimension).",
    "filodb_xla_executables": "Live executables in the kernel observatory's registry.",
    "filodb_kernel_exec_dispatches": "Kernel dispatches accounted by the executable registry, per family.",
    "filodb_kernel_exec_device_seconds": "Per-dispatch device cost of warm (non-compiling) dispatches, per kernel family (host dispatch wall; exact block_until_ready deltas with kernel_obs.device_timing).",
    "filodb_compile_cache_hits": "Compile-cache hits by tier (in_process = warm jit cache, persistent = compile deserialized from the on-disk XLA cache).",
    "filodb_compile_cache_misses": "Compile-cache misses by tier (in_process = a compile happened, persistent = a fresh trace wrote a new on-disk entry).",
    "filodb_index_postings_bytes": "Host posting-bitmap footprint of the part-key index, per shard.",
    "filodb_index_device_staged_bytes": "Posting bitmaps staged to device (HBM) by the index's opt-in hot tier, per shard.",
    "filodb_index_dictionary_size": "Distinct (label, value) dictionary entries in the part-key index, per shard.",
    "filodb_rollup_entries": "Registered rollup entries (selector x resolution summary blocks) per dataset.",
    "filodb_rollup_maintenance": "Rollup maintainer outcomes (add|build|fold|rebuild|retire|error).",
    "filodb_rollup_serves": "Queries served from rollup blocks instead of raw samples, by kind (window|agg|hist_quantile).",
    "filodb_rollup_chooser": "Workload-chooser decisions (add|retire) over querylog fingerprints.",
    "filodb_superblock_pinned_bytes": "Superblock cache bytes pinned by standing queries (skipped by eviction).",
    "filodb_replica_selection": "Remote dispatches by which replica served (primary|sibling).",
    "filodb_replica_failovers": "Dispatches re-pinned away from a replica endpoint, by reason (breaker_open|endpoint_failure).",
    "filodb_replica_acks": "Per-replica ingest fan-out append outcomes (ok|error|skipped).",
    "filodb_replica_watermark_ms": "Per shard+replica ingest lag watermark (max acked sample timestamp, ms).",
    "filodb_rebalance": "Live shard rebalance outcomes (clean|replayed|rebuilt|damped|failed).",
    "filodb_rebalance_standing_moves": "Standing queries re-registered on a shard's new owner after a rebalance.",
    "filodb_alerts": "Alerting rules/labelsets by state (inactive|pending|firing).",
    "filodb_alert_eval_seconds": "Alert-rule evaluation latency (state machine + write-back per tick).",
    "filodb_alert_eval_failures": "Alert-rule evaluation failures, per rule (refresh errors included).",
    "filodb_alert_notify": "Alert notification deliveries per receiver and outcome (ok|retry|error|breaker_open).",
    "filodb_costmodel_error_ratio": "Cost-model prediction quality per completed query: max(predicted/realized, realized/predicted) device-seconds.",
    "filodb_prewarm": "Executable pre-warm attempts by outcome (ok|error): recurrence-ring keys trace+compiled off the serving path.",
}


class Registry:
    def __init__(self):
        self._metrics: dict[tuple[str, tuple], object] = {}
        # scrape-time collectors: keyed callbacks run at the top of expose()
        # to refresh gauges that mirror live state (per-shard stats etc.) —
        # ONE exposition path instead of handlers hand-rolling text
        self._collectors: dict[str, object] = {}
        self._help: dict[str, str] = {}
        self._lock = threading.Lock()

    def register_collector(self, key: str, fn) -> None:
        """Register (or replace) a zero-arg callback invoked at scrape time
        before rendering. Keyed so re-created servers replace, not stack."""
        with self._lock:
            self._collectors[key] = fn

    def unregister_collector(self, key: str) -> None:
        with self._lock:
            self._collectors.pop(key, None)

    def _get(self, cls, name: str, labels: dict | None):
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls()
                self._metrics[key] = m
            return m

    def counter(self, name: str, **labels) -> Counter_:
        return self._get(Counter_, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(Histogram, name, labels)

    def micro_histogram(self, name: str, **labels) -> MicroHistogram:
        """Histogram with µs-scale buckets (one family must use ONE bucket
        layout consistently — pick this or :meth:`histogram`, never both)."""
        return self._get(MicroHistogram, name, labels)

    def remove(self, name: str, **labels) -> bool:
        """Drop one series (a vanished tenant's gauges must not be exposed
        forever — TenantIngestionMetering ages them out on publish).
        Returns True when the series existed."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._metrics.pop(key, None) is not None

    def remove_matching(self, name: str, predicate) -> int:
        """Drop every series of ``name`` whose label dict satisfies
        ``predicate``; returns the count removed."""
        with self._lock:
            gone = [
                k for k in self._metrics
                if k[0] == name and predicate(dict(k[1]))
            ]
            for k in gone:
                del self._metrics[k]
        return len(gone)

    def counter_samples(self, *families: str) -> dict[str, float]:
        """Rendered ``family{labels} -> value`` for the named counter
        families — the public snapshot surface for consumers outside this
        module (the bench kernel-snapshot dump, attestation) so they never
        couple to the private storage layout."""
        out: dict[str, float] = {}
        with self._lock:
            for (name, labels), m in self._metrics.items():
                if name in families and isinstance(m, Counter_):
                    lbl = ",".join(f"{k}={v}" for k, v in labels)
                    out[f"{name}{{{lbl}}}"] = m.value
        return out

    def describe(self, name: str, help_text: str) -> None:
        """Register/override help text for a metric family (exposed as the
        ``# HELP`` line; defaults come from :data:`HELP_TEXTS`)."""
        with self._lock:
            self._help[name] = str(help_text)

    def _render_exemplar(self, ex) -> str:
        labels, value, ts = ex
        inner = ",".join(
            f'{k}="{escape_label_value(v)}"' for k, v in labels.items()
        )
        return f" # {{{inner}}} {value:g} {ts:.3f}"

    def expose(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition of everything registered, with
        ``# HELP``/``# TYPE`` per family. ``openmetrics=True`` renders
        OpenMetrics 1.0 instead: family names lose the ``_total`` suffix in
        metadata lines, histogram bucket lines carry trace-id exemplars,
        and the payload ends with ``# EOF``."""
        with self._lock:
            collectors = list(self._collectors.values())
        for fn in collectors:
            try:
                fn()
            except Exception:  # noqa: BLE001 — a sick collector must not kill /metrics
                pass
        lines = []
        with self._lock:
            items = sorted(self._metrics.items(), key=lambda kv: kv[0][0])
            help_map = dict(self._help)
        seen_families: set[str] = set()

        def header(name: str, mtype: str):
            # text format 0.0.4 names counter families WITH the _total
            # suffix samples carry; OpenMetrics strips it
            family = (
                name if (openmetrics or mtype != "counter") else f"{name}_total"
            )
            if family in seen_families:
                return
            seen_families.add(family)
            help_text = help_map.get(name, HELP_TEXTS.get(name))
            if help_text:
                lines.append(f"# HELP {family} {escape_help(help_text)}")
            lines.append(f"# TYPE {family} {mtype}")

        for (name, labels), m in items:
            lbl = (
                "{" + ",".join(f'{k}="{escape_label_value(v)}"' for k, v in labels) + "}"
                if labels else ""
            )
            if isinstance(m, Counter_):
                header(name, "counter")
                lines.append(f"{name}_total{lbl} {m.value:g}")
            elif isinstance(m, Gauge):
                header(name, "gauge")
                lines.append(f"{name}{lbl} {m.value:g}")
            elif isinstance(m, Histogram):
                header(name, "histogram")
                base = [f'{k}="{escape_label_value(v)}"' for k, v in labels]
                cum = 0
                for i, (b, c) in enumerate(zip(m.BOUNDS, m.counts)):
                    cum += c
                    inner = ",".join(base + [f'le="{b:g}"'])
                    ex = m.exemplars[i] if openmetrics else None
                    suffix = self._render_exemplar(ex) if ex else ""
                    lines.append(f"{name}_bucket{{{inner}}} {cum}{suffix}")
                inner = ",".join(base + ['le="+Inf"'])
                ex = m.exemplars[-1] if openmetrics else None
                suffix = self._render_exemplar(ex) if ex else ""
                lines.append(f"{name}_bucket{{{inner}}} {m.total}{suffix}")
                lines.append(f"{name}_sum{lbl} {m.sum:g}")
                lines.append(f"{name}_count{lbl} {m.total}")
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"


REGISTRY = Registry()

# -- fault-tolerance instrumentation ----------------------------------------
# (query/faults.py circuit breakers + remote retries; reference Kamon
# counters around PromQlRemoteExec / ShardHealthStats)

_BREAKER_STATE_VALUE = {"closed": 0.0, "half_open": 0.5, "open": 1.0}


def record_breaker_transition(endpoint: str, from_state: str, to_state: str) -> None:
    """Count a circuit-breaker state transition and expose the current
    state as a gauge (0 closed, 0.5 half-open, 1 open)."""
    REGISTRY.counter(
        "filodb_breaker_transitions", endpoint=endpoint,
        frm=from_state, to=to_state,
    ).inc()
    REGISTRY.gauge("filodb_breaker_state", endpoint=endpoint).set(
        _BREAKER_STATE_VALUE.get(to_state, -1.0)
    )


def record_remote_retry(endpoint: str) -> None:
    REGISTRY.counter("filodb_remote_retries", endpoint=endpoint).inc()


def record_partial_result(dataset: str) -> None:
    """A query answered with merged partials (some children lost)."""
    REGISTRY.counter("filodb_partial_results", dataset=dataset).inc()


def record_shard_reassignment(shard: int, damped: bool) -> None:
    """ShardManager ingestion-error handling: reassigned vs damper-DOWN,
    per shard so one flapping shard is distinguishable from many."""
    REGISTRY.counter(
        "filodb_shard_reassignments", shard=str(shard),
        outcome="down" if damped else "moved",
    ).inc()


# -- replicated shard plane (coordinator/replication.py) ---------------------


def record_replica_selection(which: str) -> None:
    """A remote dispatch served by its primary replica or a sibling."""
    REGISTRY.counter("filodb_replica_selection", which=which).inc()


def record_replica_failover(endpoint: str, reason: str) -> None:
    """A dispatch re-pinned away from a replica endpoint (breaker_open =
    routed around before calling; endpoint_failure = failed then moved)."""
    REGISTRY.counter(
        "filodb_replica_failovers", endpoint=endpoint, reason=reason,
    ).inc()


def record_replica_ack(outcome: str) -> None:
    """Ingest fan-out append outcome for one (shard, replica) leg."""
    REGISTRY.counter("filodb_replica_acks", outcome=outcome).inc()


def record_replica_watermark(shard: int, node: str, ts_ms: int) -> None:
    """Lag watermark: the max sample timestamp a replica has acked. A
    recovering replica serves queries only behind this mark."""
    REGISTRY.gauge(
        "filodb_replica_watermark_ms", shard=str(shard), node=node,
    ).set(float(ts_ms))


def record_rebalance(outcome: str) -> None:
    """Live shard rebalance: clean (effect log proved no concurrent
    ingest), replayed (tail re-replayed), rebuilt (full log replay),
    damped, or failed."""
    REGISTRY.counter("filodb_rebalance", outcome=outcome).inc()


def record_rebalance_standing_move() -> None:
    REGISTRY.counter("filodb_rebalance_standing_moves").inc()


# -- query-phase taxonomy ----------------------------------------------------

# the ONE canonical per-query phase set (doc/observability.md "Query
# observatory"). Mirrors FUSED_FALLBACK_REASONS: tools/check_spans.py lints
# every phase literal in the package against this tuple, and
# obs/querylog.PhaseRecorder rejects unknown names at runtime — a typo'd
# phase must fail loudly, never mint an undashboarded series.
#
# - parse_plan  — PromQL parse + logical-plan build + materialize
# - admission   — admission-control gate + batch-window queue wait
# - stage       — superblock resolution (cache hit / extend / build+upload)
# - dispatch    — the kernel launch itself (batched or solo)
# - transfer    — device→host result pull at the serving edge
# - render      — response encoding + write at the serving edge
# - other       — engine residual (everything the named phases don't cover,
#                 computed at query end so the phase sum equals wall time)
QUERY_PHASES = (
    "parse_plan", "admission", "stage", "dispatch", "transfer", "render",
    "other",
)


# the executing query's PhaseRecorder (obs/querylog.py), activated per
# thread by ExecPlan.execute exactly like the QueryStats attribution
# target below: spans tagged with phase= and the fused dispatch path bump
# it without threading a context object through every signature
_phases_local = threading.local()


@contextlib.contextmanager
def activate_phases(rec):
    """Bind ``rec`` (an obs.querylog.PhaseRecorder, or None for a no-op)
    as this thread's phase-attribution target. Nests/restores like
    ``activate_stats``."""
    prev = getattr(_phases_local, "rec", None)
    _phases_local.rec = rec
    try:
        yield
    finally:
        _phases_local.rec = prev


def current_phases():
    return getattr(_phases_local, "rec", None)


# -- tracing ----------------------------------------------------------------

_trace_local = threading.local()


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass(frozen=True)
class TraceContext:
    """The portable identity of an active span: what crosses thread pools
    (by reference, via ``current_span``/``activate``) and process
    boundaries (by value, via gRPC call metadata / HTTP headers)."""

    trace_id: str
    span_id: str
    parent_id: str | None = None

    # wire names, shared by the gRPC metadata keys and HTTP headers
    TRACE_ID_HEADER = "X-FiloDB-Trace-Id"
    PARENT_SPAN_HEADER = "X-FiloDB-Parent-Span"


@dataclass
class Span:
    name: str
    start_ns: int
    end_ns: int = 0
    children: list = field(default_factory=list)
    trace_id: str = ""
    span_id: str = field(default_factory=new_span_id)
    parent_id: str | None = None
    # free-form annotations (retries, breaker states, lost children, plan
    # args); must stay JSON-serializable — they cross the wire in to_dict()
    tags: dict = field(default_factory=dict)
    # per-node QueryStats delta (series/samples scanned, bytes staged, ...)
    stats: dict = field(default_factory=dict)

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id, self.parent_id)

    def tree(self, depth=0) -> str:
        line = f"{'  ' * depth}{self.name}: {self.duration_ms:.2f}ms"
        if self.stats:
            brief = " ".join(f"{k}={v}" for k, v in self.stats.items() if v)
            if brief:
                line += f" [{brief}]"
        out = [line]
        for c in self.children:
            out.append(c.tree(depth + 1))
        return "\n".join(out)

    def to_dict(self) -> dict:
        """JSON form: the EXPLAIN ANALYZE / slow-query-log rendering and the
        in-band cross-node trace payload (durations, never raw clocks — the
        perf counters of two processes do not compare)."""
        d = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "duration_ms": round(self.duration_ms, 3),
        }
        if self.tags:
            d["tags"] = self.tags
        if self.stats:
            d["stats"] = self.stats
        d["children"] = [c.to_dict() for c in self.children]
        return d

    @classmethod
    def from_dict(cls, d: dict, trace_id: str | None = None,
                  parent_id: str | None = None) -> "Span":
        """Rebuild a span tree from its wire form. ``trace_id``/``parent_id``
        override the remote identifiers so a grafted subtree joins the LOCAL
        trace (the stitch rewrites linkage; durations are preserved)."""
        s = cls(str(d.get("name", "remote")), 0)
        s.end_ns = int(float(d.get("duration_ms", 0.0)) * 1e6)
        s.trace_id = trace_id if trace_id is not None else str(d.get("trace_id", ""))
        s.span_id = str(d.get("span_id") or new_span_id())
        s.parent_id = parent_id if parent_id is not None else d.get("parent_id")
        s.tags = dict(d.get("tags") or {})
        s.stats = dict(d.get("stats") or {})
        s.children = [
            cls.from_dict(c, trace_id=s.trace_id, parent_id=s.span_id)
            for c in (d.get("children") or [])
        ]
        return s


_UNSET = object()


@contextlib.contextmanager
def span(name: str, parent=_UNSET, phase: str | None = None, **tags):
    """Nested timing spans (Kamon.runWithSpan analog). The thread-local
    current span is the default parent; an explicit ``parent=`` Span wires a
    span into a trace across thread hops (a worker thread has no thread-local
    context — the submitter captures ``current_span()`` and either passes it
    here or re-activates it via ``activate``). The root span of a thread is
    retrievable via current_trace().

    ``phase=`` additionally attributes the span's wall time to the active
    query's phase decomposition (QUERY_PHASES; the recorder bound via
    ``activate_phases``) — the query-observatory capture point for phases
    that already run under a span (e.g. ``fused:stage``)."""
    cur = getattr(_trace_local, "current", None)
    eff_parent = cur if cur is not None else (None if parent is _UNSET else parent)
    s = Span(name, time.perf_counter_ns())
    if tags:
        s.tags.update(tags)
    if eff_parent is not None:
        s.trace_id = eff_parent.trace_id
        s.parent_id = eff_parent.span_id
        # list.append is atomic under the GIL: children may attach from
        # concurrent pool threads re-activating the same parent
        eff_parent.children.append(s)
    else:
        s.trace_id = new_trace_id()
        _trace_local.root = s
    _trace_local.current = s
    try:
        yield s
    finally:
        s.end_ns = time.perf_counter_ns()
        _trace_local.current = cur
        if phase is not None:
            rec = current_phases()
            if rec is not None:
                rec.add(phase, (s.end_ns - s.start_ns) / 1e9)


@contextlib.contextmanager
def activate(span_obj: Span | None):
    """Re-activate a captured span as this thread's current trace context —
    the cross-thread propagation primitive (``execute_children`` captures the
    dispatching span and re-activates it inside pool workers so child spans
    attach under the right parent instead of starting orphan traces)."""
    if span_obj is None:
        yield
        return
    prev = getattr(_trace_local, "current", None)
    prev_root = getattr(_trace_local, "root", None)
    _trace_local.current = span_obj
    _trace_local.root = span_obj
    try:
        yield
    finally:
        _trace_local.current = prev
        _trace_local.root = prev_root


def current_span() -> Span | None:
    """The innermost active span on this thread (the capture point for
    cross-thread and cross-node propagation)."""
    return getattr(_trace_local, "current", None)


def current_trace() -> Span | None:
    return getattr(_trace_local, "root", None)


def trace_to_dict(trace) -> dict | None:
    """Normalize a QueryResult.trace (local Span or already-rendered dict
    from a remote peer) to its JSON form."""
    if trace is None:
        return None
    return trace.to_dict() if isinstance(trace, Span) else trace


# -- slow-query log ---------------------------------------------------------


class SlowQueryLog:
    """Ring buffer of queries that exceeded the slow-query threshold, each
    entry carrying the PromQL, duration, QueryStats and the rendered trace
    tree (served at /debug/slow_queries; counted as
    filodb_slow_queries_total in /metrics)."""

    def __init__(self, max_entries: int = 64):
        self._entries: deque = deque(maxlen=max_entries)
        self._lock = threading.Lock()

    def configure(self, max_entries: int) -> None:
        with self._lock:
            self._entries = deque(self._entries, maxlen=max(1, int(max_entries)))

    def record(self, promql: str, duration_s: float, dataset: str = "",
               trace=None, stats: dict | None = None,
               query_id: str | None = None) -> None:
        entry = {
            "time": time.time(),
            "dataset": dataset,
            "promql": promql,
            "duration_s": round(float(duration_s), 6),
            "stats": stats or {},
            "trace": trace_to_dict(trace),
        }
        if query_id:
            # link to the query observatory: the same execution's
            # exemplar-level cost record (obs/querylog.py) is one GET away
            # instead of a disjoint debug surface
            entry["query_id"] = query_id
            entry["profile"] = f"/api/v1/query_profile?id={query_id}"
        with self._lock:
            self._entries.append(entry)
        REGISTRY.counter("filodb_slow_queries", dataset=dataset).inc()

    def entries(self) -> list[dict]:
        """Newest first."""
        with self._lock:
            return list(reversed(self._entries))

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


SLOW_QUERY_LOG = SlowQueryLog()


# the ONE fused-fallback reason taxonomy (doc/perf.md's fallback table
# documents each entry; tools/check_metrics.py lints code and table against
# each other). Tree-fallback reasons delegate to the reference scatter
# tree; the grid_* entries are DEGRADED-KERNEL reasons — the dispatch
# stays one fused program, it just lost its jitter-tolerant fast variant.
FUSED_FALLBACK_REASONS = frozenset({
    "partial_results", "dispatcher", "mixed_schemas", "hist_scheme",
    "hist_op", "hist_func", "hist_quantile_scalar", "mesh_unsupported",
    "grid_jitter", "grid_holes", "standing_nondecomposable",
    "rollup_ineligible", "stage_span",
})


def record_fused_fallback(reason: str) -> None:
    """A FusedAggregateExec delegated to its reference scatter tree at
    runtime — or, for the ``grid_*`` reasons, degraded a jittered/holey
    grid to the general fused kernel. Exposed as
    ``filodb_fused_fallback_total{reason=...}`` so operators see
    fused-path coverage at aggregate level (the reason was previously only
    a span tag, visible per-query only); doc/perf.md documents the reason
    taxonomy, and an unknown reason label is a bug caught here rather than
    minted as an undashboarded series."""
    if reason not in FUSED_FALLBACK_REASONS:
        reason = "unknown"
    REGISTRY.counter("filodb_fused_fallback", reason=reason).inc()


ROLLUP_EVENTS = frozenset({"add", "build", "fold", "rebuild", "retire",
                           "error"})


def record_rollup_event(event: str) -> None:
    """Rollup-maintainer lifecycle accounting, exposed as
    ``filodb_rollup_maintenance_total{event=...}`` (doc/perf.md "Sketch
    rollup tier"). Same closed-taxonomy discipline as
    :func:`record_fused_fallback` — an unknown event collapses to
    ``unknown`` instead of minting an undashboarded series."""
    if event not in ROLLUP_EVENTS:
        event = "unknown"
    REGISTRY.counter("filodb_rollup_maintenance", event=event).inc()


def record_rollup_serve(kind: str) -> None:
    """A query was served from rollup blocks (querylog ``path=rollup``),
    by serve kind: ``window`` (per-series range function), ``agg`` (fused
    aggregate over moments or merged sketches), ``hist_quantile``
    (classic-histogram bucket fold from counter rollups)."""
    REGISTRY.counter("filodb_rollup_serves", kind=kind).inc()


def record_rollup_chooser(action: str) -> None:
    """Workload-chooser decision: ``add`` (a repeatedly-seen long-range
    fingerprint earned a rollup) or ``retire`` (an idle rollup was
    dropped)."""
    REGISTRY.counter("filodb_rollup_chooser", action=action).inc()


def record_stage_insert_drop(reason: str) -> None:
    """A freshly staged block was NOT inserted into the shard staging cache
    because ingest effects since its stage provably-or-possibly touched its
    range. Exposed as ``filodb_stage_cache_insert_dropped_total{reason}``
    (reasons: overlap | full_clear | log_truncated); a sustained non-zero
    rate under fine-grained ingest is the cache-starvation signal the
    interval-aware insert re-check exists to eliminate for disjoint-range
    ingest (doc/observability.md)."""
    REGISTRY.counter("filodb_stage_cache_insert_dropped", reason=reason).inc()


def record_superblock_event(outcome: str) -> None:
    """Superblock cache maintenance outcome under ingest, exposed as
    ``filodb_superblock_maintenance_total{outcome}``:

    - ``revalidate`` — ingest since the entry was built was provably
      disjoint from its range; the entry was re-stamped and served as-is
    - ``extend`` — overlapping live-edge appends were absorbed by extending
      the device superblock in place (the single-dispatch path survives)
    - ``extend_abort`` — an extension raced a conflicting ingest and was
      discarded
    - ``restage`` — extension preconditions failed; full rebuild paid"""
    REGISTRY.counter("filodb_superblock_maintenance", outcome=outcome).inc()


def record_downsample_claim(event: str) -> None:
    """Distributed-downsample claim lifecycle, exposed as
    ``filodb_downsample_claims_total{event}``: ``steal`` (stale claim
    broken), ``release`` (owner released its own claim), and
    ``tombstone_restored`` (a release found its claim had been stolen and
    re-created mid-release — the renamed tombstone was put back instead of
    deleting the new owner's claim)."""
    REGISTRY.counter("filodb_downsample_claims", event=event).inc()


# -- kernel dispatch instrumentation ----------------------------------------

# the executing query's QueryStats, activated per thread by
# ExecPlan.execute (and re-activated in pool workers through the same
# path): kernel entry points attribute their dispatch seconds to the query
# WITHOUT threading a context object through every ops/ signature
_stats_local = threading.local()


@contextlib.contextmanager
def activate_stats(stats):
    """Bind ``stats`` (a QueryStats) as this thread's attribution target for
    record_kernel_dispatch. Nests/restores like ``activate``."""
    prev = getattr(_stats_local, "stats", None)
    _stats_local.stats = stats
    try:
        yield
    finally:
        _stats_local.stats = prev


def current_stats():
    return getattr(_stats_local, "stats", None)


def record_kernel_dispatch(kernel: str, seconds: float,
                           compiled: bool | None = None,
                           key: dict | None = None, result=None) -> None:
    """Latency histogram around an ops/ kernel entry point, plus JIT
    compile-cache hit/miss accounting when the caller can observe its jit
    cache (a grown cache across the call means this dispatch compiled).
    Also attributes the dispatch seconds to the active query's QueryStats
    (kernel_ns) — the per-query/per-tenant device accounting feed. Pure
    host-side bookkeeping: no device sync is added around the (async)
    dispatch.

    ``key`` (executable-key parts: variant/epilogue/shapes/mesh/batch —
    obs.kernels.KEY_DIMS) and ``result`` (the dispatch's device output,
    for the opt-in exact device timing) additionally feed the kernel &
    compile observatory's per-executable registry; the family dimension is
    ``kernel`` itself, so the registry and this histogram's ``kernel=``
    label stay the same vocabulary."""
    REGISTRY.histogram("filodb_kernel_dispatch_seconds", kernel=kernel).observe(seconds)
    st = current_stats()
    if st is not None:
        st.bump(kernel_ns=int(seconds * 1e9))
    if compiled is not None:
        REGISTRY.counter(
            "filodb_jit_cache", kernel=kernel,
            outcome="miss" if compiled else "hit",
        ).inc()
    # kernel & compile observatory (obs/kernels.py): per-executable
    # compile/dispatch/device-cost attribution + recompile-storm detection
    from .obs.kernels import KERNELS

    KERNELS.observe_dispatch(kernel, seconds, compiled=compiled, parts=key,
                             result=result)


# -- sampling profiler ------------------------------------------------------


class SamplingProfiler:
    """Periodic all-thread stack sampler (reference SimpleProfiler.java:19,
    launched at server start with config filodb.profiler)."""

    def __init__(self, interval_s: float = 0.01, top_frames: int = 1):
        self.interval_s = interval_s
        self.top_frames = top_frames
        self.samples: Counter = Counter()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self):
        # idempotent: a second start() must not leak the first sampler
        # thread (it would double-count every stack forever)
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=1)

    def _run(self):
        me = threading.get_ident()
        while not self._stop.wait(self.interval_s):
            for tid, frame in sys._current_frames().items():
                if tid == me:
                    continue
                stack = traceback.extract_stack(frame, limit=self.top_frames + 4)
                if not stack:
                    continue
                top = stack[-1]
                self.samples[f"{top.name} ({top.filename.rsplit('/', 1)[-1]}:{top.lineno})"] += 1

    def report(self, n: int = 20) -> str:
        total = sum(self.samples.values()) or 1
        lines = [f"{cnt / total * 100:5.1f}%  {name}" for name, cnt in self.samples.most_common(n)]
        return "\n".join(lines)
