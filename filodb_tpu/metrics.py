"""Metrics, tracing, profiling (reference aux subsystems, SURVEY.md §5:
core/.../metrics/FilodbMetrics.scala Kamon facade + OTel export;
Kamon spans threading ExecPlan.execute; standalone SimpleProfiler.java:19
sampling profiler).

- ``Registry``: counters / gauges / histograms with Prometheus text
  exposition (served at /metrics by the HTTP API).
- ``span``: lightweight tracing context manager; spans accumulate into the
  per-query stats and an optional global trace log.
- ``SamplingProfiler``: periodic stack sampler over all threads (the
  SimpleProfiler analog) with top-of-stack aggregation.
"""

from __future__ import annotations

import bisect
import contextlib
import sys
import threading
import time
import traceback
from collections import Counter, defaultdict
from dataclasses import dataclass, field


class Counter_:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0):
        with self._lock:
            self.value += amount


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float):
        self.value = v


class Histogram:
    """Fixed-bucket latency histogram (seconds)."""

    BOUNDS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

    def __init__(self):
        self.counts = [0] * (len(self.BOUNDS) + 1)
        self.sum = 0.0
        self.total = 0
        self._lock = threading.Lock()

    def observe(self, v: float):
        i = bisect.bisect_left(self.BOUNDS, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.total += 1


class Registry:
    def __init__(self):
        self._metrics: dict[tuple[str, tuple], object] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, labels: dict | None):
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls()
                self._metrics[key] = m
            return m

    def counter(self, name: str, **labels) -> Counter_:
        return self._get(Counter_, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(Histogram, name, labels)

    def expose(self) -> str:
        """Prometheus text exposition of everything registered."""
        lines = []
        for (name, labels), m in sorted(self._metrics.items(), key=lambda kv: kv[0][0]):
            lbl = "{" + ",".join(f'{k}="{v}"' for k, v in labels) + "}" if labels else ""
            if isinstance(m, Counter_):
                lines.append(f"{name}_total{lbl} {m.value:g}")
            elif isinstance(m, Gauge):
                lines.append(f"{name}{lbl} {m.value:g}")
            elif isinstance(m, Histogram):
                base = [f'{k}="{v}"' for k, v in labels]
                cum = 0
                for b, c in zip(m.BOUNDS, m.counts):
                    cum += c
                    inner = ",".join(base + [f'le="{b:g}"'])
                    lines.append(f"{name}_bucket{{{inner}}} {cum}")
                inner = ",".join(base + ['le="+Inf"'])
                lines.append(f"{name}_bucket{{{inner}}} {m.total}")
                lines.append(f"{name}_sum{lbl} {m.sum:g}")
                lines.append(f"{name}_count{lbl} {m.total}")
        return "\n".join(lines) + "\n"


REGISTRY = Registry()

# -- fault-tolerance instrumentation ----------------------------------------
# (query/faults.py circuit breakers + remote retries; reference Kamon
# counters around PromQlRemoteExec / ShardHealthStats)

_BREAKER_STATE_VALUE = {"closed": 0.0, "half_open": 0.5, "open": 1.0}


def record_breaker_transition(endpoint: str, from_state: str, to_state: str) -> None:
    """Count a circuit-breaker state transition and expose the current
    state as a gauge (0 closed, 0.5 half-open, 1 open)."""
    REGISTRY.counter(
        "filodb_breaker_transitions", endpoint=endpoint,
        frm=from_state, to=to_state,
    ).inc()
    REGISTRY.gauge("filodb_breaker_state", endpoint=endpoint).set(
        _BREAKER_STATE_VALUE.get(to_state, -1.0)
    )


def record_remote_retry(endpoint: str) -> None:
    REGISTRY.counter("filodb_remote_retries", endpoint=endpoint).inc()


def record_partial_result(dataset: str) -> None:
    """A query answered with merged partials (some children lost)."""
    REGISTRY.counter("filodb_partial_results", dataset=dataset).inc()


def record_shard_reassignment(shard: int, damped: bool) -> None:
    """ShardManager ingestion-error handling: reassigned vs damper-DOWN,
    per shard so one flapping shard is distinguishable from many."""
    REGISTRY.counter(
        "filodb_shard_reassignments", shard=str(shard),
        outcome="down" if damped else "moved",
    ).inc()


# -- tracing ----------------------------------------------------------------

_trace_local = threading.local()


@dataclass
class Span:
    name: str
    start_ns: int
    end_ns: int = 0
    children: list = field(default_factory=list)

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    def tree(self, depth=0) -> str:
        out = [f"{'  ' * depth}{self.name}: {self.duration_ms:.2f}ms"]
        for c in self.children:
            out.append(c.tree(depth + 1))
        return "\n".join(out)


@contextlib.contextmanager
def span(name: str):
    """Nested timing spans (Kamon.runWithSpan analog). The root span of a
    thread is retrievable via current_trace()."""
    s = Span(name, time.perf_counter_ns())
    parent = getattr(_trace_local, "current", None)
    if parent is not None:
        parent.children.append(s)
    else:
        _trace_local.root = s
    _trace_local.current = s
    try:
        yield s
    finally:
        s.end_ns = time.perf_counter_ns()
        _trace_local.current = parent


def current_trace() -> Span | None:
    return getattr(_trace_local, "root", None)


# -- sampling profiler ------------------------------------------------------


class SamplingProfiler:
    """Periodic all-thread stack sampler (reference SimpleProfiler.java:19,
    launched at server start with config filodb.profiler)."""

    def __init__(self, interval_s: float = 0.01, top_frames: int = 1):
        self.interval_s = interval_s
        self.top_frames = top_frames
        self.samples: Counter = Counter()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=1)

    def _run(self):
        me = threading.get_ident()
        while not self._stop.wait(self.interval_s):
            for tid, frame in sys._current_frames().items():
                if tid == me:
                    continue
                stack = traceback.extract_stack(frame, limit=self.top_frames + 4)
                if not stack:
                    continue
                top = stack[-1]
                self.samples[f"{top.name} ({top.filename.rsplit('/', 1)[-1]}:{top.lineno})"] += 1

    def report(self, n: int = 20) -> str:
        total = sum(self.samples.values()) or 1
        lines = [f"{cnt / total * 100:5.1f}%  {name}" for name, cnt in self.samples.most_common(n)]
        return "\n".join(lines)
