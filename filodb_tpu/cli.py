"""CLI (reference L7: cli/CliMain.scala — PromQL queries against a running
server, label/series debug tools, CSV import, local server launch).

Usage:
  python -m filodb_tpu.cli serve [--config cfg.json] [--port 9090]
  python -m filodb_tpu.cli query        --host URL "sum(rate(m[5m]))" --time T
  python -m filodb_tpu.cli query-range  --host URL "m" --start A --end B --step S
  python -m filodb_tpu.cli labels       --host URL
  python -m filodb_tpu.cli label-values --host URL instance
  python -m filodb_tpu.cli series       --host URL 'm{job="x"}'
  python -m filodb_tpu.cli ingest-csv   --host URL data.csv   (metric,tags,ts_ms,value)
  python -m filodb_tpu.cli partkey      'm{job="x"}'          (debug: hash/shard)
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.parse
import urllib.request


def _get(url: str):
    with urllib.request.urlopen(url, timeout=120) as r:
        return json.loads(r.read())


def _print(obj):
    json.dump(obj, sys.stdout, indent=2)
    print()


def cmd_query(args):
    q = urllib.parse.quote(args.query)
    t = f"&time={args.time}" if args.time else ""
    _print(_get(f"{args.host}/api/v1/query?query={q}{t}"))


def cmd_query_range(args):
    q = urllib.parse.quote(args.query)
    _print(_get(
        f"{args.host}/api/v1/query_range?query={q}&start={args.start}&end={args.end}&step={args.step}"
    ))


def cmd_labels(args):
    _print(_get(f"{args.host}/api/v1/labels"))


def cmd_label_values(args):
    _print(_get(f"{args.host}/api/v1/label/{args.label}/values"))


def cmd_series(args):
    m = urllib.parse.quote(args.match)
    _print(_get(f"{args.host}/api/v1/series?match[]={m}"))


def cmd_ingest_csv(args):
    import csv

    lines = []
    with open(args.file) as f:
        for row in csv.reader(f):
            if not row or row[0].startswith("#"):
                continue
            metric, tagstr, ts_ms, value = row
            tags = {"__name__": metric}
            if tagstr:
                for kv in tagstr.split(";"):
                    k, _, v = kv.partition("=")
                    tags[k] = v
            lines.append(json.dumps({"tags": tags, "ts_ms": int(ts_ms), "value": float(value)}))
    req = urllib.request.Request(
        f"{args.host}/ingest", data="\n".join(lines).encode(), method="POST"
    )
    with urllib.request.urlopen(req, timeout=300) as r:
        _print(json.loads(r.read()))


def cmd_partkey(args):
    """Debug: show canonical partkey, hashes, shard routing (reference
    CliMain promFilterToPartKeyBR:222 / partKeyBrAsString debug tools)."""
    from .core import schemas as S
    from .query.promql import Parser

    sel = Parser(args.selector).selector()
    tags = {f.column: f.value for f in sel.matchers}
    if sel.metric:
        tags[S.METRIC_TAG] = sel.metric
    _print(
        {
            "tags": tags,
            "partkey": S.canonical_partkey(tags).decode(errors="replace"),
            "partkey_hash": f"{S.partkey_hash(tags):016x}",
            "shardkey_hash": f"{S.shardkey_hash(tags):016x}",
            "shard": {
                f"spread={sp},shards={n}": S.shard_for(tags, sp, n)
                for sp, n in ((1, 8), (3, 32), (5, 128))
            },
        }
    )


def cmd_downsample_batch(args):
    """Batch downsample job (reference spark-jobs DownsamplerMain)."""
    if getattr(args, "distributed", False):
        import os as _os

        from .downsample.distributed import job_complete, run_worker

        shard_nums = sorted(
            int(d.split("-")[1])
            for d in _os.listdir(_os.path.join(args.store, args.dataset))
            if d.startswith("shard-") and d.split("-")[1].isdigit()
        )
        rep = run_worker(
            args.store, args.dataset, shard_nums,
            tuple(int(m) * 60_000 for m in args.periods.split(",")),
            worker_id=args.worker_id or None, label=args.job_label,
            stale_s=args.stale_s,
        )
        _print({
            "worker": rep.worker_id, "shards_done": rep.shards_done,
            "shards_skipped": rep.shards_skipped,
            "shards_failed": rep.shards_failed,
            "claims_broken": rep.claims_broken, "samples": rep.samples,
            "job_complete": job_complete(args.store, args.dataset,
                                         shard_nums, args.job_label),
        })
        return
    from .core.schemas import Dataset
    from .downsample.downsampler import ShardDownsampler
    from .memstore.memstore import TimeSeriesMemStore
    from .store.columnstore import LocalColumnStore
    from .store.flush import FlushCoordinator
    from .downsample.downsampler import batch_downsample

    store = LocalColumnStore(args.store)
    shard_nums = sorted(
        int(d.split("-")[1])
        for d in __import__("os").listdir(__import__("os").path.join(args.store, args.dataset))
        if d.startswith("shard-")
    )
    ms = TimeSeriesMemStore()
    dsm = TimeSeriesMemStore()
    d = ShardDownsampler(dsm, args.dataset,
                         periods_ms=tuple(int(m) * 60_000 for m in args.periods.split(",")))
    n = batch_downsample(store, ms, args.dataset, shard_nums, dsm, d,
                         processes=args.processes)
    # persist the downsample datasets back to the store
    written = 0
    for period in d.periods_ms:
        ds_name = d.dataset_for(period)
        if ds_name not in dsm._datasets:
            continue
        fc = FlushCoordinator(dsm, store)
        for s in dsm.shard_nums(ds_name):
            r = fc.flush_shard(ds_name, s)
            written += r.chunks_written
    _print({"downsampled_rows": n, "chunks_written": written})


def cmd_churn_find(args):
    """Find churning labels in a persisted store (reference spark-jobs
    LabelChurnFinder: HLL sketches of total-vs-active label values)."""
    import os as _os
    import time as _time

    from .downsample.churn import LabelChurnFinder
    from .store.columnstore import LocalColumnStore

    store = LocalColumnStore(args.store)
    shard_nums = sorted(
        int(d.split("-")[1])
        for d in _os.listdir(_os.path.join(args.store, args.dataset))
        if d.startswith("shard-")
    )
    finder = LabelChurnFinder(
        store, args.dataset, shard_nums, now_ms=int(_time.time() * 1000),
        active_ms=int(args.active_hours * 3_600_000),
    )
    rows = finder.report(min_total=args.min_total, min_ratio=args.min_ratio)
    _print([
        {"prefix": list(r.prefix), "label": r.label, "total": r.total,
         "active": r.active, "ratio": round(r.ratio, 2)}
        for r in rows
    ])


def cmd_cardbust(args):
    """Delete persisted series matching a selector (reference
    CardinalityBusterMain)."""
    import os as _os

    from .store.columnstore import LocalColumnStore
    from .store.repair import bust_cardinality

    store = LocalColumnStore(args.store)
    filters = _matchers_from_selector(args.selector)
    shard_nums = sorted(
        int(d.split("-")[1])
        for d in _os.listdir(_os.path.join(args.store, args.dataset))
        if d.startswith("shard-")
    )
    deleted = bust_cardinality(store, args.dataset, shard_nums, filters)
    _print({"series_deleted": deleted})


def cmd_copy_store(args):
    """Copy chunks+partkeys between stores (reference repair ChunkCopier)."""
    import os as _os

    from .store.columnstore import LocalColumnStore
    from .store.repair import copy_chunks, copy_partkeys

    src = LocalColumnStore(args.src)
    dst = LocalColumnStore(args.dst)
    shard_nums = sorted(
        int(d.split("-")[1])
        for d in _os.listdir(_os.path.join(args.src, args.dataset))
        if d.startswith("shard-")
    )
    n_chunks = copy_chunks(src, dst, args.dataset, shard_nums)
    n_keys = copy_partkeys(src, dst, args.dataset, shard_nums)
    _print({"chunks_copied": n_chunks, "partkeys_copied": n_keys})


def _matchers_from_selector(expr: str):
    from .core.filters import ColumnFilter
    from .core.schemas import METRIC_TAG
    from .query.promql import Parser

    sel = Parser(expr).selector()
    filters = list(sel.matchers)
    if sel.metric:
        filters.append(ColumnFilter(METRIC_TAG, "=", sel.metric))
    return filters


def cmd_serve(args):
    from .server import main as server_main

    argv = []
    if args.config:
        argv += ["--config", args.config]
    if args.port:
        argv += ["--port", str(args.port)]
    server_main(argv)


def main(argv=None):
    p = argparse.ArgumentParser("filodb-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    def host_arg(sp):
        sp.add_argument("--host", default="http://127.0.0.1:9090")

    sp = sub.add_parser("serve")
    sp.add_argument("--config")
    sp.add_argument("--port", type=int)
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("query")
    host_arg(sp)
    sp.add_argument("query")
    sp.add_argument("--time", default=None)
    sp.set_defaults(fn=cmd_query)

    sp = sub.add_parser("query-range")
    host_arg(sp)
    sp.add_argument("query")
    sp.add_argument("--start", required=True)
    sp.add_argument("--end", required=True)
    sp.add_argument("--step", default="15")
    sp.set_defaults(fn=cmd_query_range)

    sp = sub.add_parser("labels")
    host_arg(sp)
    sp.set_defaults(fn=cmd_labels)

    sp = sub.add_parser("label-values")
    host_arg(sp)
    sp.add_argument("label")
    sp.set_defaults(fn=cmd_label_values)

    sp = sub.add_parser("series")
    host_arg(sp)
    sp.add_argument("match")
    sp.set_defaults(fn=cmd_series)

    sp = sub.add_parser("ingest-csv")
    host_arg(sp)
    sp.add_argument("file")
    sp.set_defaults(fn=cmd_ingest_csv)

    sp = sub.add_parser("partkey")
    sp.add_argument("selector")
    sp.set_defaults(fn=cmd_partkey)

    sp = sub.add_parser("downsample-batch")
    sp.add_argument("--store", required=True)
    sp.add_argument("--dataset", default="prometheus")
    sp.add_argument("--periods", default="5,60", help="minutes, comma-separated")
    sp.add_argument("--processes", type=int, default=0,
                    help="process-pool workers for the scan+reduce phase "
                         "(one task per shard; the Spark-executor analog)")
    sp.add_argument("--distributed", action="store_true",
                    help="run as ONE worker of a multi-process job: claim "
                         "shards via the store root, commit atomically, "
                         "break stale claims (reference DownsamplerMain "
                         "over executors; rerun to resume after crashes)")
    sp.add_argument("--worker-id", default="")
    sp.add_argument("--job-label", default="default")
    sp.add_argument("--stale-s", type=float, default=30.0)
    sp.set_defaults(fn=cmd_downsample_batch)

    sp = sub.add_parser("churn-find")
    sp.add_argument("--store", required=True)
    sp.add_argument("--dataset", default="prometheus")
    sp.add_argument("--active-hours", type=float, default=2.0,
                    help="liveness window: series ended within this many "
                         "hours count as active")
    sp.add_argument("--min-total", type=int, default=100)
    sp.add_argument("--min-ratio", type=float, default=2.0)
    sp.set_defaults(fn=cmd_churn_find)

    sp = sub.add_parser("cardbust")
    sp.add_argument("--store", required=True)
    sp.add_argument("--dataset", default="prometheus")
    sp.add_argument("selector")
    sp.set_defaults(fn=cmd_cardbust)

    sp = sub.add_parser("copy-store")
    sp.add_argument("--src", required=True)
    sp.add_argument("--dst", required=True)
    sp.add_argument("--dataset", default="prometheus")
    sp.set_defaults(fn=cmd_copy_store)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
