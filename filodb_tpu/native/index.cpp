// Native part-key index core (reference analog: the Rust tantivy index,
// core/src/rust/filodb_core — ingestDocument / queryPartIds hot paths).
//
// Posting lists: tag key -> value -> sorted vector of part ids, plus
// per-part start/end times for range overlap filtering. The Python wrapper
// (memstore/index_native.py) keeps tag maps for label introspection and
// regex filtering; this core answers the hot equality-AND + time-overlap
// queries.
//
// Build: g++ -O3 -shared -fPIC index.cpp -o libfilodbindex.so

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Index {
    // key -> value -> sorted part ids
    std::unordered_map<std::string, std::unordered_map<std::string, std::vector<int32_t>>> postings;
    std::unordered_map<int32_t, int64_t> start_ts;
    std::unordered_map<int32_t, int64_t> end_ts;
    std::vector<int32_t> all_ids;  // sorted
};

std::string make_key(const char* p, long n) { return std::string(p, (size_t)n); }

void sorted_insert(std::vector<int32_t>& v, int32_t id) {
    auto it = std::lower_bound(v.begin(), v.end(), id);
    if (it == v.end() || *it != id) v.insert(it, id);
}

void sorted_erase(std::vector<int32_t>& v, int32_t id) {
    auto it = std::lower_bound(v.begin(), v.end(), id);
    if (it != v.end() && *it == id) v.erase(it);
}

}  // namespace

extern "C" {

void* fdb_idx_new() { return new Index(); }

void fdb_idx_free(void* h) { delete (Index*)h; }

void fdb_idx_add(void* h, int32_t part_id, int32_t n_pairs,
                 const char** keys, const long* key_lens,
                 const char** vals, const long* val_lens,
                 int64_t start, int64_t end) {
    Index* idx = (Index*)h;
    for (int32_t i = 0; i < n_pairs; i++) {
        auto& post = idx->postings[make_key(keys[i], key_lens[i])][make_key(vals[i], val_lens[i])];
        sorted_insert(post, part_id);
    }
    idx->start_ts[part_id] = start;
    idx->end_ts[part_id] = end;
    sorted_insert(idx->all_ids, part_id);
}

void fdb_idx_update_end(void* h, int32_t part_id, int64_t end) {
    ((Index*)h)->end_ts[part_id] = end;
}

void fdb_idx_remove(void* h, int32_t part_id, int32_t n_pairs,
                    const char** keys, const long* key_lens,
                    const char** vals, const long* val_lens) {
    Index* idx = (Index*)h;
    for (int32_t i = 0; i < n_pairs; i++) {
        auto kit = idx->postings.find(make_key(keys[i], key_lens[i]));
        if (kit == idx->postings.end()) continue;
        auto vit = kit->second.find(make_key(vals[i], val_lens[i]));
        if (vit == kit->second.end()) continue;
        sorted_erase(vit->second, part_id);
        if (vit->second.empty()) kit->second.erase(vit);
    }
    idx->start_ts.erase(part_id);
    idx->end_ts.erase(part_id);
    sorted_erase(idx->all_ids, part_id);
}

// AND of equality terms + [start,end] overlap. Returns count written
// (clipped to cap); -1 signals "no equality terms" (caller scans all).
long fdb_idx_query(void* h, int32_t n_terms,
                   const char** keys, const long* key_lens,
                   const char** vals, const long* val_lens,
                   int64_t start, int64_t end,
                   int32_t* out, long cap) {
    Index* idx = (Index*)h;
    if (n_terms == 0) return -1;
    // find smallest posting list first
    const std::vector<int32_t>* lists[64];
    if (n_terms > 64) return -2;
    for (int32_t i = 0; i < n_terms; i++) {
        auto kit = idx->postings.find(make_key(keys[i], key_lens[i]));
        if (kit == idx->postings.end()) return 0;
        auto vit = kit->second.find(make_key(vals[i], val_lens[i]));
        if (vit == kit->second.end()) return 0;
        lists[i] = &vit->second;
    }
    std::sort(lists, lists + n_terms,
              [](const std::vector<int32_t>* a, const std::vector<int32_t>* b) {
                  return a->size() < b->size();
              });
    long n_out = 0;
    for (int32_t id : *lists[0]) {
        bool ok = true;
        for (int32_t i = 1; i < n_terms && ok; i++) {
            const auto& l = *lists[i];
            ok = std::binary_search(l.begin(), l.end(), id);
        }
        if (!ok) continue;
        auto s = idx->start_ts.find(id);
        auto e = idx->end_ts.find(id);
        if (s == idx->start_ts.end() || s->second > end) continue;
        if (e == idx->end_ts.end() || e->second < start) continue;
        if (n_out < cap) out[n_out] = id;
        n_out++;
    }
    return n_out;
}

// ids of every series matching one key=value (for regex unions in python)
long fdb_idx_postings_of(void* h, const char* key, long key_len,
                         const char* val, long val_len,
                         int32_t* out, long cap) {
    Index* idx = (Index*)h;
    auto kit = idx->postings.find(make_key(key, key_len));
    if (kit == idx->postings.end()) return 0;
    auto vit = kit->second.find(make_key(val, val_len));
    if (vit == kit->second.end()) return 0;
    long n = (long)vit->second.size();
    long w = n < cap ? n : cap;
    std::memcpy(out, vit->second.data(), (size_t)w * sizeof(int32_t));
    return n;
}

long fdb_idx_size(void* h) { return (long)((Index*)h)->all_ids.size(); }

long fdb_idx_all(void* h, int64_t start, int64_t end, int32_t* out, long cap) {
    Index* idx = (Index*)h;
    long n_out = 0;
    for (int32_t id : idx->all_ids) {
        auto s = idx->start_ts.find(id);
        auto e = idx->end_ts.find(id);
        if (s == idx->start_ts.end() || s->second > end) continue;
        if (e == idx->end_ts.end() || e->second < start) continue;
        if (n_out < cap) out[n_out] = id;
        n_out++;
    }
    return n_out;
}

}  // extern "C"
