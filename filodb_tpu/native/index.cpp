// Native part-key index core (reference analog: the Rust tantivy index,
// core/src/rust/filodb_core — ingestDocument / queryPartIds hot paths).
//
// Posting lists: tag key -> value -> sorted vector of part ids, plus
// per-part start/end times for range overlap filtering. The Python wrapper
// (memstore/index_native.py) keeps tag maps for label introspection and
// regex filtering; this core answers the hot equality-AND + time-overlap
// queries.
//
// Build: g++ -O3 -shared -fPIC index.cpp -o libfilodbindex.so

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t kAbsent = INT64_MIN;

struct Index {
    // key -> value -> sorted part ids. The value dictionary is ORDERED so
    // anchored-regex/prefix queries narrow to a range scan instead of
    // walking every value (reference: tantivy_utils' range-aware regex).
    std::unordered_map<std::string, std::map<std::string, std::vector<int32_t>>> postings;
    // part ids are dense small ints: flat time vectors beat hash maps on
    // the per-candidate overlap filter (the hot loop of every query)
    std::vector<int64_t> start_ts;
    std::vector<int64_t> end_ts;
    std::vector<int32_t> all_ids;  // sorted

    void set_times(int32_t id, int64_t s, int64_t e) {
        if ((size_t)id >= start_ts.size()) {
            start_ts.resize((size_t)id + 1, kAbsent);
            end_ts.resize((size_t)id + 1, kAbsent);
        }
        start_ts[(size_t)id] = s;
        end_ts[(size_t)id] = e;
    }
    bool overlaps(int32_t id, int64_t qs, int64_t qe) const {
        if ((size_t)id >= start_ts.size()) return false;
        int64_t s = start_ts[(size_t)id];
        int64_t e = end_ts[(size_t)id];
        return s != kAbsent && s <= qe && e != kAbsent && e >= qs;
    }
};

std::string make_key(const char* p, long n) { return std::string(p, (size_t)n); }

void sorted_insert(std::vector<int32_t>& v, int32_t id) {
    auto it = std::lower_bound(v.begin(), v.end(), id);
    if (it == v.end() || *it != id) v.insert(it, id);
}

void sorted_erase(std::vector<int32_t>& v, int32_t id) {
    auto it = std::lower_bound(v.begin(), v.end(), id);
    if (it != v.end() && *it == id) v.erase(it);
}

// walk the ordered value dictionary over the prefix range, calling
// fn(value, postings) for each entry (the ONE definition of the
// prefix-termination rule)
template <typename Fn>
void for_prefix_range(const std::map<std::string, std::vector<int32_t>>& values,
                      const std::string& pre, Fn&& fn) {
    auto it = pre.empty() ? values.begin() : values.lower_bound(pre);
    for (; it != values.end(); ++it) {
        const std::string& v = it->first;
        if (!pre.empty() &&
            (v.size() < pre.size() || v.compare(0, pre.size(), pre) != 0))
            break;  // ordered map: past the prefix range
        fn(v, it->second);
    }
}

// sort+dedup merged ids, apply the [start,end] overlap filter, emit into
// out (clipped to cap); the shared tail of every union query
long emit_union(Index* idx, std::vector<int32_t>& merged,
                int64_t start, int64_t end, int32_t* out, long cap) {
    std::sort(merged.begin(), merged.end());
    merged.erase(std::unique(merged.begin(), merged.end()), merged.end());
    long n_out = 0;
    for (int32_t id : merged) {
        if (!idx->overlaps(id, start, end)) continue;
        if (n_out < cap) out[n_out] = id;
        n_out++;
    }
    return n_out;
}

}  // namespace

extern "C" {

void* fdb_idx_new() { return new Index(); }

void fdb_idx_free(void* h) { delete (Index*)h; }

void fdb_idx_add(void* h, int32_t part_id, int32_t n_pairs,
                 const char** keys, const long* key_lens,
                 const char** vals, const long* val_lens,
                 int64_t start, int64_t end) {
    Index* idx = (Index*)h;
    for (int32_t i = 0; i < n_pairs; i++) {
        auto& post = idx->postings[make_key(keys[i], key_lens[i])][make_key(vals[i], val_lens[i])];
        sorted_insert(post, part_id);
    }
    idx->set_times(part_id, start, end);
    sorted_insert(idx->all_ids, part_id);
}

void fdb_idx_update_end(void* h, int32_t part_id, int64_t end) {
    Index* idx = (Index*)h;
    if ((size_t)part_id < idx->end_ts.size()) idx->end_ts[(size_t)part_id] = end;
}

void fdb_idx_remove(void* h, int32_t part_id, int32_t n_pairs,
                    const char** keys, const long* key_lens,
                    const char** vals, const long* val_lens) {
    Index* idx = (Index*)h;
    for (int32_t i = 0; i < n_pairs; i++) {
        auto kit = idx->postings.find(make_key(keys[i], key_lens[i]));
        if (kit == idx->postings.end()) continue;
        auto vit = kit->second.find(make_key(vals[i], val_lens[i]));
        if (vit == kit->second.end()) continue;
        sorted_erase(vit->second, part_id);
        if (vit->second.empty()) kit->second.erase(vit);
    }
    if ((size_t)part_id < idx->start_ts.size()) {
        idx->start_ts[(size_t)part_id] = kAbsent;
        idx->end_ts[(size_t)part_id] = kAbsent;
    }
    sorted_erase(idx->all_ids, part_id);
}

// AND of equality terms + [start,end] overlap. Returns count written
// (clipped to cap); -1 signals "no equality terms" (caller scans all).
long fdb_idx_query(void* h, int32_t n_terms,
                   const char** keys, const long* key_lens,
                   const char** vals, const long* val_lens,
                   int64_t start, int64_t end,
                   int32_t* out, long cap) {
    Index* idx = (Index*)h;
    if (n_terms == 0) return -1;
    // find smallest posting list first
    const std::vector<int32_t>* lists[64];
    if (n_terms > 64) return -2;
    for (int32_t i = 0; i < n_terms; i++) {
        auto kit = idx->postings.find(make_key(keys[i], key_lens[i]));
        if (kit == idx->postings.end()) return 0;
        auto vit = kit->second.find(make_key(vals[i], val_lens[i]));
        if (vit == kit->second.end()) return 0;
        lists[i] = &vit->second;
    }
    std::sort(lists, lists + n_terms,
              [](const std::vector<int32_t>* a, const std::vector<int32_t>* b) {
                  return a->size() < b->size();
              });
    long n_out = 0;
    for (int32_t id : *lists[0]) {
        bool ok = true;
        for (int32_t i = 1; i < n_terms && ok; i++) {
            const auto& l = *lists[i];
            ok = std::binary_search(l.begin(), l.end(), id);
        }
        if (!ok) continue;
        if (!idx->overlaps(id, start, end)) continue;
        if (n_out < cap) out[n_out] = id;
        n_out++;
    }
    return n_out;
}

// ids of every series matching one key=value (for regex unions in python)
long fdb_idx_postings_of(void* h, const char* key, long key_len,
                         const char* val, long val_len,
                         int32_t* out, long cap) {
    Index* idx = (Index*)h;
    auto kit = idx->postings.find(make_key(key, key_len));
    if (kit == idx->postings.end()) return 0;
    auto vit = kit->second.find(make_key(val, val_len));
    if (vit == kit->second.end()) return 0;
    long n = (long)vit->second.size();
    long w = n < cap ? n : cap;
    std::memcpy(out, vit->second.data(), (size_t)w * sizeof(int32_t));
    return n;
}

// values of ``key`` starting with ``prefix``, packed as
// [u32 len][bytes]... into out. Returns the number of values found (the
// caller grows the buffer and retries when the returned byte length in
// *used exceeds cap). An empty prefix scans the whole dictionary.
long fdb_idx_values_prefix(void* h, const char* key, long key_len,
                           const char* prefix, long prefix_len,
                           char* out, long cap, long* used) {
    Index* idx = (Index*)h;
    auto kit = idx->postings.find(make_key(key, key_len));
    *used = 0;
    if (kit == idx->postings.end()) return 0;
    long n = 0;
    long w = 0;
    for_prefix_range(kit->second, make_key(prefix, prefix_len),
                     [&](const std::string& v, const std::vector<int32_t>&) {
        long need = 4 + (long)v.size();
        if (w + need <= cap) {
            uint32_t len = (uint32_t)v.size();
            std::memcpy(out + w, &len, 4);
            std::memcpy(out + w + 4, v.data(), v.size());
        }
        w += need;
        n++;
    });
    *used = w;
    return n;
}

// sorted unique union of postings for ``key`` over the given values,
// filtered by [start, end] overlap. Returns count written (clipped to cap).
long fdb_idx_union(void* h, const char* key, long key_len,
                   int32_t n_vals, const char** vals, const long* val_lens,
                   int64_t start, int64_t end, int32_t* out, long cap) {
    Index* idx = (Index*)h;
    auto kit = idx->postings.find(make_key(key, key_len));
    if (kit == idx->postings.end()) return 0;
    std::vector<int32_t> merged;
    for (int32_t i = 0; i < n_vals; i++) {
        auto vit = kit->second.find(make_key(vals[i], val_lens[i]));
        if (vit == kit->second.end()) continue;
        merged.insert(merged.end(), vit->second.begin(), vit->second.end());
    }
    return emit_union(idx, merged, start, end, out, cap);
}

// union of postings for EVERY value of ``key`` in the prefix range —
// the pure-prefix regex (``http_.*``) answered entirely inside the core,
// no per-value matching anywhere.
long fdb_idx_union_prefix(void* h, const char* key, long key_len,
                          const char* prefix, long prefix_len,
                          int64_t start, int64_t end,
                          int32_t* out, long cap) {
    Index* idx = (Index*)h;
    auto kit = idx->postings.find(make_key(key, key_len));
    if (kit == idx->postings.end()) return 0;
    std::vector<int32_t> merged;
    for_prefix_range(kit->second, make_key(prefix, prefix_len),
                     [&](const std::string&, const std::vector<int32_t>& ids) {
        merged.insert(merged.end(), ids.begin(), ids.end());
    });
    return emit_union(idx, merged, start, end, out, cap);
}

long fdb_idx_size(void* h) { return (long)((Index*)h)->all_ids.size(); }

long fdb_idx_all(void* h, int64_t start, int64_t end, int32_t* out, long cap) {
    Index* idx = (Index*)h;
    long n_out = 0;
    for (int32_t id : idx->all_ids) {
        if (!idx->overlaps(id, start, end)) continue;
        if (n_out < cap) out[n_out] = id;
        n_out++;
    }
    return n_out;
}

}  // extern "C"
