// Native Prometheus text-exposition scanner (host ingest hot path).
//
// Reference analog: the gateway's JVM parsers (gateway/.../InputRecord.scala:15
// PrometheusInputRecord + the netty pipeline) — the reference parses ingest
// protocols in native-compiled code; here a C++ scanner tokenizes the payload
// in one pass and hands Python COLUMNAR records: a (offset, len) span of the
// series key (`name{labels}` exactly as spelled), the parsed value, optional
// timestamp, and the # TYPE-resolved type code. Python memoizes label parsing
// per unique key span (scrapes repeat the same series every interval), so the
// per-record Python work is O(new series), not O(samples).
//
// Parity contract: the scanner NEVER rejects a line. Anything it cannot
// tokenize exactly like the Python parser would — exemplar suffixes, value
// tokens with '_'/hex chars, '+'-signed or overflowing timestamps, stray
// braces, unusual whitespace — is DEFERRED: emitted as a whole-line span with
// flags=1, and Python applies its full regex semantics (including raising
// ValueError for genuinely bad lines). Acceptance behavior is therefore
// identical with and without the native lib; only speed differs. Known
// micro-corner: a deferred line whose metric name the scanner could not even
// start to read carries type_code=0, so an exotic line like
// "\xc2\xa0name ..." (Unicode-space-prefixed) for a TYPEd metric would
// schema-route as untyped — Python itself only reaches such lines via its
// wider Unicode stripping. (Payloads containing U+0085/U+2028/U+2029 line
// separators skip the native path entirely; see parse_prom_records.)
//
// Build: g++ -O3 -march=native -std=c++17 -shared -fPIC promparse.cpp -o libfilodbprom.so

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string_view>
#include <unordered_map>

namespace {

struct FdbPromRec {
    uint32_t key_off;
    uint32_t key_len;
    double value;
    int64_t ts_ms;     // INT64_MIN = absent
    uint8_t type_code; // 0 untyped, 1 counter, 2 gauge, 3 histogram, 4 summary
    uint8_t flags;     // 1 = deferred line (span = whole line; Python parses)
    uint16_t _pad;
};

const int64_t TS_ABSENT = INT64_MIN;

inline bool is_space(char c) { return c == ' ' || c == '\t' || c == '\r'; }
// line separators, matching str.splitlines' ASCII/C1 set (\n \r \v \f and
// the \x1c-\x1e file/group/record separators; \r\n collapses because the
// empty in-between line is skipped)
inline bool is_sep(char c) {
    return c == '\n' || c == '\r' || c == '\v' || c == '\f' ||
           c == '\x1c' || c == '\x1d' || c == '\x1e';
}
inline bool name_start(char c) {
    return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') || c == '_' || c == ':';
}
inline bool name_char(char c) { return name_start(c) || (c >= '0' && c <= '9'); }

uint8_t type_code_of(std::string_view t) {
    if (t == "counter") return 1;
    if (t == "gauge") return 2;
    if (t == "histogram") return 3;
    if (t == "summary") return 4;
    return 0;
}

}  // namespace

extern "C" {

// Returns the record count, or -2 when out is too small. buf must be
// NUL-terminated at buf[len] (CPython bytes are), so strtod/strtoll cannot
// overrun.
long fdb_parse_prom(const char* buf, long len, FdbPromRec* out, long max_out) {
    std::unordered_map<std::string_view, uint8_t> types;
    long n = 0;
    long pos = 0;
    while (pos < len) {
        long line_start = pos;
        long eol = pos;
        while (eol < len && !is_sep(buf[eol])) eol++;
        pos = eol + 1;
        long b = line_start, e = eol;
        while (b < e && is_space(buf[b])) b++;
        while (e > b && is_space(buf[e - 1])) e--;
        if (b == e) continue;
        if (buf[b] == '#') {
            // exactly `# TYPE` prefix (Python: stripped.startswith("# TYPE")),
            // then whitespace-split tokens: parts[2]=name, parts[3]=type
            if (e - b >= 6 && std::memcmp(buf + b, "# TYPE", 6) == 0) {
                long p = b;
                std::string_view parts[4];
                int np = 0;
                while (p < e && np < 4) {
                    while (p < e && is_space(buf[p])) p++;
                    long t0 = p;
                    while (p < e && !is_space(buf[p])) p++;
                    if (p > t0) parts[np++] = std::string_view(buf + t0, (size_t)(p - t0));
                }
                if (np >= 4) types[parts[2]] = type_code_of(parts[3]);
            }
            continue;
        }
        if (n >= max_out) return -2;

        uint8_t tcode = 0;
        long p = b;
        bool defer = false;
        long key_end = b;
        double v = 0.0;
        int64_t ts = TS_ABSENT;

        // name (identical charset to the Python regex)
        if (!name_start(buf[p])) {
            defer = true;
        } else {
            while (p < e && name_char(buf[p])) p++;
            std::string_view nm(buf + b, (size_t)(p - b));
            auto it = types.find(nm);
            if (it != types.end()) tcode = it->second;
        }
        // exemplar suffix " # {" anywhere -> Python handles the whole line
        if (!defer) {
            for (long q = b; q + 3 < e; q++) {
                if (buf[q] == ' ' && buf[q + 1] == '#' && buf[q + 2] == ' ' &&
                    buf[q + 3] == '{') {
                    defer = true;
                    break;
                }
            }
        }
        // optional {labels} — quote-aware scan to the closing brace
        if (!defer && p < e && buf[p] == '{') {
            bool in_q = false;
            p++;
            for (;; p++) {
                if (p >= e) { defer = true; break; }
                char c = buf[p];
                if (in_q) {
                    if (c == '\\') { p++; continue; }
                    if (c == '"') in_q = false;
                } else if (c == '"') {
                    in_q = true;
                } else if (c == '}') {
                    p++;
                    break;
                }
            }
        }
        if (!defer) {
            key_end = p;
            // value token: must be whitespace-delimited and fully consumed by
            // strtod, with no chars strtod and Python float() disagree on
            // ('x'/'X' hex floats, '_' digit separators)
            if (p >= e || !is_space(buf[p])) defer = true;
            while (!defer && p < e && is_space(buf[p])) p++;
            if (!defer && p >= e) defer = true;
            if (!defer) {
                long tok = p;
                while (p < e && !is_space(buf[p])) p++;
                for (long q = tok; q < p; q++) {
                    char c = buf[q];
                    if (c == 'x' || c == 'X' || c == '_') { defer = true; break; }
                }
                if (!defer) {
                    char* endp = nullptr;
                    v = strtod(buf + tok, &endp);
                    if (endp - buf != p) defer = true;
                }
            }
            // optional timestamp: Python accepts -?\d+ only, as int64
            if (!defer) {
                while (p < e && is_space(buf[p])) p++;
                if (p < e) {
                    if (buf[p] == '+') {
                        defer = true;  // Python's regex rejects '+'
                    } else {
                        errno = 0;
                        char* endt = nullptr;
                        long long t = strtoll(buf + p, &endt, 10);
                        if (endt - buf != e || errno == ERANGE) defer = true;
                        else ts = (int64_t)t;
                    }
                }
            }
        }
        if (defer) {
            out[n++] = FdbPromRec{(uint32_t)b, (uint32_t)(e - b), 0.0,
                                  TS_ABSENT, tcode, 1, 0};
        } else {
            out[n++] = FdbPromRec{(uint32_t)b, (uint32_t)(key_end - b), v, ts,
                                  tcode, 0, 0};
        }
    }
    return n;
}

}  // extern "C"
