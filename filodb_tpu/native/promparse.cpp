// Native Prometheus text-exposition scanner (host ingest hot path).
//
// Reference analog: the gateway's JVM parsers (gateway/.../InputRecord.scala:15
// PrometheusInputRecord + the netty pipeline) — the reference parses ingest
// protocols in native-compiled code; here a C++ scanner tokenizes the payload
// in one pass and hands Python COLUMNAR records: a (offset, len) span of the
// series key (`name{labels}` exactly as spelled), the parsed value, optional
// timestamp, and the # TYPE-resolved type code. Python memoizes label parsing
// per unique key span (scrapes repeat the same series every interval), so the
// per-record Python work is O(new series), not O(samples).
//
// Parity contract: the scanner NEVER rejects a line. Anything it cannot
// tokenize exactly like the Python parser would — exemplar suffixes, value
// tokens with '_'/hex chars, '+'-signed or overflowing timestamps, stray
// braces, unusual whitespace — is DEFERRED: emitted as a whole-line span with
// flags=1, and Python applies its full regex semantics (including raising
// ValueError for genuinely bad lines). Acceptance behavior is therefore
// identical with and without the native lib; only speed differs. Known
// micro-corner: a deferred line whose metric name the scanner could not even
// start to read carries type_code=0, so an exotic line like
// "\xc2\xa0name ..." (Unicode-space-prefixed) for a TYPEd metric would
// schema-route as untyped — Python itself only reaches such lines via its
// wider Unicode stripping. (Payloads containing U+0085/U+2028/U+2029 line
// separators skip the native path entirely; see parse_prom_records.)
//
// Build: g++ -O3 -march=native -std=c++17 -shared -fPIC promparse.cpp -o libfilodbprom.so

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string_view>
#include <unordered_map>

namespace {

struct FdbPromRec {
    uint32_t key_off;
    uint32_t key_len;
    double value;
    int64_t ts_ms;     // INT64_MIN = absent
    uint8_t type_code; // 0 untyped, 1 counter, 2 gauge, 3 histogram, 4 summary
    uint8_t flags;     // 1 = deferred line (span = whole line; Python parses)
    uint16_t _pad;
};

const int64_t TS_ABSENT = INT64_MIN;

inline bool is_space(char c) { return c == ' ' || c == '\t' || c == '\r'; }
// line-EDGE trimming matches str.strip(): also \x1f, which Python considers
// whitespace (isspace) though neither splitlines nor regex \s treats it so
inline bool is_strip(char c) { return is_space(c) || c == '\x1f'; }
// line separators, matching str.splitlines' ASCII/C1 set (\n \r \v \f and
// the \x1c-\x1e file/group/record separators; \r\n collapses because the
// empty in-between line is skipped)
inline bool is_sep(char c) {
    return c == '\n' || c == '\r' || c == '\v' || c == '\f' ||
           c == '\x1c' || c == '\x1d' || c == '\x1e';
}
inline bool name_start(char c) {
    return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') || c == '_' || c == ':';
}
inline bool name_char(char c) { return name_start(c) || (c >= '0' && c <= '9'); }

uint8_t type_code_of(std::string_view t) {
    if (t == "counter") return 1;
    if (t == "gauge") return 2;
    if (t == "histogram") return 3;
    if (t == "summary") return 4;
    return 0;
}

inline bool ends_with(std::string_view s, std::string_view suf) {
    return s.size() >= suf.size()
        && s.compare(s.size() - suf.size(), suf.size(), suf) == 0;
}

// Resolve a sample's type understanding family suffixes (mirror of the
// Python parsers._series_type): a histogram/summary family's _bucket/
// _count/_sum series are cumulative -> counter semantics, and OpenMetrics
// counters declare the family WITHOUT the _total their samples carry.
uint8_t series_type(std::string_view nm,
                    const std::unordered_map<std::string_view, uint8_t>& types) {
    auto it = types.find(nm);
    if (it != types.end()) return it->second;
    for (std::string_view suf : {std::string_view("_bucket"),
                                 std::string_view("_count"),
                                 std::string_view("_sum")}) {
        if (ends_with(nm, suf)) {
            auto fam = types.find(nm.substr(0, nm.size() - suf.size()));
            if (fam != types.end() && (fam->second == 3 || fam->second == 4))
                return 1;
        }
    }
    if (ends_with(nm, std::string_view("_total"))) {
        auto fam = types.find(nm.substr(0, nm.size() - 6));
        if (fam != types.end() && fam->second == 1) return 1;
    }
    return 0;
}

}  // namespace

extern "C" {

// Returns the record count, or -2 when out is too small. buf must be
// NUL-terminated at buf[len] (CPython bytes are), so strtod/strtoll cannot
// overrun.
long fdb_parse_prom(const char* buf, long len, FdbPromRec* out, long max_out) {
    std::unordered_map<std::string_view, uint8_t> types;
    long n = 0;
    long pos = 0;
    while (pos < len) {
        long line_start = pos;
        long eol = pos;
        while (eol < len && !is_sep(buf[eol])) eol++;
        pos = eol + 1;
        long b = line_start, e = eol;
        while (b < e && is_strip(buf[b])) b++;
        while (e > b && is_strip(buf[e - 1])) e--;
        if (b == e) continue;
        if (buf[b] == '#') {
            // exactly `# TYPE` prefix (Python: stripped.startswith("# TYPE")),
            // then whitespace-split tokens: parts[2]=name, parts[3]=type
            if (e - b >= 6 && std::memcmp(buf + b, "# TYPE", 6) == 0) {
                long p = b;
                std::string_view parts[4];
                int np = 0;
                while (p < e && np < 4) {
                    while (p < e && is_strip(buf[p])) p++;
                    long t0 = p;
                    while (p < e && !is_strip(buf[p])) p++;
                    if (p > t0) parts[np++] = std::string_view(buf + t0, (size_t)(p - t0));
                }
                if (np >= 4) types[parts[2]] = type_code_of(parts[3]);
            }
            continue;
        }
        if (n >= max_out) return -2;

        uint8_t tcode = 0;
        long p = b;
        bool defer = false;
        long key_end = b;
        double v = 0.0;
        int64_t ts = TS_ABSENT;

        // name (identical charset to the Python regex)
        if (!name_start(buf[p])) {
            defer = true;
        } else {
            while (p < e && name_char(buf[p])) p++;
            std::string_view nm(buf + b, (size_t)(p - b));
            tcode = series_type(nm, types);
        }
        // exemplar suffix " # {" anywhere -> Python handles the whole line
        if (!defer) {
            for (long q = b; q + 3 < e; q++) {
                if (buf[q] == ' ' && buf[q + 1] == '#' && buf[q + 2] == ' ' &&
                    buf[q + 3] == '{') {
                    defer = true;
                    break;
                }
            }
        }
        // optional {labels} — quote-aware scan to the closing brace
        if (!defer && p < e && buf[p] == '{') {
            bool in_q = false;
            p++;
            for (;; p++) {
                if (p >= e) { defer = true; break; }
                char c = buf[p];
                if (in_q) {
                    if (c == '\\') { p++; continue; }
                    if (c == '"') in_q = false;
                } else if (c == '"') {
                    in_q = true;
                } else if (c == '}') {
                    p++;
                    break;
                }
            }
        }
        if (!defer) {
            key_end = p;
            // value token: must be whitespace-delimited and fully consumed by
            // strtod, with no chars strtod and Python float() disagree on
            // ('x'/'X' hex floats, '_' digit separators)
            if (p >= e || !is_space(buf[p])) defer = true;
            while (!defer && p < e && is_space(buf[p])) p++;
            if (!defer && p >= e) defer = true;
            if (!defer) {
                long tok = p;
                while (p < e && !is_space(buf[p])) p++;
                for (long q = tok; q < p; q++) {
                    char c = buf[q];
                    if (c == 'x' || c == 'X' || c == '_' || c == '(' || c == ')') { defer = true; break; }
                }
                if (!defer) {
                    char* endp = nullptr;
                    v = strtod(buf + tok, &endp);
                    if (endp - buf != p) defer = true;
                }
            }
            // optional timestamp: Python accepts -?\d+ only, as int64
            if (!defer) {
                while (p < e && is_space(buf[p])) p++;
                if (p < e) {
                    if (buf[p] == '+') {
                        defer = true;  // Python's regex rejects '+'
                    } else {
                        errno = 0;
                        char* endt = nullptr;
                        long long t = strtoll(buf + p, &endt, 10);
                        if (endt - buf != e || errno == ERANGE) defer = true;
                        else ts = (int64_t)t;
                    }
                }
            }
        }
        if (defer) {
            out[n++] = FdbPromRec{(uint32_t)b, (uint32_t)(e - b), 0.0,
                                  TS_ABSENT, tcode, 1, 0};
        } else {
            out[n++] = FdbPromRec{(uint32_t)b, (uint32_t)(key_end - b), v, ts,
                                  tcode, 0, 0};
        }
    }
    return n;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Influx line protocol (reference gateway/.../InfluxProtocolParser.scala).
// Same defer contract: any token the scanner can't classify exactly like
// parse_influx_line goes back as a whole-line flags=1 record.
// ---------------------------------------------------------------------------

namespace {

struct FdbInfluxRec {
    uint32_t key_off;    // measurement[,tag=v...] span (raw, escapes intact)
    uint32_t key_len;
    uint32_t field_off;  // field key span (raw); unused when deferred
    uint32_t field_len;
    double value;
    int64_t ts_ms;       // INT64_MIN = absent
    uint8_t flags;       // 1 = deferred line (key span = whole line)
    uint8_t _pad[7];
};

// split points mirror Python's (?<!\\) lookbehind: a separator counts unless
// the SINGLE preceding char is a backslash
inline long find_unescaped(const char* buf, long from, long to, char sep) {
    for (long p = from; p < to; p++)
        if (buf[p] == sep && (p == from || buf[p - 1] != '\\')) return p;
    return to;
}

// Python str.partition: first occurrence, escapes NOT honored
inline long find_plain(const char* buf, long from, long to, char sep) {
    for (long p = from; p < to; p++)
        if (buf[p] == sep) return p;
    return to;
}

inline bool token_clean_double(const char* buf, long b, long e, double* out) {
    for (long q = b; q < e; q++) {
        char c = buf[q];
        // 'x'/'X' hex floats, '_' digit separators, parens in nan(...)
        if (c == 'x' || c == 'X' || c == '_' || c == '(' || c == ')') return false;
    }
    char* endp = nullptr;
    double v = strtod(buf + b, &endp);
    if (endp - buf != e || b == e) return false;
    *out = v;
    return true;
}

inline bool tok_eq(const char* buf, long b, long e, const char* s) {
    size_t n = strlen(s);
    return (size_t)(e - b) == n && std::memcmp(buf + b, s, n) == 0;
}

}  // namespace

extern "C" {

// Returns emitted record count or -2 when out is too small.
long fdb_parse_influx(const char* buf, long len, FdbInfluxRec* out, long max_out) {
    long n = 0;
    long pos = 0;
    while (pos < len) {
        long line_start = pos;
        long eol = pos;
        while (eol < len && !is_sep(buf[eol])) eol++;
        pos = eol + 1;
        long b = line_start, e = eol;
        while (b < e && is_strip(buf[b])) b++;
        while (e > b && is_strip(buf[e - 1])) e--;
        if (b == e || buf[b] == '#') continue;

        bool defer = false;
        // any non-ASCII byte: Python's wider Unicode strip/split semantics
        for (long q = b; q < e && !defer; q++)
            if ((unsigned char)buf[q] >= 0x80) defer = true;

        long sp1 = defer ? e : find_unescaped(buf, b, e, ' ');
        if (!defer && sp1 >= e) defer = true;  // needs key + fields
        long key_b = b, key_e = sp1;
        long f_b = 0, f_e = 0;
        int64_t ts = TS_ABSENT;
        if (!defer) {
            f_b = sp1 + 1;
            long sp2 = find_unescaped(buf, f_b, e, ' ');
            f_e = sp2;
            if (sp2 < e) {  // third token = ns timestamp; extras ignored
                long t_b = sp2 + 1;
                long t_e = find_unescaped(buf, t_b, e, ' ');
                for (long q = t_b; q < t_e && !defer; q++)
                    if (buf[q] == '_') defer = true;  // int("1_0") quirk
                if (!defer) {
                    errno = 0;
                    char* endt = nullptr;
                    long long t = strtoll(buf + t_b, &endt, 10);
                    if (endt - buf != t_e || t_b == t_e || errno == ERANGE) defer = true;
                    else ts = (int64_t)(t / 1000000);  // ns -> ms (trunc, like //)
                    // Python's // floors; match for negatives
                    if (!defer && t < 0 && t % 1000000 != 0) ts -= 1;
                }
            }
        }
        if (defer) {
            if (n >= max_out) return -2;
            out[n++] = FdbInfluxRec{(uint32_t)b, (uint32_t)(e - b), 0, 0, 0.0,
                                    TS_ABSENT, 1, {0}};
            continue;
        }
        // one record per field, splitting fields on unescaped commas
        long fp = f_b;
        long line_first = n;  // roll back to a single defer record if needed
        while (fp <= f_e) {
            long fc = find_unescaped(buf, fp, f_e, ',');
            long eq = find_plain(buf, fp, fc, '=');  // partition() semantics
            long vb = (eq < fc) ? eq + 1 : fc;  // missing '=' -> empty value
            long ve = fc;
            while (vb < ve && is_strip(buf[vb])) vb++;   // Python v.strip()
            while (ve > vb && is_strip(buf[ve - 1])) ve--;
            double v = 0.0;
            bool emit = true;
            // EXACT Python ordering (parse_influx_line): endswith('i') first,
            // then booleans, then string skip, then plain float
            if (vb < ve && buf[ve - 1] == 'i') {
                if (!token_clean_double(buf, vb, ve - 1, &v)) { defer = true; break; }
            } else if (tok_eq(buf, vb, ve, "t") || tok_eq(buf, vb, ve, "T") ||
                       tok_eq(buf, vb, ve, "true") || tok_eq(buf, vb, ve, "True")) {
                v = 1.0;
            } else if (tok_eq(buf, vb, ve, "f") || tok_eq(buf, vb, ve, "F") ||
                       tok_eq(buf, vb, ve, "false") || tok_eq(buf, vb, ve, "False")) {
                v = 0.0;
            } else if (vb < ve && buf[vb] == '"') {
                emit = false;  // string field: not a time series value
            } else {
                if (!token_clean_double(buf, vb, ve, &v)) { defer = true; break; }
            }
            if (emit) {
                if (n >= max_out) return -2;
                out[n++] = FdbInfluxRec{(uint32_t)key_b, (uint32_t)(key_e - key_b),
                                        (uint32_t)fp, (uint32_t)(eq < fc ? eq - fp : fc - fp),
                                        v, ts, 0, {0}};
            }
            fp = fc + 1;
        }
        if (defer) {
            n = line_first;
            if (n >= max_out) return -2;
            out[n++] = FdbInfluxRec{(uint32_t)b, (uint32_t)(e - b), 0, 0, 0.0,
                                    TS_ABSENT, 1, {0}};
        }
    }
    return n;
}

}  // extern "C"
