// Native codec + reduction kernels (host side).
//
// Reference analogs: NibblePack (core/.../format/NibblePack.scala:108 pack8 /
// :395 unpack8) and the Rust SIMD NaN-aware sum/count
// (core/src/rust/filodb_core/src/simd_vectors.rs:174,202). The wire format
// here matches filodb_tpu/core/encodings.py exactly (groups of 8 u64:
// nonzero bitmask byte, then [trailing-zero-nibbles | nnibbles-1] header and
// packed nibbles, low-nibble-first, byte-padded per group).
//
// Build: g++ -O3 -march=native -shared -fPIC codecs.cpp -o libfilodbcodecs.so

#include <cstdint>
#include <cstring>
#include <cmath>

extern "C" {

// returns bytes written, or -1 if out_cap too small
long fdb_nibble_pack(const uint64_t* in, long n, uint8_t* out, long out_cap) {
    long pos = 0;
    for (long g0 = 0; g0 < n; g0 += 8) {
        int glen = (int)((n - g0) < 8 ? (n - g0) : 8);
        uint8_t bitmask = 0;
        for (int i = 0; i < glen; i++)
            if (in[g0 + i] != 0) bitmask |= (uint8_t)(1u << i);
        if (pos + 1 > out_cap) return -1;
        out[pos++] = bitmask;
        if (bitmask == 0) continue;
        int tz_bits = 64, lz_bits = 64;
        for (int i = 0; i < glen; i++) {
            uint64_t x = in[g0 + i];
            if (x == 0) continue;
            int tz = __builtin_ctzll(x);
            int lz = __builtin_clzll(x);
            if (tz < tz_bits) tz_bits = tz;
            if (lz < lz_bits) lz_bits = lz;
        }
        int tz_nib = tz_bits / 4;
        int lz_nib = lz_bits / 4;
        int nnib = 16 - tz_nib - lz_nib;
        if (nnib < 1) nnib = 1;
        if (pos + 1 > out_cap) return -1;
        out[pos++] = (uint8_t)(((tz_nib & 0xF) << 4) | (nnib - 1));
        uint32_t acc = 0;
        int acc_n = 0;
        for (int i = 0; i < glen; i++) {
            uint64_t x = in[g0 + i];
            if (x == 0) continue;
            x >>= (tz_nib * 4);
            for (int k = 0; k < nnib; k++) {
                acc |= (uint32_t)((x >> (4 * k)) & 0xF) << (4 * acc_n);
                if (++acc_n == 2) {
                    if (pos + 1 > out_cap) return -1;
                    out[pos++] = (uint8_t)acc;
                    acc = 0;
                    acc_n = 0;
                }
            }
        }
        if (acc_n) {
            if (pos + 1 > out_cap) return -1;
            out[pos++] = (uint8_t)acc;
        }
    }
    return pos;
}

// returns bytes consumed, or -1 on malformed input
long fdb_nibble_unpack(const uint8_t* in, long in_len, uint64_t* out, long n) {
    long pos = 0;
    long i = 0;
    while (i < n) {
        int glen = (int)((n - i) < 8 ? (n - i) : 8);
        if (pos >= in_len) return -1;
        uint8_t bitmask = in[pos++];
        if (bitmask == 0) {
            for (int b = 0; b < glen; b++) out[i + b] = 0;
            i += glen;
            continue;
        }
        if (pos >= in_len) return -1;
        uint8_t hdr = in[pos++];
        int tz_nib = hdr >> 4;
        int nnib = (hdr & 0xF) + 1;
        int n_nz = __builtin_popcount(bitmask);
        long total_nibbles = (long)n_nz * nnib;
        long nbytes = (total_nibbles + 1) / 2;
        if (pos + nbytes > in_len) return -1;
        const uint8_t* chunk = in + pos;
        long nib_idx = 0;
        for (int b = 0; b < glen; b++) {
            if (!(bitmask & (1u << b))) {
                out[i + b] = 0;
                continue;
            }
            uint64_t val = 0;
            for (int k = 0; k < nnib; k++) {
                long ni = nib_idx + k;
                uint8_t byte = chunk[ni >> 1];
                uint8_t nib = (ni & 1) ? (byte >> 4) : (byte & 0xF);
                val |= (uint64_t)nib << (4 * k);
            }
            nib_idx += nnib;
            out[i + b] = val << (4 * tz_nib);
        }
        pos += nbytes;
        i += glen;
    }
    return pos;
}

// zigzag helpers for delta-delta residual streams
void fdb_zigzag(const int64_t* in, long n, uint64_t* out) {
    for (long i = 0; i < n; i++)
        out[i] = ((uint64_t)in[i] << 1) ^ (uint64_t)(in[i] >> 63);
}

void fdb_unzigzag(const uint64_t* in, long n, int64_t* out) {
    for (long i = 0; i < n; i++)
        out[i] = (int64_t)(in[i] >> 1) ^ -(int64_t)(in[i] & 1);
}

// Branchless NaN-zeroing sum / count (reference simd_vectors.rs:34-38:
// NaN-as-zero via mask; unrolled so the compiler vectorizes)
double fdb_nan_sum(const double* in, long n) {
    double acc0 = 0, acc1 = 0, acc2 = 0, acc3 = 0;
    long i = 0;
    for (; i + 4 <= n; i += 4) {
        double a = in[i], b = in[i + 1], c = in[i + 2], d = in[i + 3];
        acc0 += (a == a) ? a : 0.0;
        acc1 += (b == b) ? b : 0.0;
        acc2 += (c == c) ? c : 0.0;
        acc3 += (d == d) ? d : 0.0;
    }
    for (; i < n; i++) acc0 += (in[i] == in[i]) ? in[i] : 0.0;
    return acc0 + acc1 + acc2 + acc3;
}

long fdb_nan_count(const double* in, long n) {
    long cnt = 0;
    for (long i = 0; i < n; i++) cnt += (in[i] == in[i]);
    return cnt;
}

}  // extern "C"
