// Prometheus JSON sample-array renderer (the serving-edge hot loop).
//
// Renders one series' samples as the JSON fragment
//     [[<t_seconds>,"<value>"],[...],...]
// skipping NaN samples (Prometheus absence). Timestamps render in fixed
// 3-decimal seconds (Prometheus' millisecond convention, e.g.
// 1600000000.000) — byte-identical to the Python fallback in
// api/promjson.py. Values use std::to_chars shortest round-trip form;
// specials render as "NaN"/"+Inf"/"-Inf". The f32 variant widens to double
// first — identical to Python's float(np.float32(x)).
//
// Reference analog: prometheus/.../query/PrometheusModel.scala:256 (the JVM
// circe render). Throughput numbers of record: BENCH_LOCAL.json metrics
// prom_render_native_2M_random / _2M_integral / prom_render_python_100k_random
// (benchmarks/run.py bench_render measures all three).
//
// Build: g++ -O3 -march=native -std=c++17 -shared -fPIC promrender.cpp \
//        -o libfilodbrender.so

#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstring>

namespace {

// two-digit-pair lookup: halves the division chain in the hot itoa loops
// ("00".."99" as 200 contiguous bytes)
constexpr char kDigitPairs[201] =
    "00010203040506070809101112131415161718192021222324"
    "25262728293031323334353637383940414243444546474849"
    "50515253545556575859606162636465666768697071727374"
    "75767778798081828384858687888990919293949596979899";

inline char* emit_u64(char* p, unsigned long long v) {
    char tmp[20];
    char* q = tmp + 20;
    while (v >= 100) {
        unsigned d = unsigned(v % 100) * 2;
        v /= 100;
        *--q = kDigitPairs[d + 1];
        *--q = kDigitPairs[d];
    }
    if (v >= 10) {
        unsigned d = unsigned(v) * 2;
        *--q = kDigitPairs[d + 1];
        *--q = kDigitPairs[d];
    } else {
        *--q = char('0' + v);
    }
    std::memcpy(p, q, tmp + 20 - q);
    return p + (tmp + 20 - q);
}

// fixed 3-decimal seconds from a seconds-as-double timestamp; ~4x the
// throughput of to_chars shortest-form and format-stable across platforms.
// Matches the Python fallback's sign + magnitude-of-truncating-div/mod form
// exactly (llround = round-half-away; promjson._ts3).
inline char* render_ts(char* p, double t_sec) {
    long long ms = llround(t_sec * 1000.0);
    long long sec = ms / 1000;
    long long frac = ms % 1000;
    if (ms < 0) {  // pre-epoch: render sign, then magnitude
        *p++ = '-';
        sec = -sec;
        frac = -frac;
    }
    p = emit_u64(p, (unsigned long long)sec);
    *p++ = '.';
    unsigned d = unsigned(frac / 10) * 2;  // frac < 1000
    *p++ = kDigitPairs[d];
    *p++ = kDigitPairs[d + 1];
    *p++ = char('0' + frac % 10);
    return p;
}

// integral |v| < 1e15 with <= 4 trailing zeros: the fixed digit string is
// provably std::to_chars' shortest choice (scientific needs sig+5 bytes
// when sig >= 2, sig+4 when sig == 1, vs sig+zeros fixed — to_chars
// resolves length ties in favor of fixed), so emit it directly via the
// pair table instead of running the full Ryu shortest-form search.
// Counter/gauge exports are overwhelmingly integral, so this branch is the
// common case at the serving edge.
inline bool try_render_integral(char*& p, double v) {
    double av = v < 0 ? -v : v;
    if (!(av < 1e15)) return false;
    unsigned long long u = (unsigned long long)av;
    if ((double)u != av) return false;
    unsigned long long z = 0;  // trailing-zero count
    unsigned long long t = u;
    while (z <= 4 && t != 0 && t % 10 == 0) {
        t /= 10;
        z++;
    }
    if (z > 4) return false;
    if (std::signbit(v)) *p++ = '-';  // covers -0.0 -> "-0" like to_chars
    p = emit_u64(p, u);
    return true;
}

long render(const double* ts, const double* vals_d, const float* vals_f,
            long n, char* out, long cap) {
    char* p = out;
    char* e = out + cap;
    if (e - p < 2) return -1;
    *p++ = '[';
    bool first = true;
    for (long i = 0; i < n; i++) {
        double v = vals_d ? vals_d[i] : (double)vals_f[i];
        if (std::isnan(v)) continue;
        if (e - p < 64) return -1;
        if (!first) *p++ = ',';
        first = false;
        *p++ = '[';
        p = render_ts(p, ts[i]);
        *p++ = ',';
        *p++ = '"';
        if (std::isinf(v)) {
            std::memcpy(p, v > 0 ? "+Inf" : "-Inf", 4);
            p += 4;
        } else if (!try_render_integral(p, v)) {
            auto r2 = std::to_chars(p, e, v);
            if (r2.ec != std::errc()) return -1;
            p = r2.ptr;
        }
        *p++ = '"';
        *p++ = ']';
    }
    if (e - p < 1) return -1;
    *p++ = ']';
    return p - out;
}

}  // namespace

extern "C" {

long fdb_render_values_f64(const double* ts, const double* vals, long n,
                           char* out, long cap) {
    return render(ts, vals, nullptr, n, out, cap);
}

long fdb_render_values_f32(const double* ts, const float* vals, long n,
                           char* out, long cap) {
    return render(ts, nullptr, vals, n, out, cap);
}
}
