// Prometheus JSON sample-array renderer (the serving-edge hot loop).
//
// Renders one series' samples as the JSON fragment
//     [[<t_seconds>,"<value>"],[...],...]
// skipping NaN samples (Prometheus absence). Timestamps render in fixed
// 3-decimal seconds (Prometheus' millisecond convention, e.g.
// 1600000000.000) — byte-identical to the Python fallback in
// api/promjson.py. Values use std::to_chars shortest round-trip form;
// specials render as "NaN"/"+Inf"/"-Inf". The f32 variant widens to double
// first — identical to Python's float(np.float32(x)).
//
// Reference analog: prometheus/.../query/PrometheusModel.scala:256 (the JVM
// circe render). Measured on this machine (benchmarks/run.py bench_render,
// 2M random-f64 samples, warm): ~0.3 Msamples/s pure Python, >10 Msamples/s
// through this path (see BENCH_LOCAL.json for the number of record).
//
// Build: g++ -O3 -march=native -std=c++17 -shared -fPIC promrender.cpp \
//        -o libfilodbrender.so

#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstring>

namespace {

// fixed 3-decimal seconds from a seconds-as-double timestamp; ~2x the
// throughput of to_chars shortest-form and format-stable across platforms.
// Matches the Python fallback's int(floor(t*1000+0.5)) exactly for the
// non-negative timestamps Prometheus uses (llround = round-half-away).
inline char* render_ts(char* p, double t_sec) {
    long long ms = llround(t_sec * 1000.0);
    long long sec = ms / 1000;
    long long frac = ms % 1000;
    if (ms < 0) {  // pre-epoch: render sign, then magnitude
        *p++ = '-';
        sec = -sec;
        frac = -frac;
    }
    char tmp[20];
    char* q = tmp + 20;
    do {
        *--q = char('0' + sec % 10);
        sec /= 10;
    } while (sec);
    std::memcpy(p, q, tmp + 20 - q);
    p += tmp + 20 - q;
    *p++ = '.';
    *p++ = char('0' + frac / 100);
    *p++ = char('0' + (frac / 10) % 10);
    *p++ = char('0' + frac % 10);
    return p;
}

long render(const double* ts, const double* vals_d, const float* vals_f,
            long n, char* out, long cap) {
    char* p = out;
    char* e = out + cap;
    if (e - p < 2) return -1;
    *p++ = '[';
    bool first = true;
    for (long i = 0; i < n; i++) {
        double v = vals_d ? vals_d[i] : (double)vals_f[i];
        if (std::isnan(v)) continue;
        if (e - p < 64) return -1;
        if (!first) *p++ = ',';
        first = false;
        *p++ = '[';
        p = render_ts(p, ts[i]);
        *p++ = ',';
        *p++ = '"';
        if (std::isinf(v)) {
            std::memcpy(p, v > 0 ? "+Inf" : "-Inf", 4);
            p += 4;
        } else {
            auto r2 = std::to_chars(p, e, v);
            if (r2.ec != std::errc()) return -1;
            p = r2.ptr;
        }
        *p++ = '"';
        *p++ = ']';
    }
    if (e - p < 1) return -1;
    *p++ = ']';
    return p - out;
}

}  // namespace

extern "C" {

long fdb_render_values_f64(const double* ts, const double* vals, long n,
                           char* out, long cap) {
    return render(ts, vals, nullptr, n, out, cap);
}

long fdb_render_values_f32(const double* ts, const float* vals, long n,
                           char* out, long cap) {
    return render(ts, nullptr, vals, n, out, cap);
}
}
