// Prometheus JSON sample-array renderer (the serving-edge hot loop).
//
// Renders one series' samples as the JSON fragment
//     [[<t_seconds>,"<value>"],[...],...]
// skipping NaN samples (Prometheus absence). Timestamps render in fixed
// 3-decimal seconds (Prometheus' millisecond convention, e.g.
// 1600000000.000). Values render byte-identically to CPython's
// repr(float(v)) — shortest round-trip decimal with repr's fixed/scientific
// switch (-4 <= e10 < 16), integral values carrying a ".0" suffix — so the
// native fragment is byte-for-byte the Python fallback's output
// (api/promjson.py golden-asserts this). Specials render as "+Inf"/"-Inf".
// The f32 variant widens to double first — identical to float(np.float32(x)).
//
// The shortest-repr search is hand-rolled because this container's gcc 10
// libstdc++ ships integer std::to_chars but not the float overload: a
// double-long-double (Dekker) scaling by a ~128-bit power-of-10 table
// produces the 17-digit decimal plus an error term tight enough (~1e-21)
// to probe shorter candidates against the exact round-trip interval
// [v - ulp_down/2, v + ulp_up/2]. The interval is asymmetric at powers of
// two, so candidates are tested against each half-width rather than by
// distance alone. Ambiguous cases (genuine decimal ties near *.5, interval
// edges within 1e-9 ulp17) fall back to a snprintf/strtod probe loop that
// also tries the last-digit neighbour on the far side — near pow2
// boundaries the nearest k-digit decimal can fail the round trip while the
// neighbour passes. Fallback rate is ~0.6% on f32-widened data, ~0 on f64.
//
// Reference analog: prometheus/.../query/PrometheusModel.scala:256 (the JVM
// circe render). Throughput numbers of record: BENCH_LOCAL.json metrics
// prom_render_native_2M_random / _2M_integral / prom_render_python_100k_random
// (benchmarks/run.py bench_render measures all three).
//
// Build: g++ -O3 -march=native -std=c++17 -shared -fPIC promrender.cpp \
//        -o libfilodbrender.so

#include <cfloat>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

// two-digit-pair lookup: halves the division chain in the hot itoa loops
// ("00".."99" as 200 contiguous bytes)
constexpr char kDigitPairs[201] =
    "00010203040506070809101112131415161718192021222324"
    "25262728293031323334353637383940414243444546474849"
    "50515253545556575859606162636465666768697071727374"
    "75767778798081828384858687888990919293949596979899";

inline char* emit_u64(char* p, unsigned long long v) {
  char tmp[20];
  char* q = tmp + 20;
  while (v >= 100) {
    unsigned d = unsigned(v % 100) * 2;
    v /= 100;
    *--q = kDigitPairs[d + 1];
    *--q = kDigitPairs[d];
  }
  if (v >= 10) {
    unsigned d = unsigned(v) * 2;
    *--q = kDigitPairs[d + 1];
    *--q = kDigitPairs[d];
  } else {
    *--q = char('0' + v);
  }
  std::memcpy(p, q, tmp + 20 - q);
  return p + (tmp + 20 - q);
}

// fixed 3-decimal seconds from a seconds-as-double timestamp; ~4x the
// throughput of shortest-form and format-stable across platforms.
// Matches the Python fallback's sign + magnitude-of-truncating-div/mod form
// exactly (llround = round-half-away; promjson._ts3).
inline char* render_ts(char* p, double t_sec) {
  long long ms = llround(t_sec * 1000.0);
  long long sec = ms / 1000;
  long long frac = ms % 1000;
  if (ms < 0) {  // pre-epoch: render sign, then magnitude
    *p++ = '-';
    sec = -sec;
    frac = -frac;
  }
  p = emit_u64(p, (unsigned long long)sec);
  *p++ = '.';
  unsigned d = unsigned(frac / 10) * 2;  // frac < 1000
  *p++ = kDigitPairs[d];
  *p++ = kDigitPairs[d + 1];
  *p++ = char('0' + frac % 10);
  return p;
}

// ---- shortest round-trip digits, repr()-identical --------------------------

// double-long-double helpers (Dekker two_prod / two_sum on the 64-bit
// x87 mantissa)
const long double kLdSplit = 4294967297.0L;  // 2^32 + 1

inline void dd_two_prod(long double a, long double b, long double* hi,
                        long double* lo) {
  long double p = a * b;
  long double t = kLdSplit * a, ahi = t - (t - a), alo = a - ahi;
  t = kLdSplit * b;
  long double bhi = t - (t - b), blo = b - bhi;
  *hi = p;
  *lo = ((ahi * bhi - p) + ahi * blo + alo * bhi) + alo * blo;
}

inline void dd_two_sum(long double a, long double b, long double* hi,
                       long double* lo) {
  long double s = a + b, v = s - a;
  *hi = s;
  *lo = (a - (s - v)) + (b - v);
}

// dd power-of-10 table: P10H[i] + P10L2[i] ~= 10^(i-350) to ~128 bits
long double P10H[701], P10L2[701];

void p10_init() {
  P10H[350] = 1.0L;
  P10L2[350] = 0.0L;
  for (int n = 1; n <= 350; n++) {
    long double h, l, h2, l2;
    dd_two_prod(P10H[350 + n - 1], 10.0L, &h, &l);
    l += P10L2[350 + n - 1] * 10.0L;
    dd_two_sum(h, l, &h2, &l2);
    P10H[350 + n] = h2;
    P10L2[350 + n] = l2;
    // negative powers: dd division by the exactly-representable 10 via
    // quotient + residual correction
    long double q = P10H[350 - n + 1] / 10.0L;
    long double ph, pl;
    dd_two_prod(q, 10.0L, &ph, &pl);
    long double r = ((P10H[350 - n + 1] - ph) - pl) + P10L2[350 - n + 1];
    long double qlo = r / 10.0L;
    dd_two_sum(q, qlo, &h2, &l2);
    P10H[350 - n] = h2;
    P10L2[350 - n] = l2;
  }
}

const bool g_p10_ready = (p10_init(), true);  // runs at dlopen

const uint64_t POW10[18] = {1ull,
                            10ull,
                            100ull,
                            1000ull,
                            10000ull,
                            100000ull,
                            1000000ull,
                            10000000ull,
                            100000000ull,
                            1000000000ull,
                            10000000000ull,
                            100000000000ull,
                            1000000000000ull,
                            10000000000000ull,
                            100000000000000ull,
                            1000000000000000ull,
                            10000000000000000ull,
                            100000000000000000ull};

long g_slow_count = 0;

// slow-path helper: does the k-digit decimal D * 10^(e10-k+1) parse back to
// av? On success strips trailing zeros into digits/e10_out.
bool parse_eq(uint64_t D, int k, int e10, double av, char* digits,
              int* e10_out, int* nd_out) {
  char tmp[24], buf[48];
  auto r = std::to_chars(tmp, tmp + sizeof tmp, D);
  if ((int)(r.ptr - tmp) != k) return false;
  char* p = buf;
  *p++ = tmp[0];
  if (k > 1) {
    *p++ = '.';
    std::memcpy(p, tmp + 1, k - 1);
    p += k - 1;
  }
  *p++ = 'e';
  p += snprintf(p, 8, "%d", e10);
  *p = 0;
  if (strtod(buf, nullptr) != av) return false;
  int nd = k;
  while (nd > 1 && tmp[nd - 1] == '0') nd--;
  std::memcpy(digits, tmp, nd);
  *e10_out = e10;
  *nd_out = nd;
  return true;
}

// reference slow path: snprintf probing. For each digit count k, tries the
// correctly-rounded candidate AND its last-digit neighbour on the other side
// of av: near asymmetric ulp boundaries (powers of two) the nearest k-digit
// decimal can fail the round trip while the farther neighbour passes.
int slow_digits(double av, char* digits, int* e10_out) {
  char buf[64];
  for (int k = 1; k <= 17; k++) {
    snprintf(buf, sizeof buf, "%.*e", k - 1, av);
    double sv = strtod(buf, nullptr);
    uint64_t D = 0;
    for (const char* p = buf; *p && *p != 'e'; p++)
      if (*p >= '0' && *p <= '9') D = D * 10 + (uint64_t)(*p - '0');
    int e10 = atoi(strchr(buf, 'e') + 1);
    int nd;
    if (sv == av) {
      char tmp[24];
      std::to_chars(tmp, tmp + sizeof tmp, D);
      nd = k;
      while (nd > 1 && tmp[nd - 1] == '0') nd--;
      std::memcpy(digits, tmp, nd);
      *e10_out = e10;
      return nd;
    }
    if (sv < av) {
      if (D + 1 >= POW10[k]) {  // 999... carries into the next decade
        if (parse_eq(POW10[k - 1], k, e10 + 1, av, digits, e10_out, &nd))
          return nd;
      } else if (parse_eq(D + 1, k, e10, av, digits, e10_out, &nd)) {
        return nd;
      }
    } else if (D > POW10[k - 1]) {
      if (parse_eq(D - 1, k, e10, av, digits, e10_out, &nd)) return nd;
    }
  }
  return 0;
}

// fast path: dd scaling + integer candidate probing against the round-trip
// interval. Returns digit count, or -1 when a guard band is hit and the
// answer must come from slow_digits.
int fast_digits(double av, char* digits, int* e10_out) {
  if (LDBL_MANT_DIG < 64) return -1;  // needs the x87 64-bit mantissa
  int e2;
  (void)frexp(av, &e2);
  int e10 = (int)floor((e2 - 1) * 0.3010299956639812);
  if (e10 < -280 || e10 > 280) return -1;  // subnormal/extreme: slow path
  int i = 366 - e10;  // table index for 10^(16-e10)
  long double L, Le, t;
  dd_two_prod((long double)av, P10H[i], &L, &t);
  Le = t + (long double)av * P10L2[i];
  for (int k = 0; k < 3 && (L < 1e16L || L >= 1e17L); k++) {
    e10 += (L >= 1e17L) ? 1 : -1;
    if (e10 < -280 || e10 > 280) return -1;
    i = 366 - e10;
    dd_two_prod((long double)av, P10H[i], &L, &t);
    Le = t + (long double)av * P10L2[i];
  }
  if (L < 1e16L || L >= 1e17L) return -1;

  const long double GTIE = 1e-9L;  // >> dd error (~1e-21), << real margins
  uint64_t D17 = (uint64_t)(L + 0.5L);
  long double f17 = (L - (long double)D17) + Le;  // L_true - D17
  if (f17 >= 0.5L) {
    D17++;
    f17 -= 1.0L;
  } else if (f17 < -0.5L) {
    D17--;
    f17 += 1.0L;
  }
  // genuine decimal tie at the 17th digit (f32-widened data hits these)
  if (fabsl(fabsl(f17) - 0.5L) < GTIE) return -1;
  if (D17 < POW10[16] || D17 >= POW10[17]) return -1;

  // round-trip interval half-widths in ulp17 units (asymmetric at pow2)
  uint64_t ab;
  std::memcpy(&ab, &av, 8);
  double up, dn;
  uint64_t ub = ab + 1, db = ab - 1;
  std::memcpy(&up, &ub, 8);
  std::memcpy(&dn, &db, 8);
  long double hu = (long double)(up - av) * 0.5L * P10H[i];
  long double hd = (long double)(av - dn) * 0.5L * P10H[i];

  uint64_t flo = (f17 < 0) ? D17 - 1 : D17;
  uint64_t Dbest = D17;
  int jbest = 17, ebest = e10;
  for (int j = 16; j >= 1; j--) {
    uint64_t q = POW10[17 - j];
    uint64_t c1 = flo - flo % q;  // floor candidate at j digits
    uint64_t c2 = c1 + q;        // ceil candidate
    long double o1 = (long double)(int64_t)(c1 - D17) - f17;  // <= 0
    long double o2 = (long double)(int64_t)(c2 - D17) - f17;  // > 0
    bool ok1 = -o1 < hd, ok2 = o2 < hu;
    if (fabsl(-o1 - hd) < GTIE || fabsl(o2 - hu) < GTIE) return -1;
    if (!ok1 && !ok2) break;  // monotone: shorter can't round-trip either
    uint64_t D;
    if (ok1 && ok2) {
      if (fabsl(-o1 - o2) < GTIE) return -1;  // equidistant candidates
      D = (-o1 < o2) ? c1 : c2;
    } else {
      D = ok1 ? c1 : c2;
    }
    if (D >= POW10[17]) {  // ceil carried to 10^17: one digit, next decade
      Dbest = POW10[16];
      jbest = 17;
      ebest = e10 + 1;
    } else {
      Dbest = D / q;
      jbest = j;
      ebest = e10;
      if (Dbest >= POW10[j]) {  // in-decade carry (e.g. 999 -> 100, e+1)
        Dbest /= 10;
        ebest = e10 + 1;
      }
    }
  }
  while (jbest > 1 && Dbest % 10 == 0) {
    Dbest /= 10;
    jbest--;
  }
  char tmp[24];
  auto r = std::to_chars(tmp, tmp + sizeof tmp, Dbest);
  int len = (int)(r.ptr - tmp);
  std::memcpy(digits, tmp, len);
  *e10_out = ebest;
  return len;
}

// digits + decimal exponent -> repr() surface form: fixed for -4 <= e10 < 16
// (integral magnitudes carry ".0"), scientific d[.ddd]e±NN otherwise.
int format_repr(bool neg, const char* digits, int nd, int e10, char* out) {
  char* p = out;
  if (neg) *p++ = '-';
  if (-4 <= e10 && e10 < 16) {
    if (e10 >= nd - 1) {
      std::memcpy(p, digits, nd);
      p += nd;
      for (int i = 0; i < e10 - nd + 1; i++) *p++ = '0';
      *p++ = '.';
      *p++ = '0';
    } else if (e10 >= 0) {
      std::memcpy(p, digits, e10 + 1);
      p += e10 + 1;
      *p++ = '.';
      std::memcpy(p, digits + e10 + 1, nd - e10 - 1);
      p += nd - e10 - 1;
    } else {
      *p++ = '0';
      *p++ = '.';
      for (int i = 0; i < -e10 - 1; i++) *p++ = '0';
      std::memcpy(p, digits, nd);
      p += nd;
    }
  } else {
    *p++ = digits[0];
    if (nd > 1) {
      *p++ = '.';
      std::memcpy(p, digits + 1, nd - 1);
      p += nd - 1;
    }
    *p++ = 'e';
    *p++ = e10 < 0 ? '-' : '+';
    unsigned ae = e10 < 0 ? -e10 : e10;
    if (ae < 10) {  // repr pads the exponent to two digits
      *p++ = '0';
      *p++ = (char)('0' + ae);
    } else {
      auto rr = std::to_chars(p, p + 8, ae);
      p = rr.ptr;
    }
  }
  return (int)(p - out);
}

// finite, non-zero v -> repr(float(v)) bytes
inline char* render_value(char* p, double v) {
  bool neg = std::signbit(v);
  double av = neg ? -v : v;
  if (av < 1e16) {  // integral fast path: repr gives digits + ".0"
    double r = std::nearbyint(av);
    if (r == av) {
      if (neg) *p++ = '-';
      p = emit_u64(p, (unsigned long long)r);
      *p++ = '.';
      *p++ = '0';
      return p;
    }
  }
  char digits[24];
  int e10;
  int nd = fast_digits(av, digits, &e10);
  if (nd <= 0) {
    g_slow_count++;
    nd = slow_digits(av, digits, &e10);
  }
  return p + format_repr(neg, digits, nd, e10, p);
}

long render(const double* ts, const double* vals_d, const float* vals_f,
            long n, char* out, long cap) {
  char* p = out;
  char* e = out + cap;
  if (e - p < 2) return -1;
  *p++ = '[';
  bool first = true;
  for (long i = 0; i < n; i++) {
    double v = vals_d ? vals_d[i] : (double)vals_f[i];
    if (std::isnan(v)) continue;
    if (e - p < 64) return -1;
    if (!first) *p++ = ',';
    first = false;
    *p++ = '[';
    p = render_ts(p, ts[i]);
    *p++ = ',';
    *p++ = '"';
    if (std::isinf(v)) {
      std::memcpy(p, v > 0 ? "+Inf" : "-Inf", 4);
      p += 4;
    } else if (v == 0.0) {
      if (std::signbit(v)) *p++ = '-';
      std::memcpy(p, "0.0", 3);
      p += 3;
    } else {
      p = render_value(p, v);
    }
    *p++ = '"';
    *p++ = ']';
  }
  if (e - p < 1) return -1;
  *p++ = ']';
  return p - out;
}

}  // namespace

extern "C" {

// repr(float(v)) bytes into out (>= 32 bytes); returns length. Specials use
// repr's own names (nan/inf/-inf) — the JSON layer maps its NaN/+Inf/-Inf
// before reaching here. Exposed for the byte-parity torture test.
int fdb_format_double(double v, char* out) {
  if (std::isnan(v)) {
    std::memcpy(out, "nan", 3);
    return 3;
  }
  if (std::isinf(v)) {
    if (v > 0) {
      std::memcpy(out, "inf", 3);
      return 3;
    }
    std::memcpy(out, "-inf", 4);
    return 4;
  }
  if (v == 0.0) {
    bool neg = std::signbit(v);
    std::memcpy(out, neg ? "-0.0" : "0.0", 4);
    return neg ? 4 : 3;
  }
  char* p = render_value(out, v);
  return (int)(p - out);
}

// diagnostic: how many values fell through to the snprintf/strtod slow path
long fdb_fmt_slow_count() { return g_slow_count; }

long fdb_render_values_f64(const double* ts, const double* vals, long n,
                           char* out, long cap) {
  return render(ts, vals, nullptr, n, out, cap);
}

long fdb_render_values_f32(const double* ts, const float* vals, long n,
                           char* out, long cap) {
  return render(ts, nullptr, vals, n, out, cap);
}

// [G,J] matrix -> G per-series fragments written back-to-back into out.
// offsets (length G+1) gets each fragment's start byte; offsets[G] = total.
// Returns total bytes, or -1 if cap is too small.
long long fdb_render_matrix_f64(const double* ts, const double* vals,
                                long long G, long long J, char* out,
                                long long cap, long long* offsets) {
  char* p = out;
  for (long long g = 0; g < G; g++) {
    offsets[g] = p - out;
    long w = render(ts, vals + g * J, nullptr, (long)J, p,
                    (long)(out + cap - p));
    if (w < 0) return -1;
    p += w;
  }
  offsets[G] = p - out;
  return p - out;
}

long long fdb_render_matrix_f32(const double* ts, const float* vals,
                                long long G, long long J, char* out,
                                long long cap, long long* offsets) {
  char* p = out;
  for (long long g = 0; g < G; g++) {
    offsets[g] = p - out;
    long w = render(ts, nullptr, vals + g * J, (long)J, p,
                    (long)(out + cap - p));
    if (w < 0) return -1;
    p += w;
  }
  offsets[G] = p - out;
  return p - out;
}
}
