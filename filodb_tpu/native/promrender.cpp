// Prometheus JSON sample-array renderer (the serving-edge hot loop).
//
// Renders one series' samples as the JSON fragment
//     [[<t_seconds>,"<value>"],[...],...]
// skipping NaN samples (Prometheus absence). Numbers use std::to_chars
// shortest round-trip form; specials render as "NaN"/"+Inf"/"-Inf" exactly
// like the Python renderer (api/promjson.py _fmt). The f32 variant widens to
// double first — identical to Python's float(np.float32(x)).
//
// Reference analog: prometheus/.../query/PrometheusModel.scala:256 (the JVM
// circe render); measured 0.30 Msamples/s in pure Python, ~40+ Msamples/s
// here.
//
// Build: g++ -O3 -march=native -std=c++17 -shared -fPIC promrender.cpp \
//        -o libfilodbrender.so

#include <charconv>
#include <cmath>
#include <cstring>

namespace {

long render(const double* ts, const double* vals_d, const float* vals_f,
            long n, char* out, long cap) {
    char* p = out;
    char* e = out + cap;
    if (e - p < 2) return -1;
    *p++ = '[';
    bool first = true;
    for (long i = 0; i < n; i++) {
        double v = vals_d ? vals_d[i] : (double)vals_f[i];
        if (std::isnan(v)) continue;
        if (e - p < 64) return -1;
        if (!first) *p++ = ',';
        first = false;
        *p++ = '[';
        auto r = std::to_chars(p, e, ts[i]);
        if (r.ec != std::errc()) return -1;
        p = r.ptr;
        *p++ = ',';
        *p++ = '"';
        if (std::isinf(v)) {
            std::memcpy(p, v > 0 ? "+Inf" : "-Inf", 4);
            p += 4;
        } else {
            auto r2 = std::to_chars(p, e, v);
            if (r2.ec != std::errc()) return -1;
            p = r2.ptr;
        }
        *p++ = '"';
        *p++ = ']';
    }
    if (e - p < 1) return -1;
    *p++ = ']';
    return p - out;
}

}  // namespace

extern "C" {

long fdb_render_values_f64(const double* ts, const double* vals, long n,
                           char* out, long cap) {
    return render(ts, vals, nullptr, n, out, cap);
}

long fdb_render_values_f32(const double* ts, const float* vals, long n,
                           char* out, long cap) {
    return render(ts, nullptr, vals, n, out, cap);
}
}
