"""ctypes bindings for the native codec library (reference analog: the Rust
JNI shims, SimdNativeMethods.scala:15 / TantivyNativeMethods).

Builds libfilodbcodecs.so from codecs.cpp with g++ on first use if missing;
all callers fall back to the numpy implementations when no compiler exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(__file__)
_SO = os.path.join(_HERE, "libfilodbcodecs.so")
_SRC = os.path.join(_HERE, "codecs.cpp")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC", _SRC, "-o", _SO],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def lib():
    """The loaded native library, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not _build():
                return None
        try:
            L = ctypes.CDLL(_SO)
        except OSError:
            return None
        L.fdb_nibble_pack.restype = ctypes.c_long
        L.fdb_nibble_pack.argtypes = [
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_long,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_long,
        ]
        L.fdb_nibble_unpack.restype = ctypes.c_long
        L.fdb_nibble_unpack.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_long,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_long,
        ]
        L.fdb_nan_sum.restype = ctypes.c_double
        L.fdb_nan_sum.argtypes = [ctypes.POINTER(ctypes.c_double), ctypes.c_long]
        L.fdb_nan_count.restype = ctypes.c_long
        L.fdb_nan_count.argtypes = [ctypes.POINTER(ctypes.c_double), ctypes.c_long]
        _lib = L
        return _lib


def nibble_pack_native(values: np.ndarray) -> bytes | None:
    L = lib()
    if L is None:
        return None
    v = np.ascontiguousarray(values, dtype=np.uint64)
    n = len(v)
    cap = 2 + n * 9 + (n // 8 + 1) * 2
    out = np.empty(cap, dtype=np.uint8)
    written = L.fdb_nibble_pack(
        v.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap,
    )
    if written < 0:
        return None
    return out[:written].tobytes()


def nibble_unpack_native(data: bytes, n: int) -> np.ndarray | None:
    L = lib()
    if L is None:
        return None
    src = np.frombuffer(data, dtype=np.uint8)
    out = np.empty(n, dtype=np.uint64)
    consumed = L.fdb_nibble_unpack(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(src),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n,
    )
    if consumed < 0:
        return None
    return out


def nan_sum(values: np.ndarray) -> float:
    L = lib()
    v = np.ascontiguousarray(values, dtype=np.float64)
    if L is None:
        return float(np.nansum(v))
    return L.fdb_nan_sum(v.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), len(v))


def nan_count(values: np.ndarray) -> int:
    L = lib()
    v = np.ascontiguousarray(values, dtype=np.float64)
    if L is None:
        return int(np.count_nonzero(~np.isnan(v)))
    return L.fdb_nan_count(v.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), len(v))


# ---------------------------------------------------------------------------
# Prometheus text-exposition scanner (promparse.cpp -> libfilodbprom.so)
# ---------------------------------------------------------------------------

_PROM_SO = os.path.join(_HERE, "libfilodbprom.so")
_PROM_SRC = os.path.join(_HERE, "promparse.cpp")
_prom_lib = None
_prom_tried = False

# must mirror FdbPromRec in promparse.cpp (x86-64 struct layout, 8-aligned)
PROM_REC_DTYPE = np.dtype(
    {
        "names": ["key_off", "key_len", "value", "ts_ms", "type_code", "flags"],
        "formats": [np.uint32, np.uint32, np.float64, np.int64, np.uint8, np.uint8],
        "offsets": [0, 4, 8, 16, 24, 25],
        "itemsize": 32,
    }
)

TS_ABSENT = np.iinfo(np.int64).min


def prom_lib():
    global _prom_lib, _prom_tried
    if _prom_lib is not None or _prom_tried:
        return _prom_lib
    with _lock:
        if _prom_lib is not None or _prom_tried:
            return _prom_lib
        _prom_tried = True
        try:  # binary-only deployments may ship the .so without the source
            stale = (not os.path.exists(_PROM_SO)
                     or os.path.getmtime(_PROM_SO) < os.path.getmtime(_PROM_SRC))
        except OSError:
            stale = not os.path.exists(_PROM_SO)
        if stale:
            try:
                subprocess.run(
                    ["g++", "-O3", "-march=native", "-std=c++17", "-shared",
                     "-fPIC", _PROM_SRC, "-o", _PROM_SO],
                    check=True, capture_output=True, timeout=120,
                )
            except Exception:
                return None
        try:
            L = ctypes.CDLL(_PROM_SO)
        except OSError:
            return None
        L.fdb_parse_prom.restype = ctypes.c_long
        L.fdb_parse_prom.argtypes = [
            ctypes.c_char_p, ctypes.c_long,
            ctypes.c_void_p, ctypes.c_long,
        ]
        _prom_lib = L
        return _prom_lib


# splitlines() separators the byte scanner cannot see (multi-byte UTF-8):
# payloads containing them take the pure-Python path for exact parity
_UNICODE_SEPS = (b"\xc2\x85", b"\xe2\x80\xa8", b"\xe2\x80\xa9")


def parse_prom_records(payload: bytes):
    """Scan a Prometheus exposition payload natively. Returns a structured
    array (PROM_REC_DTYPE) of records, or None when the native lib is
    unavailable (callers fall back to the Python parser). Never raises on
    content: lines the scanner can't tokenize exactly like Python come back
    flagged (flags=1) for per-line Python parsing."""
    L = prom_lib()
    if L is None:
        return None
    if any(s in payload for s in _UNICODE_SEPS):
        return None
    # every record consumes at least one line; count all separator bytes
    max_out = sum(payload.count(s) for s in b"\n\r\v\f\x1c\x1d\x1e") + 2
    out = np.zeros(max_out, dtype=PROM_REC_DTYPE)
    n = L.fdb_parse_prom(payload, len(payload), out.ctypes.data, max_out)
    if n < 0:  # defensive: max_out is sized from separator count
        return None
    return out[:n]


INFLUX_REC_DTYPE = np.dtype(
    {
        "names": ["key_off", "key_len", "field_off", "field_len", "value",
                  "ts_ms", "flags"],
        "formats": [np.uint32, np.uint32, np.uint32, np.uint32, np.float64,
                    np.int64, np.uint8],
        "offsets": [0, 4, 8, 12, 16, 24, 32],
        "itemsize": 40,
    }
)


def parse_influx_records(payload: bytes):
    """Scan an Influx line-protocol payload natively; None when the lib is
    unavailable. Same defer contract as parse_prom_records."""
    L = prom_lib()
    if L is None:
        return None
    if any(s in payload for s in _UNICODE_SEPS):
        return None
    if not hasattr(L, "_influx_bound"):
        L.fdb_parse_influx.restype = ctypes.c_long
        L.fdb_parse_influx.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_void_p, ctypes.c_long,
        ]
        L._influx_bound = True
    # a line can hold many fields: size by commas+lines (upper bound)
    max_out = (sum(payload.count(s) for s in b"\n\r\v\f\x1c\x1d\x1e")
               + payload.count(b",") + 2)
    out = np.zeros(max_out, dtype=INFLUX_REC_DTYPE)
    n = L.fdb_parse_influx(payload, len(payload), out.ctypes.data, max_out)
    if n < 0:
        return None
    return out[:n]


# ---------------------------------------------------------------------------
# Prometheus JSON sample renderer (promrender.cpp -> libfilodbrender.so)
# ---------------------------------------------------------------------------

_RENDER_SO = os.path.join(_HERE, "libfilodbrender.so")
_RENDER_SRC = os.path.join(_HERE, "promrender.cpp")
_render_lib = None
_render_tried = False
_render_scratch = threading.local()


def render_lib():
    global _render_lib, _render_tried
    if _render_lib is not None or _render_tried:
        return _render_lib
    with _lock:
        if _render_lib is not None or _render_tried:
            return _render_lib
        _render_tried = True
        try:
            stale = (not os.path.exists(_RENDER_SO)
                     or os.path.getmtime(_RENDER_SO) < os.path.getmtime(_RENDER_SRC))
        except OSError:
            stale = not os.path.exists(_RENDER_SO)
        if stale:
            try:
                subprocess.run(
                    ["g++", "-O3", "-march=native", "-std=c++17", "-shared",
                     "-fPIC", _RENDER_SRC, "-o", _RENDER_SO],
                    check=True, capture_output=True, timeout=120,
                )
            except Exception:
                return None
        try:
            L = ctypes.CDLL(_RENDER_SO)
        except OSError:
            return None
        for name, vt in (("fdb_render_values_f64", ctypes.POINTER(ctypes.c_double)),
                         ("fdb_render_values_f32", ctypes.POINTER(ctypes.c_float))):
            fn = getattr(L, name)
            fn.restype = ctypes.c_long
            fn.argtypes = [ctypes.POINTER(ctypes.c_double), vt,
                           ctypes.c_long, ctypes.c_void_p, ctypes.c_long]
        for name, vt in (("fdb_render_matrix_f64", ctypes.POINTER(ctypes.c_double)),
                         ("fdb_render_matrix_f32", ctypes.POINTER(ctypes.c_float))):
            fn = getattr(L, name)
            fn.restype = ctypes.c_longlong
            fn.argtypes = [ctypes.POINTER(ctypes.c_double), vt,
                           ctypes.c_longlong, ctypes.c_longlong,
                           ctypes.c_void_p, ctypes.c_longlong,
                           ctypes.POINTER(ctypes.c_longlong)]
        L.fdb_format_double.restype = ctypes.c_int
        L.fdb_format_double.argtypes = [ctypes.c_double, ctypes.c_char_p]
        L.fdb_fmt_slow_count.restype = ctypes.c_long
        L.fdb_fmt_slow_count.argtypes = []
        _render_lib = L
        return _render_lib


def render_values(ts_s: np.ndarray, vals: np.ndarray):
    """Render [[t,"v"],...] (NaN samples skipped) natively; None when the
    lib is unavailable (callers fall back to the Python renderer)."""
    L = render_lib()
    if L is None:
        return None
    ts = np.ascontiguousarray(ts_s, dtype=np.float64)
    n = len(ts)
    cap = 64 * n + 16
    # thread-local reusable scratch + a copy of only the written bytes: the
    # previous create_string_buffer + .raw[:nw] zero-filled AND copied the
    # full 64*n capacity every call (and freshly-mapped pages fault during
    # the render), capping large renders at ~2-3 Msamples/s by memory traffic
    out = getattr(_render_scratch, "buf", None)
    if out is None or len(out) < cap:
        out = np.empty(max(cap, 1 << 20), dtype=np.uint8)
        _render_scratch.buf = out
    if vals.dtype == np.float32:
        v = np.ascontiguousarray(vals, dtype=np.float32)
        nw = L.fdb_render_values_f32(
            ts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n,
            out.ctypes.data, cap)
    else:
        v = np.ascontiguousarray(vals, dtype=np.float64)
        nw = L.fdb_render_values_f64(
            ts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n,
            out.ctypes.data, cap)
    if nw < 0:
        return None
    return out[:nw].tobytes()


def render_matrix_rows(ts_s: np.ndarray, vals: np.ndarray):
    """Render a [G,J] matrix as G per-series [[t,"v"],...] fragments in ONE
    native call (per-row ctypes dispatch costs ~2us, which dominates small
    rows); returns a list of G bytes objects, or None when the lib is
    unavailable."""
    L = render_lib()
    if L is None or vals.ndim != 2:
        return None
    ts = np.ascontiguousarray(ts_s, dtype=np.float64)
    G, J = vals.shape
    if len(ts) != J:
        return None
    cap = 64 * G * J + 4 * G + 16
    out = getattr(_render_scratch, "buf", None)
    if out is None or len(out) < cap:
        out = np.empty(max(cap, 1 << 20), dtype=np.uint8)
        _render_scratch.buf = out
    offs = np.empty(G + 1, dtype=np.int64)
    offs_p = offs.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong))
    if vals.dtype == np.float32:
        v = np.ascontiguousarray(vals, dtype=np.float32)
        nw = L.fdb_render_matrix_f32(
            ts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            G, J, out.ctypes.data, cap, offs_p)
    else:
        v = np.ascontiguousarray(vals, dtype=np.float64)
        nw = L.fdb_render_matrix_f64(
            ts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            G, J, out.ctypes.data, cap, offs_p)
    if nw < 0:
        return None
    raw = out[:nw].tobytes()
    return [raw[offs[g]:offs[g + 1]] for g in range(G)]


def format_double(v: float) -> str | None:
    """repr(float(v)) via the native formatter; None when unavailable.
    Exposed for the byte-parity torture test."""
    L = render_lib()
    if L is None:
        return None
    buf = ctypes.create_string_buffer(40)
    n = L.fdb_format_double(float(v), buf)
    return buf.raw[:n].decode()
