"""ctypes bindings for the native codec library (reference analog: the Rust
JNI shims, SimdNativeMethods.scala:15 / TantivyNativeMethods).

Builds libfilodbcodecs.so from codecs.cpp with g++ on first use if missing;
all callers fall back to the numpy implementations when no compiler exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(__file__)
_SO = os.path.join(_HERE, "libfilodbcodecs.so")
_SRC = os.path.join(_HERE, "codecs.cpp")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC", _SRC, "-o", _SO],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def lib():
    """The loaded native library, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not _build():
                return None
        try:
            L = ctypes.CDLL(_SO)
        except OSError:
            return None
        L.fdb_nibble_pack.restype = ctypes.c_long
        L.fdb_nibble_pack.argtypes = [
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_long,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_long,
        ]
        L.fdb_nibble_unpack.restype = ctypes.c_long
        L.fdb_nibble_unpack.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_long,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_long,
        ]
        L.fdb_nan_sum.restype = ctypes.c_double
        L.fdb_nan_sum.argtypes = [ctypes.POINTER(ctypes.c_double), ctypes.c_long]
        L.fdb_nan_count.restype = ctypes.c_long
        L.fdb_nan_count.argtypes = [ctypes.POINTER(ctypes.c_double), ctypes.c_long]
        _lib = L
        return _lib


def nibble_pack_native(values: np.ndarray) -> bytes | None:
    L = lib()
    if L is None:
        return None
    v = np.ascontiguousarray(values, dtype=np.uint64)
    n = len(v)
    cap = 2 + n * 9 + (n // 8 + 1) * 2
    out = np.empty(cap, dtype=np.uint8)
    written = L.fdb_nibble_pack(
        v.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap,
    )
    if written < 0:
        return None
    return out[:written].tobytes()


def nibble_unpack_native(data: bytes, n: int) -> np.ndarray | None:
    L = lib()
    if L is None:
        return None
    src = np.frombuffer(data, dtype=np.uint8)
    out = np.empty(n, dtype=np.uint64)
    consumed = L.fdb_nibble_unpack(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(src),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n,
    )
    if consumed < 0:
        return None
    return out


def nan_sum(values: np.ndarray) -> float:
    L = lib()
    v = np.ascontiguousarray(values, dtype=np.float64)
    if L is None:
        return float(np.nansum(v))
    return L.fdb_nan_sum(v.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), len(v))


def nan_count(values: np.ndarray) -> int:
    L = lib()
    v = np.ascontiguousarray(values, dtype=np.float64)
    if L is None:
        return int(np.count_nonzero(~np.isnan(v)))
    return L.fdb_nan_count(v.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), len(v))
