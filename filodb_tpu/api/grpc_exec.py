"""gRPC cross-cluster exec: server + client exec nodes (reference analog:
grpc/.../query_service.proto service RemoteExec:1126-1134 and its
GrpcCommonUtils/PromQLGrpcServer — exec, execStreaming, executePlan).

Service stubs are hand-written over grpc generic handlers (grpc_tools is
not in the image); messages are protoc-generated (query_exec_pb2). Two
methods, both server-streaming (the reference's non-streaming `exec` is
subsumed — a unary result is a one-grid stream):

- ``Exec``        PromQL string + grid params -> StreamFrame stream
- ``ExecutePlan`` serialized LogicalPlan      -> StreamFrame stream

Cross-host semantics mirror the HTTP scatter path exactly: ``local_only``
pins the peer to its own shard slice (the X-FiloDB-Local twin), bearer
tokens ride call metadata, and errors travel in-band as the final frame so
clients re-raise typed QueryErrors.
"""

from __future__ import annotations

import logging
import os
import threading
from concurrent import futures

import grpc

from ..query.proto_plan import (
    PlanDecodeError,
    RemoteExecError,
    error_frame,
    frames_to_result,
    plan_to_proto,
    proto_to_plan,
    result_to_frames,
)
from . import query_exec_pb2 as pb

log = logging.getLogger("filodb_tpu.grpc")

SERVICE = "filodb_tpu.exec.RemoteExec"
_EXEC = f"/{SERVICE}/Exec"
_EXECUTE_PLAN = f"/{SERVICE}/ExecutePlan"


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _RemoteExecServicer:
    def __init__(self, engine, local_engine=None, auth_token: str | None = None):
        self.engine = engine
        self.local_engine = local_engine
        self.auth_token = auth_token

    # -- helpers ----------------------------------------------------------

    def _authorize(self, context) -> bool:
        if not self.auth_token:
            return True
        import hmac

        got = ""
        for k, v in context.invocation_metadata():
            if k == "authorization":
                got = v
        # constant-time compare, same as the HTTP edge (api/http.py)
        if hmac.compare_digest(got, f"Bearer {self.auth_token}"):
            return True
        context.abort(grpc.StatusCode.UNAUTHENTICATED, "bad or missing bearer token")
        return False  # unreached

    def _engine_for(self, params: "pb.QueryParams"):
        if params.local_only and self.local_engine is not None:
            return self.local_engine
        return self.engine

    def _stream(self, run):
        """Run ``run()`` -> QueryResult and stream frames; errors go in-band
        as the final frame (clients re-raise typed)."""
        from ..coordinator.scheduler import QueryRejected
        from ..query.exec.transformers import QueryDeadlineExceeded, QueryError
        from ..query.promql import PromQLError

        try:
            res = run()
        except QueryRejected as e:
            yield error_frame("QueryRejected", str(e))
            return
        except QueryDeadlineExceeded as e:
            yield error_frame("DeadlineExceeded", str(e))
            return
        except PlanDecodeError as e:
            yield error_frame("PlanDecodeError", str(e))
            return
        except (QueryError, PromQLError) as e:
            yield error_frame("QueryError", str(e))
            return
        except Exception as e:  # noqa: BLE001
            log.exception("remote exec failed")
            yield error_frame("Internal", f"{type(e).__name__}: {e}")
            return
        yield from result_to_frames(res)

    # -- methods ----------------------------------------------------------

    def Exec(self, request: "pb.ExecRequest", context):
        self._authorize(context)
        eng = self._engine_for(request.params)
        p = request.params

        def run():
            if request.instant:
                return eng.query_instant(request.promql, p.end_ms / 1000.0)
            return eng.query_range(
                request.promql, p.start_ms / 1000.0, p.end_ms / 1000.0,
                (p.step_ms or 1000) / 1000.0,
            )

        yield from self._stream(run)

    def ExecutePlan(self, request: "pb.ExecutePlanRequest", context):
        self._authorize(context)
        eng = self._engine_for(request.params)
        p = request.params

        def run():
            plan = proto_to_plan(request.plan)
            return eng.execute_plan(plan, deadline_s=p.deadline_s,
                                    max_series=p.max_series)

        yield from self._stream(run)


def serve_grpc(engine, port: int = 0, auth_token: str | None = None,
               local_engine=None, max_workers: int = 8,
               host: str = "127.0.0.1"):
    """Start the RemoteExec gRPC server; returns (server, bound_port)."""
    servicer = _RemoteExecServicer(engine, local_engine, auth_token)
    handlers = {
        "Exec": grpc.unary_stream_rpc_method_handler(
            servicer.Exec,
            request_deserializer=pb.ExecRequest.FromString,
            response_serializer=pb.StreamFrame.SerializeToString,
        ),
        "ExecutePlan": grpc.unary_stream_rpc_method_handler(
            servicer.ExecutePlan,
            request_deserializer=pb.ExecutePlanRequest.FromString,
            response_serializer=pb.StreamFrame.SerializeToString,
        ),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers,
                                   thread_name_prefix="filodb-grpc"),
        options=[("grpc.so_reuseport", 0)],
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise OSError(f"cannot bind gRPC port {port}")
    server.start()
    return server, bound


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

_channels: dict[str, grpc.Channel] = {}
_channels_lock = threading.Lock()


def grpc_target(endpoint: str) -> str:
    """'grpc://host:port' or 'host:port' -> grpc channel target."""
    return endpoint[len("grpc://"):] if endpoint.startswith("grpc://") else endpoint


def _channel(endpoint: str) -> grpc.Channel:
    target = grpc_target(endpoint)
    with _channels_lock:
        ch = _channels.get(target)
        if ch is None:
            ch = grpc.insecure_channel(
                target,
                options=[
                    ("grpc.max_receive_message_length", 64 * 1024 * 1024),
                    ("grpc.max_send_message_length", 64 * 1024 * 1024),
                ],
            )
            _channels[target] = ch
        return ch


def _metadata(auth_token: str | None):
    return (("authorization", f"Bearer {auth_token}"),) if auth_token else None


def _call_stream(endpoint: str, method: str, request, serializer, auth_token,
                 timeout_s: float | None, retries: int = 1):
    """unary_stream call with bounded UNAVAILABLE retries (mirrors the HTTP
    transport's retry discipline in planners.fetch_json)."""
    ch = _channel(endpoint)
    call = ch.unary_stream(
        method,
        request_serializer=serializer,
        response_deserializer=pb.StreamFrame.FromString,
    )
    attempt = 0
    while True:
        try:
            return frames_to_result(
                call(request, timeout=timeout_s, metadata=_metadata(auth_token))
            )
        except grpc.RpcError as e:
            code = e.code() if hasattr(e, "code") else None
            if code == grpc.StatusCode.UNAVAILABLE and attempt < retries:
                attempt += 1
                import time as _t

                _t.sleep(0.2 * attempt)
                continue
            raise RemoteExecError(str(code), e.details() if hasattr(e, "details") else str(e)) from e


def exec_promql(endpoint: str, promql: str, start_ms: int, end_ms: int, step_ms: int,
                auth_token: str | None = None, local_only: bool = False,
                instant: bool = False, timeout_s: float | None = None):
    req = pb.ExecRequest(
        promql=promql, instant=instant,
        params=pb.QueryParams(start_ms=start_ms, end_ms=end_ms, step_ms=step_ms,
                              local_only=local_only),
    )
    return _call_stream(endpoint, _EXEC, req, pb.ExecRequest.SerializeToString,
                        auth_token, timeout_s)


def exec_plan_remote(endpoint: str, logical_plan, auth_token: str | None = None,
                     local_only: bool = False, deadline_s: float = 0.0,
                     max_series: int = 0, timeout_s: float | None = None):
    req = pb.ExecutePlanRequest(
        plan=plan_to_proto(logical_plan),
        params=pb.QueryParams(local_only=local_only, deadline_s=deadline_s,
                              max_series=max_series),
    )
    return _call_stream(endpoint, _EXECUTE_PLAN, req,
                        pb.ExecutePlanRequest.SerializeToString, auth_token, timeout_s)


from ..query.exec.plans import ExecPlan  # noqa: E402  (no cycle: query/ never imports api/)


class GrpcPlanRemoteExec(ExecPlan):
    """ExecPlan leaf executing a serialized LogicalPlan subtree on a peer
    over gRPC (reference executePlan handler of service RemoteExec)."""

    is_remote = True

    def __init__(self, endpoint: str, logical_plan, auth_token: str | None = None,
                 local_only: bool = False, timeout_s: float | None = None):
        super().__init__()
        self.endpoint = endpoint
        self.logical_plan = logical_plan
        # same env fallback as PromQlRemoteExec so token-protected federation
        # works over either transport
        self.auth_token = auth_token or os.environ.get("FILODB_REMOTE_TOKEN")
        self.local_only = local_only
        self.timeout_s = timeout_s

    def push_aggregate(self, wrapped_logical) -> None:
        """Aggregate pushdown rewrite: ship ``sum by(...)`` of the leaf
        instead of raw series (planner._push_peer_aggregate)."""
        self.logical_plan = wrapped_logical

    def args_str(self) -> str:
        return f"endpoint={self.endpoint} plan={type(self.logical_plan).__name__}"

    def do_execute(self, ctx):
        return exec_plan_remote(
            self.endpoint, self.logical_plan, auth_token=self.auth_token,
            local_only=self.local_only, deadline_s=ctx.deadline_s,
            max_series=ctx.max_series, timeout_s=self.timeout_s or ctx.deadline_s,
        )


def remote_metadata(endpoint: str, plan, auth_token: str | None = None,
                    timeout_s: float | None = 60.0):
    """Metadata scatter over gRPC: execute a metadata LogicalPlan on the
    peer (locally pinned) and return its ``metadata`` payload."""
    res = exec_plan_remote(endpoint, plan, auth_token=auth_token,
                           local_only=True, timeout_s=timeout_s)
    return res.metadata or []
