"""gRPC cross-cluster exec: server + client exec nodes (reference analog:
grpc/.../query_service.proto service RemoteExec:1126-1134 and its
GrpcCommonUtils/PromQLGrpcServer — exec, execStreaming, executePlan).

Service stubs are hand-written over grpc generic handlers (grpc_tools is
not in the image); messages are protoc-generated (query_exec_pb2). Two
methods, both server-streaming (the reference's non-streaming `exec` is
subsumed — a unary result is a one-grid stream):

- ``Exec``        PromQL string + grid params -> StreamFrame stream
- ``ExecutePlan`` serialized LogicalPlan      -> StreamFrame stream

Cross-host semantics mirror the HTTP scatter path exactly: ``local_only``
pins the peer to its own shard slice (the X-FiloDB-Local twin), bearer
tokens ride call metadata, and errors travel in-band as the final frame so
clients re-raise typed QueryErrors.
"""

from __future__ import annotations

import logging
import os
import threading
from concurrent import futures

import grpc

from ..query.proto_plan import (
    PlanDecodeError,
    RemoteExecError,
    error_frame,
    frames_to_result,
    plan_to_proto,
    proto_to_plan,
    result_to_frames,
)
from . import query_exec_pb2 as pb

log = logging.getLogger("filodb_tpu.grpc")

SERVICE = "filodb_tpu.exec.RemoteExec"
_EXEC = f"/{SERVICE}/Exec"
_EXECUTE_PLAN = f"/{SERVICE}/ExecutePlan"


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _RemoteExecServicer:
    def __init__(self, engine, local_engine=None, auth_token: str | None = None):
        self.engine = engine
        self.local_engine = local_engine
        self.auth_token = auth_token
        # shard-subset engines (replica routing): a caller may pin the call
        # to a subset of this node's shards via x-filodb-shards metadata —
        # engines are built lazily per distinct subset and cached
        self._subset_engines: dict = {}
        self._subset_lock = threading.Lock()

    # -- helpers ----------------------------------------------------------

    def _authorize(self, context) -> bool:
        if not self.auth_token:
            return True
        import hmac

        got = ""
        for k, v in context.invocation_metadata():
            if k == "authorization":
                got = v
        # constant-time compare, same as the HTTP edge (api/http.py)
        if hmac.compare_digest(got, f"Bearer {self.auth_token}"):
            return True
        context.abort(grpc.StatusCode.UNAUTHENTICATED, "bad or missing bearer token")
        return False  # unreached

    def _engine_for(self, params: "pb.QueryParams", context=None):
        base = self.engine
        is_local = False
        if params.local_only and self.local_engine is not None:
            base = self.local_engine
            is_local = True
        subset = self._shard_subset(context) if context is not None else None
        if subset is None:
            return base
        return self._subset_engine(base, is_local, subset)

    def _subset_engine(self, base, is_local: bool, subset: tuple):
        """Engine pinned to a subset of this node's shards (replica routing:
        the origin asks for exactly the shards this replica serves for it).
        Cached per distinct subset; peer fan-out and replica routing are
        stripped so the subset engine only reads local state."""
        key = (is_local, subset)
        with self._subset_lock:
            eng = self._subset_engines.get(key)
            if eng is not None:
                return eng
            import dataclasses

            from ..coordinator.planner import QueryEngine

            owned = set(base.memstore.shard_nums(base.dataset))
            shards = [s for s in subset if s in owned]
            params = dataclasses.replace(
                base.planner.params, peer_endpoints=(), replica_router=None,
            )
            eng = QueryEngine(base.memstore, base.dataset, params=params,
                              shard_nums=shards)
            self._subset_engines[key] = eng
            return eng

    @staticmethod
    def _shard_subset(context) -> tuple | None:
        """Sorted shard subset from x-filodb-shards metadata, or None."""
        for k, v in context.invocation_metadata():
            if k == SHARDS_MD_KEY:
                try:
                    return tuple(sorted(int(x) for x in v.split(",") if x))
                except ValueError:
                    return None
        return None

    @staticmethod
    def _allow_partial(context) -> bool | None:
        """Tri-state like the HTTP edge: absent metadata means the engine's
        configured default, not False."""
        for k, v in context.invocation_metadata():
            if k == ALLOW_PARTIAL_MD_KEY:
                return v == "1"
        return None

    @staticmethod
    def _stats_ext(context) -> bool:
        """Origin advertises StatsExt support via metadata; absent = older
        origin that would fail on the unknown frame type, so don't send."""
        for k, v in context.invocation_metadata():
            if k == STATS_EXT_MD_KEY:
                return v == "1"
        return False

    @staticmethod
    def _trace_parent(context) -> tuple[str | None, str | None]:
        """(trace_id, parent_span_id) from call metadata: the origin's span
        identity, so this peer's span tree joins the origin's trace and its
        slow-query entries share the origin's trace id."""
        trace_id = parent = None
        for k, v in context.invocation_metadata():
            if k == TRACE_ID_MD_KEY:
                trace_id = v
            elif k == PARENT_SPAN_MD_KEY:
                parent = v
        return trace_id, parent

    def _stream(self, run, context=None, stats_ext: bool = False):
        """Run ``run()`` -> QueryResult and stream frames; errors go in-band
        as the final frame (clients re-raise typed)."""
        import json as _json

        from ..coordinator.scheduler import QueryRejected
        from ..query.exec.transformers import QueryDeadlineExceeded, QueryError
        from ..query.promql import PromQLError
        from ..query.scheduler import AdmissionRejected

        try:
            res = run()
        except AdmissionRejected as e:
            # admission shed: typed in-band frame (clients re-raise the
            # local AdmissionRejected) + the HTTP Retry-After's gRPC
            # equivalent riding trailing call metadata
            if context is not None:
                context.set_trailing_metadata(
                    ((RETRY_AFTER_MD_KEY, f"{e.retry_after_s:.3f}"),)
                )
            yield error_frame("AdmissionRejected", _json.dumps(e.warning()))
            return
        except QueryRejected as e:
            yield error_frame("QueryRejected", str(e))
            return
        except QueryDeadlineExceeded as e:
            yield error_frame("DeadlineExceeded", str(e))
            return
        except PlanDecodeError as e:
            yield error_frame("PlanDecodeError", str(e))
            return
        except (QueryError, PromQLError) as e:
            yield error_frame("QueryError", str(e))
            return
        except Exception as e:  # noqa: BLE001
            log.exception("remote exec failed")
            yield error_frame("Internal", f"{type(e).__name__}: {e}")
            return
        # result-plane accounting parity with the HTTP edge: the gRPC leg
        # is already columnar (proto frames wrap the raw f32 grid bytes) —
        # time the frame encode and count wire bytes under format=grpc
        import time as _time

        from ..metrics import REGISTRY

        t_r = _time.perf_counter()
        nbytes = 0
        for frame in result_to_frames(res, stats_ext=stats_ext):
            nbytes += frame.ByteSize()
            yield frame
        REGISTRY.histogram("filodb_render_seconds", format="grpc").observe(
            _time.perf_counter() - t_r)
        REGISTRY.counter("filodb_response_bytes", format="grpc").inc(nbytes)

    # -- methods ----------------------------------------------------------

    def Exec(self, request: "pb.ExecRequest", context):
        self._authorize(context)
        eng = self._engine_for(request.params, context)
        p = request.params
        allow_partial = self._allow_partial(context)
        trace_id, parent_span = self._trace_parent(context)

        def run():
            if request.instant:
                return eng.query_instant(request.promql, p.end_ms / 1000.0,
                                         allow_partial_results=allow_partial,
                                         trace_id=trace_id,
                                         parent_span_id=parent_span)
            return eng.query_range(
                request.promql, p.start_ms / 1000.0, p.end_ms / 1000.0,
                (p.step_ms or 1000) / 1000.0,
                allow_partial_results=allow_partial,
                trace_id=trace_id, parent_span_id=parent_span,
            )

        yield from self._stream(run, context=context,
                                stats_ext=self._stats_ext(context))

    def ExecutePlan(self, request: "pb.ExecutePlanRequest", context):
        self._authorize(context)
        eng = self._engine_for(request.params, context)
        p = request.params
        allow_partial = self._allow_partial(context)
        trace_id, parent_span = self._trace_parent(context)

        def run():
            plan = proto_to_plan(request.plan)
            return eng.execute_plan(plan, deadline_s=p.deadline_s,
                                    max_series=p.max_series,
                                    allow_partial_results=allow_partial,
                                    trace_id=trace_id,
                                    parent_span_id=parent_span)

        yield from self._stream(run, context=context,
                                stats_ext=self._stats_ext(context))


def serve_grpc(engine, port: int = 0, auth_token: str | None = None,
               local_engine=None, max_workers: int = 8,
               host: str = "127.0.0.1"):
    """Start the RemoteExec gRPC server; returns (server, bound_port)."""
    servicer = _RemoteExecServicer(engine, local_engine, auth_token)
    handlers = {
        "Exec": grpc.unary_stream_rpc_method_handler(
            servicer.Exec,
            request_deserializer=pb.ExecRequest.FromString,
            response_serializer=pb.StreamFrame.SerializeToString,
        ),
        "ExecutePlan": grpc.unary_stream_rpc_method_handler(
            servicer.ExecutePlan,
            request_deserializer=pb.ExecutePlanRequest.FromString,
            response_serializer=pb.StreamFrame.SerializeToString,
        ),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers,
                                   thread_name_prefix="filodb-grpc"),
        options=[("grpc.so_reuseport", 0)],
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise OSError(f"cannot bind gRPC port {port}")
    server.start()
    return server, bound


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

_channels: dict[str, grpc.Channel] = {}
_channels_lock = threading.Lock()


def grpc_target(endpoint: str) -> str:
    """'grpc://host:port' or 'host:port' -> grpc channel target."""
    return endpoint[len("grpc://"):] if endpoint.startswith("grpc://") else endpoint


def _channel(endpoint: str) -> grpc.Channel:
    target = grpc_target(endpoint)
    with _channels_lock:
        ch = _channels.get(target)
        if ch is None:
            ch = grpc.insecure_channel(
                target,
                options=[
                    ("grpc.max_receive_message_length", 64 * 1024 * 1024),
                    ("grpc.max_send_message_length", 64 * 1024 * 1024),
                ],
            )
            _channels[target] = ch
        return ch


# the flag rides call metadata (no proto change): peers answering a
# partial-tolerant origin degrade gracefully instead of failing the RPC
ALLOW_PARTIAL_MD_KEY = "x-filodb-allow-partial"

# trace propagation rides call metadata too: the origin's trace id and the
# dispatching span's id, so the peer's spans join the origin's trace (its
# tree returns in-band as a TraceTree frame and gets stitched)
TRACE_ID_MD_KEY = "x-filodb-trace-id"
PARENT_SPAN_MD_KEY = "x-filodb-parent-span"

# origin capability flag: "1" = the caller's frames_to_result understands
# the in-band StatsExt frame (kernel_ns + cache events); peers never send
# the frame unsolicited so older origins keep working mid-rolling-deploy
STATS_EXT_MD_KEY = "x-filodb-stats-ext"

# replica routing: the origin pins the call to a subset of the peer's
# shards (comma-joined ints) — the peer serves exactly those shards so a
# scatter leg re-routed to a sibling replica reads the same slice
SHARDS_MD_KEY = "x-filodb-shards"

# admission-control shed: the peer's Retry-After (seconds) rides trailing
# call metadata — the gRPC equivalent of the HTTP 429 Retry-After header
# (the typed rejection itself travels in-band as an AdmissionRejected frame)
RETRY_AFTER_MD_KEY = "x-filodb-retry-after"

# transient codes; DEADLINE_EXCEEDED is excluded — the budget is already
# burnt. Retry ownership: plan-scatter children (GrpcPlanRemoteExec) pass
# retries=0 and mark the error retryable so the dispatch layer
# (query/faults.py) owns the retry loop — breaker-aware, jittered, budgeted
# by the query deadline, tunable via config query.retry.*. Direct client
# helpers (exec_promql / remote_metadata) keep one transport-level retry
# instead; either way exactly ONE layer retries.
_RETRYABLE_CODES = (grpc.StatusCode.UNAVAILABLE, grpc.StatusCode.RESOURCE_EXHAUSTED)

# codes that are NOT peer-health evidence and must not open the endpoint's
# breaker: auth/arg/config problems are real answers from a live peer, and
# DEADLINE_EXCEEDED reflects the ORIGIN's (possibly nearly-spent) budget —
# a healthy peer given a 50ms window says nothing about the peer
_NOT_PEER_HEALTH_CODES = (
    grpc.StatusCode.UNAUTHENTICATED,
    grpc.StatusCode.PERMISSION_DENIED,
    grpc.StatusCode.INVALID_ARGUMENT,
    grpc.StatusCode.UNIMPLEMENTED,
    grpc.StatusCode.FAILED_PRECONDITION,
    grpc.StatusCode.NOT_FOUND,
    grpc.StatusCode.OUT_OF_RANGE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
)


def _metadata(auth_token: str | None, allow_partial: bool | None = None,
              trace: tuple[str, str] | None = None, shards=None):
    """``allow_partial`` is tri-state: None omits the key (peer uses its own
    default); True/False send "1"/"0" so an origin's explicit choice —
    including strict mode — overrides the peer's configured default.
    ``trace`` is (trace_id, parent_span_id) of the dispatching span.
    ``shards`` pins the peer to a shard subset (replica routing)."""
    md = []
    if auth_token:
        md.append(("authorization", f"Bearer {auth_token}"))
    if allow_partial is not None:
        md.append((ALLOW_PARTIAL_MD_KEY, "1" if allow_partial else "0"))
    if trace is not None:
        md.append((TRACE_ID_MD_KEY, trace[0]))
        md.append((PARENT_SPAN_MD_KEY, trace[1]))
    if shards:
        md.append((SHARDS_MD_KEY, ",".join(str(int(s)) for s in shards)))
    # this client understands the StatsExt frame (proto_plan.STATS_EXT);
    # peers only send it when the origin advertises so
    md.append((STATS_EXT_MD_KEY, "1"))
    return tuple(md) or None


def _call_stream(endpoint: str, method: str, request, serializer, auth_token,
                 timeout_s: float | None, retries: int = 1,
                 allow_partial: bool | None = None,
                 trace: tuple[str, str] | None = None, shards=None):
    """unary_stream call with bounded UNAVAILABLE retries (mirrors the HTTP
    transport's retry discipline in planners.fetch_json). ``timeout_s`` is a
    TOTAL budget: retries and their per-attempt RPC deadlines all fit inside
    it, so a hung peer cannot stall past the caller's query deadline."""
    import time as _t

    ch = _channel(endpoint)
    call = ch.unary_stream(
        method,
        request_serializer=serializer,
        response_deserializer=pb.StreamFrame.FromString,
    )
    deadline = None if timeout_s is None else _t.monotonic() + timeout_s
    md = _metadata(auth_token, allow_partial, trace, shards)
    attempt = 0
    while True:
        per_attempt = (
            None if deadline is None else max(deadline - _t.monotonic(), 0.001)
        )
        try:
            return frames_to_result(call(request, timeout=per_attempt, metadata=md))
        except grpc.RpcError as e:
            code = e.code() if hasattr(e, "code") else None
            backoff = 0.2 * (attempt + 1)
            if (
                code in _RETRYABLE_CODES
                and attempt < retries
                and (deadline is None or _t.monotonic() + backoff < deadline)
            ):
                attempt += 1
                _t.sleep(backoff)
                continue
            err = RemoteExecError(
                str(code), e.details() if hasattr(e, "details") else str(e)
            )
            # only when NO transport retry happened: the dispatch layer may
            # retry a transient code it knows was tried exactly once
            err.retryable = retries == 0 and code in _RETRYABLE_CODES
            err.endpoint_failure = code not in _NOT_PEER_HEALTH_CODES
            raise err from e


def exec_promql(endpoint: str, promql: str, start_ms: int, end_ms: int, step_ms: int,
                auth_token: str | None = None, local_only: bool = False,
                instant: bool = False, timeout_s: float | None = None,
                allow_partial: bool | None = None):
    req = pb.ExecRequest(
        promql=promql, instant=instant,
        params=pb.QueryParams(start_ms=start_ms, end_ms=end_ms, step_ms=step_ms,
                              local_only=local_only),
    )
    return _call_stream(endpoint, _EXEC, req, pb.ExecRequest.SerializeToString,
                        auth_token, timeout_s, allow_partial=allow_partial)


def exec_plan_remote(endpoint: str, logical_plan, auth_token: str | None = None,
                     local_only: bool = False, deadline_s: float = 0.0,
                     max_series: int = 0, timeout_s: float | None = None,
                     allow_partial: bool | None = None, transport_retries: int = 1,
                     trace: tuple[str, str] | None = None, shard_subset=None):
    req = pb.ExecutePlanRequest(
        plan=plan_to_proto(logical_plan),
        params=pb.QueryParams(local_only=local_only, deadline_s=deadline_s,
                              max_series=max_series),
    )
    return _call_stream(endpoint, _EXECUTE_PLAN, req,
                        pb.ExecutePlanRequest.SerializeToString, auth_token,
                        timeout_s, retries=transport_retries,
                        allow_partial=allow_partial, trace=trace,
                        shards=shard_subset)


from ..query.exec.plans import ExecPlan  # noqa: E402  (no cycle: query/ never imports api/)


class GrpcPlanRemoteExec(ExecPlan):
    """ExecPlan leaf executing a serialized LogicalPlan subtree on a peer
    over gRPC (reference executePlan handler of service RemoteExec)."""

    is_remote = True

    def __init__(self, endpoint: str, logical_plan, auth_token: str | None = None,
                 local_only: bool = False, timeout_s: float | None = None,
                 shard_subset=None, sibling_endpoints=()):
        super().__init__()
        self.endpoint = endpoint
        self.logical_plan = logical_plan
        # same env fallback as PromQlRemoteExec so token-protected federation
        # works over either transport
        self.auth_token = auth_token or os.environ.get("FILODB_REMOTE_TOKEN")
        self.local_only = local_only
        self.timeout_s = timeout_s
        # replica routing: pin the peer to exactly these shards, with the
        # sibling replicas the dispatch layer may fail over to
        self.shard_subset = tuple(shard_subset) if shard_subset else None
        self.sibling_endpoints = tuple(sibling_endpoints)

    def with_endpoint(self, endpoint: str) -> "GrpcPlanRemoteExec":
        """Clone for replica failover: same plan/subset/token on a sibling
        endpoint (the failover layer manages the candidate list)."""
        clone = GrpcPlanRemoteExec(
            endpoint, self.logical_plan, auth_token=self.auth_token,
            local_only=self.local_only, timeout_s=self.timeout_s,
            shard_subset=self.shard_subset,
        )
        clone.transformers = list(self.transformers)
        return clone

    def push_aggregate(self, wrapped_logical) -> None:
        """Aggregate pushdown rewrite: ship ``sum by(...)`` of the leaf
        instead of raw series (planner._push_peer_aggregate)."""
        self.logical_plan = wrapped_logical

    def args_str(self) -> str:
        s = f"endpoint={self.endpoint} plan={type(self.logical_plan).__name__}"
        if self.shard_subset:
            s += " shards=" + ",".join(str(x) for x in self.shard_subset)
        return s

    def do_execute(self, ctx):
        from ..metrics import current_span

        # budget with the REMAINING deadline, not the full deadline_s: by
        # the time this child dispatches (or re-dispatches on retry), part
        # of the query budget is already spent, and both the per-RPC timeout
        # and the peer's own deadline must fit in what's left
        remaining = ctx.remaining_deadline_s()
        # the active span here is this exec node's (ExecPlan.execute): its
        # identity rides call metadata so the peer's spans join our trace
        sp = current_span()
        return exec_plan_remote(
            self.endpoint, self.logical_plan, auth_token=self.auth_token,
            local_only=self.local_only, deadline_s=remaining,
            max_series=ctx.max_series,
            timeout_s=min(self.timeout_s, remaining) if self.timeout_s else remaining,
            allow_partial=getattr(ctx, "allow_partial_results", False),
            # the dispatch layer (faults.call_with_retries) owns this
            # child's retries: transient errors come back marked retryable
            transport_retries=0,
            trace=(sp.trace_id, sp.span_id) if sp is not None else None,
            shard_subset=self.shard_subset,
        )


def remote_metadata(endpoint: str, plan, auth_token: str | None = None,
                    timeout_s: float | None = 60.0):
    """Metadata scatter over gRPC: execute a metadata LogicalPlan on the
    peer (locally pinned) and return its ``metadata`` payload."""
    res = exec_plan_remote(endpoint, plan, auth_token=auth_token,
                           local_only=True, timeout_s=timeout_s)
    return res.metadata or []
