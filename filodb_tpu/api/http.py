"""Prometheus-compatible HTTP API (reference L6:
http/.../PrometheusApiRoute.scala:43-130 — query_range:49, query:68,
labels:85, label-values:105; AdminRoutes health).

Stdlib ThreadingHTTPServer: the API edge is not the hot path (queries run on
device); zero extra dependencies.

Endpoints:
  GET/POST /api/v1/query_range?query&start&end&step
  GET/POST /api/v1/query?query&time
  GET      /api/v1/labels
  GET      /api/v1/label/<name>/values
  GET      /api/v1/series?match[]=...
  GET      /api/v1/metadata (types from live schemas), /api/v1/status/buildinfo
  GET      /api/v1/query_exemplars (OpenMetrics exemplars ingested via /ingest/prom)
  GET      /api/v1/rules  (recording + alerting rule groups, Prometheus
           shape; ?type=alert|record, ?state=inactive|pending|firing)
  GET      /api/v1/alerts (active alerts from the alerting plane,
           obs/alerting.py; ?state= filter)
  POST     /api/v1/rules/record, /api/v1/rules/alert (runtime rules)
  GET      /admin/health
  POST     /ingest  (JSON lines of {metric, tags, ts_ms, value} — test/dev
           ingest transport; production path is the gateway)
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..coordinator.planner import QueryEngine
from ..core.filters import ColumnFilter
from ..query.exec.transformers import QueryError
from ..query.promql import PromQLError, Parser as PromParser
from ..query.proto_plan import RemoteExecError
from . import promjson as J


def _parse_time(s: str, default: float | None = None) -> float:
    if s is None:
        if default is None:
            raise ValueError("missing time parameter")
        return default
    try:
        return float(s)
    except ValueError:
        # RFC3339
        import datetime as dt

        return dt.datetime.fromisoformat(s.replace("Z", "+00:00")).timestamp()


def _parse_step(s: str) -> float:
    if s is None:
        return 15.0
    try:
        return float(s)
    except ValueError:
        from ..query.promql import parse_duration_ms

        return parse_duration_ms(s) / 1000.0


def _matchers_from(expr: str) -> list[ColumnFilter]:
    """Parse a series matcher like {job="x"} or metric{a="b"}."""
    node = PromParser(expr).selector()
    from ..core.schemas import METRIC_TAG

    filters = list(node.matchers)
    if node.metric:
        filters.append(ColumnFilter(METRIC_TAG, "=", node.metric))
    return [
        ColumnFilter(METRIC_TAG, f.op, f.value) if f.column == "__name__" else f
        for f in filters
    ]


class PromApiHandler(BaseHTTPRequestHandler):
    engine: QueryEngine = None  # set by server factory
    # optional zero-arg flush hook (FiloServer.flush_now) behind POST
    # /admin/flush (reference AdminRoutes; ops + crash-recovery tests)
    flush_hook = None
    members_hook = None
    join_hook = None
    # engine answering from this process's shards only (no peer scatter);
    # selected by the X-FiloDB-Local header peers set — the multi-host
    # anti-recursion guard. None = same as engine. TRUST BOUNDARY: any
    # caller presenting the header (after passing bearer auth, when
    # configured) gets the shard-local view on the unbounded local engine —
    # multi-host deployments should set http_auth_token so only peers (who
    # share the token) can reach it, and keep the port off the public edge.
    local_engine: QueryEngine = None
    # additional per-dataset engines reachable via ?dataset=<name> — the
    # `_system` self-telemetry dataset rides this so the server's own
    # metrics are queryable through the standard (fused) query API
    dataset_engines: dict = {}
    # standing-query engine (filodb_tpu/standing/): registration +
    # recording-rules APIs, SSE push subscriptions, /debug/standing.
    # None = endpoints 404 (engine disabled or embedded without one).
    standing = None
    # second StandingEngine bound to the _system engine: maintains the
    # query observatory's SLO burn-rate recording rules (obs/slo.py);
    # its rules merge into /api/v1/rules. None = no SLO maintainer.
    standing_system = None
    # RollupManager (downsample/rollup.py): the sketch-rollup summary
    # tier's admin surface, /debug/rollups. None = endpoint 404s.
    rollups = None
    # AlertingEngine (obs/alerting.py): alerting rule groups + active
    # alerts; serves /api/v1/alerts and merges its groups into
    # /api/v1/rules. None = alerts list empty, no alerting groups.
    alerting = None
    auth_token: str | None = None  # optional bearer auth (server factory)
    # zero-arg profiler report hook; wired by the server ONLY when the
    # profiler config block enables it (/debug/profile gate)
    profiler_hook = None
    # zero-arg cluster snapshot hook (ShardManager.snapshot or
    # ReplicationPlane.snapshot): shard -> replica table with statuses, lag
    # watermarks, damper state, recent reassignments (/debug/cluster).
    # None = endpoint 404s (single-node deployment without a shard plane).
    cluster_hook = None
    protocol_version = "HTTP/1.1"
    GZIP_MIN_BYTES = 1024
    STREAM_MIN_SAMPLES = 200_000  # above this, query_range streams chunked
    # series rows per device->host block on the streaming path (the
    # D2H/encode overlap granularity; 0 = pull whole grids upfront).
    # Config key result_plane.stream_block_rows.
    STREAM_BLOCK_ROWS = 512
    # peer columnar edge: honor "Accept: application/vnd.filodb.arrow.v1"
    # on query_range with Arrow IPC bodies (config result_plane.peer_exchange
    # = json disables, forcing decimal JSON on every hop)
    ARROW_EDGE = True

    def _engine_for_request(self, params: dict | None = None) -> QueryEngine:
        if self.local_engine is not None and self.headers.get("X-FiloDB-Local"):
            return self.local_engine
        if params is not None:
            # handlers pass their parsed params so a POSTed form body's
            # dataset= routes too (the body is consumable only once)
            ds = (params.get("dataset") or [None])[0]
        else:
            qs = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
            ds = (qs.get("dataset") or [None])[0]
        if ds:
            eng = (self.dataset_engines or {}).get(ds)
            if eng is not None:
                return eng
            if ds != getattr(self.engine, "dataset", None):
                # a typo must be a 400, never silently the default dataset
                raise ValueError(f"unknown dataset {ds!r}")
        return self.engine

    # -- plumbing ---------------------------------------------------------

    def log_message(self, fmt, *args):  # quiet by default
        pass

    @staticmethod
    def _observe_render(fmt: str, render_s: float, nbytes: int,
                        stalls: int = 0) -> None:
        """Result-plane encode accounting: filodb_render_seconds{format},
        filodb_response_bytes_total{format}, and (streaming only)
        filodb_render_stream_stalls_total — encoder waits on a D2H block
        the double-buffer failed to hide."""
        from ..metrics import REGISTRY

        REGISTRY.histogram("filodb_render_seconds", format=fmt).observe(render_s)
        REGISTRY.counter("filodb_response_bytes", format=fmt).inc(nbytes)
        if stalls:
            REGISTRY.counter("filodb_render_stream_stalls").inc(stalls)

    def _peer_accepts_arrow(self) -> bool:
        """Version negotiation for the node-to-node columnar hop: only a
        peer that explicitly lists the Arrow media type in Accept gets IPC
        frames; everyone else (browsers, Grafana, older FiloDB builds) gets
        JSON. Requires pyarrow locally — an arrow-less install quietly
        answers JSON, which the requesting peer equally accepts."""
        if not self.ARROW_EDGE:
            return False
        accept = self.headers.get("Accept") or ""
        if "application/vnd.filodb.arrow" not in accept:
            return False
        try:
            from . import arrow_edge  # noqa: F401 (pyarrow gate)
        except Exception:
            return False
        return True

    @staticmethod
    def _count_response(code: int) -> None:
        """Per-status response accounting — the availability-SLO feed
        (obs/slo.py): ``filodb_http_responses_total{code,class}``. Class
        ``shed`` (429 admission sheds) is deliberate load management and
        is excluded from BOTH sides of the availability ratio; ``5xx`` is
        the error budget's numerator."""
        from ..metrics import REGISTRY

        klass = ("shed" if code == 429 else "5xx" if code >= 500
                 else "4xx" if code >= 400 else "2xx")
        REGISTRY.counter("filodb_http_responses", code=str(code),
                         **{"class": klass}).inc()

    def _send(self, code: int, payload: dict, headers: dict | None = None):
        """Returns the UNCOMPRESSED body byte count — the query
        observatory records it as the result size, which must measure the
        query, not the client's Accept-Encoding."""
        return self._send_body(code, json.dumps(payload).encode(), headers)

    def _send_body(self, code: int, body: bytes, headers: dict | None = None,
                   content_type: str = "application/json"):
        """Pre-encoded-body twin of _send (same gzip/accounting contract) —
        the buffered matrix path sends stream_matrix's joined chunks through
        here so buffered and streamed bodies are byte-identical."""
        raw_len = len(body)
        self._count_response(code)
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        # transparent gzip for big results (remote execs request it)
        if (
            len(body) >= self.GZIP_MIN_BYTES
            and "gzip" in (self.headers.get("Accept-Encoding") or "")
        ):
            import gzip

            body = gzip.compress(body, compresslevel=1)
            self.send_header("Content-Encoding", "gzip")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        return raw_len

    def _send_chunked(self, code: int, chunks):
        """Stream an iterable of byte chunks with chunked transfer encoding
        (HTTP/1.1 keep-alive safe); memory stays bounded by one chunk.
        Returns total bytes streamed.

        A producer error after the 200 status line cannot become a real
        error response any more — without care the client would see a
        truncated 200 that json-parses as nothing. Instead the stream ends
        with a newline-delimited error envelope (valid JSON on its own
        line — machine-detectable by any client that notices the body
        doesn't parse) and a CLEAN chunked terminator, and the abort is
        counted under filodb_http_responses_total{class="stream_abort"}
        (the availability SLO's 5xx-equivalent for streamed bodies). A
        transport error (client gone) just stops the stream."""
        self._count_response(code)
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        total = 0
        try:
            for chunk in chunks:
                if chunk:
                    self.wfile.write(f"{len(chunk):X}\r\n".encode() + chunk + b"\r\n")
                    total += len(chunk)
        except (BrokenPipeError, ConnectionResetError):
            raise  # client is gone; nothing to mark
        except Exception as e:  # noqa: BLE001 — producer died mid-stream
            from ..metrics import REGISTRY

            marker = (b'\n{"status":"error","errorType":"stream_aborted",'
                      + b'"error":' + json.dumps(f"{type(e).__name__}: {e}").encode()
                      + b"}\n")
            self.wfile.write(f"{len(marker):X}\r\n".encode() + marker + b"\r\n")
            total += len(marker)
            REGISTRY.counter("filodb_http_responses", code=str(code),
                             **{"class": "stream_abort"}).inc()
        self.wfile.write(b"0\r\n\r\n")
        return total

    def _read_body(self) -> str:
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length).decode() if length else ""

    def _params(self) -> dict:
        parsed = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(parsed.query)
        if self.command == "POST":
            body = self._read_body()
            ctype = self.headers.get("Content-Type", "")
            # urllib clients default to the form content-type even for raw
            # payloads; only parse as a form when it actually looks like one
            if "urlencoded" in ctype and "=" in body and "\n" not in body:
                for k, v in urllib.parse.parse_qs(body).items():
                    qs.setdefault(k, v)
            elif body:
                qs["__body__"] = [body]
        return {k: v for k, v in qs.items()}

    def _q(self, params, name, default=None):
        v = params.get(name)
        return v[0] if v else default

    def _allow_partial(self, params) -> bool | None:
        """Tri-state: None = engine default, else the request's choice."""
        v = self._q(params, "allow_partial_results")
        if v is None:
            return None
        return v.lower() in ("1", "true", "yes")

    def _trace_requested(self, params) -> bool:
        """``?trace=true`` / ``?explain=analyze``: return the annotated span
        tree (per-node durations, QueryStats, retries/breaker/partial
        annotations) alongside the result."""
        v = self._q(params, "trace")
        if v is not None and v.lower() in ("1", "true", "yes"):
            return True
        return (self._q(params, "explain") or "").lower() == "analyze"

    def _trace_parent(self) -> tuple[str | None, str | None]:
        """Upstream trace linkage headers (a scattering origin's span
        identity) — this node's spans join that trace."""
        from ..metrics import TraceContext

        return (
            self.headers.get(TraceContext.TRACE_ID_HEADER),
            self.headers.get(TraceContext.PARENT_SPAN_HEADER),
        )

    # -- routing ----------------------------------------------------------

    def do_GET(self):
        self._route()

    def do_POST(self):
        self._route()

    def _route(self):
        path = urllib.parse.urlparse(self.path).path
        if self.auth_token and path != "/admin/health":
            import hmac

            got = self.headers.get("Authorization") or ""
            if not hmac.compare_digest(got, f"Bearer {self.auth_token}"):
                # drain the body first: this handler speaks HTTP/1.1
                # keep-alive, and leftover body bytes would desync the
                # connection's next request
                length = int(self.headers.get("Content-Length") or 0)
                while length > 0:
                    chunk = self.rfile.read(min(length, 65536))
                    if not chunk:
                        break
                    length -= len(chunk)
                return self._send(401, J.error("unauthorized", "missing or bad bearer token"))
        try:
            if path == "/api/v1/query_range":
                return self._query_range()
            if path == "/api/v1/query":
                return self._query()
            if path == "/api/v1/labels":
                return self._labels()
            m = re.fullmatch(r"/api/v1/label/([^/]+)/values", path)
            if m:
                return self._label_values(m.group(1))
            if path == "/api/v1/series":
                return self._series()
            if path == "/api/v1/metadata":
                return self._send(
                    200,
                    J.success(self.engine.memstore.metric_metadata(self.engine.dataset)),
                )
            if path == "/api/v1/status/buildinfo":
                from .. import __version__

                return self._send(200, J.success({"version": __version__, "application": "filodb-tpu"}))
            if path == "/admin/health":
                return self._send(200, {"status": "healthy", "shards": len(self.engine.memstore.shards(self.engine.dataset))})
            if path == "/__members":
                # cluster membership contract (reference akka-bootstrapper's
                # /__members endpoint; coordinator/bootstrap.py). POST with
                # {"url": ...} announces the caller (one-RTT join): we learn
                # them, they get the member list back.
                if self.members_hook is None:
                    return self._send(404, J.error("not_found", "no bootstrapper attached"))
                if self.command == "POST":
                    try:
                        body = json.loads(self._read_body() or b"{}")
                    except ValueError:
                        return self._send(400, J.error("bad_data", "invalid JSON body"))
                    url = body.get("url")
                    if url and self.join_hook is not None:
                        self.join_hook(str(url), node_id=body.get("id"))
                return self._send(200, J.success(self.members_hook()))
            if path == "/admin/flush" and self.command == "POST":
                if self.flush_hook is None:
                    return self._send(404, J.error("not_found", "no flusher attached"))
                self._read_body()  # drain: keep-alive connections desync otherwise
                res = self.flush_hook()
                return self._send(200, J.success({
                    "chunks_written": res.chunks_written,
                    "partkeys_written": res.partkeys_written,
                }))
            if path == "/metrics":
                return self._metrics()
            if path == "/debug/slow_queries":
                from ..metrics import SLOW_QUERY_LOG

                return self._send(200, J.success(SLOW_QUERY_LOG.entries()))
            if path == "/debug/querylog":
                return self._querylog()
            if path == "/api/v1/query_profile":
                return self._query_profile()
            if path == "/debug/resources":
                return self._resources()
            if path == "/debug/scheduler":
                return self._scheduler()
            if path == "/debug/cluster":
                return self._cluster()
            if path == "/debug/kernels":
                return self._kernels()
            if path == "/debug/costmodel":
                return self._costmodel()
            if path == "/debug/superblocks":
                return self._superblocks()
            if path == "/debug/index":
                return self._index_debug()
            if path == "/debug/profile":
                return self._profile()
            if path == "/api/v1/cardinality":
                return self._cardinality()
            if path == "/ingest":
                return self._ingest()
            if path == "/ingest/prom":
                return self._ingest_prom()
            if path == "/ingest/influx":
                return self._ingest_influx()
            if path == "/api/v1/write":
                return self._remote_write()
            if path == "/api/v1/read":
                return self._remote_read()
            if path == "/api/v1/query_exemplars":
                return self._query_exemplars()
            if path == "/api/v1/standing/register" and self.command == "POST":
                return self._standing_register()
            if path == "/api/v1/standing/unregister" and self.command == "POST":
                return self._standing_unregister()
            if path == "/api/v1/standing/subscribe":
                return self._standing_subscribe()
            if path == "/api/v1/standing":
                if self.standing is None:
                    return self._send(404, J.error("not_found", "standing engine disabled"))
                return self._send(200, J.success(self.standing.registry.snapshot()))
            if path == "/api/v1/rules/record" and self.command == "POST":
                return self._rules_record()
            if path == "/api/v1/rules/alert" and self.command == "POST":
                return self._rules_alert()
            if path == "/debug/standing":
                if self.standing is None:
                    return self._send(404, J.error("not_found", "standing engine disabled"))
                return self._send(200, J.success(self.standing.snapshot()))
            if path == "/debug/rollups":
                if self.rollups is None:
                    return self._send(404, J.error("not_found", "rollup tier disabled"))
                return self._send(200, J.success(self.rollups.snapshot()))
            if path == "/api/v1/rules":
                return self._rules()
            if path == "/api/v1/alerts":
                return self._alerts()
            if path == "/api/v1/status/flags" or path == "/api/v1/status/config":
                return self._send(200, J.success({}))
            self._send(404, J.error("not_found", f"unknown path {path}"))
        except (PromQLError, QueryError, ValueError, RemoteExecError) as e:
            import math

            from ..coordinator.planners import RemoteFetchError
            from ..coordinator.scheduler import QueryRejected
            from ..query.exec.transformers import QueryDeadlineExceeded
            from ..query.faults import CircuitOpenError
            from ..query.scheduler import AdmissionRejected

            if isinstance(e, AdmissionRejected):
                # admission control shed: 429 + Retry-After (the overload
                # contract, distinct from 503 pool saturation — the client
                # should back off for a KNOWN interval, not fail over) plus
                # the structured warning in the error envelope
                payload = J.error("throttled", str(e))
                payload["warnings"] = [e.warning()]
                self._send(429, payload, headers={
                    "Retry-After": str(max(
                        1, math.ceil(e.retry_after_s)
                    )),
                })
            elif isinstance(e, (QueryRejected, CircuitOpenError, RemoteFetchError,
                                RemoteExecError)):
                # overload / open breaker / peer transport outage (either
                # transport): availability conditions, not bad queries
                # (Prometheus: 503)
                self._send(503, J.error("unavailable", str(e)))
            elif isinstance(e, QueryDeadlineExceeded):
                self._send(503, J.error("timeout", str(e)))
            else:
                self._send(400, J.error("bad_data", str(e)))
        except Exception as e:  # noqa: BLE001 — the API edge must not die
            self._send(500, J.error("internal", f"{type(e).__name__}: {e}"))

    # -- endpoints --------------------------------------------------------

    def _query_range(self):
        p = self._params()
        query = self._q(p, "query")
        if not query:
            return self._send(400, J.error("bad_data", "missing query"))
        start = _parse_time(self._q(p, "start"))
        end = _parse_time(self._q(p, "end"))
        step = _parse_step(self._q(p, "step"))
        if step <= 0:
            return self._send(
                400, J.error("bad_data", "zero or negative query resolution step")
            )
        if end < start:
            return self._send(400, J.error("bad_data", "end timestamp before start"))
        trace_on = self._trace_requested(p)
        trace_id, parent_span = self._trace_parent()
        engine = self._engine_for_request(p)
        res = None
        served_standing = False
        if (self.standing is not None and engine is self.engine
                and not trace_on):
            # a registered standing query already holds this result's
            # matrix as retained partials — splice + render instead of
            # re-executing (ROADMAP leftover: only SSE subscribers rode
            # them before). Trace requests bypass: the retained state has
            # no span tree to annotate.
            res = self.standing.serve_range(query, start, end, step)
            served_standing = res is not None
        if res is None:
            res = engine.query_range(
                query, start, end, step,
                allow_partial_results=self._allow_partial(p),
                trace_id=trace_id, parent_span_id=parent_span,
            )
        from ..metrics import trace_to_dict
        from ..obs.querylog import QUERY_LOG

        # the query-observatory record this execution published (None for
        # remote-child legs); the edge folds in its serving phases below
        record = getattr(res, "query_log", None)
        trace = trace_to_dict(res.trace) if trace_on and res.trace is not None else None
        warnings = res.warnings or None
        render_format = "json-" + J.active_render_format()
        if res.result_type == "scalar":
            # range query over a scalar: render as matrix of the scalar
            sc = res.scalar
            data = {
                "resultType": "matrix",
                "result": [
                    {
                        "metric": {},
                        "values": [
                            [t / 1000.0, J._fmt(v)]
                            for t, v in zip(
                                sc.start_ms + np.arange(sc.num_steps) * sc.step_ms, sc.values
                            )
                        ],
                    }
                ]
                if sc is not None
                else [],
            }
            if trace is not None:
                data["trace"] = trace
            t_r = time.perf_counter()
            nbytes = self._send(200, J.success(data, warnings=warnings,
                                               partial=res.partial))
            if record is not None:
                QUERY_LOG.finish_serving(record, 0.0,
                                         time.perf_counter() - t_r,
                                         body_bytes=nbytes, code=200,
                                         render_format=render_format)
            return
        stats = {
            "seriesScanned": res.stats.series_scanned,
            "samplesScanned": res.stats.samples_scanned,
            "cpuNanos": res.stats.cpu_ns,
            "bytesStaged": res.stats.bytes_staged,
            # resource attribution (doc/observability.md): device dispatch
            # seconds and staging/superblock cache events for THIS query
            "kernelSeconds": round(res.stats.kernel_ns / 1e9, 9),
            "cacheHits": res.stats.cache_hits,
            "cacheMisses": res.stats.cache_misses,
            "cacheExtends": res.stats.cache_extends,
        }
        if served_standing:
            stats["servedFrom"] = "standing"
        # peer edge: a FiloDB peer advertises Arrow via Accept and gets the
        # grids as columnar IPC frames — floats cross bit-exact, no decimal
        # render here and no parse there. Browsers/old peers never send the
        # media type and fall through to JSON: the user edge renders decimal
        # JSON exactly once, at the outermost hop.
        if self._peer_accepts_arrow():
            from . import arrow_edge as AE

            t_tr = time.perf_counter()
            for g in res.grids:
                g.values = np.asarray(g.values)
                if g.hist is not None:
                    g.hist = np.asarray(g.hist)
            transfer_s = time.perf_counter() - t_tr
            t_r = time.perf_counter()
            body = AE.result_to_ipc(res, trace=trace)
            nbytes = self._send_body(200, body,
                                     content_type=AE.ARROW_CONTENT_TYPE)
            render_s = time.perf_counter() - t_r
            self._observe_render("arrow", render_s, nbytes)
            if record is not None:
                QUERY_LOG.finish_serving(record, transfer_s, render_s,
                                         body_bytes=nbytes, code=200,
                                         render_format="arrow")
            return
        # large results stream chunked: memory stays bounded instead of
        # holding matrix + full JSON string (reference executeStreaming,
        # ExecPlan.scala:146); small ones keep the gzip-capable buffered
        # path — built from the SAME stream_matrix fragments, so streamed
        # and buffered bodies are byte-identical
        n_samples = sum(g.n_series * g.num_steps for g in res.grids)
        if res.raw is not None:
            n_samples += sum(len(t) for _, t, _ in res.raw)
        if n_samples >= self.STREAM_MIN_SAMPLES:
            # streaming path: grid values stay on device; stream_matrix
            # pulls them in STREAM_BLOCK_ROWS-series blocks through a
            # double-buffered prefetch thread, so the first body bytes
            # leave before the full D2H completes and transfer overlaps
            # encode. render phase = send wall minus the encoder's waits
            # on unfetched blocks (those waits ARE the transfer phase
            # leaking through the overlap — counted as stream stalls).
            phases: dict = {}
            t_r = time.perf_counter()
            nbytes = self._send_chunked(
                200, J.stream_matrix(res, stats, warnings=warnings,
                                     trace=trace, partial=res.partial,
                                     block_rows=self.STREAM_BLOCK_ROWS or None,
                                     phases=phases)
            )
            total_s = time.perf_counter() - t_r
            transfer_s = phases.get("transfer", 0.0)
            render_s = max(total_s - phases.get("stall_s", 0.0), 0.0)
            self._observe_render(render_format, render_s, nbytes,
                                 stalls=phases.get("stalls", 0))
            if record is not None:
                QUERY_LOG.finish_serving(record, transfer_s, render_s,
                                         body_bytes=nbytes, code=200,
                                         render_format=render_format)
            return
        # buffered path: pull every result grid to host HERE, timed,
        # instead of implicitly inside the JSON encoder — the transfer vs
        # render decomposition the result-plane phase plane needs. Not an
        # added sync: rendering forced the same conversion one call later.
        t_tr = time.perf_counter()
        for g in res.grids:
            g.values = np.asarray(g.values)
            if g.hist is not None:
                g.hist = np.asarray(g.hist)
        transfer_s = time.perf_counter() - t_tr
        t_r = time.perf_counter()
        body = b"".join(J.stream_matrix(res, stats, warnings=warnings,
                                        trace=trace, partial=res.partial))
        nbytes = self._send_body(200, body)
        render_s = time.perf_counter() - t_r
        self._observe_render(render_format, render_s, nbytes)
        if record is not None:
            QUERY_LOG.finish_serving(record, transfer_s, render_s,
                                     body_bytes=nbytes, code=200,
                                     render_format=render_format)
        return

    def _query(self):
        p = self._params()
        query = self._q(p, "query")
        if not query:
            return self._send(400, J.error("bad_data", "missing query"))
        t = _parse_time(self._q(p, "time"), default=time.time())
        trace_on = self._trace_requested(p)
        trace_id, parent_span = self._trace_parent()
        res = self._engine_for_request(p).query_instant(
            query, t, allow_partial_results=self._allow_partial(p),
            trace_id=trace_id, parent_span_id=parent_span,
        )
        from ..obs.querylog import QUERY_LOG

        record = getattr(res, "query_log", None)
        t_tr = time.perf_counter()
        for g in res.grids:
            g.values = np.asarray(g.values)
            if g.hist is not None:
                g.hist = np.asarray(g.hist)
        transfer_s = time.perf_counter() - t_tr
        warnings = res.warnings or None
        t_r = time.perf_counter()
        if res.result_type == "scalar":
            data = J.render_scalar(res, t)
        elif res.raw is not None:
            data = J.render_matrix(res)
        else:
            data = J.render_vector(res, t)
        if trace_on and res.trace is not None:
            from ..metrics import trace_to_dict

            data["trace"] = trace_to_dict(res.trace)
        nbytes = self._send(200, J.success(data, warnings=warnings,
                                           partial=res.partial))
        if record is not None:
            QUERY_LOG.finish_serving(record, transfer_s,
                                     time.perf_counter() - t_r,
                                     body_bytes=nbytes, code=200)
        return

    def _labels(self):
        p = self._params()
        start = _parse_time(self._q(p, "start"), 0.0)
        end = _parse_time(self._q(p, "end"), time.time() + 1e9)
        limit = self._q(p, "limit")
        match = p.get("match[]", [])
        filters = _matchers_from(match[0]) if match else []
        names = self._engine_for_request(p).label_names(
            filters, int(start * 1000), int(end * 1000)
        )
        names = ["__name__" if n == "_metric_" else n for n in names]
        if limit:
            names = names[: int(limit)]
        return self._send(200, J.success(names))

    def _label_values(self, label: str):
        p = self._params()
        if label == "__name__":
            label = "_metric_"
        start = _parse_time(self._q(p, "start"), 0.0)
        end = _parse_time(self._q(p, "end"), time.time() + 1e9)
        match = p.get("match[]", [])
        limit = self._q(p, "limit")
        filters = _matchers_from(match[0]) if match else []
        vals = self._engine_for_request(p).label_values(
            filters, label, int(start * 1000), int(end * 1000),
            limit=int(limit) if limit else None,
        )
        return self._send(200, J.success(vals))

    def _series(self):
        p = self._params()
        start = _parse_time(self._q(p, "start"), 0.0)
        end = _parse_time(self._q(p, "end"), time.time() + 1e9)
        out = []
        for expr in p.get("match[]", []):
            filters = _matchers_from(expr)
            for tags in self._engine_for_request(p).series(
                filters, int(start * 1000), int(end * 1000), limit=10000
            ):
                out.append(J._labels_out(dict(tags)))
        return self._send(200, J.success(out))

    def _metrics(self):
        """Prometheus exposition of internal metrics. Per-shard stats are a
        scrape-time collector registered by make_server (reference
        TimeSeriesShardStats gauges + Kamon reporters) — one exposition
        path, with proper label escaping, for everything. Content-type
        negotiation: an Accept header naming application/openmetrics-text
        gets the OpenMetrics 1.0 rendering (HELP/TYPE metadata, trace-id
        exemplars on latency buckets, # EOF terminator)."""
        from ..metrics import REGISTRY

        openmetrics = "application/openmetrics-text" in (
            self.headers.get("Accept") or ""
        )
        body = REGISTRY.expose(openmetrics=openmetrics).encode()
        ctype = (
            "application/openmetrics-text; version=1.0.0; charset=utf-8"
            if openmetrics else "text/plain; version=0.0.4"
        )
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _kernels(self):
        """Kernel & compile observatory (doc/observability.md "Kernel &
        compile observatory"): the per-executable table — compiles,
        dispatches, device p50/p99, executable bytes, compile-cache
        provenance — plus recompile-storm annotations (each naming the
        unstable key dimension) and registered-wrapper cache sizes.
        ``?limit=`` caps the executable table."""
        from ..obs.kernels import KERNELS

        p = self._params()
        limit = self._q(p, "limit")
        return self._send(
            200, J.success(KERNELS.snapshot(int(limit) if limit else None))
        )

    def _costmodel(self):
        """Work cost model (doc/perf.md "Cost-model scheduling"): the
        per-fingerprint predicted vs realized device-second table (EWMA
        cost, unit cost, last error ratio), per-family priors, and the
        prediction-source mix (fingerprint / family / prior). ``?limit=``
        caps the fingerprint table (newest first)."""
        from ..query.costmodel import COST_MODEL

        p = self._params()
        limit = self._q(p, "limit")
        return self._send(
            200,
            J.success(COST_MODEL.snapshot(int(limit) if limit else 64)),
        )

    def _resources(self):
        """Resource-ledger introspection: per-kind device bytes, the
        ledger-vs-cold-walk drift check, and per-tenant query-resource
        totals (doc/observability.md "Resource accounting")."""
        from ..ledger import LEDGER
        from ..metering import tenant_query_snapshot

        verify = LEDGER.verify()
        return self._send(200, J.success({
            "device_bytes": LEDGER.balances(),
            "kinds": verify["kinds"],
            "accounts": verify["accounts"],
            "tenants": tenant_query_snapshot(),
        }))

    def _scheduler(self):
        """Query-dispatch-scheduler introspection (doc/observability.md):
        the micro-batcher's queue depth / open batch windows / cumulative
        batching outcomes, and the admission controller's per-tenant token
        balances, in-flight counts and shed totals — alongside
        /debug/resources like the rest of the debug surface."""
        params = self.engine.planner.params
        sched = getattr(params, "dispatch_scheduler", None)
        adm = getattr(params, "admission", None)
        return self._send(200, J.success({
            "batch": sched.snapshot() if sched is not None else None,
            "admission": adm.snapshot() if adm is not None else None,
        }))

    def _cluster(self):
        """Replicated-shard-plane introspection (doc/operations.md): the
        shard -> replica table (per-replica status + lag watermark), node
        liveness, damper state and the recent-reassignment ring — how an
        operator confirms a failover routed and a rebalance cut over."""
        if self.cluster_hook is None:
            return self._send(404, J.error("no cluster plane configured"))
        return self._send(200, J.success(self.cluster_hook()))

    def _superblocks(self):
        """Superblock-cache introspection: one entry per cached superblock
        (key, true device bytes, age, hits, last maintenance outcome from
        the filodb_superblock_maintenance_total taxonomy; mesh-sharded
        entries additionally carry their sharding spec + per-device byte
        split, rolled up in device_bytes)."""
        cache = getattr(self.engine.memstore, "_superblock_cache", None)
        entries = cache.snapshot() if cache is not None else []
        device_bytes: dict = {}
        for e in entries:
            for dev, b in (e.get("device_bytes") or {}).items():
                device_bytes[dev] = device_bytes.get(dev, 0) + int(b)
        return self._send(200, J.success({
            "entries": entries,
            "count": len(entries),
            "bytes": sum(e["bytes"] for e in entries),
            # per-device roll-up over SHARDED entries (mesh path); also
            # published as filodb_device_bytes{kind="superblock",device}
            "device_bytes": device_bytes,
            # THIS cache's ledger balance (the kind-wide filodb_device_bytes
            # gauge sums every live cache in the process)
            "ledger_bytes": cache.ledger.bytes if cache is not None else 0,
        }))

    def _index_debug(self):
        """Part-key index introspection (doc/perf.md "Vectorized part-key
        index"): per-label cardinality + postings footprint per shard, the
        rolled-up label dictionary, and the hot device-staged posting
        bitmaps when the opt-in HBM tier is on."""
        from ..memstore.cardinality import label_top_values

        p = self._params()
        drill_label = self._q(p, "label")
        ds = self.engine.dataset
        shards = []
        labels_rollup: dict[str, dict] = {}
        drill: dict[str, int] = {}
        total_bytes = device_bytes = 0
        for sh in self.engine.memstore.shards(ds):
            st = sh.index_stats()
            if drill_label:
                for rec in label_top_values(sh.index, drill_label, k=50):
                    drill[rec["value"]] = (
                        drill.get(rec["value"], 0) + rec["series"]
                    )
            for k, rec in st.get("labels", {}).items():
                slot = labels_rollup.setdefault(
                    k, {"values": 0, "postings_bytes": 0}
                )
                slot["values"] += rec["values"]
                slot["postings_bytes"] += rec["postings_bytes"]
            total_bytes += st.get("postings_bytes", 0)
            dev = st.get("device")
            if dev:
                device_bytes += dev.get("staged_bytes", 0)
            shards.append({
                "shard": sh.shard_num,
                "part_keys": st.get("num_part_keys", 0),
                "postings_bytes": st.get("postings_bytes", 0),
                "dictionary_size": st.get("dictionary_size", 0),
                "lookups": st.get("lookups", 0),
                "device": dev,
            })
        return self._send(200, J.success({
            "dataset": ds,
            "shards": shards,
            # per-label cardinality summed over shards (a label's true
            # cross-shard value cardinality is <= this sum; exact dedup
            # would require merging dictionaries)
            "labels": dict(sorted(
                labels_rollup.items(),
                key=lambda kv: -kv[1]["postings_bytes"],
            )),
            "postings_bytes": total_bytes,
            "device_staged_bytes": device_bytes,
            # ?label= drill-down: top values of that label by series count
            "label_values": (sorted(
                ({"value": v, "series": n} for v, n in drill.items()),
                key=lambda r: (-r["series"], r["value"]),
            )[:50] if drill_label else None),
        }))

    def _profile(self):
        """Sampling-profiler report (config-gated: the server wires
        profiler_hook only when filodb.profiler is enabled)."""
        if self.profiler_hook is None:
            return self._send(404, J.error("not_found", "profiler not enabled"))
        body = str(self.profiler_hook()).encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _cardinality(self):
        """Per-shard-key-prefix cardinality scan (reference TsCardinalities
        metadata plan + /api/v1/metering endpoints)."""
        p = self._params()
        prefix = [x for x in (self._q(p, "prefix", "") or "").split(",") if x]
        depth = int(self._q(p, "depth", str(len(prefix) + 1)))
        out = self._engine_for_request(p).ts_cardinalities(prefix, depth)
        return self._send(200, J.success(out))

    def _querylog(self):
        """Query-observatory ring (doc/observability.md "Query
        observatory"): exemplar-level per-query cost records, newest
        first; ``?limit=`` caps the page, ``?fingerprint=`` keeps only one
        normalized query shape, ``?path=`` one execution path (e.g.
        ``standing:delta`` — the alerting plane's evaluations filter out
        this way). Filters apply BEFORE the limit, so a page of a rare
        fingerprint/path is still a full page."""
        from ..obs.querylog import QUERY_LOG

        p = self._params()
        limit = self._q(p, "limit")
        fingerprint = self._q(p, "fingerprint")
        path_f = self._q(p, "path")
        entries = QUERY_LOG.entries(None)
        if fingerprint:
            entries = [e for e in entries
                       if e.get("fingerprint") == fingerprint]
        if path_f:
            entries = [e for e in entries if e.get("path") == path_f]
        if limit:
            entries = entries[: int(limit)]
        return self._send(200, J.success(entries))

    def _query_profile(self):
        """One query's full cost record by id (= its trace id) — the
        target of slow-query-log ``profile`` links and OpenMetrics
        exemplars."""
        from ..obs.querylog import QUERY_LOG

        p = self._params()
        qid = self._q(p, "id")
        if not qid:
            return self._send(400, J.error("bad_data", "missing id"))
        e = QUERY_LOG.get(str(qid))
        if e is None:
            return self._send(
                404, J.error("not_found", f"no query-log record {qid!r}")
            )
        return self._send(200, J.success(e))

    def _query_exemplars(self):
        """Prometheus /api/v1/query_exemplars: exemplars of the series a
        selector matches, within [start, end]."""
        from ..query.logical import leaf_raw_series
        from ..query.promql import query_to_logical_plan

        p = self._params()
        query = self._q(p, "query")
        if not query:
            return self._send(400, J.error("bad_data", "missing query"))
        start = _parse_time(self._q(p, "start") or "0")
        end = _parse_time(self._q(p, "end") or str(2**31))
        plan = query_to_logical_plan(query, end)
        leaves = leaf_raw_series(plan)
        out = []
        for leaf in leaves:
            out.extend(
                self.engine.memstore.query_exemplars(
                    self.engine.dataset, leaf.filters, int(start * 1000), int(end * 1000)
                )
            )
        return self._send(200, J.success(out))

    # -- standing queries / recording rules (filodb_tpu/standing/) ---------

    def _json_body(self, params) -> dict:
        """POSTed JSON body (handlers pass their parsed params — the body
        is consumable only once and _params() stashes it)."""
        body = self._q(params, "__body__") or ""
        if not body:
            return {}
        try:
            out = json.loads(body)
        except ValueError as e:
            raise ValueError(f"invalid JSON body: {e}") from None
        if not isinstance(out, dict):
            raise ValueError("JSON body must be an object")
        return out

    def _standing_register(self):
        """Register a standing query: ``{"query", "step", "range"?}`` (step
        and range in seconds or PromQL durations). Returns its id, mode
        (delta|full) and grid shape."""
        if self.standing is None:
            return self._send(404, J.error("not_found", "standing engine disabled"))
        p = self._params()
        body = self._json_body(p)
        query = body.get("query") or self._q(p, "query")
        if not query:
            return self._send(400, J.error("bad_data", "missing query"))
        step_ms = int(_parse_step(str(body.get("step") or
                                      self._q(p, "step") or 15)) * 1000)
        rng = body.get("range") or self._q(p, "range")
        span_ms = int(_parse_step(str(rng)) * 1000) if rng else None
        sq = self.standing.register(query, step_ms, span_ms=span_ms)
        return self._send(200, J.success(sq.snapshot()))

    def _standing_unregister(self):
        if self.standing is None:
            return self._send(404, J.error("not_found", "standing engine disabled"))
        p = self._params()
        qid = self._json_body(p).get("id") or self._q(p, "id")
        if not qid:
            return self._send(400, J.error("bad_data", "missing id"))
        sq = self.standing.unregister(str(qid))
        if sq is None:
            return self._send(404, J.error("not_found", f"no standing query {qid}"))
        return self._send(200, J.success({"unregistered": qid}))

    def _rules_record(self):
        """Register a recording rule: ``{"name", "expr", "interval",
        "range"?}`` — a standing query whose newest closed steps write back
        into the memstore as the series ``name{group labels}``."""
        if self.standing is None:
            return self._send(404, J.error("not_found", "standing engine disabled"))
        p = self._params()
        body = self._json_body(p)
        name = body.get("name") or self._q(p, "name")
        expr = body.get("expr") or self._q(p, "expr")
        if not name or not expr:
            return self._send(400, J.error("bad_data", "missing name or expr"))
        if not re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", str(name)):
            return self._send(400, J.error("bad_data", f"invalid rule name {name!r}"))
        interval_s = _parse_step(str(body.get("interval") or
                                     self._q(p, "interval") or 15))
        step_ms = int(interval_s * 1000)
        rng = body.get("range") or self._q(p, "range")
        span_ms = int(_parse_step(str(rng)) * 1000) if rng else 4 * step_ms
        sq = self.standing.register(
            str(expr), step_ms, span_ms=span_ms, source="rule",
            rule_name=str(name), eval_interval_s=float(interval_s),
        )
        return self._send(200, J.success(sq.snapshot()))

    # -- alerting plane (obs/alerting.py) ----------------------------------

    def _rules_alert(self):
        """Register an alerting rule at runtime: the rule-file spec as
        JSON (``{"alert", "expr", "for"?, "keep_firing_for"?, "labels"?,
        "annotations"?}``) plus optional ``"group"`` (default ``api``) and
        ``"interval"``."""
        if self.alerting is None:
            return self._send(
                404, J.error("not_found", "alerting plane disabled")
            )
        from ..obs.alerting import RuleFileError

        p = self._params()
        body = self._json_body(p)
        group = str(body.pop("group", "") or "api")
        interval = body.pop("interval", None)
        interval_s = (_parse_step(str(interval))
                      if interval is not None else None)
        try:
            rule = self.alerting.add_rule(body, group=group,
                                          interval_s=interval_s)
        except RuleFileError as e:
            return self._send(400, J.error("bad_data", str(e)))
        return self._send(200, J.success({
            "group": group,
            "name": rule.name,
            "query": rule.expr,
            "duration": rule.for_s,
            "keepFiringFor": rule.keep_firing_for_s,
            "type": "alerting",
        }))

    def _rules(self):
        """Prometheus ``GET /api/v1/rules``: the standing engines'
        runtime-registered recording rules (synthetic ``standing`` group)
        plus the alerting plane's loaded groups — top-level ``groups``,
        rule ``type`` recording|alerting, camelCase eval fields.
        ``?type=alert|record`` and ``?state=`` filter rules (a state
        filter keeps only alerting rules — recording rules have no
        state); groups a filter empties are dropped."""
        from ..obs.alerting import ALERT_STATES

        p = self._params()
        rtype = self._q(p, "type")
        state = self._q(p, "state")
        if rtype and rtype not in ("alert", "record"):
            return self._send(400, J.error(
                "bad_data", "type must be alert|record"
            ))
        if state and state not in ALERT_STATES:
            return self._send(400, J.error(
                "bad_data",
                f"state must be one of {'|'.join(ALERT_STATES)}",
            ))
        groups: list = []
        # names the alerting plane owns: its file/API-registered recording
        # rules also live in the standing registry, so the synthetic
        # `standing` group must not double-list them
        owned = (self.alerting.rule_names()
                 if self.alerting is not None else set())
        for eng in (self.standing, self.standing_system):
            if eng is not None:
                for g in eng.rules_payload()["groups"]:
                    g["rules"] = [r for r in g["rules"]
                                  if r["name"] not in owned]
                    groups.append(g)
        if self.alerting is not None:
            groups.extend(self.alerting.rules_payload()["groups"])
        want = {"alert": "alerting", "record": "recording"}.get(rtype)
        out = []
        for g in groups:
            rules = g["rules"]
            if want:
                rules = [r for r in rules if r["type"] == want]
            if state:
                rules = [r for r in rules if r.get("state") == state]
            if not rules:
                continue
            out.append({**g, "rules": rules})
        return self._send(200, J.success({"groups": out}))

    def _alerts(self):
        """Prometheus ``GET /api/v1/alerts``: active (pending|firing)
        alerts with expanded annotations; ``?state=`` filters."""
        from ..obs.alerting import ALERT_STATES

        p = self._params()
        state = self._q(p, "state")
        if state and state not in ALERT_STATES:
            return self._send(400, J.error(
                "bad_data",
                f"state must be one of {'|'.join(ALERT_STATES)}",
            ))
        if self.alerting is None:
            return self._send(200, J.success({"alerts": []}))
        return self._send(200, J.success(
            self.alerting.alerts_payload(state)
        ))

    def _standing_subscribe(self):
        """SSE push stream for one standing query: the initial frame is
        the current materialization, then every refresh's payload — the
        SAME rendered bytes every subscriber receives (one materialization,
        N sockets). Subscriber counts are bounded per query
        (``standing.max_subscribers`` → 429 + Retry-After past it)."""
        from ..standing.hub import CLOSED, SubscriptionLimit

        if self.standing is None:
            return self._send(404, J.error("not_found", "standing engine disabled"))
        p = self._params()
        qid = self._q(p, "id")
        sq = self.standing.get(str(qid)) if qid else None
        if sq is None:
            return self._send(404, J.error("not_found", f"no standing query {qid}"))
        try:
            sub = self.standing.hub.subscribe(sq.qid)
        except SubscriptionLimit as e:
            return self._send(429, J.error("throttled", str(e)),
                              headers={"Retry-After": "5"})
        if self.standing.get(sq.qid) is None:
            # unregister raced between get() and subscribe(): hub.close
            # already ran, so this fresh subscription would never receive
            # a frame (and would resurrect a dead hub entry)
            self.standing.hub.unsubscribe(sub)
            return self._send(404, J.error("not_found",
                                           f"no standing query {qid}"))
        import queue as _queue

        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.close_connection = True
        self.end_headers()
        try:
            first = sq.last_payload
            if first:
                self.wfile.write(b"data: " + first + b"\n\n")
                self.wfile.flush()
            while not sub.closed:
                try:
                    item = sub.get(timeout=15.0)
                except _queue.Empty:
                    self.wfile.write(b": keep-alive\n\n")
                    self.wfile.flush()
                    continue
                if item is CLOSED:
                    break
                self.wfile.write(b"data: " + item + b"\n\n")
                self.wfile.flush()
        except (BrokenPipeError, ConnectionError, OSError):
            pass  # client went away — the normal end of an SSE stream
        finally:
            self.standing.hub.unsubscribe(sub)

    def _ingest_prom(self):
        """Prometheus text exposition ingest (push-gateway style; counters
        route to the prom-counter schema via # TYPE comments)."""
        import time as _time

        from ..gateway.parsers import prom_text_to_batches_and_exemplars

        length = int(self.headers.get("Content-Length") or 0)
        text = self.rfile.read(length).decode() if length else ""
        n = 0
        now_ms = int(_time.time() * 1000)
        batches, exs = prom_text_to_batches_and_exemplars(text, now_ms)
        for batch in batches:
            n += self.engine.memstore.ingest_routed(self.engine.dataset, batch, spread=3)
        # OpenMetrics exemplars ride alongside their samples
        if exs:
            self.engine.memstore.add_exemplars(self.engine.dataset, 3, exs)
        return self._send(200, J.success({"ingested": n}))

    def _ingest_influx(self):
        """Influx line protocol over HTTP (the TCP gateway's HTTP twin)."""
        import time as _time

        from ..gateway.parsers import influx_to_batch

        length = int(self.headers.get("Content-Length") or 0)
        text = self.rfile.read(length).decode() if length else ""
        batch = influx_to_batch(text, int(_time.time() * 1000))
        n = self.engine.memstore.ingest_routed(self.engine.dataset, batch, spread=3)
        return self._send(200, J.success({"ingested": n}))

    def _remote_write(self):
        """Prometheus remote write receiver (snappy+protobuf)."""
        from .remote_storage import parse_write_request

        # binary body: bypass _params (which decodes as text)
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        n = 0
        for batch in parse_write_request(raw):
            n += self.engine.memstore.ingest_routed(self.engine.dataset, batch, spread=3)
        self._count_response(204)
        self.send_response(204)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _remote_read(self):
        from .remote_storage import handle_read_request

        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        out = handle_read_request(raw, self.engine.memstore, self.engine.dataset)
        self.send_response(200)
        self.send_header("Content-Type", "application/x-protobuf")
        self.send_header("Content-Encoding", "snappy")
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)

    def _ingest(self):
        from ..core.records import gauge_batch

        p = self._params()
        body = self._q(p, "__body__", "")
        n = 0
        samples = []
        for line in body.splitlines():
            if not line.strip():
                continue
            rec = json.loads(line)
            samples.append((rec.get("tags", {}), int(rec["ts_ms"]), float(rec["value"])))
            n += 1
        if samples:
            by_metric: dict[str, list] = {}
            for tags, ts, v in samples:
                by_metric.setdefault(tags.get("__name__", tags.get("_metric_", "unknown")), []).append((tags, ts, v))
            for metric, recs in by_metric.items():
                batch = gauge_batch(metric, recs)
                self.engine.memstore.ingest_routed(self.engine.dataset, batch, spread=3)
        return self._send(200, J.success({"ingested": n}))


def register_shard_stats_collector(engine: QueryEngine) -> None:
    """Scrape-time per-shard gauges in the shared Registry (reference
    TimeSeriesShardStats): refreshed on every /metrics render. Keyed per
    ENGINE (not just dataset) so two embedded nodes sharing a dataset name
    — the federation/bootstrap test topology — each keep refreshing their
    own shard slice; gauges are disjoint by shard label. The closure holds
    the memstore WEAKLY and self-unregisters once the store dies — the
    process-global registry must not pin a shut-down server's shards
    (staged chunks included) for the process lifetime."""
    import weakref

    from ..metrics import REGISTRY

    ds = engine.dataset
    key = f"shard_stats:{ds}:{id(engine.memstore)}"
    memstore_ref = weakref.ref(engine.memstore)

    def collect():
        memstore = memstore_ref()
        if memstore is None:
            REGISTRY.unregister_collector(key)
            return
        for sh in memstore.shards(ds):
            ist = sh.index_stats()
            dev = ist.get("device") or {}
            for name, v in (
                ("filodb_shard_partitions", sh.num_partitions),
                ("filodb_shard_rows_ingested", sh.stats.rows_ingested),
                ("filodb_shard_rows_skipped", sh.stats.rows_skipped),
                ("filodb_shard_partitions_evicted", sh.stats.partitions_evicted),
                ("filodb_shard_chunks_flushed", sh.stats.chunks_flushed),
                ("filodb_index_postings_bytes", ist.get("postings_bytes", 0)),
                ("filodb_index_dictionary_size", ist.get("dictionary_size", 0)),
                ("filodb_index_device_staged_bytes", dev.get("staged_bytes", 0)),
            ):
                REGISTRY.gauge(name, dataset=ds, shard=str(sh.shard_num)).set(float(v))

    REGISTRY.register_collector(key, collect)


def make_server(engine: QueryEngine, host: str = "127.0.0.1", port: int = 9090,
                auth_token: str | None = None,
                local_engine: QueryEngine | None = None,
                flush_hook=None,
                dataset_engines: dict | None = None,
                standing=None, standing_system=None,
                rollups=None, alerting=None,
                cluster=None, result_plane: dict | None = None) -> ThreadingHTTPServer:
    # membership hooks (members_hook/join_hook) are wired as class attrs on
    # the returned server's RequestHandlerClass AFTER start — the registry
    # needs the bound port for its self URL (server.py seed bootstrap)
    register_shard_stats_collector(engine)
    attrs = {"engine": engine, "auth_token": auth_token, "local_engine": local_engine,
             "dataset_engines": dict(dataset_engines or {}),
             "standing": standing, "standing_system": standing_system,
             "rollups": rollups, "alerting": alerting,
             "cluster_hook": staticmethod(cluster) if cluster else None,
             "flush_hook": staticmethod(flush_hook) if flush_hook else None}
    if result_plane:  # config [result_plane] -> serving-edge knobs
        attrs["STREAM_MIN_SAMPLES"] = int(
            result_plane.get("stream_min_samples", PromApiHandler.STREAM_MIN_SAMPLES))
        attrs["STREAM_BLOCK_ROWS"] = int(
            result_plane.get("stream_block_rows", PromApiHandler.STREAM_BLOCK_ROWS))
        attrs["ARROW_EDGE"] = result_plane.get("peer_exchange", "arrow") == "arrow"
    handler = type("BoundHandler", (PromApiHandler,), attrs)
    return ThreadingHTTPServer((host, port), handler)


def serve_background(engine: QueryEngine, host: str = "127.0.0.1", port: int = 0,
                     auth_token: str | None = None,
                     local_engine: QueryEngine | None = None,
                     flush_hook=None, dataset_engines: dict | None = None,
                     standing=None, standing_system=None, rollups=None,
                     alerting=None, cluster=None, result_plane: dict | None = None):
    """Start the API server on a thread; returns (server, actual_port)."""
    srv = make_server(engine, host, port, auth_token, local_engine, flush_hook,
                      dataset_engines, standing, standing_system, rollups,
                      alerting, cluster, result_plane)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1]
