"""Prometheus remote write/read endpoints (reference L6: remote-read proto
support in PrometheusModel.scala + remote-storage.proto; plus the remote
WRITE receiver the gateway's Prometheus path implies).

Bodies are snappy block-compressed protobuf (api/snappy.py pure-Python
codec; api/remote.proto wire-compatible with prometheus/prompb).
"""

from __future__ import annotations

import numpy as np

from ..core.filters import ColumnFilter
from ..core.records import RecordBatch
from ..core.schemas import GAUGE, METRIC_TAG
from . import snappy


def _pb():
    from . import remote_pb2

    return remote_pb2


def parse_write_request(body: bytes) -> list[RecordBatch]:
    """snappy+proto WriteRequest -> RecordBatches (gauge schema; Prometheus
    remote write carries no type info)."""
    pb = _pb()
    req = pb.WriteRequest()
    req.ParseFromString(snappy.decompress(body))
    tags_list, ts, vals = [], [], []
    for series in req.timeseries:
        tags = {}
        for l in series.labels:
            tags[METRIC_TAG if l.name == "__name__" else l.name] = l.value
        for s in series.samples:
            tags_list.append(tags)
            ts.append(s.timestamp)
            vals.append(s.value)
    if not tags_list:
        return []
    return [
        RecordBatch(
            GAUGE,
            np.asarray(ts, dtype=np.int64),
            {"value": np.asarray(vals, dtype=np.float64)},
            tags_list,
        )
    ]


_MATCHER_OPS = {0: "=", 1: "!=", 2: "=~", 3: "!~"}


def handle_read_request(body: bytes, memstore, dataset: str) -> bytes:
    """snappy+proto ReadRequest -> snappy+proto ReadResponse with raw
    samples per query."""
    pb = _pb()
    req = pb.ReadRequest()
    req.ParseFromString(snappy.decompress(body))
    resp = pb.ReadResponse()
    for q in req.queries:
        result = resp.results.add()
        filters = []
        for m in q.matchers:
            name = METRIC_TAG if m.name == "__name__" else m.name
            filters.append(ColumnFilter(name, _MATCHER_OPS[int(m.type)], m.value))
        for shard in memstore.shards(dataset):
            pids = shard.lookup_partitions(filters, q.start_timestamp_ms, q.end_timestamp_ms)
            for pid in pids:
                part = shard.partition(int(pid))
                col = part.schema.value_column
                try:
                    t, v = part.samples_in_range(q.start_timestamp_ms, q.end_timestamp_ms, col)
                except KeyError:
                    continue
                if v.ndim != 1 or not len(t):
                    continue
                series = result.timeseries.add()
                for k, val in sorted(part.tags.items()):
                    series.labels.add(name="__name__" if k == METRIC_TAG else k, value=val)
                for i in range(len(t)):
                    if not np.isnan(v[i]):
                        series.samples.add(value=float(v[i]), timestamp=int(t[i]))
    return snappy.compress(resp.SerializeToString())
