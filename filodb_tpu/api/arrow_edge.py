"""Arrow columnar serving edge + Flight transport (reference L5:
coordinator/.../flight/ — FiloDBFlightProducer.scala:27, FlightQueryExecutor
:40, FlightClientManager, ArrowSerializedRangeVectorOps; result model
ArrowSerializedRangeVector, core/.../query/RangeVector.scala:636).

Grids serialize to Arrow RecordBatches: one row per series, label set as a
JSON utf8 column, values as a FixedSizeList<float32>[num_steps]; the step
grid rides in schema metadata. Zero-copy on the wire via Arrow IPC; the
Flight server executes PromQL range queries for peers (the intra-cluster
columnar transport the reference uses between query nodes; device-mesh
clusters use psum instead — Flight remains for cross-cluster/serving edges).
"""

from __future__ import annotations

import json
import threading

import numpy as np
import pyarrow as pa

from ..query.rangevector import Grid, QueryResult


def grid_to_record_batch(g: Grid) -> pa.RecordBatch:
    vals = np.ascontiguousarray(g.values_np(), dtype=np.float32)
    n, j = vals.shape
    labels = pa.array([json.dumps(l, sort_keys=True) for l in g.labels], type=pa.utf8())
    flat = pa.array(vals.ravel(), type=pa.float32())
    values = pa.FixedSizeListArray.from_arrays(flat, j)
    metadata = {
        b"start_ms": str(g.start_ms).encode(),
        b"step_ms": str(g.step_ms).encode(),
        b"num_steps": str(g.num_steps).encode(),
    }
    fields = [pa.field("labels", pa.utf8()), pa.field("values", pa.list_(pa.float32(), j))]
    arrays = [labels, values]
    if g.hist is not None:
        # native histogram buckets ride as a flattened [J*B] list per series
        h = np.ascontiguousarray(g.hist_np(), dtype=np.float32)
        b = h.shape[-1]
        metadata[b"n_buckets"] = str(b).encode()
        metadata[b"les"] = json.dumps([float(x) for x in np.asarray(g.les)]).encode()
        hflat = pa.array(h.reshape(n, -1).ravel(), type=pa.float32())
        arrays.append(pa.FixedSizeListArray.from_arrays(hflat, j * b))
        fields.append(pa.field("hist", pa.list_(pa.float32(), j * b)))
    schema = pa.schema(fields, metadata=metadata)
    return pa.RecordBatch.from_arrays(arrays, schema=schema)


def record_batch_to_grid(rb: pa.RecordBatch) -> Grid:
    md = rb.schema.metadata or {}
    start_ms = int(md[b"start_ms"])
    step_ms = int(md[b"step_ms"])
    num_steps = int(md[b"num_steps"])
    labels = [json.loads(s) for s in rb.column("labels").to_pylist()]
    lst = rb.column("values")
    width = lst.type.list_size
    vals = np.asarray(lst.flatten()).reshape(len(labels), width)
    hist = les = None
    if b"n_buckets" in md:
        nb = int(md[b"n_buckets"])
        les = np.asarray(json.loads(md[b"les"]), dtype=np.float64)
        hl = rb.column("hist")
        hist = np.asarray(hl.flatten()).reshape(len(labels), width * 0 + hl.type.list_size // nb, nb)
    return Grid(labels, start_ms, step_ms, num_steps, vals, hist=hist, les=les)


def result_to_ipc(res: QueryResult) -> bytes:
    """All grids as one Arrow IPC stream (batch per grid)."""
    sink = pa.BufferOutputStream()
    writer = None
    for g in res.grids:
        rb = grid_to_record_batch(g)
        if writer is None:
            writer = pa.ipc.new_stream(sink, rb.schema)
        writer.write_batch(rb)
    if writer is None:  # empty result: write an empty schema stream
        schema = pa.schema([pa.field("labels", pa.utf8())])
        writer = pa.ipc.new_stream(sink, schema)
    writer.close()
    return sink.getvalue().to_pybytes()


def ipc_to_result(data: bytes) -> QueryResult:
    reader = pa.ipc.open_stream(pa.BufferReader(data))
    grids = []
    for rb in reader:
        if rb.num_columns >= 2:
            grids.append(record_batch_to_grid(rb))
    return QueryResult(grids=grids)


# ---------------------------------------------------------------------------
# Flight server / client
# ---------------------------------------------------------------------------

try:  # pyarrow.flight needs grpc support compiled in
    import pyarrow.flight as _flight

    HAVE_FLIGHT = True
except Exception:  # pragma: no cover
    _flight = None
    HAVE_FLIGHT = False


if HAVE_FLIGHT:

    # serialized-plan ticket marker (reference FlightKryoSerDeser ships
    # ExecPlans over Flight tickets via Kryo; here the registry-validated
    # plan protobuf from query/proto_plan.py)
    PLAN_TICKET_MAGIC = b"PLAN\x00"

    class FlightQueryServer(_flight.FlightServerBase):
        """Executes queries for Flight peers (reference FiloDBFlightProducer
        + FlightQueryExecutor). Ticket = JSON {"query", "start", "end",
        "step"} for PromQL, or PLAN_TICKET_MAGIC + plan protobuf."""

        def __init__(self, engine, location="grpc://127.0.0.1:0"):
            super().__init__(location)
            self.engine = engine

        def do_get(self, context, ticket):
            raw = ticket.ticket
            if raw.startswith(PLAN_TICKET_MAGIC):
                from ..query.proto_plan import plan_from_bytes

                res = self.engine.execute_plan(plan_from_bytes(raw[len(PLAN_TICKET_MAGIC):]))
            else:
                req = json.loads(raw.decode())
                res = self.engine.query_range(
                    req["query"], float(req["start"]), float(req["end"]), float(req["step"])
                )
            # partial-results protocol: warnings/partial ride the stream's
            # schema metadata so Flight clients can tell survivors-only
            # data from a complete result
            extra = {}
            if res.warnings:
                extra = {b"partial": b"1",
                         b"warnings": json.dumps(res.warnings).encode()}
            batches = [grid_to_record_batch(g) for g in res.grids]
            if not batches:
                schema = pa.schema(
                    [pa.field("labels", pa.utf8()), pa.field("values", pa.list_(pa.float32(), 1))],
                    metadata={b"start_ms": b"0", b"step_ms": b"1", b"num_steps": b"0",
                              **extra},
                )
                return _flight.RecordBatchStream(pa.Table.from_batches([], schema=schema))
            if extra:
                batches = [
                    b.replace_schema_metadata({**(b.schema.metadata or {}), **extra})
                    for b in batches
                ]
            table = pa.Table.from_batches(batches, schema=batches[0].schema)
            return _flight.RecordBatchStream(table)

    class FlightQueryClient:
        """Pooled client (reference FlightClientManager)."""

        _clients: dict[str, "_flight.FlightClient"] = {}
        _lock = threading.Lock()

        @classmethod
        def get(cls, endpoint: str) -> "_flight.FlightClient":
            with cls._lock:
                c = cls._clients.get(endpoint)
                if c is None:
                    c = _flight.FlightClient(endpoint)
                    cls._clients[endpoint] = c
                return c

        @classmethod
        def _collect(cls, endpoint, ticket) -> QueryResult:
            reader = cls.get(endpoint).do_get(ticket)
            grids = []
            md = {}
            for chunk in reader:
                rb = chunk.data
                md = rb.schema.metadata or md
                if rb.num_rows:
                    grids.append(record_batch_to_grid(rb))
            res = QueryResult(grids=grids)
            if md.get(b"warnings"):
                res.warnings = json.loads(md[b"warnings"])
                res.partial = True
            return res

        @classmethod
        def query_range(cls, endpoint, query, start_s, end_s, step_s) -> QueryResult:
            ticket = _flight.Ticket(
                json.dumps({"query": query, "start": start_s, "end": end_s, "step": step_s}).encode()
            )
            return cls._collect(endpoint, ticket)

        @classmethod
        def execute_plan(cls, endpoint, logical_plan) -> QueryResult:
            """Ship a LogicalPlan subtree as a protobuf ticket (reference
            SingleClusterFlightPlanDispatcher + FlightKryoSerDeser)."""
            from ..query.proto_plan import plan_to_bytes

            ticket = _flight.Ticket(PLAN_TICKET_MAGIC + plan_to_bytes(logical_plan))
            return cls._collect(endpoint, ticket)
