"""Arrow columnar serving edge + Flight transport (reference L5:
coordinator/.../flight/ — FiloDBFlightProducer.scala:27, FlightQueryExecutor
:40, FlightClientManager, ArrowSerializedRangeVectorOps; result model
ArrowSerializedRangeVector, core/.../query/RangeVector.scala:636).

Grids serialize to Arrow RecordBatches: one row per series, label set as a
JSON utf8 column, values as a FixedSizeList<float32>[num_steps]; the step
grid rides in schema metadata. Zero-copy on the wire via Arrow IPC; the
Flight server executes PromQL range queries for peers (the intra-cluster
columnar transport the reference uses between query nodes; device-mesh
clusters use psum instead — Flight remains for cross-cluster/serving edges).
"""

from __future__ import annotations

import json
import threading

import numpy as np
import pyarrow as pa

from ..query.rangevector import Grid, QueryResult, QueryStats, ScalarResult

# peer-hop media type: a FiloDB peer advertises it via Accept and the serving
# edge answers Arrow IPC instead of JSON. Older peers never send it and keep
# getting JSON — that Accept header IS the version negotiation.
ARROW_CONTENT_TYPE = "application/vnd.filodb.arrow.v1"


def grid_to_record_batch(g: Grid) -> pa.RecordBatch:
    vals = np.ascontiguousarray(g.values_np())
    if vals.dtype not in (np.float32, np.float64):
        # keep engine dtypes bit-exact on the wire; everything else (ints,
        # f16) widens once to f64, which holds them losslessly
        vals = vals.astype(np.float64)
    vtype = pa.float64() if vals.dtype == np.float64 else pa.float32()
    n, j = vals.shape
    labels = pa.array([json.dumps(l, sort_keys=True) for l in g.labels], type=pa.utf8())
    flat = pa.array(vals.ravel(), type=vtype)
    values = pa.FixedSizeListArray.from_arrays(flat, j)
    metadata = {
        b"start_ms": str(g.start_ms).encode(),
        b"step_ms": str(g.step_ms).encode(),
        b"num_steps": str(g.num_steps).encode(),
    }
    if g.stale:
        metadata[b"stale"] = b"1"
    fields = [pa.field("labels", pa.utf8()), pa.field("values", pa.list_(vtype, j))]
    arrays = [labels, values]
    if g.hist is not None:
        # native histogram buckets ride as a flattened [J*B] list per series
        h = np.ascontiguousarray(g.hist_np(), dtype=np.float32)
        b = h.shape[-1]
        metadata[b"n_buckets"] = str(b).encode()
        metadata[b"les"] = json.dumps([float(x) for x in np.asarray(g.les)]).encode()
        hflat = pa.array(h.reshape(n, -1).ravel(), type=pa.float32())
        arrays.append(pa.FixedSizeListArray.from_arrays(hflat, j * b))
        fields.append(pa.field("hist", pa.list_(pa.float32(), j * b)))
    schema = pa.schema(fields, metadata=metadata)
    return pa.RecordBatch.from_arrays(arrays, schema=schema)


def record_batch_to_grid(rb: pa.RecordBatch) -> Grid:
    md = rb.schema.metadata or {}
    start_ms = int(md[b"start_ms"])
    step_ms = int(md[b"step_ms"])
    num_steps = int(md[b"num_steps"])
    labels = [json.loads(s) for s in rb.column("labels").to_pylist()]
    lst = rb.column("values")
    width = lst.type.list_size
    vals = np.asarray(lst.flatten()).reshape(len(labels), width)
    hist = les = None
    if b"n_buckets" in md:
        nb = int(md[b"n_buckets"])
        les = np.asarray(json.loads(md[b"les"]), dtype=np.float64)
        hl = rb.column("hist")
        hist = np.asarray(hl.flatten()).reshape(len(labels), width * 0 + hl.type.list_size // nb, nb)
    return Grid(labels, start_ms, step_ms, num_steps, vals, hist=hist, les=les,
                stale=md.get(b"stale") == b"1")


# ---------------------------------------------------------------------------
# Full-result envelope: the node-to-node wire format
# ---------------------------------------------------------------------------
#
# One result = magic + length-prefixed segments. Segment 0 is a JSON envelope
# (result type, warnings/partial, stats, scalar, trace — the small stuff that
# rides "warnings"/"stats"/"trace" in the JSON user edge); each grid is its
# OWN Arrow IPC stream so grids with different step widths / histogram shapes
# never have to share one stream schema; raw export series (variable-length
# ts/values per series) close the stream as a final list-typed segment.

_MAGIC = b"FARS1\n"


def _frame(parts: list, payload: bytes) -> None:
    parts.append(len(payload).to_bytes(8, "little"))
    parts.append(payload)


def _batch_bytes(rb: pa.RecordBatch) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, rb.schema) as writer:
        writer.write_batch(rb)
    return sink.getvalue().to_pybytes()


def _raw_to_batch(raw) -> pa.RecordBatch:
    labels = pa.array([json.dumps(l, sort_keys=True) for l, _, _ in raw], type=pa.utf8())
    ts = pa.array([np.asarray(t, dtype=np.int64) for _, t, _ in raw],
                  type=pa.list_(pa.int64()))
    # per-series values may be [T] (plain) or [T, B] (histogram raw): ship
    # flattened f64 + the column count so the reader can reshape
    vcols, flat = [], []
    for _, _, v in raw:
        a = np.asarray(v, dtype=np.float64)
        vcols.append(a.shape[1] if a.ndim == 2 else 0)
        flat.append(a.ravel())
    vals = pa.array(flat, type=pa.list_(pa.float64()))
    cols = pa.array(vcols, type=pa.int32())
    schema = pa.schema(
        [pa.field("labels", pa.utf8()), pa.field("ts", pa.list_(pa.int64())),
         pa.field("values", pa.list_(pa.float64())), pa.field("vcols", pa.int32())],
        metadata={b"kind": b"raw"},
    )
    return pa.RecordBatch.from_arrays([labels, ts, vals, cols], schema=schema)


def _batch_to_raw(rb: pa.RecordBatch) -> list:
    out = []
    labels = rb.column("labels").to_pylist()
    ts = rb.column("ts")
    vals = rb.column("values")
    cols = rb.column("vcols").to_pylist()
    for i, ls in enumerate(labels):
        t = np.asarray(ts[i].as_py(), dtype=np.int64)
        v = np.asarray(vals[i].as_py(), dtype=np.float64)
        if cols[i]:
            v = v.reshape(-1, cols[i])
        out.append((json.loads(ls), t, v))
    return out


def _stats_to_json(st: QueryStats) -> dict:
    return {k: int(getattr(st, k)) for k in QueryStats._KEYS}


def result_to_ipc(res: QueryResult, trace=None) -> bytes:
    """Encode a QueryResult for a peer hop: JSON envelope segment + one Arrow
    IPC stream per grid (+ an optional raw-series segment). Float payloads
    cross bit-exact — no decimal render/parse round-trip."""
    env: dict = {"resultType": res.result_type, "nGrids": len(res.grids)}
    if res.warnings:
        env["warnings"] = list(res.warnings)
    if res.partial:
        env["partial"] = True
    if res.stats is not None:
        env["stats"] = _stats_to_json(res.stats)
    if trace is None and isinstance(res.trace, dict):
        trace = res.trace
    if trace is not None:
        env["trace"] = trace
    if res.scalar is not None:
        sc = res.scalar
        env["scalar"] = {
            "start_ms": int(sc.start_ms), "step_ms": int(sc.step_ms),
            "num_steps": int(sc.num_steps),
            # repr() round-trips doubles exactly; json emits exactly that
            "values": [float(v) for v in np.asarray(sc.values, dtype=np.float64)],
        }
    if res.metadata is not None:
        env["metadata"] = res.metadata
    parts: list = [_MAGIC]
    _frame(parts, json.dumps(env).encode())
    for g in res.grids:
        _frame(parts, _batch_bytes(grid_to_record_batch(g)))
    if res.raw is not None:
        _frame(parts, _batch_bytes(_raw_to_batch(res.raw)))
    return b"".join(parts)


def ipc_to_result(data: bytes) -> QueryResult:
    if not data.startswith(_MAGIC):
        # pre-envelope peers shipped a bare IPC stream of grid batches
        reader = pa.ipc.open_stream(pa.BufferReader(data))
        grids = [record_batch_to_grid(rb) for rb in reader if rb.num_columns >= 2]
        return QueryResult(grids=grids)
    segs = []
    off = len(_MAGIC)
    while off < len(data):
        n = int.from_bytes(data[off:off + 8], "little")
        off += 8
        segs.append(data[off:off + n])
        off += n
    env = json.loads(segs[0])
    res = QueryResult(result_type=env.get("resultType", "matrix"))
    n_grids = int(env.get("nGrids", 0))
    for seg in segs[1:1 + n_grids]:
        rb = next(iter(pa.ipc.open_stream(pa.BufferReader(seg))))
        res.grids.append(record_batch_to_grid(rb))
    for seg in segs[1 + n_grids:]:
        rb = next(iter(pa.ipc.open_stream(pa.BufferReader(seg))))
        if (rb.schema.metadata or {}).get(b"kind") == b"raw":
            res.raw = _batch_to_raw(rb)
    if env.get("warnings"):
        res.warnings = list(env["warnings"])
    res.partial = bool(env.get("partial"))
    if env.get("stats"):
        res.stats = QueryStats(**{k: int(v) for k, v in env["stats"].items()
                                  if k in QueryStats._KEYS})
    if env.get("trace") is not None:
        res.trace = env["trace"]
    if env.get("scalar") is not None:
        s = env["scalar"]
        res.scalar = ScalarResult(int(s["start_ms"]), int(s["step_ms"]),
                                  int(s["num_steps"]),
                                  np.asarray(s["values"], dtype=np.float64))
    if env.get("metadata") is not None:
        res.metadata = env["metadata"]
    return res


# ---------------------------------------------------------------------------
# Flight server / client
# ---------------------------------------------------------------------------

try:  # pyarrow.flight needs grpc support compiled in
    import pyarrow.flight as _flight

    HAVE_FLIGHT = True
except Exception:  # pragma: no cover
    _flight = None
    HAVE_FLIGHT = False


if HAVE_FLIGHT:

    # serialized-plan ticket marker (reference FlightKryoSerDeser ships
    # ExecPlans over Flight tickets via Kryo; here the registry-validated
    # plan protobuf from query/proto_plan.py)
    PLAN_TICKET_MAGIC = b"PLAN\x00"

    class FlightQueryServer(_flight.FlightServerBase):
        """Executes queries for Flight peers (reference FiloDBFlightProducer
        + FlightQueryExecutor). Ticket = JSON {"query", "start", "end",
        "step"} for PromQL, or PLAN_TICKET_MAGIC + plan protobuf."""

        def __init__(self, engine, location="grpc://127.0.0.1:0"):
            super().__init__(location)
            self.engine = engine

        def do_get(self, context, ticket):
            raw = ticket.ticket
            if raw.startswith(PLAN_TICKET_MAGIC):
                from ..query.proto_plan import plan_from_bytes

                res = self.engine.execute_plan(plan_from_bytes(raw[len(PLAN_TICKET_MAGIC):]))
            else:
                req = json.loads(raw.decode())
                res = self.engine.query_range(
                    req["query"], float(req["start"]), float(req["end"]), float(req["step"])
                )
            # partial-results protocol: warnings/partial ride the stream's
            # schema metadata so Flight clients can tell survivors-only
            # data from a complete result
            extra = {}
            if res.warnings:
                extra = {b"partial": b"1",
                         b"warnings": json.dumps(res.warnings).encode()}
            batches = [grid_to_record_batch(g) for g in res.grids]
            if not batches:
                schema = pa.schema(
                    [pa.field("labels", pa.utf8()), pa.field("values", pa.list_(pa.float32(), 1))],
                    metadata={b"start_ms": b"0", b"step_ms": b"1", b"num_steps": b"0",
                              **extra},
                )
                return _flight.RecordBatchStream(pa.Table.from_batches([], schema=schema))
            if extra:
                batches = [
                    b.replace_schema_metadata({**(b.schema.metadata or {}), **extra})
                    for b in batches
                ]
            table = pa.Table.from_batches(batches, schema=batches[0].schema)
            return _flight.RecordBatchStream(table)

    class FlightQueryClient:
        """Pooled client (reference FlightClientManager)."""

        _clients: dict[str, "_flight.FlightClient"] = {}
        _lock = threading.Lock()

        @classmethod
        def get(cls, endpoint: str) -> "_flight.FlightClient":
            with cls._lock:
                c = cls._clients.get(endpoint)
                if c is None:
                    c = _flight.FlightClient(endpoint)
                    cls._clients[endpoint] = c
                return c

        @classmethod
        def _collect(cls, endpoint, ticket) -> QueryResult:
            reader = cls.get(endpoint).do_get(ticket)
            grids = []
            md = {}
            for chunk in reader:
                rb = chunk.data
                md = rb.schema.metadata or md
                if rb.num_rows:
                    grids.append(record_batch_to_grid(rb))
            res = QueryResult(grids=grids)
            if md.get(b"warnings"):
                res.warnings = json.loads(md[b"warnings"])
                res.partial = True
            return res

        @classmethod
        def query_range(cls, endpoint, query, start_s, end_s, step_s) -> QueryResult:
            ticket = _flight.Ticket(
                json.dumps({"query": query, "start": start_s, "end": end_s, "step": step_s}).encode()
            )
            return cls._collect(endpoint, ticket)

        @classmethod
        def execute_plan(cls, endpoint, logical_plan) -> QueryResult:
            """Ship a LogicalPlan subtree as a protobuf ticket (reference
            SingleClusterFlightPlanDispatcher + FlightKryoSerDeser)."""
            from ..query.proto_plan import plan_to_bytes

            ticket = _flight.Ticket(PLAN_TICKET_MAGIC + plan_to_bytes(logical_plan))
            return cls._collect(endpoint, ticket)
