"""Pure-Python snappy block-format codec (no python-snappy in this image).

Prometheus remote write/read bodies are snappy block-compressed protobuf.
Decompression implements the full format (literals + copies); compression
emits valid all-literal output (legal snappy — peers decompress it fine;
ratio sacrificed for simplicity).

Format: uvarint uncompressed length, then tagged elements:
  tag & 3 == 0: literal, len = (tag>>2)+1 (60..63 escape to 1-4 length bytes)
  tag & 3 == 1: copy, len = ((tag>>2)&7)+4, offset = (tag>>5)<<8 | next byte
  tag & 3 == 2: copy, len = (tag>>2)+1, offset = next 2 bytes LE
  tag & 3 == 3: copy, len = (tag>>2)+1, offset = next 4 bytes LE
"""

from __future__ import annotations


def _read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("uvarint too long")


def _write_uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decompress(data: bytes) -> bytes:
    if not data:
        return b""
    total, pos = _read_uvarint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            length = tag >> 2
            if length < 60:
                length += 1
            else:
                nbytes = length - 59
                length = int.from_bytes(data[pos : pos + nbytes], "little") + 1
                pos += nbytes
            out += data[pos : pos + length]
            pos += length
        else:
            if kind == 1:
                length = ((tag >> 2) & 0x7) + 4
                offset = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif kind == 2:
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[pos : pos + 2], "little")
                pos += 2
            else:
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[pos : pos + 4], "little")
                pos += 4
            if offset == 0 or offset > len(out):
                raise ValueError("bad snappy copy offset")
            start = len(out) - offset
            # copies may overlap forward (run-length style)
            for i in range(length):
                out.append(out[start + i])
    if len(out) != total:
        raise ValueError(f"snappy length mismatch: {len(out)} != {total}")
    return bytes(out)


def compress(data: bytes) -> bytes:
    """All-literal snappy encoding (valid, uncompressed-size output)."""
    out = bytearray(_write_uvarint(len(data)))
    pos = 0
    n = len(data)
    while pos < n:
        chunk = min(n - pos, 65536)
        if chunk <= 60:
            out.append((chunk - 1) << 2)
        elif chunk <= 0xFF:
            out.append(60 << 2)
            out += (chunk - 1).to_bytes(1, "little")
        else:
            out.append(61 << 2)
            out += (chunk - 1).to_bytes(2, "little")
        out += data[pos : pos + chunk]
        pos += chunk
    return bytes(out)
