"""Prometheus HTTP API JSON rendering (reference L6:
query/PrometheusModel.scala — result types matrix/vector/scalar, success/
error envelopes, label normalization)."""

from __future__ import annotations

import json
import math
from typing import Any

import numpy as np

from ..core.schemas import METRIC_TAG
from ..query.rangevector import QueryResult


def _fmt(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


def _fmt_val(v):
    """Scalar samples format as Prometheus value strings; 2-D (histogram
    bucket-row) samples as a list of them."""
    if np.ndim(v) == 0:
        return _fmt(float(v))
    return [_fmt(float(x)) for x in v]


def _labels_out(labels: dict) -> dict:
    out = {}
    for k, v in labels.items():
        if k == METRIC_TAG:
            out["__name__"] = v
        elif not k.startswith("__comp__"):
            out[k] = v
    return out


def render_matrix(res: QueryResult) -> dict:
    data = []
    if res.raw is not None:
        for labels, ts, vals in res.raw:
            keep = ~np.isnan(vals) if vals.ndim == 1 else np.ones(len(ts), bool)
            data.append(
                {
                    "metric": _labels_out(labels),
                    "values": [[t / 1000.0, _fmt_val(v)] for t, v in zip(ts[keep], vals[keep])],
                }
            )
    for labels, ts, vals in res.all_series():
        data.append(
            {
                "metric": _labels_out(labels),
                "values": [[t / 1000.0, _fmt(v)] for t, v in zip(ts, vals)],
            }
        )
    return {"resultType": "matrix", "result": data}


def render_vector(res: QueryResult, time_s: float) -> dict:
    data = []
    for labels, ts, vals in res.all_series():
        if len(vals):
            data.append(
                {"metric": _labels_out(labels), "value": [time_s, _fmt(vals[-1])]}
            )
    return {"resultType": "vector", "result": data}


def render_scalar(res: QueryResult, time_s: float) -> dict:
    v = float("nan")
    if res.scalar is not None and len(res.scalar.values):
        v = float(res.scalar.values[-1])
    return {"resultType": "scalar", "result": [time_s, _fmt(v)]}


def _ts3(t: float) -> str:
    """Fixed 3-decimal seconds (Prometheus' millisecond convention),
    byte-identical to the native renderer's llround-based form: half-away
    rounding, and negatives render as sign + magnitude of the truncating
    div/mod (t=-0.5 -> "-0.500", never "-1.500")."""
    ms = int(math.floor(abs(t) * 1000.0 + 0.5))
    sign = "-" if (t < 0 and ms > 0) else ""
    return f"{sign}{ms // 1000}.{ms % 1000:03d}"


def _ts_decorated(ts_s: np.ndarray) -> np.ndarray:
    """Per-step decorated timestamp strings ``"],[<ts3>,"`` — the inter-sample
    glue of a values fragment. Built once per grid and reused across every
    series row (the numpy fast path's main saving at high series counts)."""
    return np.array(['"],[' + _ts3(float(t)) + ',"' for t in ts_s], dtype=object)


def _rows_numpy(tdec: np.ndarray, vals: np.ndarray) -> list[bytes]:
    """Vectorized fragment assembly for a [G,J] float64 matrix: one bulk
    ``json.dumps`` call per row formats every finite value at C speed (the
    json encoder uses float.__repr__, so the digits are byte-identical to
    ``_fmt``), then timestamp/value strings interleave via strided slice
    assignment instead of a per-sample Python loop. ~5x the per-sample
    f-string path; the native renderer (promrender.cpp) is faster still."""
    out = []
    nan = np.isnan(vals)
    for i in range(len(vals)):
        row = vals[i]
        if nan[i].any():
            row = row[~nan[i]]
        k = len(row)
        if k == 0:
            out.append(b"[]")
            continue
        vs = np.array(
            json.dumps(row.tolist(), separators=(",", ":"))[1:-1].split(","),
            dtype=object,
        )
        inf = np.isinf(row)
        if inf.any():  # json spells them Infinity/-Infinity; Prometheus +Inf/-Inf
            vs[inf & (row > 0)] = "+Inf"
            vs[inf & (row < 0)] = "-Inf"
        parts = np.empty(2 * k, dtype=object)
        parts[0::2] = tdec if k == len(tdec) else tdec[~nan[i]]
        parts[1::2] = vs
        s = "".join(parts)
        # s begins with the first step's '"],[' decoration: drop the '"],'
        # (3 bytes), keep its '[', and prepend/append the array brackets
        out.append(("[" + s[3:] + '"]]').encode())
    return out


def render_rows(ts_s: np.ndarray, vals: np.ndarray) -> list[bytes]:
    """[[t,"v"],...] fragments for every row of a [G,J] matrix sharing one
    step grid. Tiered: native matrix renderer (one ctypes call for the whole
    block) -> vectorized numpy assembly -> per-sample Python. All three are
    byte-identical (golden-asserted in tests/test_promrender.py)."""
    from .. import native as N

    rows = N.render_matrix_rows(ts_s, vals)
    if rows is not None:
        return rows
    v64 = np.ascontiguousarray(vals, dtype=np.float64)
    return _rows_numpy(_ts_decorated(ts_s), v64)


def _values_fragment(ts_s: np.ndarray, vals: np.ndarray) -> bytes:
    """[[t,"v"],...] fragment for one series; native renderer when built
    (promrender.cpp), vectorized numpy assembly otherwise — both
    byte-identical to the per-sample Python form (kept below as the
    last-resort path for exotic dtypes)."""
    from .. import native as N

    frag = N.render_values(ts_s, vals)
    if frag is not None:
        return frag
    try:
        v64 = np.ascontiguousarray(vals, dtype=np.float64)
    except (TypeError, ValueError):
        keep = ~np.isnan(vals)
        parts = (
            f'[{_ts3(float(t))},"{_fmt(v)}"]'
            for t, v in zip(ts_s[keep], vals[keep])
        )
        return ("[" + ",".join(parts) + "]").encode()
    return _rows_numpy(_ts_decorated(ts_s), v64[None, :])[0]


def active_render_format() -> str:
    """Which fragment-renderer tier serves this process: ``native`` when
    libfilodbrender.so is loaded, ``numpy`` otherwise (the vectorized
    fallback; the per-sample ``python`` tier only handles exotic dtypes).
    Querylog records and ``filodb_render_seconds{format}`` label with it."""
    from .. import native as N

    return "native" if N.render_lib() is not None else "numpy"


def _grid_blocks(grids, block_rows: int, phases: dict | None):
    """Yield ``(grid, row_offset, host_block)`` for every ``block_rows``-row
    slice of every grid, with the NEXT block's device->host transfer running
    on a helper thread while the caller encodes the current one (Tailwind's
    boundary-as-dataflow framing: D2H and encode as an overlapped pipeline,
    not a barrier). The queue is bounded at 2 blocks, so a slow socket
    back-pressures the helper thread — never the scheduler's dispatch
    thread, which finished with this query before serving began.

    ``phases`` (when given) accumulates:
      transfer  — seconds the helper spent in device fetches
      stall_s   — seconds the encoder sat waiting for a block (D2H-bound)
      stalls    — number of waits above 1ms (filodb_render_stream_stalls)
    """
    import queue
    import threading
    import time as _time

    q: queue.Queue = queue.Queue(maxsize=2)

    def fetch():
        try:
            for g in grids:
                for i0 in range(0, g.n_series, block_rows):
                    i1 = min(i0 + block_rows, g.n_series)
                    t0 = _time.perf_counter()
                    blk = np.asarray(g.values[i0:i1])[:, : g.num_steps]
                    if phases is not None:
                        phases["transfer"] = (phases.get("transfer", 0.0)
                                              + _time.perf_counter() - t0)
                    q.put((g, i0, blk))
        except BaseException as e:  # surfaced on the serving thread
            q.put(e)
            return
        q.put(None)

    threading.Thread(target=fetch, daemon=True, name="fdb-d2h-prefetch").start()
    while True:
        t0 = _time.perf_counter()
        item = q.get()
        wait = _time.perf_counter() - t0
        if phases is not None:
            phases["stall_s"] = phases.get("stall_s", 0.0) + wait
            if wait > 1e-3:
                phases["stalls"] = phases.get("stalls", 0) + 1
        if item is None:
            return
        if isinstance(item, BaseException):
            raise item
        yield item


def stream_matrix(res: QueryResult, stats: dict | None = None,
                  chunk_target: int = 1 << 18, warnings: list | None = None,
                  trace: dict | None = None, partial: bool = False,
                  block_rows: int | None = None, phases: dict | None = None):
    """Generator of JSON byte chunks for a matrix result envelope.

    The serving-edge answer to reference executeStreaming
    (query/exec/ExecPlan.scala:146) + SerializedRangeVector: root-node memory
    stays bounded by ``chunk_target`` + one series fragment instead of the
    whole rendered matrix (a 100k-series raw export is ~10M samples; the
    non-streaming path held matrix + JSON string concurrently).

    With ``block_rows`` set, grid values are pulled device->host in
    ``block_rows``-series blocks through a double-buffered prefetch thread
    (see _grid_blocks) so the first body bytes leave before the full D2H
    completes and transfer overlaps encode; ``phases`` receives the
    transfer/stall attribution."""
    buf = bytearray()
    buf += b'{"status":"success","data":{"resultType":"matrix","result":['
    first = True

    def emit_frag(labels, frag):
        nonlocal first
        head = b"" if first else b","
        first = False
        return (
            head + b'{"metric":'
            + json.dumps(_labels_out(labels)).encode()
            + b',"values":' + frag + b"}"
        )

    def emit(labels, ts_s, vals, keep_empty):
        frag = _values_fragment(ts_s, vals)
        if frag == b"[]" and not keep_empty:
            return None
        return emit_frag(labels, frag)

    if res.raw is not None:
        for labels, ts, vals in res.raw:
            if vals.ndim != 1:
                # 2-D (histogram-column) raw values would be read as a flat
                # f64 buffer by the native renderer — degrade this series to
                # a Python row-list fragment (same shape as render_matrix's
                # output, with this path's fixed 3-decimal timestamps; no
                # 500 for callers that skip http.py's pre-filter)
                rows = ",".join(
                    f'[{_ts3(t / 1000.0)},{json.dumps(_fmt_val(v))}]'
                    for t, v in zip(ts, vals)
                )
                piece = emit_frag(labels, ("[" + rows + "]").encode())
                if piece:
                    buf += piece
                if len(buf) >= chunk_target:
                    yield bytes(buf)
                    buf.clear()
                continue
            piece = emit(labels, ts.astype(np.float64) / 1e3, vals, True)
            if piece:
                buf += piece
            if len(buf) >= chunk_target:
                yield bytes(buf)
                buf.clear()
    def emit_rows(g, i0, vals_blk, ts_cache):
        ts_s = ts_cache.get(id(g))
        if ts_s is None:
            ts_s = g.step_times_ms().astype(np.float64) / 1e3
            ts_cache[id(g)] = ts_s
        rows = render_rows(ts_s, vals_blk)
        for j, frag in enumerate(rows):
            if frag == b"[]":
                continue
            yield emit_frag(g.labels[i0 + j], frag)

    ts_cache: dict = {}
    if block_rows:
        block_iter = _grid_blocks(res.grids, block_rows, phases)
    else:
        block_iter = ((g, 0, g.values_np()) for g in res.grids)
    for g, i0, vals_blk in block_iter:
        for piece in emit_rows(g, i0, vals_blk, ts_cache):
            buf += piece
            if len(buf) >= chunk_target:
                yield bytes(buf)
                buf.clear()
    buf += b"]"
    if stats is not None:
        buf += b',"stats":' + json.dumps(stats).encode()
    if trace is not None:
        buf += b',"trace":' + json.dumps(trace).encode()
    buf += b"}"  # close data
    if warnings:
        buf += b',"partial":true,"warnings":' + json.dumps(warnings).encode()
    elif partial:
        buf += b',"partial":true'
    buf += b"}"
    yield bytes(buf)


def success(data: Any, warnings: list | None = None, partial: bool = False) -> dict:
    """Success envelope; partial results carry top-level ``warnings`` (the
    Prometheus envelope's warnings slot, structured) + ``"partial": true``."""
    out = {"status": "success", "data": data}
    if warnings:
        out["partial"] = True
        out["warnings"] = warnings
    elif partial:
        out["partial"] = True
    return out


def error(err_type: str, message: str) -> dict:
    return {"status": "error", "errorType": err_type, "error": message}
