"""Prometheus HTTP API JSON rendering (reference L6:
query/PrometheusModel.scala — result types matrix/vector/scalar, success/
error envelopes, label normalization)."""

from __future__ import annotations

import json
import math
from typing import Any

import numpy as np

from ..core.schemas import METRIC_TAG
from ..query.rangevector import QueryResult


def _fmt(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


def _fmt_val(v):
    """Scalar samples format as Prometheus value strings; 2-D (histogram
    bucket-row) samples as a list of them."""
    if np.ndim(v) == 0:
        return _fmt(float(v))
    return [_fmt(float(x)) for x in v]


def _labels_out(labels: dict) -> dict:
    out = {}
    for k, v in labels.items():
        if k == METRIC_TAG:
            out["__name__"] = v
        elif not k.startswith("__comp__"):
            out[k] = v
    return out


def render_matrix(res: QueryResult) -> dict:
    data = []
    if res.raw is not None:
        for labels, ts, vals in res.raw:
            keep = ~np.isnan(vals) if vals.ndim == 1 else np.ones(len(ts), bool)
            data.append(
                {
                    "metric": _labels_out(labels),
                    "values": [[t / 1000.0, _fmt_val(v)] for t, v in zip(ts[keep], vals[keep])],
                }
            )
    for labels, ts, vals in res.all_series():
        data.append(
            {
                "metric": _labels_out(labels),
                "values": [[t / 1000.0, _fmt(v)] for t, v in zip(ts, vals)],
            }
        )
    return {"resultType": "matrix", "result": data}


def render_vector(res: QueryResult, time_s: float) -> dict:
    data = []
    for labels, ts, vals in res.all_series():
        if len(vals):
            data.append(
                {"metric": _labels_out(labels), "value": [time_s, _fmt(vals[-1])]}
            )
    return {"resultType": "vector", "result": data}


def render_scalar(res: QueryResult, time_s: float) -> dict:
    v = float("nan")
    if res.scalar is not None and len(res.scalar.values):
        v = float(res.scalar.values[-1])
    return {"resultType": "scalar", "result": [time_s, _fmt(v)]}


def _ts3(t: float) -> str:
    """Fixed 3-decimal seconds (Prometheus' millisecond convention),
    byte-identical to the native renderer's llround-based form: half-away
    rounding, and negatives render as sign + magnitude of the truncating
    div/mod (t=-0.5 -> "-0.500", never "-1.500")."""
    ms = int(math.floor(abs(t) * 1000.0 + 0.5))
    sign = "-" if (t < 0 and ms > 0) else ""
    return f"{sign}{ms // 1000}.{ms % 1000:03d}"


def _values_fragment(ts_s: np.ndarray, vals: np.ndarray) -> bytes:
    """[[t,"v"],...] fragment for one series; native renderer when built
    (promrender.cpp), Python fallback otherwise. Both skip NaN samples,
    render timestamps as fixed 3-decimal seconds, and render specials as
    NaN/+Inf/-Inf — the two paths emit identical bytes for finite values
    whose shortest repr agrees between std::to_chars and Python repr."""
    from .. import native as N

    frag = N.render_values(ts_s, vals)
    if frag is not None:
        return frag
    keep = ~np.isnan(vals)
    parts = (
        f'[{_ts3(float(t))},"{_fmt(v)}"]'
        for t, v in zip(ts_s[keep], vals[keep])
    )
    return ("[" + ",".join(parts) + "]").encode()


def stream_matrix(res: QueryResult, stats: dict | None = None,
                  chunk_target: int = 1 << 18, warnings: list | None = None,
                  trace: dict | None = None):
    """Generator of JSON byte chunks for a matrix result envelope.

    The serving-edge answer to reference executeStreaming
    (query/exec/ExecPlan.scala:146) + SerializedRangeVector: root-node memory
    stays bounded by ``chunk_target`` + one series fragment instead of the
    whole rendered matrix (a 100k-series raw export is ~10M samples; the
    non-streaming path held matrix + JSON string concurrently)."""
    buf = bytearray()
    buf += b'{"status":"success","data":{"resultType":"matrix","result":['
    first = True

    def emit_frag(labels, frag):
        nonlocal first
        head = b"" if first else b","
        first = False
        return (
            head + b'{"metric":'
            + json.dumps(_labels_out(labels)).encode()
            + b',"values":' + frag + b"}"
        )

    def emit(labels, ts_s, vals, keep_empty):
        frag = _values_fragment(ts_s, vals)
        if frag == b"[]" and not keep_empty:
            return None
        return emit_frag(labels, frag)

    if res.raw is not None:
        for labels, ts, vals in res.raw:
            if vals.ndim != 1:
                # 2-D (histogram-column) raw values would be read as a flat
                # f64 buffer by the native renderer — degrade this series to
                # a Python row-list fragment (same shape as render_matrix's
                # output, with this path's fixed 3-decimal timestamps; no
                # 500 for callers that skip http.py's pre-filter)
                rows = ",".join(
                    f'[{_ts3(t / 1000.0)},{json.dumps(_fmt_val(v))}]'
                    for t, v in zip(ts, vals)
                )
                piece = emit_frag(labels, ("[" + rows + "]").encode())
                if piece:
                    buf += piece
                if len(buf) >= chunk_target:
                    yield bytes(buf)
                    buf.clear()
                continue
            piece = emit(labels, ts.astype(np.float64) / 1e3, vals, True)
            if piece:
                buf += piece
            if len(buf) >= chunk_target:
                yield bytes(buf)
                buf.clear()
    for g in res.grids:
        ts_s = g.step_times_ms().astype(np.float64) / 1e3
        vals = g.values_np()
        for i, labels in enumerate(g.labels):
            piece = emit(labels, ts_s, vals[i], False)
            if piece:
                buf += piece
            if len(buf) >= chunk_target:
                yield bytes(buf)
                buf.clear()
    buf += b"]"
    if stats is not None:
        buf += b',"stats":' + json.dumps(stats).encode()
    if trace is not None:
        buf += b',"trace":' + json.dumps(trace).encode()
    buf += b"}"  # close data
    if warnings:
        buf += b',"partial":true,"warnings":' + json.dumps(warnings).encode()
    buf += b"}"
    yield bytes(buf)


def success(data: Any, warnings: list | None = None, partial: bool = False) -> dict:
    """Success envelope; partial results carry top-level ``warnings`` (the
    Prometheus envelope's warnings slot, structured) + ``"partial": true``."""
    out = {"status": "success", "data": data}
    if warnings:
        out["partial"] = True
        out["warnings"] = warnings
    elif partial:
        out["partial"] = True
    return out


def error(err_type: str, message: str) -> dict:
    return {"status": "error", "errorType": err_type, "error": message}
