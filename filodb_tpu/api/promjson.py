"""Prometheus HTTP API JSON rendering (reference L6:
query/PrometheusModel.scala — result types matrix/vector/scalar, success/
error envelopes, label normalization)."""

from __future__ import annotations

import math
from typing import Any

import numpy as np

from ..core.schemas import METRIC_TAG
from ..query.rangevector import QueryResult


def _fmt(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


def _labels_out(labels: dict) -> dict:
    out = {}
    for k, v in labels.items():
        if k == METRIC_TAG:
            out["__name__"] = v
        elif not k.startswith("__comp__"):
            out[k] = v
    return out


def render_matrix(res: QueryResult) -> dict:
    data = []
    if res.raw is not None:
        for labels, ts, vals in res.raw:
            keep = ~np.isnan(vals) if vals.ndim == 1 else np.ones(len(ts), bool)
            data.append(
                {
                    "metric": _labels_out(labels),
                    "values": [[t / 1000.0, _fmt(v)] for t, v in zip(ts[keep], vals[keep])],
                }
            )
    for labels, ts, vals in res.all_series():
        data.append(
            {
                "metric": _labels_out(labels),
                "values": [[t / 1000.0, _fmt(v)] for t, v in zip(ts, vals)],
            }
        )
    return {"resultType": "matrix", "result": data}


def render_vector(res: QueryResult, time_s: float) -> dict:
    data = []
    for labels, ts, vals in res.all_series():
        if len(vals):
            data.append(
                {"metric": _labels_out(labels), "value": [time_s, _fmt(vals[-1])]}
            )
    return {"resultType": "vector", "result": data}


def render_scalar(res: QueryResult, time_s: float) -> dict:
    v = float("nan")
    if res.scalar is not None and len(res.scalar.values):
        v = float(res.scalar.values[-1])
    return {"resultType": "scalar", "result": [time_s, _fmt(v)]}


def success(data: Any) -> dict:
    return {"status": "success", "data": data}


def error(err_type: str, message: str) -> dict:
    return {"status": "error", "errorType": err_type, "error": message}
