"""Synthetic data generators shared by tests and benchmarks (reference
core/src/test/scala/filodb.core/TestData.scala:27,239 MachineMetricsData —
synthetic machine-metric streams used across every layer's specs), plus the
deterministic fault-injection harness (:class:`FaultInjector`) the chaos
tests drive the query/faults.py retry/breaker/partial-results machinery
with, and the in-process cluster harness (:func:`grpc_cluster`) for
distributed parent -> remote-gRPC-child tests."""

from __future__ import annotations

import random
import threading
import time
from collections import Counter
from dataclasses import dataclass

import numpy as np

from .core.histograms import PROM_DEFAULT, BucketScheme
from .core.records import RecordBatch
from .core.schemas import GAUGE, METRIC_TAG, PROM_COUNTER, PROM_HISTOGRAM, Schema


def kernel_dispatch_total() -> int:
    """Total ``filodb_kernel_dispatch_seconds`` observations so far — the
    ONE definition of the O(1)-dispatch assertion's counter, shared by the
    fused/fused-mesh test suites, bench.py's fused_mesh workload, and the
    MULTICHIP dryrun (a warm fused query must move this by exactly 1)."""
    from .metrics import REGISTRY

    total = 0
    with REGISTRY._lock:
        for (name, _lbls), m in REGISTRY._metrics.items():
            if name == "filodb_kernel_dispatch_seconds":
                total += m.total
    return total


def machine_metrics(
    n_series: int = 100,
    n_samples: int = 720,
    start_ms: int = 1_600_000_000_000,
    interval_ms: int = 10_000,
    metric: str = "heap_usage0",
    ws: str = "demo",
    ns: str = "App-2",
    seed: int = 42,
) -> RecordBatch:
    """Gauge batch: n_series hosts, regular interval, noisy values."""
    rng = np.random.default_rng(seed)
    ts = start_ms + np.arange(n_samples, dtype=np.int64) * interval_ms
    tags = [
        {METRIC_TAG: metric, "_ws_": ws, "_ns_": ns, "instance": f"host-{i}", "job": "machine"}
        for i in range(n_series)
    ]
    all_ts = np.tile(ts, n_series)
    vals = (50 + 20 * rng.standard_normal((n_series, n_samples))).ravel()
    all_tags = [t for t in tags for _ in range(n_samples)]
    return RecordBatch(GAUGE, all_ts, {"value": vals}, all_tags)


def counter_batch(
    n_series: int = 100,
    n_samples: int = 720,
    start_ms: int = 1_600_000_000_000,
    interval_ms: int = 10_000,
    metric: str = "http_requests_total",
    ws: str = "demo",
    ns: str = "App-2",
    seed: int = 7,
    resets: bool = False,
) -> RecordBatch:
    """Counter batch: monotonically increasing, optional resets-to-zero."""
    rng = np.random.default_rng(seed)
    ts = start_ms + np.arange(n_samples, dtype=np.int64) * interval_ms
    incr = rng.uniform(0, 10, size=(n_series, n_samples))
    vals = np.cumsum(incr, axis=1)
    if resets:
        for i in range(n_series):
            k = rng.integers(n_samples // 4, 3 * n_samples // 4)
            vals[i, k:] -= vals[i, k]  # counter restarts at 0
    tags = [
        {METRIC_TAG: metric, "_ws_": ws, "_ns_": ns, "instance": f"host-{i}", "job": "api"}
        for i in range(n_series)
    ]
    all_tags = [t for t in tags for _ in range(n_samples)]
    return RecordBatch(PROM_COUNTER, np.tile(ts, n_series), {"count": vals.ravel()}, all_tags)


def histogram_batch(
    n_series: int = 10,
    n_samples: int = 100,
    start_ms: int = 1_600_000_000_000,
    interval_ms: int = 10_000,
    metric: str = "http_request_latency",
    scheme: BucketScheme = PROM_DEFAULT,
    seed: int = 11,
    schema: Schema = PROM_HISTOGRAM,
) -> RecordBatch:
    """Native cumulative histogram batch: [N, B] bucket counts + sum/count."""
    rng = np.random.default_rng(seed)
    b = scheme.num_buckets
    ts = start_ms + np.arange(n_samples, dtype=np.int64) * interval_ms
    tags = [
        {METRIC_TAG: metric, "_ws_": "demo", "_ns_": "App-2", "instance": f"host-{i}"}
        for i in range(n_series)
    ]
    # per-interval observations land in buckets ~ lognormal; cumulative over time
    incr = rng.poisson(2.0, size=(n_series, n_samples, b)).astype(np.float64)
    incr[..., -1] = incr.sum(-1)  # +Inf bucket grows with everything
    hist = np.cumsum(np.cumsum(incr, axis=2), axis=1)
    count = hist[..., -1]
    total = np.cumsum(rng.uniform(0, 5, size=(n_series, n_samples)) * count / (count + 1), axis=1)
    all_tags = [t for t in tags for _ in range(n_samples)]
    return RecordBatch(
        schema,
        np.tile(ts, n_series),
        {
            "sum": total.ravel(),
            "count": count.ravel(),
            "h": hist.reshape(-1, b),
        },
        all_tags,
        bucket_les=scheme.bounds(),
    )


# ---------------------------------------------------------------------------
# deterministic fault injection (the chaos-test dispatcher)
# ---------------------------------------------------------------------------


class InjectedFault(RuntimeError):
    """A fault raised by :class:`FaultInjector`. Classified like a remote
    transport failure (query/faults.py): retried with backoff and counted
    against the endpoint's circuit breaker."""

    retryable = True
    endpoint_failure = True


@dataclass
class FaultRule:
    """One scheduled fault. ``target`` is substring-matched against the
    child's descriptor — ``ClassName(args_str()) endpoint`` — so rules can
    pin a shard (``"shard=2"``), an endpoint (``"grpc://peer:7777"``), or a
    plan class (``"SelectRawPartitionsExec"``).

    kinds:
      - ``error``   raise :class:`InjectedFault` on every matching dispatch
      - ``latency`` sleep ``latency_s`` then execute normally (stragglers)
      - ``flap``    alternate phases of ``period`` failing dispatches and
                    ``period`` healthy ones (breaker open/re-close drills)

    ``count`` bounds how many matching dispatches the rule applies to
    (None = forever); ``probability`` gates each application through the
    injector's seeded RNG (1.0 = always, fully deterministic)."""

    target: str
    kind: str = "error"
    count: int | None = None
    probability: float = 1.0
    latency_s: float = 0.0
    period: int = 2


class FaultInjector:
    """Seeded dispatcher wrapper injecting failures, latency spikes, and
    flapping per a schedule of :class:`FaultRule`s.

    Installed as ``QueryContext.dispatcher`` (via
    ``PlannerParams.dispatcher``), it sits BELOW the retry/breaker layer in
    query/faults.py, so injected faults exercise exactly the production
    fault-tolerance path. Same seed + same schedule + same query order =>
    same outcomes."""

    def __init__(self, rules, seed: int = 0, sleep=time.sleep):
        self.rules = list(rules)
        self.rng = random.Random(seed)
        self.sleep = sleep
        self.calls: Counter = Counter()      # per-target rule-match counts
        self.injected: Counter = Counter()   # per-target injected faults
        # schedule state is PER RULE, not per target: two rules sharing a
        # target must not corrupt each other's count/flap phases. Guarded by
        # a lock — concurrent remote children dispatch from pool threads,
        # and per-rule counting must stay exact for the schedule to hold.
        self._rule_calls = [0] * len(self.rules)
        self._lock = threading.Lock()

    @staticmethod
    def describe(child) -> str:
        endpoint = getattr(child, "endpoint", "") or ""
        return f"{type(child).__name__}({child.args_str()}) {endpoint}".strip()

    def dispatch(self, child, ctx):
        desc = self.describe(child)
        latency = 0.0
        fault: InjectedFault | None = None
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.target not in desc:
                    continue
                n = self._rule_calls[ri]
                self._rule_calls[ri] += 1
                self.calls[rule.target] += 1
                if rule.count is not None and n >= rule.count:
                    continue
                if rule.probability < 1.0 and self.rng.random() >= rule.probability:
                    continue
                if rule.kind == "latency":
                    latency += rule.latency_s
                    continue
                if rule.kind == "flap" and (n % (2 * rule.period)) >= rule.period:
                    continue  # healthy phase
                self.injected[rule.target] += 1
                fault = InjectedFault(
                    f"injected {rule.kind} for {rule.target!r} (dispatch {n})"
                )
                break
        # act OUTSIDE the lock: a latency spike must not serialize siblings
        if latency:
            self.sleep(latency)
        if fault is not None:
            raise fault
        return child.execute(ctx)


# ---------------------------------------------------------------------------
# in-process distributed cluster (parent -> remote gRPC child)
# ---------------------------------------------------------------------------


def grpc_cluster(batch=None, n_shards: int = 4, owned=(0, 1),
                 dataset: str = "prometheus", spread: int = 2,
                 deadline_s: float = 120.0, **params_kw):
    """Two-node in-process cluster over the gRPC plan transport: a parent
    engine owning ``owned`` shards that scatters every selector to a peer
    engine owning the rest (the distributed scatter-gather path, without
    FiloServer weight). ``batch`` (if given) is routed into BOTH memstores —
    shard ownership splits it across the nodes exactly like production
    ingest routing.

    Returns ``(parent_engine, peer_engine, stop)``; call ``stop()`` to shut
    the peer's gRPC server down. Extra kwargs land on both engines'
    PlannerParams (e.g. slow_query_threshold_s, allow_partial_results)."""
    from .api.grpc_exec import serve_grpc
    from .coordinator.planner import PlannerParams, QueryEngine
    from .core.schemas import Dataset
    from .memstore.memstore import TimeSeriesMemStore

    owned = list(owned)
    peer_shards = [s for s in range(n_shards) if s not in set(owned)]
    ms_parent = TimeSeriesMemStore()
    ms_parent.setup(Dataset(dataset), owned, total_shards=n_shards)
    ms_peer = TimeSeriesMemStore()
    ms_peer.setup(Dataset(dataset), peer_shards, total_shards=n_shards)
    if batch is not None:
        ms_parent.ingest_routed(dataset, batch, spread=spread)
        ms_peer.ingest_routed(dataset, batch, spread=spread)
    common = dict(spread=spread, num_shards=n_shards, deadline_s=deadline_s,
                  **params_kw)
    peer_engine = QueryEngine(ms_peer, dataset, PlannerParams(**common))
    server, port = serve_grpc(peer_engine, port=0)
    parent_engine = QueryEngine(
        ms_parent, dataset,
        PlannerParams(peer_endpoints=(f"grpc://127.0.0.1:{port}",), **common),
    )

    def stop():
        server.stop(grace=0)

    return parent_engine, peer_engine, stop


# ---------------------------------------------------------------------------
# replica topology (replicated shard plane chaos harness)
# ---------------------------------------------------------------------------


@dataclass
class ReplicaNode:
    """One data node of a :func:`replica_cluster`: its memstore + engine +
    gRPC server, and the plane handle they register under."""

    name: str
    memstore: object
    engine: object
    server: object
    endpoint: str
    standing: object = None


class ReplicaCluster:
    """Front coordinator + N replicated data nodes, all in-process.

    ``engine`` is the query edge: it owns NO shards and scatters every
    selector through the ReplicaRouter (one shard-pinned gRPC leg per
    selected replica, siblings attached for dispatch-layer failover).
    ``kill(name)`` stops a node's gRPC server AND reports it to the plane —
    the deterministic chaos primitive."""

    def __init__(self, engine, plane, manager, router, nodes, breakers):
        self.engine = engine
        self.plane = plane
        self.manager = manager
        self.router = router
        self.nodes: dict[str, ReplicaNode] = nodes
        self.breakers = breakers

    def kill(self, name: str) -> None:
        n = self.nodes[name]
        n.server.stop(grace=0)
        self.plane.set_node_down(name)

    def stop(self) -> None:
        for n in self.nodes.values():
            n.server.stop(grace=0)


def replica_cluster(batch=None, n_shards: int = 4, num_nodes: int = 2,
                    num_replicas: int = 2, dataset: str = "prometheus",
                    spread: int = 2, deadline_s: float = 120.0,
                    standing: bool = False, retry_policy=None,
                    **params_kw) -> ReplicaCluster:
    """In-process replicated cluster: ``num_nodes`` data nodes behind a
    front coordinator, replication factor ``num_replicas``.

    With the default 2 nodes / RF 2 / shards_per_node == n_shards, every
    node replicates EVERY shard, so killing one node must serve bit-equal
    results from the survivor. ``batch`` (if given) fans out through the
    ReplicationPlane — the production ingest path, acks and watermarks
    included. ``standing=True`` attaches a StandingEngine per data node so
    rebalance handoff tests can follow standing queries across owners."""
    from .api.grpc_exec import serve_grpc
    from .coordinator.cluster import ShardManager, ShardStatus
    from .coordinator.planner import PlannerParams, QueryEngine
    from .coordinator.replication import ReplicaRouter, ReplicationPlane
    from .core.schemas import Dataset
    from .memstore.memstore import TimeSeriesMemStore
    from .query.faults import BreakerRegistry, RetryPolicy

    manager = ShardManager(n_shards, shards_per_node=n_shards,
                           num_replicas=num_replicas)
    plane = ReplicationPlane(manager, dataset, spread=spread)
    common = dict(spread=spread, num_shards=n_shards, deadline_s=deadline_s,
                  **params_kw)
    nodes: dict[str, ReplicaNode] = {}
    for i in range(num_nodes):
        name = f"node-{i}"
        ms = TimeSeriesMemStore()
        ms.setup(Dataset(dataset), [], total_shards=n_shards)
        engine = QueryEngine(ms, dataset, PlannerParams(**common))
        server, port = serve_grpc(engine, port=0)
        endpoint = f"grpc://127.0.0.1:{port}"
        st = None
        if standing:
            from .standing.maintainer import StandingEngine

            st = StandingEngine(engine)
        plane.add_node(name, ms, endpoint=endpoint, standing=st)
        manager.node_joined(name)
        nodes[name] = ReplicaNode(name, ms, engine, server, endpoint, st)
    # fresh topology: every replica is live from the start
    for s in range(n_shards):
        for node in list(manager.mapper.nodes_of(s)):
            manager.mapper.set_replica(s, node, ShardStatus.ACTIVE)
    if batch is not None:
        plane.append(batch)
    router = ReplicaRouter(plane)
    breakers = BreakerRegistry()
    if retry_policy is None:
        # deterministic + fast: seeded jitter, no real sleeping — chaos
        # outcomes must not depend on wall-clock scheduling
        retry_policy = RetryPolicy(seed=0, sleep=lambda s: None)
    ms_front = TimeSeriesMemStore()
    ms_front.setup(Dataset(dataset), [], total_shards=n_shards)
    front = QueryEngine(
        ms_front, dataset,
        PlannerParams(replica_router=router, breakers=breakers,
                      retry_policy=retry_policy, **common),
    )
    return ReplicaCluster(front, plane, manager, router, nodes, breakers)
