"""Synthetic data generators shared by tests and benchmarks (reference
core/src/test/scala/filodb.core/TestData.scala:27,239 MachineMetricsData —
synthetic machine-metric streams used across every layer's specs)."""

from __future__ import annotations

import numpy as np

from .core.histograms import PROM_DEFAULT, BucketScheme
from .core.records import RecordBatch
from .core.schemas import GAUGE, METRIC_TAG, PROM_COUNTER, PROM_HISTOGRAM, Schema


def machine_metrics(
    n_series: int = 100,
    n_samples: int = 720,
    start_ms: int = 1_600_000_000_000,
    interval_ms: int = 10_000,
    metric: str = "heap_usage0",
    ws: str = "demo",
    ns: str = "App-2",
    seed: int = 42,
) -> RecordBatch:
    """Gauge batch: n_series hosts, regular interval, noisy values."""
    rng = np.random.default_rng(seed)
    ts = start_ms + np.arange(n_samples, dtype=np.int64) * interval_ms
    tags = [
        {METRIC_TAG: metric, "_ws_": ws, "_ns_": ns, "instance": f"host-{i}", "job": "machine"}
        for i in range(n_series)
    ]
    all_ts = np.tile(ts, n_series)
    vals = (50 + 20 * rng.standard_normal((n_series, n_samples))).ravel()
    all_tags = [t for t in tags for _ in range(n_samples)]
    return RecordBatch(GAUGE, all_ts, {"value": vals}, all_tags)


def counter_batch(
    n_series: int = 100,
    n_samples: int = 720,
    start_ms: int = 1_600_000_000_000,
    interval_ms: int = 10_000,
    metric: str = "http_requests_total",
    ws: str = "demo",
    ns: str = "App-2",
    seed: int = 7,
    resets: bool = False,
) -> RecordBatch:
    """Counter batch: monotonically increasing, optional resets-to-zero."""
    rng = np.random.default_rng(seed)
    ts = start_ms + np.arange(n_samples, dtype=np.int64) * interval_ms
    incr = rng.uniform(0, 10, size=(n_series, n_samples))
    vals = np.cumsum(incr, axis=1)
    if resets:
        for i in range(n_series):
            k = rng.integers(n_samples // 4, 3 * n_samples // 4)
            vals[i, k:] -= vals[i, k]  # counter restarts at 0
    tags = [
        {METRIC_TAG: metric, "_ws_": ws, "_ns_": ns, "instance": f"host-{i}", "job": "api"}
        for i in range(n_series)
    ]
    all_tags = [t for t in tags for _ in range(n_samples)]
    return RecordBatch(PROM_COUNTER, np.tile(ts, n_series), {"count": vals.ravel()}, all_tags)


def histogram_batch(
    n_series: int = 10,
    n_samples: int = 100,
    start_ms: int = 1_600_000_000_000,
    interval_ms: int = 10_000,
    metric: str = "http_request_latency",
    scheme: BucketScheme = PROM_DEFAULT,
    seed: int = 11,
    schema: Schema = PROM_HISTOGRAM,
) -> RecordBatch:
    """Native cumulative histogram batch: [N, B] bucket counts + sum/count."""
    rng = np.random.default_rng(seed)
    b = scheme.num_buckets
    ts = start_ms + np.arange(n_samples, dtype=np.int64) * interval_ms
    tags = [
        {METRIC_TAG: metric, "_ws_": "demo", "_ns_": "App-2", "instance": f"host-{i}"}
        for i in range(n_series)
    ]
    # per-interval observations land in buckets ~ lognormal; cumulative over time
    incr = rng.poisson(2.0, size=(n_series, n_samples, b)).astype(np.float64)
    incr[..., -1] = incr.sum(-1)  # +Inf bucket grows with everything
    hist = np.cumsum(np.cumsum(incr, axis=2), axis=1)
    count = hist[..., -1]
    total = np.cumsum(rng.uniform(0, 5, size=(n_series, n_samples)) * count / (count + 1), axis=1)
    all_tags = [t for t in tags for _ in range(n_samples)]
    return RecordBatch(
        schema,
        np.tile(ts, n_series),
        {
            "sum": total.ravel(),
            "count": count.ravel(),
            "h": hist.reshape(-1, b),
        },
        all_tags,
        bucket_les=scheme.bounds(),
    )
