"""Self-telemetry: the server scrapes ITSELF into a ``_system`` dataset.

This is a Prometheus-compatible TSDB — its own metrics should be queryable
through its own (fused) PromQL path, not only through an external
Prometheus. The :class:`SelfScraper` samples the process ``REGISTRY`` every
``telemetry.self_scrape_interval_s`` seconds, renders the standard text
exposition, and feeds it through the PRODUCTION ingest parser
(``gateway.parsers.prom_text_to_batches_and_exemplars`` — TYPE comments
route counters and histogram families to the counter schema) into the
memstore's ``_system`` dataset. ``rate(filodb_kernel_dispatch_seconds_count[5m])``
and per-tenant byte dashboards then run through the standard query API
(``?dataset=_system``) and the fused single-dispatch path like any other
workload.

The query observatory (obs/querylog.py) rides this pipeline into
``_system``: the per-phase histograms
(``filodb_query_phase_seconds{phase,dataset}``) and the per-tenant /
per-path cumulative aggregates
(``filodb_tenant_phase_seconds_total{phase,ws,ns}``,
``filodb_query_path_total{path,dataset}``) are ordinary registry families,
so every scrape ingests them as real series and
``histogram_quantile(0.99, sum by (le)
(rate(filodb_query_phase_seconds_bucket{phase="render"}[5m])))`` answers
through the fused path — which is also what the SLO burn-rate recording
rules (obs/slo.py) evaluate against.

Also here: the scrape-time collector that surfaces ``tools/tpu_watch.py``
device-probe results as ``filodb_tpu_*`` gauges (the watchdog's log is the
source of truth; parsing it at scrape time means the server needs no side
channel to the watchdog process), and the query-log ring-depth collector.
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time

from .metrics import REGISTRY

log = logging.getLogger("filodb_tpu.telemetry")

SYSTEM_DATASET = "_system"


class SelfScraper:
    """Config-gated internal collector: REGISTRY -> text exposition ->
    prom parser -> ``_system`` dataset, every ``interval_s`` seconds."""

    def __init__(self, memstore, dataset: str = SYSTEM_DATASET,
                 interval_s: float = 15.0, spread: int = 1,
                 registry=REGISTRY, ws: str = "system", ns: str = "filodb"):
        self.memstore = memstore
        self.dataset = dataset
        self.interval_s = float(interval_s)
        self.spread = int(spread)
        self.registry = registry
        self.ws = ws
        self.ns = ns
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def scrape_once(self, now_ms: int | None = None) -> int:
        """One scrape cycle; returns samples ingested (synchronous — the
        unit the tests drive directly)."""
        from .gateway.parsers import prom_text_to_batches_and_exemplars

        if now_ms is None:
            now_ms = int(time.time() * 1000)
        text = self.registry.expose()
        batches, _exemplars = prom_text_to_batches_and_exemplars(
            text, now_ms, ws=self.ws, ns=self.ns
        )
        n = 0
        for batch in batches:
            n += self.memstore.ingest_routed(self.dataset, batch, self.spread)
        REGISTRY.counter("filodb_self_scrapes").inc()
        REGISTRY.counter("filodb_self_scrape_samples").inc(n)
        return n

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return  # idempotent, like SamplingProfiler.start
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="filodb-self-scrape"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — telemetry must never kill serving
                log.exception("self-scrape failed")


# -- query-observatory collector ---------------------------------------------


def register_querylog_collector(registry=REGISTRY) -> None:
    """Expose the query-log ring's depth as ``filodb_querylog_entries``,
    refreshed at scrape time (keyed — re-registration replaces). The
    per-phase/per-tenant/per-path aggregates need no collector: they are
    plain counters/histograms bumped at record time (obs/querylog.py) and
    every self-scrape carries them into ``_system``."""
    from .obs.querylog import QUERY_LOG

    def collect():
        registry.gauge("filodb_querylog_entries").set(float(len(QUERY_LOG)))

    registry.register_collector("querylog", collect)


# -- tpu-watch probe gauges --------------------------------------------------

_PROBE_RE = re.compile(
    r"^(?P<ts>\S+) probe (?P<outcome>OK|FAIL|TIMEOUT)", re.M
)
_ATTEST_RE = re.compile(r"^\S+ ATTESTED ", re.M)
_TS_FMT = "%Y-%m-%dT%H:%M:%S%z"


def parse_tpu_watch_log(text: str) -> dict:
    """Aggregate a TPU_WATCH_LOG.txt payload into probe stats: total/ok
    counts, attested measurements, last outcome and its timestamp."""
    probes = ok = 0
    last_outcome = None
    last_ts = None
    for m in _PROBE_RE.finditer(text):
        probes += 1
        healthy = m.group("outcome") == "OK"
        ok += healthy
        last_outcome = healthy
        try:
            last_ts = time.mktime(
                time.strptime(m.group("ts")[:19], "%Y-%m-%dT%H:%M:%S")
            )
        except ValueError:
            last_ts = None
    return {
        "probes": probes,
        "ok": ok,
        "attested": len(_ATTEST_RE.findall(text)),
        "last_healthy": last_outcome,
        "last_ts": last_ts,
    }


def register_tpu_watch_collector(log_path: str,
                                 registry=REGISTRY) -> None:
    """Expose the tpu-watch watchdog's device-probe results as
    ``filodb_tpu_*`` gauges, refreshed at scrape time from its log file
    (keyed per path — re-registration replaces). Gauges:

    - ``filodb_tpu_probe_healthy`` — last probe outcome (1/0; -1 = no
      probes seen yet or log absent)
    - ``filodb_tpu_probe_age_seconds`` — seconds since the last probe
    - ``filodb_tpu_probes`` / ``filodb_tpu_probes_ok`` — cumulative counts
      from the log
    - ``filodb_tpu_bench_attested`` — attested benchmark measurements"""

    def collect():
        stats = None
        try:
            if os.path.exists(log_path):
                with open(log_path) as f:
                    stats = parse_tpu_watch_log(f.read())
        except OSError:
            stats = None
        if not stats or not stats["probes"]:
            registry.gauge("filodb_tpu_probe_healthy").set(-1.0)
            return
        registry.gauge("filodb_tpu_probe_healthy").set(
            1.0 if stats["last_healthy"] else 0.0
        )
        if stats["last_ts"] is not None:
            registry.gauge("filodb_tpu_probe_age_seconds").set(
                max(0.0, time.time() - stats["last_ts"])
            )
        registry.gauge("filodb_tpu_probes").set(float(stats["probes"]))
        registry.gauge("filodb_tpu_probes_ok").set(float(stats["ok"]))
        registry.gauge("filodb_tpu_bench_attested").set(float(stats["attested"]))

    registry.register_collector(f"tpu_watch:{log_path}", collect)
