"""Multi-host distributed runtime (reference: the Akka-Cluster /
FiloDbClusterDiscovery control plane + NCCL-style data plane, SURVEY.md §2
"Distributed communication backends" — here the JAX distributed runtime:
one coordination service, per-process local devices, XLA collectives over
ICI within a host/slice and DCN across hosts).

Bootstrap order (each process):
  1. ``init_distributed(...)`` BEFORE any backend touch — wires the process
     into the global device view (reference analog: node joins the cluster
     via discovery, NewFiloServerMain.scala:45-47);
  2. ``make_multihost_mesh(...)`` — one global mesh over every device of
     every process; the planner/mesh execs are unchanged (the same compiled
     psum program now spans hosts, riding ICI within a slice and DCN
     between them);
  3. shard ownership: ``shards_for_process`` splits shard numbers by
     process ordinal exactly like the v2 stateful-set discovery
     (coordinator/cluster.py ClusterDiscovery), so ingest lands on the host
     whose devices hold that shard's mesh slot.

Mesh axis layout follows the scaling-book recipe: put the axis with the
highest-volume collectives innermost (ICI). For us the time-halo exchange
(ring ppermute, O(halo) per step) outranks the shard psum (O(groups x
steps) once per query), so hybrid 2D meshes place ``time`` on ICI and
``shard`` across DCN.
"""

from __future__ import annotations

import os

import numpy as np


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize the JAX distributed runtime for this process. Env vars
    FILODB_COORDINATOR / FILODB_NUM_PROCESSES / FILODB_PROCESS_ID OVERRIDE
    the arguments — the stateful-set ordinal pattern ships one config file
    and injects the per-pod identity via env. No-ops (returns False) for
    single-process deployments so the server can call it unconditionally."""
    import jax

    coordinator_address = os.environ.get("FILODB_COORDINATOR") or coordinator_address
    env_np = os.environ.get("FILODB_NUM_PROCESSES")
    num_processes = int(env_np) if env_np else (num_processes or 1)
    env_pid = os.environ.get("FILODB_PROCESS_ID")
    process_id = int(env_pid) if env_pid else (process_id or 0)
    if num_processes <= 1 or not coordinator_address:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def make_multihost_mesh(axis: str = "shard"):
    """One global 1D mesh over every device of every process (after
    ``init_distributed``, ``jax.devices()`` is the global view)."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), axis_names=(axis,))


def make_hybrid_mesh2d(shard_axis_size: int | None = None):
    """2D ``(shard, time)`` mesh for multi-host: ``shard`` spans hosts (DCN)
    and ``time`` stays within a host (ICI), so the per-step ring halo
    exchange of the time axis rides the fast interconnect. Falls back to a
    plain reshape on a single process."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    n_proc = max(getattr(jax, "process_count", lambda: 1)(), 1)
    shard_size = shard_axis_size or n_proc
    if len(devices) % shard_size:
        raise ValueError(f"{len(devices)} devices not divisible by shard axis {shard_size}")
    time_size = len(devices) // shard_size
    if n_proc > 1:
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1, time_size),
            dcn_mesh_shape=(shard_size, 1),
            devices=devices,
        )
    else:
        arr = np.array(devices).reshape(shard_size, time_size)
    return Mesh(arr, axis_names=("shard", "time"))


def shards_for_process(num_shards: int, num_processes: int | None = None,
                       process_id: int | None = None) -> list[int]:
    """Contiguous shard ownership by process ordinal (reference
    FiloDbClusterDiscovery.scala:37-47 ordinal -> shard assignment)."""
    import jax

    if num_processes is None:
        num_processes = max(getattr(jax, "process_count", lambda: 1)(), 1)
    if process_id is None:
        process_id = getattr(jax, "process_index", lambda: 0)()
    per = (num_shards + num_processes - 1) // num_processes
    lo = process_id * per
    return list(range(lo, min(lo + per, num_shards)))
