"""Time-axis sharding with ring halo exchange — the ring-attention analog
for range queries (SURVEY.md §5 "long-context": sharded time blocks with a
±lookback halo exchange; reference analog: time-splitting planners +
lookback-window sharing).

For very long ranges the time dimension, not series count, dominates. The
staged block's time axis shards across the mesh into DISJOINT sample
slices; device d computes the output steps inside its span. Windows at a
slice's left edge reach up to ``window`` back into the previous slice, so
at runtime each device sends the right-aligned TAIL of its slice to its
right neighbor with ONE ``ppermute`` over ICI — exactly ring attention's
KV halo pattern with the lookback window as the attention span.

Sort discipline that makes the general kernel work unchanged on the
concatenated [halo | slice] array: halo padding uses an INT32_MIN sentinel
(sorts before every real sample and never lands in a window because window
lower bounds are real times), so boundary counting, prefix sums, and
positional gathers stay exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..jax_compat import shard_map
from ..ops import kernels as K
from ..ops.staging import TS_PAD, StagedBlock

TS_NEG = np.int32(-(2**31) + 1)  # sorts before all real samples


def make_time_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), axis_names=("time",))


def split_time_axis(block: StagedBlock, n_devices: int, window_ms: int,
                    start_ms: int, step_ms: int, num_steps: int):
    """Host-side prep: disjoint per-device sample slices + right-aligned
    tails for the halo exchange.

    Device d owns steps [d*J_dev, (d+1)*J_dev) and the samples in
    (owned_end[d-1], owned_end[d]] (device 0 additionally owns the global
    lookback span before the first step). Halo width H = max samples any
    window needs from the previous slice, measured from the data.

    Returns (ts [D,S,Tl], vals, raw, lens [D,S], tail_ts [D,S,H],
    tail_vals, tail_raw, j_dev).
    """
    D = n_devices
    S, T = block.ts.shape
    ts = np.asarray(block.ts)
    vals = np.asarray(block.vals)
    raw = np.asarray(block.raw) if block.raw is not None else vals
    lens = np.asarray(block.lens)
    J_dev = -(-num_steps // D)
    start_off = start_ms - block.base_ms
    owned_end = [start_off + (min((d + 1) * J_dev, num_steps) - 1) * step_ms for d in range(D)]
    owned_start = [start_off - window_ms] + owned_end[:-1]
    bounds = np.empty((D, S, 2), dtype=np.int64)
    Tl = 1
    H = 1
    for d in range(D):
        for s in range(S):
            row = ts[s, : lens[s]]
            lo = np.searchsorted(row, owned_start[d], side="right")
            hi = np.searchsorted(row, owned_end[d], side="right")
            bounds[d, s] = (lo, hi)
            Tl = max(Tl, hi - lo)
            if d > 0:
                # halo this device needs: samples in the previous slice
                # within window of its first step
                first_step = start_off + d * J_dev * step_ms
                need_lo = np.searchsorted(row, first_step - window_ms, side="right")
                H = max(H, lo - min(need_lo, lo))
    Tl = max(((int(Tl) + 127) // 128) * 128, 128)
    H = max(((int(H) + 127) // 128) * 128, 128)
    out_ts = np.full((D, S, Tl), TS_PAD, dtype=np.int32)
    out_vals = np.zeros((D, S, Tl), dtype=np.float32)
    out_raw = np.zeros((D, S, Tl), dtype=np.float32)
    out_lens = np.zeros((D, S), dtype=np.int32)
    tail_ts = np.full((D, S, H), TS_NEG, dtype=np.int32)
    tail_vals = np.zeros((D, S, H), dtype=np.float32)
    tail_raw = np.zeros((D, S, H), dtype=np.float32)
    for d in range(D):
        for s in range(S):
            lo, hi = bounds[d, s]
            n = hi - lo
            out_ts[d, s, :n] = ts[s, lo:hi]
            out_vals[d, s, :n] = vals[s, lo:hi]
            out_raw[d, s, :n] = raw[s, lo:hi]
            out_lens[d, s] = n
            # right-aligned tail of THIS device's slice (sent to d+1)
            k = min(H, n)
            if k:
                tail_ts[d, s, H - k :] = ts[s, hi - k : hi]
                tail_vals[d, s, H - k :] = vals[s, hi - k : hi]
                tail_raw[d, s, H - k :] = raw[s, hi - k : hi]
    return out_ts, out_vals, out_raw, out_lens, tail_ts, tail_vals, tail_raw, J_dev


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "func", "j_dev", "is_counter", "is_delta"),
)
def timesharded_range(
    mesh: Mesh,
    func: str,
    ts, vals, raw,  # [D, S, Tl] disjoint slices
    lens,  # [D, S]
    tail_ts, tail_vals, tail_raw,  # [D, S, H] right-aligned own tails
    baseline,  # [S] replicated
    start_off, step_ms, window,
    j_dev: int,
    is_counter: bool = False,
    is_delta: bool = False,
):
    """One compiled program: ppermute halo to the right neighbor, then the
    standard range kernel per device on [halo | slice]. Returns
    [D, S, j_dev] step grids (device-major)."""
    D = mesh.devices.size
    axis = mesh.axis_names[0]  # works over any single-axis mesh name
    perm = [(i, (i + 1) % D) for i in range(D)]

    def local(ts_l, vals_l, raw_l, lens_l, tts, tv, tr, base):
        d = jax.lax.axis_index(axis)
        # halo arrives from the LEFT neighbor (ring shift right)
        h_ts = jax.lax.ppermute(tts, axis, perm)[0]
        h_v = jax.lax.ppermute(tv, axis, perm)[0]
        h_r = jax.lax.ppermute(tr, axis, perm)[0]
        # device 0 has no left neighbor: neutralize the wrapped halo
        h_ts = jnp.where(d == 0, jnp.int32(TS_NEG), h_ts)
        h_v = jnp.where(d == 0, 0.0, h_v)
        h_r = jnp.where(d == 0, 0.0, h_r)
        H = h_ts.shape[1]
        comb_ts = jnp.concatenate([h_ts, ts_l[0]], axis=1)
        comb_v = jnp.concatenate([h_v, vals_l[0]], axis=1)
        comb_r = jnp.concatenate([h_r, raw_l[0]], axis=1)
        comb_lens = lens_l[0] + H  # sentinel slots sort first and never match
        my_start = start_off + d.astype(jnp.int32) * j_dev * step_ms
        grid = K.range_kernel(
            func, comb_ts, comb_v, comb_lens, base, comb_r,
            my_start, step_ms, window, j_dev,
            is_counter=is_counter, is_delta=is_delta,
        )
        return grid[None]

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis),
                  P(axis), P(axis), P(axis), P()),
        out_specs=P(axis, None, None),
        check=False,
    )(ts, vals, raw, lens, tail_ts, tail_vals, tail_raw, baseline)


def run_timesharded(mesh: Mesh, func: str, block: StagedBlock, params: K.RangeParams,
                    is_counter=False, is_delta=False):
    """Host entry: shard the time axis over the mesh and execute. Returns
    [S, num_steps] (numpy-sliceable device array)."""
    D = mesh.devices.size
    ts, vals, raw, lens, tts, tv, tr, j_dev = split_time_axis(
        block, D, params.window_ms, params.start_ms, params.step_ms, params.num_steps
    )
    dev = NamedSharding(mesh, P(mesh.axis_names[0]))
    rep = NamedSharding(mesh, P())
    out = timesharded_range(
        mesh, func,
        jax.device_put(ts, dev), jax.device_put(vals, dev), jax.device_put(raw, dev),
        jax.device_put(lens, dev),
        jax.device_put(tts, dev), jax.device_put(tv, dev), jax.device_put(tr, dev),
        jax.device_put(np.asarray(block.baseline), rep),
        np.int32(params.start_ms - block.base_ms),
        np.int32(params.step_ms), np.int32(params.window_ms),
        j_dev, is_counter=is_counter, is_delta=is_delta,
    )
    S = out.shape[1]
    flat = jnp.moveaxis(out, 0, 1).reshape(S, -1)
    return flat[:, : params.num_steps]
