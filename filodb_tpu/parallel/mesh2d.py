"""2D mesh execution: series-parallel x time-parallel in one program.

The full SPMD composition for ``sum by (...) (rate(m[w]))`` over both huge
cardinality AND long ranges: mesh axes ``(shard, time)`` —

- the ``shard`` axis partitions series (data-parallel); cross-series
  aggregation is a ``psum`` over it (parallel/mesh.py's pattern);
- the ``time`` axis partitions samples (the sequence-parallel axis); window
  lookback crosses slice boundaries via a ring ``ppermute`` halo
  (parallel/timeshard.py's pattern).

One jit: per-tile range kernel -> local segment-reduce -> psum(shard);
outputs concatenate along the step axis across the time ring. This is the
TSDB analog of dp+sp sharding in model training.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..jax_compat import shard_map
from ..ops import kernels as K
from ..ops.staging import StagedBlock
from .timeshard import TS_NEG, split_time_axis


def make_mesh2d(n_shard: int, n_time: int, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    assert len(devices) >= n_shard * n_time
    arr = np.array(devices[: n_shard * n_time]).reshape(n_shard, n_time)
    return Mesh(arr, axis_names=("shard", "time"))


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "func", "op", "j_dev", "num_groups", "is_counter", "is_delta"),
)
def mesh2d_agg_range(
    mesh: Mesh,
    func: str,
    op: str,
    ts, vals, raw,  # [Ds*S_l, Dt, Tl] — series blocks x time slices
    lens,  # [Ds*S_l, Dt]
    tail_ts, tail_vals, tail_raw,  # [Ds*S_l, Dt, H]
    gids,  # [Ds*S_l] global group ids
    baseline,  # [Ds*S_l]
    start_off, step_ms, window,
    j_dev: int,
    num_groups: int,
    is_counter: bool = False,
    is_delta: bool = False,
):
    Dt = mesh.shape["time"]
    perm = [(i, (i + 1) % Dt) for i in range(Dt)]

    def local(ts_l, vals_l, raw_l, lens_l, tts, tv, tr, gids_l, base_l):
        # [S_l, 1, Tl] tiles: drop the time-slice axis
        t_idx = jax.lax.axis_index("time")
        h_ts = jax.lax.ppermute(tts, "time", perm)[:, 0]
        h_v = jax.lax.ppermute(tv, "time", perm)[:, 0]
        h_r = jax.lax.ppermute(tr, "time", perm)[:, 0]
        h_ts = jnp.where(t_idx == 0, jnp.int32(TS_NEG), h_ts)
        h_v = jnp.where(t_idx == 0, 0.0, h_v)
        h_r = jnp.where(t_idx == 0, 0.0, h_r)
        H = h_ts.shape[1]
        comb_ts = jnp.concatenate([h_ts, ts_l[:, 0]], axis=1)
        comb_v = jnp.concatenate([h_v, vals_l[:, 0]], axis=1)
        comb_r = jnp.concatenate([h_r, raw_l[:, 0]], axis=1)
        comb_lens = lens_l[:, 0] + H
        my_start = start_off + t_idx.astype(jnp.int32) * j_dev * step_ms
        grid = K.range_kernel(
            func, comb_ts, comb_v, comb_lens, base_l, comb_r,
            my_start, step_ms, window, j_dev,
            is_counter=is_counter, is_delta=is_delta,
        )
        valid = ~jnp.isnan(grid)
        v0 = jnp.where(valid, grid, 0.0)
        s = jax.ops.segment_sum(v0, gids_l, num_groups)
        c = jax.ops.segment_sum(valid.astype(jnp.float32), gids_l, num_groups)
        s = jax.lax.psum(s, "shard")
        c = jax.lax.psum(c, "shard")
        if op == "sum":
            out = jnp.where(c > 0, s, jnp.nan)
        elif op == "count":
            out = jnp.where(c > 0, c, jnp.nan)
        elif op == "avg":
            out = jnp.where(c > 0, s / jnp.maximum(c, 1.0), jnp.nan)
        else:
            raise ValueError(f"2d mesh aggregation supports sum/count/avg, got {op}")
        return out[None, None]  # [1, 1, G, j_dev]

    out = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P("shard", "time"), P("shard", "time"), P("shard", "time"),
            P("shard", "time"),
            P("shard", "time"), P("shard", "time"), P("shard", "time"),
            P("shard"), P("shard"),
        ),
        out_specs=P("shard", "time", None, None),
        check=False,
    )(ts, vals, raw, lens, tail_ts, tail_vals, tail_raw, gids, baseline)
    # [Ds, Dt, G, j_dev]: shard axis already reduced (psum) — take slice 0,
    # concat time along steps
    out = out[0]  # [Dt, G, j_dev]
    return jnp.moveaxis(out, 0, 1).reshape(out.shape[1], -1)  # [G, Dt*j_dev]


def run_mesh2d(mesh: Mesh, func: str, op: str, blocks: list[StagedBlock],
               gids_per_block, num_groups: int, params: K.RangeParams,
               is_counter=False, is_delta=False):
    """blocks: one staged block per series shard (<= mesh 'shard' size).
    Each block's time axis is split across the 'time' axis with halos."""
    Ds = mesh.shape["shard"]
    Dt = mesh.shape["time"]
    assert len(blocks) <= Ds
    # per-shard time split, then stack along a padded series axis
    parts = [
        split_time_axis(b, Dt, params.window_ms, params.start_ms, params.step_ms, params.num_steps)
        for b in blocks
    ]
    j_dev = parts[0][-1]
    S_l = max(p[0].shape[1] for p in parts)
    Tl = max(p[0].shape[2] for p in parts)
    H = max(p[4].shape[2] for p in parts)

    def stack(idx, fill, dtype, width):
        out = np.full((Ds * S_l, Dt, width), fill, dtype=dtype)
        for bi, p in enumerate(parts):
            arr = p[idx]  # [Dt, S_b, w]
            out[bi * S_l : bi * S_l + arr.shape[1], :, : arr.shape[2]] = np.moveaxis(arr, 0, 1)
        return out

    from ..ops.staging import TS_PAD

    ts = stack(0, TS_PAD, np.int32, Tl)
    vals = stack(1, 0.0, np.float32, Tl)
    raw = stack(2, 0.0, np.float32, Tl)
    tail_ts = stack(4, TS_NEG, np.int32, H)
    tail_vals = stack(5, 0.0, np.float32, H)
    tail_raw = stack(6, 0.0, np.float32, H)
    lens = np.zeros((Ds * S_l, Dt), dtype=np.int32)
    gids = np.zeros(Ds * S_l, dtype=np.int32)
    baseline = np.zeros(Ds * S_l, dtype=np.float32)
    for bi, (p, b, g) in enumerate(zip(parts, blocks, gids_per_block)):
        lens[bi * S_l : bi * S_l + p[3].shape[1], :] = np.moveaxis(p[3], 0, 1)
        k = b.n_series
        gids[bi * S_l : bi * S_l + k] = g
        baseline[bi * S_l : bi * S_l + k] = np.asarray(b.baseline)[:k]
        # padded series rows: zero-length, group 0 — contribute nothing
    sh2 = NamedSharding(mesh, P("shard", "time"))
    sh1 = NamedSharding(mesh, P("shard"))
    out = mesh2d_agg_range(
        mesh, func, op,
        jax.device_put(ts, sh2), jax.device_put(vals, sh2), jax.device_put(raw, sh2),
        jax.device_put(lens, sh2),
        jax.device_put(tail_ts, sh2), jax.device_put(tail_vals, sh2),
        jax.device_put(tail_raw, sh2),
        jax.device_put(gids, sh1), jax.device_put(baseline, sh1),
        np.int32(params.start_ms - blocks[0].base_ms),
        np.int32(params.step_ms), np.int32(params.window_ms),
        j_dev, num_groups,
        is_counter=is_counter, is_delta=is_delta,
    )
    return out[:, : params.num_steps]