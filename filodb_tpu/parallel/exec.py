"""Mesh-resident distributed aggregation exec (the end-to-end wiring of
parallel/mesh.py into the exec tree): when the planner is configured with a
device mesh, `sum|min|max|count|avg by (...) (range_fn(...))` executes as ONE
compiled program — per-device range kernel + local segment-reduce + psum over
the `shard` axis — instead of host-side partial merging (reference: the
ReduceAggregateExec network gather this replaces).
"""

from __future__ import annotations

import numpy as np

from ..ops import aggregations as AGG
from ..ops import kernels as K
from ..ops import staging as ST
from ..query.exec.plans import ExecPlan, QueryContext
from ..query.exec.transformers import QueryError, _strip_metric
from ..query.rangevector import Grid, QueryResult
from . import mesh as M

# device-resident WindowMatrices keyed by (grid bytes, query params); shared
# across exec instances — repeated queries skip host precompute + uploads.
# Single-flight + LRU via the one shared utility (filodb_tpu/singleflight):
# two racing same-key misses would each upload the full matrix set to HBM
# and the loser's copy would linger until GC.
from ..singleflight import SingleFlightLRU

_WM_CACHE = SingleFlightLRU(capacity=16)


def _get_wm(wm_key, ctor):
    """Get-or-create a device-resident window-matrices object in the shared
    bounded cache (one lock/eviction discipline for every mesh fast path).
    LRU on hit; a concurrent same-key miss blocks on the key's flight lock
    until the single builder finishes."""
    return _WM_CACHE.get_or_build(wm_key, ctor)


def _harmonized_masked_grid(nb):
    """The masked mesh kernel applies one block's window structure to every
    shard's rows — sound only when harmonize_masked succeeded. Re-verify
    from the blocks (the stage cache doesn't record the harmonize result):
    returns the common MaskedGrid descriptor, or None."""
    if not nb or any(b.mgrid is None for b in nb):
        return None
    g0 = nb[0].mgrid
    nom0 = np.asarray(g0.nominal_ts)[: g0.n_valid]
    for b in nb:
        g = b.mgrid
        if (
            g.maxdev_ms != g0.maxdev_ms
            or g.n_valid != g0.n_valid
            or len(np.asarray(g.nominal_ts)) < g0.n_valid
            or (np.asarray(g.nominal_ts)[: g0.n_valid] != nom0).any()
        ):
            return None
    return g0

MESH_OPS = {"sum", "count", "avg", "min", "max"}


class MeshAggregateExec(ExecPlan):
    """Aggregate a windowed range function across shards on the mesh.

    The aggregate path DELEGATES to the mesh-sharded fused superblock
    kernels (one pjit/shard_map dispatch over a series-partitioned
    ``[ΣS, T]`` superblock — ops/staging + ops/aggregations) whenever the
    op/function is in the fused set; the pre-fusion per-shard stack +
    psum kernels below remain as the ``mesh_unsupported`` fallback for
    everything else (and as the explicit ``fused=False`` escape hatch)."""

    def __init__(self, mesh, shard_nums, filters, raw_start_ms, raw_end_ms,
                 op: str, by, without, function: str,
                 start_ms: int, end_ms: int, step_ms: int, window_ms: int,
                 is_counter=False, is_delta=False, fused: bool = True,
                 fused_fallback=None):
        super().__init__()
        self.mesh = mesh
        self.shard_nums = list(shard_nums)
        self.filters = tuple(filters)
        self.raw_start_ms = raw_start_ms
        self.raw_end_ms = raw_end_ms
        self.op = op
        self.by = by
        self.without = without
        self.function = function
        self.start_ms = start_ms
        self.end_ms = end_ms
        self.step_ms = step_ms
        self.window_ms = window_ms
        self.is_counter = is_counter
        self.is_delta = is_delta
        # sharded-fused delegation: the planner passes the reference-tree
        # factory the delegate needs as ITS runtime fallback (partial
        # results, mixed schemas, ...). fused_fallback None (direct
        # construction) disables delegation outright.
        self.fused = fused
        self.fused_fallback = fused_fallback
        self._fused_params: tuple = ()
        self._fused_delegate = None

    def args_str(self):
        return (
            f"op={self.op} fn={self.function} shards={self.shard_nums} "
            f"devices={self.mesh.devices.size}"
        )

    def _cache(self, ctx: QueryContext, kind: str):
        cache = getattr(ctx.memstore, "_mesh_stage_cache", None)
        if cache is None:
            cache = {}
            ctx.memstore._mesh_stage_cache = cache
        versions = tuple(
            ctx.memstore.shard(ctx.dataset, s).version for s in self.shard_nums
        )
        key = (
            kind, self.filters, self.raw_start_ms, self.raw_end_ms,
            self.by, self.without, versions, self.mesh.devices.size,
            self.is_counter, self.is_delta,
            # the "stack" entry embeds msk_sh, which is built only for MXU
            # mesh functions — a non-member function must not decide the
            # cached value for member functions (or vice versa)
            self.function in self._MXU_MESH_FUNCS,
        )
        return cache, key

    def _staged_blocks(self, ctx: QueryContext):
        """Stage every shard + GLOBAL group numbering so on-device segment
        ids agree across shards. Returns (blocks, gids_per_block,
        group_labels) or None; cached per (selection, range, grouping,
        shard versions)."""
        cache, key = self._cache(ctx, "blocks")
        hit = cache.get(key)
        if hit is not None:
            return hit
        blocks, labels_per_shard = [], []
        for s in self.shard_nums:
            shard = ctx.memstore.shard(ctx.dataset, s)
            pids = shard.lookup_partitions(self.filters, self.raw_start_ms, self.raw_end_ms)
            if shard.odp_store is not None and len(pids):
                shard.odp_page_in(pids, self.raw_start_ms, self.raw_end_ms)
            block = ST.stage_from_shard(
                shard, pids, self._column(ctx, shard, pids), self.raw_start_ms,
                self.raw_end_ms, is_counter=self.is_counter and not self.is_delta,
            )
            labels = [dict(shard.partition(int(p)).tags) for p in pids]
            ctx.stats.bump(series_scanned=len(pids))
            blocks.append(block)
            labels_per_shard.append(labels)
        all_labels = [l for ls in labels_per_shard for l in ls]
        if not all_labels:
            return None
        # per-shard staging estimates per-block nominal grids independently;
        # put every near-regular block on ONE common grid so the mesh kernel
        # can share a single window structure (no-op for exact shared grids)
        r0 = blocks[0].regular_ts
        all_exact = r0 is not None and all(
            b.regular_ts is not None and len(b.regular_ts) == len(r0)
            and not (b.regular_ts != r0).any() for b in blocks[1:]
        )
        if not all_exact:
            if not ST.harmonize_nominal(blocks):
                # unequal counts (a dropped scrape somewhere): try the
                # missing-scrape masked common grid instead
                ST.harmonize_masked(blocks)
        gids_all, group_labels = AGG.group_ids_for(
            all_labels, list(self.by) if self.by else None,
            list(self.without) if self.without else None,
        )
        gids_per_block, off = [], 0
        for ls in labels_per_shard:
            gids_per_block.append(gids_all[off : off + len(ls)].astype(np.int32))
            off += len(ls)
        result = (blocks, gids_per_block, group_labels)
        if len(cache) >= 8:
            cache.pop(next(iter(cache)))
        cache[key] = result
        return result

    def _stage_all(self, ctx: QueryContext):
        """The 1D form: staged blocks stacked + pinned in HBM (cached)."""
        cache, key = self._cache(ctx, "stack")
        hit = cache.get(key)
        if hit is not None:
            return hit
        staged = self._staged_blocks(ctx)
        if staged is None:
            return None
        blocks, gids_per_block, group_labels = staged
        nb = [b for b in blocks if b.n_series > 0]
        jittered = bool(nb) and all(b.nominal_ts is not None for b in nb)
        arrays = M.stack_blocks_for_mesh(
            blocks, gids_per_block, self.mesh.devices.size, with_dev=jittered
        )
        sharded = M.shard_arrays(self.mesh, *arrays[:6])  # pin the stack in HBM
        dev_sh = None
        msk_sh = None
        if jittered:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            dev_sh = jax.device_put(
                arrays[6], NamedSharding(self.mesh, P("shard", None))
            )
        if self.function in self._MXU_MESH_FUNCS and (
            _harmonized_masked_grid(nb) is not None
        ):
            # missing-scrape masked path (harmonized in _staged_blocks):
            # stack + pin the slot-aligned sidecars — only when the grid
            # identity check the kernel needs actually holds, so a failed
            # harmonize never pays for 12 stacked arrays it can't use
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            row = NamedSharding(self.mesh, P("shard", None))
            msk_sh = tuple(
                jax.device_put(a, row)
                for a in M.stack_masked_for_mesh(blocks, self.mesh.devices.size)
            )
        result = (sharded, group_labels, blocks, dev_sh, msk_sh)
        if len(cache) >= 8:
            cache.pop(next(iter(cache)))
        cache[key] = result
        return result

    def _sharded_fused(self):
        """The mesh-sharded FusedAggregateExec this node delegates to, or
        None when the fused program doesn't model this aggregate (the
        planner's gate, re-checked here: fused_mesh_supported)."""
        from ..query.exec.plans import FusedAggregateExec, fused_mesh_supported

        if not self.fused or self.fused_fallback is None:
            return None
        smesh = M.series_mesh(self.mesh)
        if not fused_mesh_supported(smesh, self.op, self.function):
            return None
        if self._fused_delegate is None:
            self._fused_delegate = FusedAggregateExec(
                self.shard_nums, self.filters, self.raw_start_ms,
                self.raw_end_ms, None, self.op, self.by, self.without,
                self.function, self.start_ms, self.end_ms, self.step_ms,
                self.window_ms, 0, fallback=self.fused_fallback,
                params=self._fused_params, mesh=smesh,
            )
        return self._fused_delegate

    def _delegate(self, ctx: QueryContext):
        """Run the sharded-fused delegate, or record the legacy-kernel
        fallback (reason ``mesh_unsupported``) and return None."""
        delegate = self._sharded_fused()
        if delegate is not None:
            return delegate.execute(ctx)
        if self.fused and self.fused_fallback is not None:
            from ..metrics import current_span, record_fused_fallback

            s = current_span()
            if s is not None:
                s.tags["fused_fallback"] = "mesh_unsupported"
            record_fused_fallback("mesh_unsupported")
        return None

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        res = self._delegate(ctx)
        if res is not None:
            return res
        staged = self._stage_all(ctx)
        if staged is None:
            return QueryResult()
        sharded, group_labels, blocks, dev_sh, msk_sh = staged
        num_steps = int((self.end_ms - self.start_ms) // self.step_ms) + 1
        j_pad = K.pad_steps(num_steps)
        base = blocks[0].base_ms
        out = self._run_mxu(blocks, sharded, j_pad, base, len(group_labels),
                            dev_sh=dev_sh, msk_sh=msk_sh)
        if out is None:
            out = M.distributed_agg_range(
                self.mesh, self.function, self.op, *sharded,
                np.int32(self.start_ms - base), np.int32(self.step_ms),
                np.int32(self.window_ms), j_pad, len(group_labels),
                is_counter=self.is_counter, is_delta=self.is_delta,
            )
        return QueryResult(
            grids=[Grid(group_labels, self.start_ms, self.step_ms, num_steps, out)]
        )

    _MXU_MESH_FUNCS = {
        "sum_over_time", "count_over_time", "avg_over_time", "last",
        "last_over_time", "first_over_time", "present_over_time",
        "absent_over_time", "stddev_over_time", "stdvar_over_time",
        "z_score", "rate", "increase", "delta", "idelta", "irate",
    }

    def _run_mxu(self, blocks, arrays, j_pad, base, num_groups, dev_sh=None,
                 msk_sh=None):
        """Shared-scrape-grid fast path: MXU matmul kernel inside shard_map
        (single compiled call even when many shards pack one device). Falls
        through to the jittered-grid MXU path when the grids are only
        NEAR-regular (ops/mxu_jitter.py), then to the masked missing-scrape
        path when scrapes were dropped."""
        if self.function not in self._MXU_MESH_FUNCS:
            return None
        r0 = blocks[0].regular_ts
        if r0 is None or any(
            b.regular_ts is None or len(b.regular_ts) != len(r0)
            or (b.regular_ts != r0).any() for b in blocks[1:]
        ):
            out = self._run_jitter(blocks, arrays, j_pad, base, num_groups, dev_sh)
            if out is None:
                out = self._run_masked(blocks, arrays, j_pad, base, num_groups,
                                       msk_sh)
            return out
        from ..ops.mxu_kernels import WindowMatrices

        ts, vals, lens, baseline, raw, gids = arrays
        n_valid = int(np.asarray(blocks[0].lens)[0])
        # the window matrices depend only on (shared grid, query params) —
        # cache them device-resident so repeated queries skip the host
        # precompute + ~16 device_puts (dashboards repeat identical queries)
        wm_key = (r0.tobytes(), n_valid, self.start_ms - base, self.step_ms,
                  j_pad, self.window_ms)
        wm = _get_wm(wm_key, lambda: WindowMatrices(
            r0, n_valid, self.start_ms - base, self.step_ms, j_pad,
            self.window_ms,
        ))
        return M.distributed_agg_range_mxu(
            self.mesh, self.function, self.op,
            vals, raw, lens, baseline, gids,
            wm.dW, wm.dF, wm.dL, wm.dL2,
            wm.d_count, wm.d_tf, wm.d_tl, wm.d_tl2, wm.d_out_t,
            np.float32(self.window_ms), num_groups,
            is_counter=self.is_counter, is_delta=self.is_delta,
        )

    def _run_masked(self, blocks, arrays, j_pad, base, num_groups, msk_sh):
        """Missing-scrape grids: one shared window structure on the
        harmonized common nominal grid + the masked jitter kernel inside
        shard_map (validity masks absorb per-shard holes and width
        differences)."""
        if msk_sh is None:
            return None
        if self.is_delta and self.function in ("irate", "idelta"):
            return None
        g0 = _harmonized_masked_grid([b for b in blocks if b.n_series > 0])
        if g0 is None:
            return None
        from ..ops.mxu_jitter import JitterWindowMatrices
        from ..ops.mxu_kernels import fetch_strategy
        from ..ops.staging import TS_PAD

        ts, vals, lens, baseline, raw, gids = arrays
        (m_vals, m_dev, m_raw, valid, cc, ffv, ffd, bfv, bfd, ff2v, ff2d,
         bfraw) = msk_sh
        # sidecar slot width rules the window matrices (holes can stretch
        # the slot span beyond the packed T)
        T_stack = m_vals.shape[1]
        nominal = np.full(T_stack, TS_PAD, dtype=np.int32)
        nominal[: g0.n_valid] = np.asarray(g0.nominal_ts)[: g0.n_valid]
        wm_key = (
            "msk", nominal.tobytes(), g0.n_valid, g0.maxdev_ms,
            self.start_ms - base, self.step_ms, j_pad, self.window_ms,
        )
        wm = _get_wm(wm_key, lambda: JitterWindowMatrices(
            nominal, g0.n_valid, g0.maxdev_ms,
            self.start_ms - base, self.step_ms, j_pad, self.window_ms,
        ))
        if not wm.ok:
            return None
        return M.distributed_agg_range_masked(
            self.mesh, self.function, self.op,
            m_vals, m_dev, m_raw, valid, cc,
            ffv, ffd, bfv, bfd, ff2v, ff2d, bfraw,
            lens, gids,
            wm.d_W0, wm.d_SEL, wm.d_idx,
            wm.d_c0pos, wm.d_has_klo, wm.d_has_khi,
            wm.d_F0_rel, wm.d_L0_rel, wm.d_Klo_rel, wm.d_Khi_rel,
            wm.d_blo_rel, wm.d_ehi_rel,
            np.float32(self.window_ms), num_groups,
            is_counter=self.is_counter, is_delta=self.is_delta,
            fetch=fetch_strategy(),
        )

    def _run_jitter(self, blocks, arrays, j_pad, base, num_groups, dev_sh):
        """Near-regular grids: one shared certain/uncertain window structure
        (built on the harmonized common nominal grid) + the jitter kernel
        inside shard_map."""
        if dev_sh is None:
            return None
        if self.is_delta and self.function in ("irate", "idelta"):
            return None
        nb = [b for b in blocks if b.n_series > 0]
        if not nb or any(b.nominal_ts is None for b in nb):
            return None
        b0 = nb[0]
        n_valid = int(np.asarray(b0.lens)[0])
        # the kernel applies b0's window structure (nominal grid, maxdev,
        # n_valid) to EVERY shard's rows, so it is only sound when
        # harmonize_nominal actually succeeded. Its return value isn't
        # recorded through the stage cache, so re-verify here: every block on
        # the identical common grid, same maxdev, all series the same length
        # — otherwise fall back to the general gather path.
        nom0 = np.asarray(b0.nominal_ts)[:n_valid]
        for b in nb:
            lens_b = np.asarray(b.lens)[: b.n_series]
            if (
                b.maxdev_ms != b0.maxdev_ms
                or not (lens_b == n_valid).all()
                or len(np.asarray(b.nominal_ts)) < n_valid
                or (np.asarray(b.nominal_ts)[:n_valid] != nom0).any()
            ):
                return None
        from ..ops.mxu_jitter import JitterWindowMatrices
        from ..ops.staging import TS_PAD

        ts, vals, lens, baseline, raw, gids = arrays
        T_stack = vals.shape[1]
        nominal = np.full(T_stack, TS_PAD, dtype=np.int32)
        nominal[:n_valid] = np.asarray(b0.nominal_ts)[:n_valid]
        wm_key = (
            "jit", nominal.tobytes(), n_valid, b0.maxdev_ms,
            self.start_ms - base, self.step_ms, j_pad, self.window_ms,
        )
        wm = _get_wm(wm_key, lambda: JitterWindowMatrices(
            nominal, n_valid, b0.maxdev_ms,
            self.start_ms - base, self.step_ms, j_pad, self.window_ms,
        ))
        if not wm.ok:
            return None
        from ..ops.mxu_kernels import fetch_strategy

        return M.distributed_agg_range_jitter(
            self.mesh, self.function, self.op,
            vals, raw, dev_sh, lens, gids,
            wm.d_W0, wm.d_SEL, wm.d_idx, wm.d_count0, wm.d_c0pos, wm.d_c0ge2,
            wm.d_has_klo, wm.d_has_khi,
            wm.d_F0_rel, wm.d_L0_rel, wm.d_L2_rel, wm.d_Klo_rel, wm.d_Khi_rel,
            wm.d_blo_rel, wm.d_ehi_rel,
            np.float32(self.window_ms), num_groups,
            is_counter=self.is_counter, is_delta=self.is_delta,
            fetch=fetch_strategy(),
        )

    def _column(self, ctx, shard, pids) -> str | None:
        if not len(pids):
            return None
        return shard.partition(int(pids[0])).schema.value_column


def _concat_staged(bs):
    """Row-concatenate staged blocks exactly (keeps corrected values, raw
    sidecars, baselines — no restaging, no semantic drift). Delegates to the
    ONE concatenation (ops/staging.concat_blocks, shared with the fused
    superblock path); the mesh stacking consumers index ``raw``
    unconditionally, hence force_raw."""
    return ST.concat_blocks(bs, force_raw=True)


class Mesh2DAggregateExec(MeshAggregateExec):
    """sum/count/avg-by of a range function over a 2D (shard x time) mesh:
    series psum x time ring-halo in one program (parallel/mesh2d.py)."""

    def args_str(self):
        return (
            f"op={self.op} fn={self.function} mesh=({self.mesh.shape['shard']}x"
            f"{self.mesh.shape['time']})"
        )

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        from . import mesh2d as M2

        # sharded-fused delegation flattens the (shard x time) devices onto
        # one series axis (series_mesh) — still exactly one dispatch
        res = self._delegate(ctx)
        if res is not None:
            return res
        # per-shard staging (blocks + global gids) shared with the 1D path
        # (cached); mesh2d splits each block's time axis itself
        staged = self._staged_blocks(ctx)
        if staged is None:
            return QueryResult()
        blocks, gids_per_block, group_labels = staged
        Ds = self.mesh.shape["shard"]
        # pack shard blocks round-robin onto the Ds series rows
        merged_blocks: list = [[] for _ in range(min(Ds, len(blocks)))]
        merged_gids: list = [[] for _ in range(len(merged_blocks))]
        for i, (b, g) in enumerate(zip(blocks, gids_per_block)):
            merged_blocks[i % len(merged_blocks)].append(b)
            merged_gids[i % len(merged_gids)].append(g)
        # mesh2d takes one block per shard row: merge each row's blocks by
        # concatenating series host-side
        row_blocks, row_gids = [], []
        for bs, gs in zip(merged_blocks, merged_gids):
            if len(bs) == 1:
                row_blocks.append(bs[0])
                row_gids.append(gs[0])
            else:
                row_blocks.append(_concat_staged(bs))
                row_gids.append(np.concatenate(gs))
        num_steps = int((self.end_ms - self.start_ms) // self.step_ms) + 1
        params = K.RangeParams(self.start_ms, self.step_ms, num_steps, self.window_ms)
        out = M2.run_mesh2d(
            self.mesh, self.function, self.op, row_blocks, row_gids,
            len(group_labels), params,
            is_counter=self.is_counter, is_delta=self.is_delta,
        )
        return QueryResult(
            grids=[Grid(group_labels, self.start_ms, self.step_ms, num_steps,
                        np.asarray(out))]
        )


class MeshQuantileExec(MeshAggregateExec):
    """quantile(q, range_fn(...)) over the mesh via mergeable log-linear
    sketches + psum (reference ships t-digests between nodes; ops/sketch.py).
    Approximate within the log-linear bin error (~2-5%)."""

    def __init__(self, q: float, *args, **kw):
        super().__init__(*args, op="quantile", **kw)
        self.q = q
        self._fused_params = (q,)

    def args_str(self):
        return f"q={self.q} fn={self.function} shards={self.shard_nums} (sketch)"

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        from ..ops import sketch as SK

        # sharded-fused delegation: EXACT quantile (the all_gather'd
        # multiset sort epilogue) in one dispatch — strictly better than
        # the mergeable log-linear sketches, which remain the fallback
        res = self._delegate(ctx)
        if res is not None:
            return res
        staged = self._stage_all(ctx)
        if staged is None:
            return QueryResult()
        sharded, group_labels, blocks, _dev_sh, _msk_sh = staged
        num_steps = int((self.end_ms - self.start_ms) // self.step_ms) + 1
        j_pad = K.pad_steps(num_steps)
        base = blocks[0].base_ms
        sk = SK.distributed_sketch_quantile(
            self.mesh, self.function, *sharded,
            np.int32(self.start_ms - base), np.int32(self.step_ms),
            np.int32(self.window_ms), j_pad, len(group_labels),
            is_counter=self.is_counter, is_delta=self.is_delta,
        )
        vals = SK.sketch_quantile(np.asarray(sk), self.q)[:, :num_steps].astype(np.float32)
        return QueryResult(
            grids=[Grid(group_labels, self.start_ms, self.step_ms, num_steps, vals)]
        )


# planner routes non-aggregated range functions with at least this many
# output steps to the time-sharded path (ring halo exchange)
TIME_SHARD_MIN_STEPS = 512


class TimeShardRangeExec(ExecPlan):
    """Long-range windowed function over the mesh's TIME axis: all matching
    series stage into one block whose time dimension shards across devices
    with a ppermute lookback halo (parallel/timeshard.py)."""

    def __init__(self, mesh, shard_nums, filters, raw_start_ms, raw_end_ms,
                 function: str, start_ms: int, end_ms: int, step_ms: int,
                 window_ms: int, is_counter=False, is_delta=False):
        super().__init__()
        self.mesh = mesh
        self.shard_nums = list(shard_nums)
        self.filters = tuple(filters)
        self.raw_start_ms = raw_start_ms
        self.raw_end_ms = raw_end_ms
        self.function = function
        self.start_ms = start_ms
        self.end_ms = end_ms
        self.step_ms = step_ms
        self.window_ms = window_ms
        self.is_counter = is_counter
        self.is_delta = is_delta

    def args_str(self):
        return (
            f"fn={self.function} steps~{(self.end_ms - self.start_ms) // self.step_ms + 1} "
            f"time_devices={self.mesh.devices.size}"
        )

    def do_execute(self, ctx: QueryContext) -> QueryResult:
        from . import timeshard as TSH
        from ..query.exec.transformers import _strip_metric

        series, labels = [], []
        for s in self.shard_nums:
            shard = ctx.memstore.shard(ctx.dataset, s)
            pids = shard.lookup_partitions(self.filters, self.raw_start_ms, self.raw_end_ms)
            if shard.odp_store is not None and len(pids):
                shard.odp_page_in(pids, self.raw_start_ms, self.raw_end_ms)
            for pid in pids:
                part = shard.partition(int(pid))
                col = part.schema.value_column
                t, v = part.samples_in_range(self.raw_start_ms, self.raw_end_ms, col)
                if v.ndim != 1:
                    raise QueryError("time-sharded path supports scalar columns only")
                series.append((t, v))
                labels.append(dict(part.tags))
            ctx.stats.bump(series_scanned=len(pids))
        if not series:
            return QueryResult()
        block = ST.stage_series(
            series, self.raw_start_ms,
            counter_corrected=self.is_counter and not self.is_delta,
        )
        num_steps = int((self.end_ms - self.start_ms) // self.step_ms) + 1
        params = K.RangeParams(self.start_ms, self.step_ms, num_steps, self.window_ms)
        out = TSH.run_timesharded(
            self.mesh, self.function, block, params,
            is_counter=self.is_counter, is_delta=self.is_delta,
        )
        labels = [_strip_metric(l) for l in labels] if self.function not in (
            "last_over_time", "timestamp") else labels
        return QueryResult(
            grids=[Grid(labels, self.start_ms, self.step_ms, num_steps, out)]
        )
